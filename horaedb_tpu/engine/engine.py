"""MetricEngine facade: Prometheus-shaped writes and queries end-to-end.

Ties the three managers over four ColumnarStorage tables (one sub-root each:
{root}/{metrics,series,index,data}). The write path is the RFC pipeline:
populate metric ids -> populate series ids (registering new series + inverted
index entries) -> persist samples; the read path is index probe -> storage
scan with device predicate -> device aggregation.
"""

from __future__ import annotations

import copy
import hashlib
import logging
from dataclasses import dataclass, field

import numpy as np

from horaedb_tpu.common import tracing
from horaedb_tpu.common.error import ensure
from horaedb_tpu.common.time_ext import now_ms
from horaedb_tpu.engine import tables
from horaedb_tpu.engine.data import SampleManager
from horaedb_tpu.engine.index import IndexManager
from horaedb_tpu.engine.metric import MetricManager
from horaedb_tpu.ingest.types import ParsedWriteRequest
from horaedb_tpu.objstore import ObjectStore
from horaedb_tpu.storage.config import ColumnOptions, StorageConfig
from horaedb_tpu.storage.storage import ObjectBasedStorage
from horaedb_tpu.storage.types import TimeRange

logger = logging.getLogger(__name__)

NAME_LABEL = b"__name__"

DEFAULT_SEGMENT_MS = 2 * 3600_000  # 2h data segments


def sample_table_config(config: StorageConfig | None) -> StorageConfig:
    """Data/exemplars-table write config with measured encoding defaults.

    The RFC floats a custom compressed sample payload (delta-of-delta
    timestamps + XOR values packed into opaque bytes, RFC :218-232).
    Measured on realistic scrape-shaped data (benchmarks/
    compression_bench.py): parquet's own DELTA_BINARY_PACKED (int lanes)
    + BYTE_STREAM_SPLIT/zstd (values) beats that design — smaller than
    the byte-aligned gorilla variant AND decode stays columnar/vectorized,
    so scans get faster, not slower. These are therefore the sample-table
    defaults; explicit user column_options always win.

    Each default carries enable_dict=False: parquet rejects an explicit
    column_encoding for a dictionary-encoded column, so the tuned columns
    opt out of dictionary mode individually — a user's global
    enable_dict=true still applies to every other column."""
    cfg = copy.deepcopy(config) if config is not None else StorageConfig()
    opts = dict(cfg.write.column_options or {})
    defaults = {
        "metric_id": "DELTA_BINARY_PACKED",
        "tsid": "DELTA_BINARY_PACKED",
        "field_id": "DELTA_BINARY_PACKED",
        "ts": "DELTA_BINARY_PACKED",
        "value": "BYTE_STREAM_SPLIT",
    }
    for name, enc in defaults.items():
        opts.setdefault(name, ColumnOptions(
            enable_dict=False, encoding=enc,
            compression="zstd" if name == "value" else None,
        ))
    cfg.write.column_options = opts
    return cfg


@dataclass
class QueryRequest:
    metric: bytes
    start_ms: int
    end_ms: int
    filters: list[tuple[bytes, bytes]] = field(default_factory=list)
    # Prometheus-style extended matchers: (key, op, pattern) with op in
    # "ne" (!=), "re" (=~ full match), "nre" (!~)
    matchers: list[tuple[bytes, str, bytes]] = field(default_factory=list)
    bucket_ms: int | None = None  # None -> raw rows
    # Raw-row limit PUSHED INTO the scan: segments stop being read once
    # `limit` merged rows have accumulated (segments scan old->new), so a
    # 100M-row table queried with limit=100k pays ~100k rows of work, not
    # full materialization. None = unbounded. Ignored for bucketed queries.
    limit: int | None = None


class MetricEngine:
    def __init__(self) -> None:
        raise RuntimeError("use MetricEngine.open")

    @classmethod
    async def open(
        cls,
        root: str,
        store: ObjectStore,
        segment_duration_ms: int = DEFAULT_SEGMENT_MS,
        config: StorageConfig | None = None,
        enable_compaction: bool = True,
        ingest_buffer_rows: int = 0,
        flush_workers: int = 2,
        flush_queue_max: int = 4,
        flush_stall_deadline_s: float = 30.0,
        sst_executor=None,
        manifest_executor=None,
        parser_pool=None,
        fence_node_id: str | None = None,
        fence_validate_interval_s: float = 5.0,
    ) -> "MetricEngine":
        """`ingest_buffer_rows` > 0 buffers data-table rows across writes
        and flushes as one SST per segment when the threshold is reached
        (see SampleManager.__init__ for the durability trade-off);
        `flush_workers`/`flush_queue_max`/`flush_stall_deadline_s` size the
        background flush executor (engine/flush_executor.py) that decouples
        the append hot path from drain/encode/upload work.
        `sst_executor`/`manifest_executor` size CPU-heavy storage work
        (ThreadConfig, see ObjectBasedStorage.try_new). `parser_pool` shares
        the caller's ParserPool (so e.g. the server's pool telemetry covers
        engine ingest); None = engine creates its own on first use.
        `fence_node_id` claims exclusive write ownership of this engine
        root: ONE epoch fence covers all six tables (the region is the
        ownership unit, RFC :28-76); a later claimant deposes this process
        and its writes fail with FencedError (storage/fence.py)."""
        self = object.__new__(cls)
        self._store = store
        self._segment_duration = segment_duration_ms
        self._pool = parser_pool

        fence = None
        if fence_node_id is not None:
            from horaedb_tpu.storage.fence import EpochFence

            fence = await EpochFence.acquire(
                store, root.strip("/"), fence_node_id,
                validate_interval_s=fence_validate_interval_s,
            )
        self._fence = fence

        sample_cfg = sample_table_config(config)

        async def open_table(name, schema, num_pks, compaction):
            return await ObjectBasedStorage.try_new(
                root=f"{root}/{name}",
                store=store,
                arrow_schema=schema,
                num_primary_keys=num_pks,
                segment_duration_ms=segment_duration_ms,
                # sample-bearing tables get the measured encoding defaults
                config=sample_cfg if name in ("data", "exemplars") else config,
                enable_compaction_scheduler=compaction,
                sst_executor=sst_executor,
                manifest_executor=manifest_executor,
                fence=fence,
            )

        self.metrics_table = await open_table(
            "metrics", tables.METRICS_SCHEMA, tables.METRICS_NUM_PKS, False
        )
        self.series_table = await open_table(
            "series", tables.SERIES_SCHEMA, tables.SERIES_NUM_PKS, False
        )
        self.index_table = await open_table(
            "index", tables.INDEX_SCHEMA, tables.INDEX_NUM_PKS, False
        )
        self.tags_table = await open_table(
            "tags", tables.TAGS_SCHEMA, tables.TAGS_NUM_PKS, False
        )
        self.data_table = await open_table(
            "data", tables.DATA_SCHEMA, tables.DATA_NUM_PKS, enable_compaction
        )
        self.exemplars_table = await open_table(
            "exemplars", tables.EXEMPLARS_SCHEMA, tables.EXEMPLARS_NUM_PKS, False
        )

        self.metric_mgr = MetricManager(self.metrics_table, segment_duration_ms)
        self.index_mgr = IndexManager(
            self.series_table, self.index_table, segment_duration_ms,
            # base sidecar lives beside the two tables it caches, in a
            # namespace neither table's manifest/data layout touches
            sidecar_store=store,
            sidecar_path=f"{root}/index_sidecar/base.arrow",
            tags_storage=self.tags_table,
        )
        # Payload-shape fingerprint cache: scrapers resend the same series
        # set every interval, so the (metric_id, tsid) lane BYTES repeat
        # exactly payload-over-payload. A hit proves this exact lane-set was
        # fully registered (entries are added only after durable
        # registration), collapsing steady-state id resolution to one set
        # probe. Keys are 16-byte blake2b digests of the lane bytes — fixed
        # memory (64 KB at the 4096-entry cap) even for 10k-series payloads
        # whose shapes churn, at cryptographic collision resistance.
        self._lanes_fp: set[bytes] = set()
        self.sample_mgr = SampleManager(
            self.data_table, segment_duration_ms,
            buffer_rows=ingest_buffer_rows,
            flush_workers=flush_workers,
            flush_queue_max=flush_queue_max,
            flush_stall_deadline_s=flush_stall_deadline_s,
        )
        self.exemplar_mgr = SampleManager(self.exemplars_table, segment_duration_ms)
        await self.metric_mgr.open()
        await self.index_mgr.open()
        return self

    def sub_engines(self) -> "dict[str, MetricEngine]":
        """Uniform enumeration for observability surfaces — one unpartitioned
        engine; RegionedEngine returns one entry per region."""
        return {"": self}

    async def flush(self) -> None:
        """Flush any buffered ingest rows to durable SSTs (waits out any
        in-flight background flush first)."""
        await self.sample_mgr.drain()

    async def close(self) -> None:
        await self.flush()
        # quiesced now: fold the index into its sidecar so the next open
        # replays nothing (best-effort — open rebuilds from the tables if
        # this never lands)
        try:
            await self.index_mgr.dump_sidecar()
        except Exception:  # noqa: BLE001
            logger.warning("index sidecar dump failed; next open will rebuild",
                           exc_info=True)
        for t in (
            self.metrics_table,
            self.series_table,
            self.index_table,
            self.tags_table,
            self.data_table,
            self.exemplars_table,
        ):
            await t.close()

    # -- write path -----------------------------------------------------------
    def metadata(self) -> dict[bytes, str]:
        """Metric-family metadata (family name -> prom type string)."""
        return dict(self.metric_mgr.metadata)

    def _record_metadata(self, req: ParsedWriteRequest) -> None:
        """Fold remote-write METADATA records (family name -> prom type)
        into the advisory metadata cache (served at /api/v1/metadata)."""
        for i in range(len(req.meta_type)):
            self.metric_mgr.record_metadata(
                req.meta_name(i), int(req.meta_type[i])
            )

    async def write_parsed(self, req: ParsedWriteRequest) -> int:
        """Ingest one decoded remote-write request; returns sample count.

        When the native parser supplied metric-id/tsid hash lanes
        (ingest/types.py), id resolution is pure numpy + set probes — no
        per-series label slicing or Python seahash (the reference hash
        contract lives in C++, src/metric_engine/src/types.rs:18-41)."""
        if len(req.meta_type):
            self._record_metadata(req)
        if req.n_series == 0:
            return 0
        if req.series_tsid is not None:
            return await self._write_parsed_fast(req)
        ts_now = now_ms()
        # 1. metric names from __name__ labels
        names: list[bytes] = []
        label_sets: list[list[tuple[bytes, bytes]]] = []
        for s in range(req.n_series):
            labels = req.series_labels(s)
            name = b""
            rest = []
            for k, v in labels:
                if k == NAME_LABEL:
                    name = v
                else:
                    rest.append((k, v))
            ensure(bool(name), f"series {s} missing __name__ label")
            names.append(name)
            label_sets.append(rest)
        ids = await self.metric_mgr.populate_metric_ids(names, ts_now)
        metric_per_series = [ids[n] for n in names]
        # 2. series registration + tsids
        tsids = await self.index_mgr.populate_series_ids(
            metric_per_series, label_sets, ts_now
        )
        # 3. samples -> data rows
        n = req.n_samples
        metric_arr = np.asarray(metric_per_series, dtype=np.uint64)
        tsid_arr = np.asarray(tsids, dtype=np.uint64)
        if n:
            series_idx = req.sample_series
            await self.sample_mgr.persist(
                metric_arr[series_idx], tsid_arr[series_idx],
                req.sample_ts, req.sample_value,
            )
        # 4. exemplars -> exemplars table (with their labels: trace ids are
        # the entire point of exemplars)
        if len(req.exemplar_value):
            await self._persist_exemplars(req, metric_arr, tsid_arr)
        return n

    async def _resolve_ids_fast(self, req: ParsedWriteRequest):
        """Hash-lane id resolution: validate names, register unseen metrics
        and series. Returns (metric_arr, tsid_arr) u64 per series."""
        ts_now = now_ms()
        name_len = req.series_name_len
        if np.any(name_len < 0):
            s = int(np.argmax(name_len < 0))
            ensure(False, f"series {s} missing __name__ label")
        metric_arr = req.series_metric_id
        tsid_arr = req.series_tsid
        # steady-state fast path: the exact lane bytes were seen (and their
        # series durably registered) before — one set probe, no per-series
        # Python work
        h = hashlib.blake2b(metric_arr.tobytes(), digest_size=16)
        h.update(tsid_arr.tobytes())
        fp = h.digest()
        if fp in self._lanes_fp:
            return metric_arr, tsid_arr
        # 1. register unseen metrics (rare after warmup)
        new_ids = self.metric_mgr.unknown_ids(metric_arr)
        if len(new_ids):
            new_set = set(new_ids.tolist())
            seen: dict[int, bytes] = {}
            for s in range(req.n_series):
                m = int(metric_arr[s])
                if m in new_set and m not in seen:
                    seen[m] = req.series_name(s)
            ensure(all(seen.values()), "series missing __name__ label")
            await self.metric_mgr.register_named(
                list(seen.values()), list(seen.keys()), ts_now
            )
        # 2. register unseen series
        await self.index_mgr.ensure_series_fast(
            metric_arr, tsid_arr, req.series_key, ts_now,
            tag_rows_of=req.series_tag_rows,
        )
        # everything in these lanes is now durably registered — remember
        # the shape (bounded: scrape fleets send a few distinct shapes)
        if len(self._lanes_fp) >= 4096:
            self._lanes_fp.clear()
        self._lanes_fp.add(fp)
        return metric_arr, tsid_arr

    async def write_payload(self, payload: bytes) -> int:
        """Parse + ingest one wire payload end-to-end. With native buffering
        active (ingest_buffer_rows > 0 and the C++ library available),
        samples move straight from the parser arena into the C++
        accumulator — no Python-side sample materialization at all.

        Borrow discipline: the pool slot is held only for the arena-touching
        steps (parse, id resolution, accum add). Steady-state resolution has
        no awaits; only new-series registration persists while borrowed
        (series keys/names must come from the arena, and they are
        materialized to owned bytes before the await). Exemplar persistence
        and threshold flushes use owned copies and run after release."""
        import asyncio

        from horaedb_tpu.ingest import ParserPool

        from horaedb_tpu.ingest.pooled_parser import PARSE_SECONDS

        if self._pool is None:
            self._pool = ParserPool()
        if not self.sample_mgr.native_accum_active:
            parsed = await self._pool.decode(payload)
            with tracing.span("append", samples=parsed.n_samples):
                return await self.write_parsed(parsed)
        from horaedb_tpu.ingest.native import NativeParser

        total = 0
        async with self._pool.borrow() as parser:
            if not isinstance(parser, NativeParser):
                with tracing.span("parse", bytes=len(payload)), \
                        PARSE_SECONDS.time():
                    parsed = await asyncio.to_thread(parser.parse, payload)
                with tracing.span("append", samples=parsed.n_samples):
                    return await self.write_parsed(parsed)
            # small payloads parse inline: the native parse runs ~1 GB/s, so
            # a sub-256KB payload blocks the loop far less than a thread
            # handoff costs (~100us)
            with tracing.span("parse", bytes=len(payload)), \
                    PARSE_SECONDS.time():
                if len(payload) <= 256 * 1024:
                    req = parser.parse_light(payload)
                else:
                    req = await asyncio.to_thread(parser.parse_light, payload)
            if len(req.meta_type):
                self._record_metadata(req)
            if req.n_series == 0:
                return 0
            with tracing.span("append", samples=req.n_samples):
                metric_arr, tsid_arr = await self._resolve_ids_fast(req)
                if len(req.exemplar_value):
                    # the id lanes may be views into the borrowed parser's
                    # decode arena (pooled_parser.DecodeArena) — exemplar
                    # persistence runs after release, so own them first
                    metric_arr = np.array(metric_arr)
                    tsid_arr = np.array(tsid_arr)
                if req.n_samples:
                    total = self.sample_mgr.buffer_native_add(parser)
        if len(req.exemplar_value):
            await self._persist_exemplars(req, metric_arr, tsid_arr)
        if total and self.sample_mgr.should_flush(total):
            # hand the sealed memtable to the background flush executor:
            # drain/encode/upload overlap continued ingest, and a FULL
            # flush queue blocks here with a stall deadline (backpressure
            # -> 5xx -> sender retries) instead of acking rows into an
            # unbounded buffer
            await self.sample_mgr.seal_and_submit()
        if self.sample_mgr.flush_in_flight:
            # cooperative yield: the steady write path never suspends, so a
            # driver hammering write_payload back-to-back would starve the
            # flush workers; one loop turn per payload lets their
            # thread-offload completions schedule (a real server yields at
            # socket reads)
            await asyncio.sleep(0)
        return req.n_samples

    async def _write_parsed_fast(self, req: ParsedWriteRequest) -> int:
        """Hash-lane write path: per-series ids come from the C++ parser."""
        metric_arr, tsid_arr = await self._resolve_ids_fast(req)
        # 3. samples
        n = req.n_samples
        if n:
            if self.sample_mgr.buffering:
                await self.sample_mgr.buffer_request(metric_arr, tsid_arr, req)
            else:
                series_idx = req.sample_series
                await self.sample_mgr.persist(
                    metric_arr[series_idx], tsid_arr[series_idx],
                    req.sample_ts, req.sample_value,
                )
        if len(req.exemplar_value):
            await self._persist_exemplars(req, metric_arr, tsid_arr)
        return n

    async def _persist_exemplars(
        self, req: ParsedWriteRequest, metric_arr, tsid_arr
    ) -> None:
        import pyarrow as pa

        from horaedb_tpu.engine.types import series_key_of
        from horaedb_tpu.storage.read import WriteRequest as StorageWrite

        ex_idx = req.exemplar_series
        m = metric_arr[ex_idx]
        t = tsid_arr[ex_idx]
        ts = req.exemplar_ts
        vals = req.exemplar_value
        labels = [
            series_key_of(req.exemplar_labels(i)) for i in range(len(vals))
        ]
        seg = ts - (ts % self._segment_duration)
        for seg_start in np.unique(seg):
            msk = seg == seg_start
            idxs = np.nonzero(msk)[0]
            batch = pa.RecordBatch.from_pydict(
                {
                    "metric_id": m[msk].astype(np.uint64),
                    "tsid": t[msk].astype(np.uint64),
                    "ts": ts[msk],
                    "value": vals[msk],
                    "labels": [labels[i] for i in idxs],
                },
                schema=tables.EXEMPLARS_SCHEMA,
            )
            lo, hi = int(ts[msk].min()), int(ts[msk].max()) + 1
            await self.exemplars_table.write(StorageWrite(batch, TimeRange(lo, hi)))

    # -- query path -------------------------------------------------------------
    def _resolve_query(
        self, metric: bytes, filters, matchers=None
    ) -> tuple[int, list | None] | None:
        """Shared lookup prologue: metric id + TSID candidates, or None when
        the metric is unknown / no series matches the filters."""
        hit = self.metric_mgr.get(metric)
        if hit is None:
            return None
        tsids = self.index_mgr.find_tsids(hit[0], filters, matchers)
        if tsids == []:
            return None
        return hit[0], tsids

    async def _resolve_query_async(self, req: QueryRequest):
        """Regex matchers evaluate in a worker thread: Python re has no
        linear-time guarantee and must not stall the event loop."""
        import asyncio

        if req.matchers:
            return await asyncio.to_thread(
                self._resolve_query, req.metric, req.filters, req.matchers
            )
        return self._resolve_query(req.metric, req.filters, req.matchers)

    async def query(self, req: QueryRequest):
        """Raw rows (bucket_ms None) or downsample grids per series."""
        resolved = await self._resolve_query_async(req)
        if resolved is None:
            return None
        metric_id, tsids = resolved
        rng = TimeRange(req.start_ms, req.end_ms)
        if req.bucket_ms is None:
            return await self.sample_mgr.query_raw(
                metric_id, tsids, rng, limit=req.limit
            )
        filtered = tsids is not None
        if tsids is None:  # no tag filter: all series of the metric
            tsids = self.index_mgr.series_of(metric_id)
        return await self.sample_mgr.query_downsample(
            metric_id, tsids, rng, req.bucket_ms, filtered=filtered
        )

    async def query_exemplars(self, req: QueryRequest):
        """Raw exemplar rows (incl. their labels) for a metric."""
        resolved = await self._resolve_query_async(req)
        if resolved is None:
            return None
        metric_id, tsids = resolved
        return await self.exemplar_mgr.query_raw(
            metric_id, tsids, TimeRange(req.start_ms, req.end_ms), limit=req.limit
        )

    def label_values(self, metric: bytes, key: bytes) -> list[bytes]:
        hit = self.metric_mgr.get(metric)
        if hit is None:
            return []
        return self.index_mgr.label_values(hit[0], key)

    async def label_values_storage(self, metric: bytes, key: bytes) -> list[bytes]:
        """LabelValues from the durable tags table (RFC :118-130) — agrees
        with `label_values` (tested); see IndexManager.label_values_storage
        for when to prefer which."""
        hit = self.metric_mgr.get(metric)
        if hit is None:
            return []
        return await self.index_mgr.label_values_storage(hit[0], key)

    def metric_names(self) -> list[bytes]:
        """All registered metric names (the /api/v1/metrics surface)."""
        return self.metric_mgr.names()

    def label_names(self) -> list[bytes]:
        """All label KEYS across every registered series (the
        /api/v1/labels no-match[] surface; `__name__` is the endpoint's
        concern). Public like `metric_names` so regioned deployments can
        answer via fan-out instead of reaching into the managers."""
        names: set[bytes] = set()
        for metric in self.metric_mgr.names():
            hit = self.metric_mgr.get(metric)
            if hit is None:
                continue
            for labs in self.index_mgr.series_labels(hit[0]).values():
                names.update(labs)
        return sorted(names)

    def series(self, metric: bytes) -> list[dict[str, str]]:
        """Label sets of every series of a metric (the /api/v1/series
        surface), including tagless series."""
        hit = self.metric_mgr.get(metric)
        if hit is None:
            return []
        per_tsid = self.index_mgr.series_labels(hit[0])
        return [
            {k.decode(errors="replace"): v.decode(errors="replace")
             for k, v in labels.items()} | {"__tsid__": str(t)}
            for t, labels in sorted(per_tsid.items())
        ]

    def series_labels_map(
        self, metric: bytes, tsids: "list[int] | None" = None
    ) -> dict[int, dict[bytes, bytes]]:
        """tsid -> raw label map for a metric, optionally restricted to
        `tsids` (so a selective query never decodes the whole metric's
        series). PromQL/discovery surface — implemented by RegionedEngine
        too (fan-out union)."""
        hit = self.metric_mgr.get(metric)
        if hit is None:
            return {}
        per_tsid = self.index_mgr.series_labels(hit[0])
        if tsids is None:
            return per_tsid
        return {t: per_tsid[t] for t in tsids if t in per_tsid}

    async def match_series(
        self, metric: bytes, filters, matchers
    ) -> dict[int, dict[bytes, bytes]]:
        """Matched tsid -> label map (Prometheus match[] resolution). Regex
        matchers evaluate off the event loop — same safeguard as queries
        (_resolve_query_async): Python `re` has no linear-time guarantee."""
        resolved = await self._resolve_query_async(
            QueryRequest(metric=metric, start_ms=0, end_ms=1,
                         filters=filters, matchers=matchers)
        )
        if resolved is None:
            return {}
        metric_id, tsids = resolved
        per_tsid = self.index_mgr.series_labels(metric_id)
        if tsids is None:
            return per_tsid
        return {t: per_tsid[t] for t in tsids if t in per_tsid}

    async def compact(self, time_range=None) -> None:
        """Manual compaction trigger on the data table (the /compact hook).
        `time_range` scopes the pick (and its follow-on picks) to SSTs
        overlapping that window; None compacts globally."""
        from horaedb_tpu.storage.read import CompactRequest

        await self.data_table.compact(CompactRequest(time_range=time_range))
