"""Metric engine: the VictoriaMetrics-style top layer.

The reference declares this layer but left all three managers `todo!()`
(src/metric_engine/src/{metric,index,data}/mod.rs:34-41); the actual design
lives in its RFC (docs/rfcs/20240827-metric-engine.md). This package
implements that design over ColumnarStorage tables:

  metrics  {MetricName, MetricId, FieldName, FieldId, FieldType}   (RFC :108-112)
  series   {MetricId, TSID, SeriesKey}                             (RFC :114-118)
  index    {MetricId, TagKey, TagValue, TSID}  (inverted)          (RFC :132-136)
  data     {MetricId, TSID, FieldId, Timestamp, Value}             (RFC :218-232)

ids: metric_id = seahash(name), tsid = seahash(sorted tag KVs) (reference
src/metric_engine/src/types.rs:18-41).

TPU-first divergence (documented, deliberate): the RFC batches ~30min of
compressed (ts, value) bytes per data row; here data rows stay RAW numeric
columns — they feed XLA scan/aggregate kernels directly with no decompress
stage, and parquet's own column encodings provide the compression. The
first-N-columns primary key + seq-based dedup contracts are preserved.
"""

from horaedb_tpu.engine.types import MetricId, SeriesId, seahash
from horaedb_tpu.engine.engine import MetricEngine, QueryRequest
from horaedb_tpu.engine.region import RegionedEngine, RegionRouter

__all__ = [
    "MetricEngine", "QueryRequest", "MetricId", "SeriesId", "seahash",
    "RegionedEngine", "RegionRouter",
]
