"""SampleManager: sample persistence + the device-side query pipeline.

Implements the reference's `SampleManager::persist` skeleton
(src/metric_engine/src/data/mod.rs:34-41, dead code in the snapshot): raw
sample rows land in the `data` table bucketed per time segment (a storage
write must not cross a segment, storage.rs:307-316), and queries run the
storage scan with (metric_id eq + TSID set-membership + time range)
predicates followed by on-device aggregation.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time

import numpy as np
import pyarrow as pa

from horaedb_tpu.common import colblock, memtrace, tracing
from horaedb_tpu.common.aio import TaskGroup
from horaedb_tpu.engine.flush_executor import (
    FLUSH_FAILURES_TOTAL,
    FLUSH_OVERLAP_RATIO,
    FLUSH_STAGE_SECONDS,
    FlushExecutor,
    SealedMemtable,
)
from horaedb_tpu.engine.tables import DATA_SCHEMA
from horaedb_tpu.ops import aggregate as agg_ops
from horaedb_tpu.ops import filter as F
from horaedb_tpu.server.metrics import GLOBAL_METRICS
from horaedb_tpu.storage import scanstats
from horaedb_tpu.storage.read import ScanRequest, WriteRequest
from horaedb_tpu.storage.storage import ObjectBasedStorage
from horaedb_tpu.storage.types import TimeRange

logger = logging.getLogger(__name__)

FLUSH_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_ingest_flush_seconds",
    help="One buffered-ingest write-out (snapshot detach -> SSTs durable), "
         "by table root (region-qualified on regioned deployments).",
    labelnames=("table",),
)
FLUSH_ROWS = GLOBAL_METRICS.counter(
    "horaedb_ingest_flush_rows_total",
    help="Rows made durable by ingest flush write-outs.",
    labelnames=("table",),
)
FLUSH_FAILURES = GLOBAL_METRICS.counter(
    "horaedb_ingest_flush_failures_total",
    help="Failed write-outs (rows re-buffered for retry).",
    labelnames=("table",),
)
LATE_SAMPLES = GLOBAL_METRICS.counter(
    "horaedb_late_samples_total",
    help="Out-of-order/backfill samples: appended rows whose timestamp "
         "falls in a segment OLDER than the active one (the ingest low "
         "watermark). Routed to per-time-partition late buffers and "
         "flushed as ordinary per-segment SSTs; reads stay exact via "
         "merge-dedup. A sustained rate means lagging agents or a "
         "backfill import.",
    labelnames=("table",),
)


# Above this series cardinality the dense pushdown grid (num_series x
# num_buckets x 4 stats) and the device membership probe stop paying off;
# fall back to materializing + np.unique sizing by the rows actually in range.
MAX_PUSHDOWN_SERIES = 65_536

# Resolution guard: a downsample query must not demand an absurd number of
# buckets (start=0, bucket=1m would be a ~30M-bucket grid per series) —
# reject loudly, like Prometheus's max-resolution limit.
MAX_BUCKETS = 100_000

# In-flight per-segment pushdown scans PER SampleManager (shared across
# concurrent queries — a dashboard burst cannot multiply it).
SEGMENT_SCAN_CONCURRENCY = 4

# Shared read-only zeros arena for the constant field_id column: flush
# shards would otherwise allocate + zero-fill a fresh u64 lane per write
# (pyarrow wraps the view zero-copy; the batch never mutates it).
_ZEROS_U64 = np.zeros(0, dtype=np.uint64)


def _zeros_u64(n: int) -> np.ndarray:
    global _ZEROS_U64
    if len(_ZEROS_U64) < n:
        z = np.zeros(max(n, 2 * len(_ZEROS_U64), 4096), dtype=np.uint64)
        z.setflags(write=False)
        _ZEROS_U64 = z
    return _ZEROS_U64[:n]


class SampleManager:
    def __init__(
        self,
        storage: ObjectBasedStorage,
        segment_duration_ms: int,
        buffer_rows: int = 0,
        flush_workers: int = 2,
        flush_queue_max: int = 4,
        flush_stall_deadline_s: float = 30.0,
        serving=None,
    ):
        self._storage = storage
        self._segment_duration = segment_duration_ms
        # Serving tier handle (horaedb_tpu/serving.ServingTier) — the
        # query methods below are the ONE planner choke point where the
        # result cache and rollup substitution are consulted (jaxlint
        # J013). None = tier absent (storage-level tests).
        self._serving = serving
        # Observability identity: the storage root is region-qualified
        # ("metrics/region-0/data") so flush logs/metrics name the region.
        self._table_id = getattr(storage, "_root", None) or "data"
        # pre-register the flush families' children so /metrics exposes
        # them (zero state) before the first write-out
        for fam in (FLUSH_SECONDS, FLUSH_ROWS, FLUSH_FAILURES, LATE_SAMPLES):
            fam.labels(self._table_id)
        # Out-of-order/backfill low watermark: the max sample timestamp
        # this manager has ever buffered. A sample in a segment OLDER than
        # the watermark's is LATE: counted, and on the column-memtable
        # path ROUTED into per-time-partition buffers (self._buf, the
        # persist()-path per-segment dict that rides the same seal/replay
        # machinery) so the hot columnar drain keeps its O(n)
        # ts-monotone fast path and one backfill trickle cannot force a
        # full lexsort of the whole memtable.
        self._high_wm: int | None = None
        # Opt-in ingest buffering (the RFC's own data-table design batches
        # many samples per stored row, docs/rfcs/20240827-metric-engine.md
        # :218-232): rows accumulate per segment and flush as ONE storage
        # write when the total reaches buffer_rows. 0 = unbuffered — every
        # persist() is immediately durable, matching the reference's
        # write==SST contract (storage.rs:307-333). Buffered rows are NOT
        # durable until flush; queries flush first so reads stay consistent.
        self._buffer_rows = buffer_rows
        self._buf: dict[int, list[tuple[np.ndarray, ...]]] = {}
        # Dense-id column memtable: (metric_id, tsid) -> small dense int,
        # plus PREALLOCATED (dense-per-sample, ts, value) column arrays
        # appended in place (zero-copy drain: sealing hands over array
        # views; there is no flush-time concatenate and no per-row emit).
        # Flush counting-sorts by the pk rank of each dense id — O(n + k)
        # — and emits batches already in pk order so the storage write's
        # sortedness fast path skips its sort.
        self._dense: dict[tuple[int, int], int] = {}
        self._dense_keys: list[tuple[int, int]] = []
        self._cols: tuple[np.ndarray, np.ndarray, np.ndarray] | None = None
        self._fill = 0
        # recycled column backings (double-buffer arena: a successful
        # write-out returns its arrays here instead of the allocator)
        self._spare_cols: list[tuple[np.ndarray, ...]] = []
        self._buffered = 0
        # monotonic append counter — feeds the flush overlap-ratio metric
        self._appended_rows = 0
        # Native C++ accumulator (ingest/native.py NativeAccum): samples go
        # straight from the parser arena into C++ lanes, flushed pk-sorted.
        # None when the native library is unavailable (Python chunk buffer
        # serves instead).
        self._accum = None
        if buffer_rows > 0:
            try:
                from horaedb_tpu.ingest.native import NativeAccum

                self._accum = NativeAccum()
            except Exception:  # noqa: BLE001 — fall back to Python buffering
                self._accum = None
        # The overlapped ingest->flush pipeline (engine/flush_executor.py):
        # threshold flushes SEAL the active memtable (atomic swap on the
        # event loop — appends land in fresh buffers) and hand it to a
        # bounded background worker pool, so the append path never blocks
        # on drain/encode/upload. A full queue blocks appends on a
        # condition variable with a deadline (backpressure, never a drop);
        # flush() remains the strong barrier queries use.
        self._executor: "FlushExecutor | None" = None
        if buffer_rows > 0:
            self._executor = FlushExecutor(
                self._writeout_once,
                self._table_id,
                workers=flush_workers,
                queue_max=flush_queue_max,
                stall_deadline_s=flush_stall_deadline_s,
            )
        # bounded concurrent object-store PUTs across the flush pipeline
        # (lazy: binds the running loop)
        self._upload_sem: "asyncio.Semaphore | None" = None
        # shared bound for concurrent segment-pushdown scans (lazy: binds
        # the running loop)
        self._scan_sem: "asyncio.Semaphore | None" = None

    @property
    def buffering(self) -> bool:
        return self._buffer_rows > 0

    @property
    def native_accum_active(self) -> bool:
        return self._accum is not None

    def buffer_native_add(self, parser) -> int:
        """Append the parser's current parse into the C++ accumulator
        (engine.write_payload holds the parser borrowed). Returns total
        buffered rows.

        Late-sample accounting rides here too (one ts-lane copy + min/max
        per payload, ~1 ns/sample): the accumulator itself pk-sorts at
        drain and the flush splits by segment, so out-of-order rows are
        CORRECT on this path by construction — the watermark check only
        feeds `horaedb_late_samples_total` and keeps the watermark shared
        with the Python memtable paths."""
        before = self._accum.rows
        total = self._accum.add(parser)
        added = total - before
        # feed the overlap-ratio metric on the native hot path too
        self._appended_rows += added
        if added:
            ts = parser.sample_ts_view()
            if len(ts):
                late = self._late_mask(ts)
                if late is not None:
                    LATE_SAMPLES.labels(self._table_id).inc(
                        int(np.count_nonzero(late))
                    )
        return total

    def _late_mask(self, ts: np.ndarray) -> "np.ndarray | None":
        """Mask of samples whose segment is OLDER than the active segment
        of the PRE-batch high watermark, then advance the watermark — None
        when none are (the common in-order case pays one vectorized
        max/min + two compares). Lateness is judged against the watermark
        as it stood BEFORE this batch: an in-order batch that itself
        straddles a segment rollover must not count its pre-boundary
        samples as late (nothing arrived out of order)."""
        prev = self._high_wm
        mx = int(ts.max())
        if prev is None or mx > prev:
            self._high_wm = mx
        if prev is None:
            return None  # first traffic IS the stream, wherever it starts
        low = prev - prev % self._segment_duration
        if int(ts.min()) >= low:
            return None
        return ts < low

    def should_flush(self, rows: int) -> bool:
        return rows >= self._buffer_rows

    @property
    def buffered_rows(self) -> int:
        """Total rows awaiting durability (native accumulator + active
        Python memtable + sealed memtables queued/parked/in-flight on the
        flush executor)."""
        accum = self._accum.rows if self._accum is not None else 0
        pending = self._executor.pending_rows if self._executor else 0
        return accum + self._buffered + pending

    # Bound on concurrent object-store PUTs from this manager's flush
    # pipeline: several workers x several shards would otherwise fan out
    # encode+upload without limit on a small host.
    MAX_INFLIGHT_UPLOADS = 4

    @property
    def flush_in_flight(self) -> bool:
        return self._executor is not None and self._executor.busy

    @property
    def flush_executor(self) -> "FlushExecutor | None":
        return self._executor

    async def drain(self) -> None:
        """Await the flush queue empty, then flush the remainder
        (shutdown + the periodic flush loop). Loops: a concurrent writer
        may append while we await — exit only once no row is buffered
        anywhere, so nothing is abandoned at loop teardown."""
        if self._executor is None:
            return
        while True:
            await self.flush()
            if not self.buffered_rows:
                return

    @property
    def _has_pending_rows(self) -> bool:
        return bool(
            self._buffered or (self._accum is not None and self._accum.rows)
        )

    async def persist(
        self,
        metric_ids: np.ndarray,  # u64 per sample
        tsids: np.ndarray,       # u64 per sample
        ts: np.ndarray,          # i64 ms per sample
        values: np.ndarray,      # f64 per sample
    ) -> None:
        """One storage write per touched segment, rows sorted on device by
        the write path (or buffered, see __init__). Already per-segment —
        late samples land in their own partition by construction; the
        watermark check only counts them."""
        if len(ts) == 0:
            return
        late = self._late_mask(ts)
        if late is not None:
            LATE_SAMPLES.labels(self._table_id).inc(
                int(np.count_nonzero(late))
            )
        seg = ts - (ts % self._segment_duration)
        uniq = np.unique(seg)
        for seg_start in uniq:
            m = seg == seg_start if len(uniq) > 1 else slice(None)
            if self._buffer_rows > 0:
                chunk = (metric_ids[m], tsids[m], ts[m], values[m])
                self._buf.setdefault(int(seg_start), []).append(chunk)
                self._buffered += len(chunk[2])
                self._appended_rows += len(chunk[2])
            else:
                await self._write_segment(
                    metric_ids[m], tsids[m], ts[m], values[m]
                )
        if self._buffer_rows > 0 and self._buffered >= self._buffer_rows:
            await self.seal_and_submit()

    def _cols_for(self, n: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Active column arrays with room for `n` more rows — pulled from
        the recycled spare pool when a completed flush returned one
        (the double-buffer arena), grown geometrically otherwise."""
        cols = self._cols
        if cols is None:
            # eager capacity is CAPPED: an absurd buffer_rows (bench
            # sentinels, misconfiguration) must not preallocate
            # buffer_rows-sized arrays up front — growth is geometric
            cap = max(min(self._buffer_rows, 4 << 20), n, 1024)
            if self._spare_cols and len(self._spare_cols[-1][0]) >= cap:
                # double-buffer steady state: the previous generation's
                # backing re-issues without an allocation
                cols = self._spare_cols.pop()
                memtrace.track_bytes(
                    sum(int(c.nbytes) for c in cols), "append", "reuse"
                )
            else:
                cols = (
                    colblock.aligned_empty(cap, np.int64),   # dense series id
                    colblock.aligned_empty(cap, np.int64),   # ts
                    colblock.aligned_empty(cap, np.float64),  # value
                )
                for c in cols:
                    memtrace.track(c, "append", "alloc")
            self._cols = cols
        elif self._fill + n > len(cols[0]):
            cap = max(2 * len(cols[0]), self._fill + n)
            grown = tuple(colblock.aligned_empty(cap, c.dtype) for c in cols)
            for g, c in zip(grown, cols):
                memtrace.track(g, "append", "alloc")
                g[: self._fill] = c[: self._fill]
            self._cols = cols = grown
        return cols

    async def buffer_request(self, metric_arr, tsid_arr, req) -> None:
        """Hash-lane buffered ingest: one dense-id dict probe per series,
        then whole-request column appends IN PLACE into the preallocated
        active memtable (no per-request list nodes, no flush-time
        concatenate — the zero-copy drain).

        Out-of-order/backfill samples (segments older than the watermark's
        active segment) are ROUTED OUT into per-time-partition late
        buffers (`self._buf`, the persist()-path per-segment dict, which
        rides the same seal/replay machinery and flushes one SST per
        partition): the hot columnar memtable keeps its ts-monotone O(n)
        drain fast path, and a backfill trickle cannot force a full
        lexsort of everything buffered with it."""
        ts = req.sample_ts
        series_idx = req.sample_series
        vals = req.sample_value
        late = self._late_mask(ts) if len(ts) else None
        if late is not None:
            n_late = int(np.count_nonzero(late))
            LATE_SAMPLES.labels(self._table_id).inc(n_late)
            sel = np.flatnonzero(late)
            l_sidx = series_idx[sel]
            l_ts = ts[sel]
            chunk = (
                np.asarray(metric_arr, dtype=np.uint64)[l_sidx],
                np.asarray(tsid_arr, dtype=np.uint64)[l_sidx],
                l_ts,
                vals[sel],
            )
            seg = l_ts - (l_ts % self._segment_duration)
            uniq = np.unique(seg)
            for seg_start in uniq:
                m = seg == seg_start if len(uniq) > 1 else slice(None)
                self._buf.setdefault(int(seg_start), []).append(
                    tuple(a[m] for a in chunk)
                )
            self._buffered += n_late
            self._appended_rows += n_late
            keep = np.flatnonzero(~late)
            series_idx = series_idx[keep]
            ts = ts[keep]
            vals = vals[keep]
        n = len(ts)
        if n:
            dense = self._dense
            keys = self._dense_keys
            mids = metric_arr.tolist()
            tids = tsid_arr.tolist()
            per_series = np.empty(len(mids), dtype=np.int64)
            for s in range(len(mids)):
                k = (mids[s], tids[s])
                d = dense.get(k)
                if d is None:
                    d = len(keys)
                    dense[k] = d
                    keys.append(k)
                per_series[s] = d
            dcol, tcol, vcol = self._cols_for(n)
            f = self._fill
            dcol[f:f + n] = per_series[series_idx]
            tcol[f:f + n] = ts
            vcol[f:f + n] = vals
            self._fill = f + n
            self._buffered += n
            self._appended_rows += n
        if self._buffered >= self._buffer_rows:
            await self.seal_and_submit()

    def seal(self) -> "SealedMemtable | None":
        """Atomically detach the active memtable into an immutable
        SealedMemtable (the double-buffer swap): no awaits between the
        buffer detach and the accumulator take, so appends racing this
        seal land entirely in the fresh active buffers. Returns None when
        nothing is buffered — two concurrent flush() calls cannot
        double-seal the same rows.

        The memtable's dedup sequence is pinned HERE, so last-value dedup
        follows buffering order even if a later memtable's encode lands
        its SSTs (with higher file ids) first."""
        from horaedb_tpu.storage.sst import allocate_id

        has_accum = self._accum is not None and self._accum.rows
        if not (self._buffered or has_accum):
            return None
        t0 = time.perf_counter()
        buf, self._buf = self._buf, {}
        keys, self._dense_keys = self._dense_keys, []
        self._dense = {}
        cols_view = None
        backing = None
        block = None
        if self._fill:
            backing = self._cols
            # the sealed rows travel as ONE frozen column block: read-only
            # zero-copy views of the arena's filled prefix (the drain reads
            # them in place — the old recycled-array copy is gone), while
            # the writable backing recycles into the spare pool after the
            # write-out lands
            block = colblock.ColBlock.wrap({
                "__series__": backing[0][: self._fill],
                "ts": backing[1][: self._fill],
                "value": backing[2][: self._fill],
            }).freeze()
            memtrace.track_bytes(block.nbytes, "seal", "view")
            cols_view = tuple(
                block.lane(k) for k in ("__series__", "ts", "value")
            )
            self._cols = None
            self._fill = 0
        rows = self._buffered
        self._buffered = 0
        lanes = None
        if has_accum:
            # synchronous C++ drain: pk-sorted lanes copied out, arena
            # cleared — part of the same atomic swap
            lanes = self._accum.take_sorted()
            rows += len(lanes[2])
        seq = allocate_id()
        FLUSH_STAGE_SECONDS.labels(self._table_id, "drain").observe(
            time.perf_counter() - t0
        )
        return SealedMemtable(
            seq=seq, rows=rows, buf=buf, cols=cols_view, keys=keys,
            cols_backing=backing, lanes=lanes, block=block,
        )

    async def seal_and_submit(self) -> None:
        """Threshold flush trigger: swap in a fresh active memtable and
        hand the sealed one to the background executor. The append hot
        path never waits on drain/encode/upload — it blocks only when the
        bounded flush queue is full (backpressure with a stall deadline,
        horaedb_ingest_stall_seconds)."""
        ex = self._executor
        if ex is None:
            return
        # a new trigger is also the retry clock for parked failures
        ex.kick_parked()
        sealed = self.seal()
        if sealed is not None:
            try:
                await ex.submit(sealed)
            except BaseException:
                # stall deadline (or cancellation) while the queue was
                # full: the rows were already detached from the active
                # memtable — PARK them (never drop acked rows; the next
                # trigger or barrier retries) before surfacing the error
                ex.park(sealed)
                raise

    async def flush(self) -> None:
        """Strong flush barrier: every row buffered (acked) at entry is
        durable — or an error raised — by return.

        Seals the active memtable (urgent submit bypasses the queue
        bound), waits out the memtables queued/in-flight AT ENTRY (a
        snapshot — sustained ingest submitting more work cannot starve
        the barrier), then handles PARKED failures by error class
        (common/error.py):

        - a RETRYABLE failure keeps PR 5's semantics: background
          triggers re-queue it, and the barrier retries it inline
          exactly once — a second failure raises here (the memtable
          re-parks first, so no acked row is ever dropped).
        - a memtable parked on a PERSISTENT or FATAL error is skipped by
          background triggers entirely (kick_parked) — re-running a
          deterministic failure every trigger burns store budget without
          ever surfacing it. Only the barrier replays it (one inline
          attempt per barrier): still broken -> the error surfaces HERE
          on that first replay; cause fixed -> it drains. Rows stay
          parked throughout."""
        ex = self._executor
        if ex is None:
            return
        from horaedb_tpu.common import deadline as deadline_ctx

        ex.kick_parked()
        sealed = self.seal()
        if sealed is not None:
            await ex.submit(sealed, urgent=True)
        pending = ex.snapshot_pending()
        while True:
            await ex.wait_settled(pending)
            parked = ex.take_parked()
            if parked is None:
                return
            try:
                # the inline replay is durability work for ACKED rows and
                # must not run under the CALLING QUERY's deadline (the
                # barrier runs in the query task when a scan flushes
                # first): a budget-expired DeadlineExceeded here would
                # park the memtable as "persistent" and background
                # triggers would then skip it forever — acked rows stuck
                # memory-only on a healthy store
                with deadline_ctx.deadline_scope(None):
                    await self._writeout_once(parked)
            except BaseException as e:
                parked.last_error = e
                ex.park(parked)
                raise

    async def _writeout_once(self, sealed: "SealedMemtable") -> None:
        """One write-out attempt of a sealed memtable, timed and traced
        (logic in _writeout_sealed; this wrapper owns the flush
        observability so every caller — executor worker, flush-barrier
        inline retry — reports). On failure the un-landed remainder has
        already been converted into pinned-seq replay groups on `sealed`,
        so parking it loses nothing."""
        appended0 = self._appended_rows
        rows = sealed.rows
        with tracing.span(
            "ingest_flush", table=self._table_id, rows=rows, seq=sealed.seq,
        ):
            try:
                with FLUSH_SECONDS.labels(self._table_id).time():
                    await self._writeout_sealed(sealed)
            except BaseException:
                FLUSH_FAILURES.labels(self._table_id).inc()
                FLUSH_FAILURES_TOTAL.labels(self._table_id).inc()
                raise
        FLUSH_ROWS.labels(self._table_id).inc(rows)
        if rows:
            # rows appended to the ACTIVE memtable while this write-out ran,
            # per flushed row: the measured producer/consumer overlap
            FLUSH_OVERLAP_RATIO.labels(self._table_id).observe(
                (self._appended_rows - appended0) / rows
            )
        if sealed.cols_backing is not None and len(self._spare_cols) < 2:
            # recycle the column backing into the arena (success only: a
            # failed attempt's replay groups may still view into it)
            self._spare_cols.append(sealed.cols_backing)
            sealed.cols_backing = None

    async def _writeout_sealed(self, sealed: "SealedMemtable") -> None:
        """Write out one sealed memtable (one storage write per segment
        shard).

        Failure contract: on ANY write failure every un-landed row
        converts into pinned-seq replay groups on `sealed` (keeping the
        memtable's ORIGINAL sequence) before the error propagates, so
        already-acked samples survive for a retry and a delayed replay can
        never beat writes acked after them. Partial double-writes are
        safe: the storage merge dedups by pk + seq."""
        snap_seq = sealed.seq
        buf, sealed.buf = sealed.buf, {}
        cols, sealed.cols = sealed.cols, None
        keys, sealed.keys = sealed.keys, []
        lanes, sealed.lanes = sealed.lanes, None
        groups, sealed.groups = sealed.groups, []

        def _regroup_fresh() -> None:
            self._group_snapshot(sealed, buf, cols, keys, snap_seq)
            if lanes is not None:
                self._group_lanes(sealed, *lanes, seq=snap_seq)

        # 1) replay groups from failed attempts under their ORIGINAL seqs,
        # coalesced per (seq, segment) so a failed memtable of many small
        # requests replays as one SST per segment, not one per request
        # (already-landed shards of those attempts dedup by pk+seq)
        merged: "dict[tuple[int, int], list]" = {}
        for seq0, seg0, lanes0, presorted0 in groups:
            merged.setdefault((seq0, seg0), []).append((lanes0, presorted0))
        replay = list(merged.items())
        for i, ((seq0, _seg0), group) in enumerate(replay):
            if len(group) == 1:
                lanes0, presorted0 = group[0]
            else:
                lanes0 = tuple(
                    memtrace.tracked_concat(
                        [g[0][j] for g in group], "seal"
                    )
                    for j in range(4)
                )
                presorted0 = False  # concatenation breaks per-group order
            try:
                await self._write_segment(
                    *lanes0, presorted=presorted0, seq=seq0, fast=True
                )
            except BaseException:
                for (sq, sg), grp in replay[i:]:
                    for lanes1, presorted1 in grp:
                        sealed.groups.append((sq, sg, lanes1, presorted1))
                _regroup_fresh()
                raise
        # 2) this memtable's fresh rows
        try:
            for _seg_start, cols_list in sorted(buf.items()):
                seg_cols = [
                    memtrace.tracked_concat(
                        [c[i] for c in cols_list], "seal"
                    )
                    for i in range(4)
                ]
                await self._write_segment(*seg_cols, seq=snap_seq, fast=True)
            if cols is not None:
                await self._flush_cols(cols, keys, seq=snap_seq)
        except BaseException:
            _regroup_fresh()
            raise
        if lanes is not None:
            await self._flush_accum_lanes(sealed, *lanes, seq=snap_seq)

    # A flush larger than this splits into contiguous pk-range shards
    # written as independent SSTs concurrently: parquet encode (GIL-free)
    # and the per-object fsync are the flush bottleneck, and both overlap
    # across shards. More SSTs per segment is native LSM currency —
    # compaction folds them. MAX_FLUSH_SHARDS bounds thread/file fan-out —
    # by the ACTUAL cpu budget: on a 1-core box (CI, small containers)
    # shard concurrency cannot overlap anything and each extra shard just
    # pays its own fsync + manifest delta + encode setup.
    FLUSH_SHARD_ROWS = 128 * 1024
    MAX_FLUSH_SHARDS = min(8, 2 * (
        len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity")
        else (os.cpu_count() or 1)
    ))

    async def _flush_accum_lanes(
        self, sealed: "SealedMemtable", mid, tsid, ts, vals, seq=None
    ) -> None:
        """Write out pk-sorted lanes taken from the C++ accumulator (the
        take CLEARED it, so rows buffered during the awaited writes are
        never lost), split by segment (and by shard within large segments),
        the shards' parquet encodes running concurrently across the SST
        pool with the in-flight uploads bounded (_write_segment). On
        failure the lanes convert into pinned-seq replay groups on
        `sealed` so acked samples survive for a retry."""
        if not len(ts):
            return
        seg = ts - (ts % self._segment_duration)
        uniq = np.unique(seg)
        # Per-segment lanes (the lanes sort by (mid, tsid, ts), so segment
        # rows are scattered — a mask gather per segment; the overwhelmingly
        # common single-segment scrape keeps the zero-copy fast path).
        # Each per-segment lane set stays pk-sorted (mask gather preserves
        # order), so contiguous shard slices of it are pk-sorted too.
        per_seg: list[tuple[int, tuple]] = []
        if len(uniq) == 1:
            per_seg.append((int(uniq[0]), (mid, tsid, ts, vals)))
        else:
            for seg_start in uniq.tolist():
                m = seg == seg_start
                per_seg.append((int(seg_start), (mid[m], tsid[m], ts[m], vals[m])))
        work: list[tuple] = []
        for _seg_start, lanes in per_seg:
            smid, stsid, sts = lanes[0], lanes[1], lanes[2]
            n = len(sts)
            shards = min(max(1, -(-n // self.FLUSH_SHARD_ROWS)),
                         self.MAX_FLUSH_SHARDS)
            step = -(-n // shards)
            lo = 0
            while lo < n:
                hi = min(lo + step, n)
                # never split a run of identical (mid, tsid, ts) rows across
                # shards: all shards share one seq, and same-pk-same-seq
                # duplicates must stay inside one SST so the in-file row
                # order resolves them deterministically
                while hi < n and (
                    smid[hi] == smid[hi - 1]
                    and stsid[hi] == stsid[hi - 1]
                    and sts[hi] == sts[hi - 1]
                ):
                    hi += 1
                sl = slice(lo, hi)
                work.append(tuple(a[sl] for a in lanes))
                lo = hi
        try:
            if len(work) == 1:
                await self._write_segment(*work[0], presorted=True, seq=seq, fast=True)
            else:
                async with TaskGroup() as tg:
                    for lanes in work:
                        tg.create_task(
                            self._write_segment(*lanes, presorted=True, seq=seq, fast=True)
                        )
        except BaseException:
            self._group_lanes(sealed, mid, tsid, ts, vals, per_seg, seq=seq)
            raise

    def _group_lanes(
        self, sealed: "SealedMemtable", mid, tsid, ts, vals,
        per_seg=None, seq=None,
    ) -> None:
        """Convert failed accumulator lanes PER SEGMENT into pinned-seq
        replay groups on `sealed` (a batch must not cross a segment). The
        lanes keep their memtable's sequence so a later replay cannot beat
        writes acked after them. Shards that did land before the failure
        are harmless to re-write: storage dedups by pk + seq."""
        if not len(ts):
            return
        if per_seg is None:
            seg = ts - (ts % self._segment_duration)
            uniq = np.unique(seg)
            if len(uniq) == 1:
                per_seg = [(int(uniq[0]), (mid, tsid, ts, vals))]
            else:
                per_seg = [
                    (int(s), tuple(a[seg == s] for a in (mid, tsid, ts, vals)))
                    for s in uniq.tolist()
                ]
        for seg_start, lanes in per_seg:
            # accum lanes are pk-sorted; segment mask-gathers preserve that
            sealed.groups.append((seq, seg_start, lanes, True))

    def _group_snapshot(self, sealed, buf, cols, keys, seq: int) -> None:
        """Convert a failed memtable's fresh Python buffers into pinned-seq
        replay groups (per segment, original sequence preserved). Column
        views materialize into standalone per-segment lanes, so the parked
        groups never pin the active arena's backing arrays."""
        for seg_start, lst in buf.items():
            for lanes in lst:
                sealed.groups.append((seq, int(seg_start), lanes, False))
        if cols is not None:
            dense_ps, ts, vals = cols
            key_mid = np.fromiter((k[0] for k in keys), np.uint64, len(keys))
            key_tsid = np.fromiter((k[1] for k in keys), np.uint64, len(keys))
            mid = key_mid[dense_ps]
            tsid = key_tsid[dense_ps]
            seg = ts - (ts % self._segment_duration)
            for s in np.unique(seg).tolist():
                m = seg == s
                sealed.groups.append(
                    (seq, int(s), (mid[m], tsid[m], ts[m], vals[m]), False)
                )

    async def _flush_cols(self, cols, keys, seq=None) -> None:
        """Counting-sort the column memtable into pk order: rank the (few)
        unique series keys, gather rank per sample, one stable O(n + k)
        counting sort. The lanes arrive as views into the preallocated
        active arrays (zero-copy drain — no concatenate). Scrapes arrive
        in time order, so within a series the append order already sorts
        ts — verified in O(n); only genuinely out-of-order data pays a
        full lexsort."""
        t0 = time.perf_counter()
        dense_ps, ts, vals = cols
        k = len(keys)
        key_arr = np.empty((k, 2), dtype=np.uint64)
        for i, (m, t) in enumerate(keys):
            key_arr[i, 0] = m
            key_arr[i, 1] = t
        order = np.lexsort((key_arr[:, 1], key_arr[:, 0]))  # rank over k keys
        rank_of_dense = np.empty(k, dtype=np.int64)
        rank_of_dense[order] = np.arange(k)
        rank_ps = rank_of_dense[dense_ps].astype(np.int32)
        # stable radix argsort over small int ranks (numpy uses radix for
        # integer stable sorts — effectively linear, far cheaper than a
        # 3-key u64 lexsort)
        perm = np.argsort(rank_ps, kind="stable")
        counts = np.bincount(rank_ps, minlength=k)  # indexed by rank
        mid = key_arr[order, 0].repeat(counts)
        tsid = key_arr[order, 1].repeat(counts)
        ts = ts[perm]
        vals = vals[perm]
        # ts must be nondecreasing within each series group; a decrease is
        # only legal exactly at a group boundary
        dips = np.flatnonzero(np.diff(ts) < 0)
        boundaries = np.cumsum(counts)[:-1] - 1
        if np.setdiff1d(dips, boundaries).size:
            perm2 = np.lexsort((ts, tsid, mid))
            mid, tsid, ts, vals = mid[perm2], tsid[perm2], ts[perm2], vals[perm2]
        seg = ts - (ts % self._segment_duration)
        uniq = np.unique(seg)
        # pk-rank sort is the drain's CPU cost (encode/upload time below)
        FLUSH_STAGE_SECONDS.labels(self._table_id, "drain").observe(
            time.perf_counter() - t0
        )
        for seg_start in uniq:
            m = seg == seg_start if len(uniq) > 1 else slice(None)
            await self._write_segment(mid[m], tsid[m], ts[m], vals[m], seq=seq, fast=True)

    async def _write_segment(
        self, metric_ids, tsids, ts, values,
        presorted: bool = False, seq: "int | None" = None,
        fast: bool = False,
    ) -> None:
        """`fast`: flush-path (L0) writes take the fast parquet profile —
        compaction re-encodes them with the tuned one. Direct (unbuffered)
        persists keep tuned encodings: with no buffer there may be no
        compaction churn either, so those SSTs can live long.

        Flush-path writes also ride the bounded upload semaphore: several
        executor workers x several shards would otherwise fan encode+PUT
        out without limit on a small host."""
        # one frozen column block feeds the writer: the arrow batch wraps
        # the lanes zero-copy (primitive types), so the parquet encoder
        # reads the sealed bytes in place — no per-lane staging copy
        block = colblock.ColBlock.wrap({
            "metric_id": memtrace.tracked_contiguous(
                np.asarray(metric_ids, dtype=np.uint64), "append"
            ),
            "tsid": memtrace.tracked_contiguous(
                np.asarray(tsids, dtype=np.uint64), "append"
            ),
            "field_id": _zeros_u64(len(ts)),
            "ts": memtrace.tracked_contiguous(ts, "append"),
            "value": memtrace.tracked_contiguous(values, "append"),
        }).freeze()
        batch = block.to_arrow_batch(DATA_SCHEMA, stage="flush_encode")
        lo = int(ts.min())
        hi = int(ts.max()) + 1
        req = WriteRequest(batch, TimeRange(lo, hi), presorted=presorted,
                           seq=seq, fast_encode=fast)
        if fast:
            if self._upload_sem is None:
                self._upload_sem = asyncio.Semaphore(self.MAX_INFLIGHT_UPLOADS)
            async with self._upload_sem:
                await self._storage.write(req)
        else:
            await self._storage.write(req)

    # -- queries ---------------------------------------------------------------
    def _predicate(self, metric_id: int, tsids: list[int] | None, rng: TimeRange):
        parts = [
            F.Compare("metric_id", "eq", metric_id),
            F.Compare("ts", "ge", rng.start),
            F.Compare("ts", "lt", rng.end),
        ]
        if tsids is not None:
            parts.append(F.InSet("tsid", tuple(tsids)))
        return F.And(*parts)

    # -- the serving-tier choke point (jaxlint J013) ---------------------------
    # query_raw/query_downsample are the ONE place the result cache and
    # rollup substitution are consulted: every read surface (native JSON
    # queries, PromQL instant/range, exemplars) funnels through them, so
    # one lookup discipline covers the whole read plane. HORAEDB_SERVING=off
    # (the honesty switch) bypasses every shortcut — forced-cold answers
    # are the oracle serving answers are asserted bit-exact against.

    def _serving_key(
        self, kind: bytes, metric_id: int, tsids, rng: TimeRange,
        bucket_ms, limit, filtered: bool,
    ) -> "bytes | None":
        """Digest of (normalized plan fingerprint, sealed-SST id set,
        visibility epoch) — the cache key IS the invalidation contract
        (serving/cache.py). None = uncacheable: no SSTs cover the range
        (nothing worth caching), or the retention floor cuts into it
        (the floor moves every millisecond, so the masked row set is
        time-dependent and no stored answer can stay exact)."""
        import hashlib

        floor = self._storage.retention_floor()
        if floor is not None and floor > rng.start:
            return None
        ssts = self._storage.manifest.find_ssts(rng)
        if not ssts:
            return None
        h = hashlib.blake2b(digest_size=16)
        h.update(self._table_id.encode())
        h.update(kind)
        h.update(np.uint64(metric_id).tobytes())
        h.update(np.int64(rng.start).tobytes())
        h.update(np.int64(rng.end).tobytes())
        h.update(np.int64(-1 if bucket_ms is None else bucket_ms).tobytes())
        h.update(np.int64(-1 if limit is None else limit).tobytes())
        h.update(b"f" if filtered else b"u")
        if tsids is None:
            h.update(b"\x00")
        else:
            h.update(b"\x01")
            h.update(np.asarray(sorted(tsids), dtype=np.uint64).tobytes())
        h.update(
            np.asarray(sorted(s.id for s in ssts), dtype=np.uint64).tobytes()
        )
        tombs = sorted(
            t.id for t in self._storage.manifest.all_tombstones()
            if t.time_range.overlaps(rng)
        )
        h.update(np.asarray(tombs, dtype=np.uint64).tobytes())
        return h.digest()

    @staticmethod
    def _replay_notes(notes: dict) -> None:
        """Re-note a cached entry's fill-time provenance into the CURRENT
        query's collector, so EXPLAIN on a hit still names what the
        cached plan covered (rollup substitutions, SSTs selected)."""
        for k, v in notes.items():
            scanstats.note(k, int(v))

    def _serving_for_query(self):
        """The tier when it may serve this query, else None (counting the
        bypass when the honesty switch forced it off)."""
        from horaedb_tpu.serving import CACHE_REQUESTS

        serving = self._serving
        if serving is None:
            return None
        if not serving.active():
            scanstats.note("serving_cache_bypass")
            CACHE_REQUESTS.labels("bypass").inc()
            return None
        return serving

    async def _serving_cached(self, serving, key: bytes, fill):
        """Result-cache read path: hit replays and returns; miss runs
        `fill` single-flight (followers ride the leader's computation and
        replay its stored provenance)."""
        from horaedb_tpu.common import deadline as deadline_ctx
        from horaedb_tpu.serving import CACHE_REQUESTS

        hit = serving.cache.serving_get(key)
        if hit is not None:
            value, notes = hit
            scanstats.note("serving_cache_hit")
            CACHE_REQUESTS.labels("hit").inc()
            self._replay_notes(notes)
            return value
        scanstats.note("serving_cache_miss")
        CACHE_REQUESTS.labels("miss").inc()
        value, notes, leader = await serving.cache.serving_single_flight(
            key, self._table_id, fill
        )
        if not leader:
            # the leader's scan fed ITS collector; this query waited
            deadline_ctx.check("serving_cache")
            self._replay_notes(notes)
        return value

    async def query_raw(
        self,
        metric_id: int,
        tsids: list[int] | None,
        rng: TimeRange,
        limit: int | None = None,
    ) -> pa.Table | None:
        """Materialized (merged, deduped) sample rows.

        `limit` pushes down into the scan: the per-segment async generator
        stops being driven once enough rows accumulated, so later segments
        are never read (the reference's scan-stream laziness,
        storage.rs:335-370)."""
        if self._buffer_rows:
            # always flush (not just when _buffered > 0): an in-flight flush
            # has already sealed the buffers but its SSTs may not be durable
            # yet — flush() quiesces the executor, keeping reads consistent
            # with acked writes (union of active + sealed + flushed)
            await self.flush()
        serving = self._serving_for_query()
        if serving is None:
            return await self._query_raw_cold(metric_id, tsids, rng, limit)
        key = self._serving_key(
            b"raw", metric_id, tsids, rng, None, limit, tsids is not None
        )
        if key is None:
            return await self._query_raw_cold(metric_id, tsids, rng, limit)

        async def fill():
            table = await self._query_raw_cold(metric_id, tsids, rng, limit)
            nbytes = 64 + (table.nbytes if table is not None else 0)
            return table, nbytes, {}

        return await self._serving_cached(serving, key, fill)

    async def _query_raw_cold(
        self,
        metric_id: int,
        tsids: list[int] | None,
        rng: TimeRange,
        limit: int | None = None,
    ) -> pa.Table | None:
        from contextlib import aclosing

        batches = []
        total = 0
        # aclosing: an early break must run the generator's finally NOW so
        # the prefetched next-segment read is cancelled deterministically
        # (asyncgen GC finalization would let it issue the wasted I/O first)
        async with aclosing(self._storage.scan(
            ScanRequest(range=rng, predicate=self._predicate(metric_id, tsids, rng))
        )) as gen:
            async for b in gen:
                if limit is not None and total + b.num_rows >= limit:
                    batches.append(b.slice(0, limit - total))
                    total = limit
                    break
                batches.append(b)
                total += b.num_rows
        return pa.Table.from_batches(batches) if batches else None

    async def query_downsample(
        self,
        metric_id: int,
        tsids: list[int],
        rng: TimeRange,
        bucket_ms: int,
        filtered: bool = True,
    ) -> tuple[list[int], dict[str, np.ndarray]] | None:
        """Per-(series, bucket) sum/count/min/max/mean grids via aggregate
        PUSHDOWN: each segment reduces on device inside the scan (raw rows
        never return to host); per-segment partial grids combine trivially
        because the data-table pk includes the timestamp, so duplicates
        cannot span segments. Returns (tsid order, grids).

        Precision: on-device accumulation is float32 ONLY on real
        accelerators (TPU-native lane width); CPU/XLA-fallback meshes and
        the single-device path accumulate in f64 (x64 enabled), matching
        the reference's f64 aggregation exactly. On TPU the per-cell
        relative error is ~2^-24 * samples_per_cell — counter-style values
        above 2^24 (~16.7M) or cells with millions of samples lose low
        bits vs an f64 oracle. The materializing fallback (high
        cardinality) accumulates in f64 on host.

        `filtered=False` means `tsids` is just the metric's full series set
        (no tag filter): the TSID membership predicate is skipped, and very
        high cardinalities fall back to the materializing path whose output
        is sized by the series actually present in range."""
        from horaedb_tpu.common.error import ensure

        if self._buffer_rows:
            await self.flush()  # see query_raw: waits out in-flight flushes
        n_buckets = -(-(rng.end - rng.start) // bucket_ms)
        ensure(
            n_buckets <= MAX_BUCKETS,
            f"downsample resolution too high: {n_buckets} buckets "
            f"(max {MAX_BUCKETS}); narrow the range or coarsen bucket_ms",
        )
        serving = self._serving_for_query()
        if serving is None:
            return await self._query_downsample_cold(
                metric_id, tsids, rng, bucket_ms, int(n_buckets), filtered,
                serving=None,
            )
        key = self._serving_key(
            b"ds", metric_id, tsids, rng, bucket_ms, None, filtered
        )
        if key is None:
            return await self._query_downsample_cold(
                metric_id, tsids, rng, bucket_ms, int(n_buckets), filtered,
                serving=serving,
            )

        async def fill():
            prov: dict = {}
            res = await self._query_downsample_cold(
                metric_id, tsids, rng, bucket_ms, int(n_buckets), filtered,
                serving=serving, prov=prov,
            )
            nbytes = 64
            if res is not None:
                r_tsids, grids = res
                nbytes += len(r_tsids) * 8 + sum(
                    np.asarray(g).nbytes for g in grids.values()
                )
            return res, nbytes, prov

        return await self._serving_cached(serving, key, fill)

    async def _query_downsample_cold(
        self,
        metric_id: int,
        tsids: list[int],
        rng: TimeRange,
        bucket_ms: int,
        num_buckets: int,
        filtered: bool,
        serving=None,
        prov: "dict | None" = None,
    ) -> tuple[list[int], dict[str, np.ndarray]] | None:
        """One uncached downsample computation. With an active serving
        tier, segments whose rollup record passes the freshness contract
        (storage/rollup.py) fold bucket-count-scale pre-aggregated rows
        instead of scanning raw; everything else takes the device
        pushdown. `prov` collects the provenance a cached entry replays
        on later hits.

        Only cache MISSES reach here, so this is the query batcher's
        dispatch point (server/batching.py): a grid query with compatible
        concurrent company coalesces into ONE stacked kernel launch.
        Eligibility is decided HERE because only this layer knows the
        segment layout and the rollup plan:

        - grid/segment alignment (bucket_ms divides the segment duration
          AND rng.start is bucket-aligned) guarantees no bucket spans a
          segment boundary, so every cell accumulates rows of exactly one
          segment — the condition under which the batched single-stream
          reduction is bit-exact vs the solo per-segment partial fold
          (unaligned grids could differ in float association on
          cancelling data, so they run solo);
        - a non-empty rollup plan means the solo pushdown folds
          bucket-count-scale artifacts — far cheaper than the batched
          lane's raw scan — so rollup-covered queries run solo too.

        Everything else (lone queries, short deadlines, oversized
        shapes, HORAEDB_BATCH=off) continues down the solo pushdown
        unchanged."""
        from horaedb_tpu.server import batching

        if prov is None:
            prov = {}
        # retention-pruned SST selection (storage.select_ssts notes
        # ssts_retention_pruned provenance for EXPLAIN)
        ssts = self._storage.select_ssts(rng)
        if not ssts or not tsids:
            return None
        if len(tsids) > MAX_PUSHDOWN_SERIES:
            # the materialized fallback scans through ObjectBasedStorage.scan,
            # which notes its own ssts_selected — noting here too would
            # double-count the provenance
            return await self._query_downsample_materialized(
                metric_id, tsids if filtered else None, rng, bucket_ms
            )
        series_ids = np.asarray(sorted(tsids), dtype=np.uint64)
        segments = self._storage.group_by_segment(ssts)
        # Rollup substitution plan (storage/rollup.py): per segment, the
        # coarsest aligned rollup whose freshness contract passes — the
        # segment then costs a bucket-count-scale artifact read instead
        # of a raw scan. Planning is pure manifest state; a failure
        # degrades to all-raw, never an error. Computed BEFORE the
        # batching decision: rollup-covered queries must not trade the
        # artifact fold for the batched lane's raw scan.
        plan: dict = {}
        if serving is not None and serving.rollups_active:
            from horaedb_tpu.storage import rollup as rollup_mod

            try:
                plan = rollup_mod.plan_rollups(
                    self._storage, segments, rng, rng.start, bucket_ms
                )
            except Exception:  # noqa: BLE001 — raw is always available
                logger.warning("rollup planning failed; scanning raw",
                               exc_info=True)
                plan = {}
        batcher = batching.GLOBAL_BATCHER
        aligned = (
            self._segment_duration % bucket_ms == 0
            and rng.start % bucket_ms == 0
        )
        if not aligned or plan:
            batcher.note_ineligible()
            return await self._query_downsample_pushdown(
                metric_id, series_ids, ssts, segments, plan, rng,
                bucket_ms, num_buckets, filtered, prov,
            )
        tok = batcher.begin()
        try:
            res = await batcher.coalesce(
                bucket_ms=bucket_ms, num_buckets=num_buckets,
                series_ids=series_ids, t0=rng.start, filtered=filtered,
                # same-(table, metric, range) members share ONE union
                # scan — the N-panels-one-dashboard case pays one read
                share_key=(self._table_id, metric_id, rng.start, rng.end),
                scan=lambda ids: self._batch_scan_rows(metric_id, rng, ids),
            )
            if res is not batching.SOLO:
                grids, notes = res
                for k, n in (notes or {}).items():
                    if k == "batched_with":
                        scanstats.note_max(k, n)
                    else:
                        scanstats.note(k, n)
                    # cache replay must not claim a stacked launch on a
                    # later HIT — batch provenance stays out of `prov`
                    if not k.startswith(("batched_", "batch_")):
                        prov[k] = prov.get(k, 0) + n
                if grids is None:
                    return None
                return [int(x) for x in series_ids], grids
            return await self._query_downsample_pushdown(
                metric_id, series_ids, ssts, segments, plan, rng,
                bucket_ms, num_buckets, filtered, prov,
            )
        finally:
            batcher.end(tok)

    async def _batch_scan_rows(
        self,
        metric_id: int,
        rng: TimeRange,
        tsids: "list[int] | None",
    ):
        """One batch scan's row materialization (runs in the group's
        detached context): the same merged/deduped/visibility-masked rows
        a solo scan sees, as flat (ts i64, tsid u64, values f64) lanes —
        or None when nothing is in range. `tsids` may be the UNION of
        several members' series sets (batching.py de-multiplexes rows
        per member afterwards); None scans the whole metric."""
        table = await self._query_raw_cold(metric_id, tsids, rng)
        if table is None or table.num_rows == 0:
            return None
        return (
            table.column("ts").to_numpy().astype(np.int64, copy=False),
            table.column("tsid").to_numpy(),
            table.column("value").to_numpy().astype(np.float64, copy=False),
        )

    async def _query_downsample_pushdown(
        self,
        metric_id: int,
        series_ids: np.ndarray,
        ssts: list,
        segments: list,
        plan: dict,
        rng: TimeRange,
        bucket_ms: int,
        num_buckets: int,
        filtered: bool,
        prov: "dict | None" = None,
    ) -> tuple[list[int], dict[str, np.ndarray]] | None:
        """The solo per-segment device pushdown (the batcher's oracle).
        `segments`/`plan` come precomputed from the cold entry — the
        rollup plan now also feeds the batching eligibility decision."""
        if prov is None:
            prov = {}
        # EXPLAIN provenance: how many SSTs the time range selected (bloom
        # pruning and actual reads are noted per SST in storage/read.py)
        scanstats.note("ssts_selected", len(ssts))
        prov["ssts_selected"] = len(ssts)
        pred = self._predicate(
            metric_id, list(series_ids) if filtered else None, rng
        )
        # Per-segment pushdown passes run CONCURRENTLY: reads of one
        # segment overlap another's device kernel — the engine-side analog
        # of the reference's UnionExec driving per-segment plans. The
        # semaphore is SHARED across queries (one per manager) so a
        # dashboard burst cannot multiply the bound. Partials fold in
        # SEGMENT order, not completion order: float addition is not
        # associative, and the distributed scatter-gather path
        # (cluster/partial.py) promises the merged result is bit-exact vs
        # a single-node run — that only holds if the leaf fold itself is
        # deterministic. A small reorder buffer (`pending`) holds parts
        # that finish ahead of a slower earlier segment; in the common
        # case segments complete roughly in order and peak memory stays
        # the in-flight parts, not one grid per segment. TaskGroup
        # cancels + awaits siblings on first error — no detached scans
        # survive a failed query.
        if self._scan_sem is None:
            self._scan_sem = asyncio.Semaphore(SEGMENT_SCAN_CONCURRENCY)
        acc: dict[str, np.ndarray] | None = None
        pending: dict[int, dict[str, np.ndarray] | None] = {}
        next_fold = 0

        def _fold_one(part) -> None:
            nonlocal acc
            if part is None:
                return
            if acc is None:
                acc = part
            else:
                acc["sum"] = acc["sum"] + part["sum"]
                acc["count"] = acc["count"] + part["count"]
                acc["min"] = np.minimum(acc["min"], part["min"])
                acc["max"] = np.maximum(acc["max"], part["max"])

        def fold(idx: int, part) -> None:
            nonlocal next_fold
            pending[idx] = part
            while next_fold in pending:
                _fold_one(pending.pop(next_fold))
                next_fold += 1

        async def one_rollup(rec, seg, idx):
            """Fold one segment's rollup artifact instead of scanning it;
            any artifact-read failure degrades the segment to raw."""
            from horaedb_tpu.common import deadline as deadline_ctx
            from horaedb_tpu.common.error import DeadlineExceeded
            from horaedb_tpu.serving import (
                ROLLUP_ROWS,
                ROLLUP_SUBSTITUTIONS,
                resolution_label,
            )
            from horaedb_tpu.storage import rollup as rollup_mod

            lanes = None
            async with self._scan_sem:
                deadline_ctx.check("segment_scan")
                try:
                    lanes = await rollup_mod.read_rollup(self._storage, rec)
                except (DeadlineExceeded, asyncio.CancelledError):
                    raise
                except Exception:  # noqa: BLE001 — degrade to the raw scan
                    logger.warning(
                        "rollup artifact %d unreadable; raw-scanning "
                        "segment %d", rec.sst_id, rec.segment_start,
                        exc_info=True,
                    )
            if lanes is None:
                await one_segment(seg, idx)
                return
            part, rows = self._fold_rollup(
                lanes, metric_id, series_ids, rng, bucket_ms, num_buckets,
            )
            label = resolution_label(rec.resolution_ms)
            scanstats.note("rollup_segments")
            scanstats.note("rollup_rows_read", rows)
            scanstats.note(f"rollup_res_{label}")
            prov["rollup_segments"] = prov.get("rollup_segments", 0) + 1
            prov["rollup_rows_read"] = prov.get("rollup_rows_read", 0) + rows
            prov[f"rollup_res_{label}"] = prov.get(f"rollup_res_{label}", 0) + 1
            ROLLUP_SUBSTITUTIONS.labels(label).inc()
            ROLLUP_ROWS.inc(rows)
            fold(idx, part)

        async def one_segment(seg, idx):
            async with self._scan_sem:
                # cooperative deadline: a segment pass acquired AFTER the
                # budget died must not read + reduce (the TaskGroup
                # cancels siblings on the first raise)
                from horaedb_tpu.common import deadline as deadline_ctx

                deadline_ctx.check("segment_scan")
                # retry wrapper: a compaction may delete this snapshot's
                # files mid-query; the refresh re-reads the live SSTs
                part = await self._storage.scan_segment_retrying(
                    seg, rng,
                    lambda fresh: self._storage.parquet_reader.scan_segment_downsample(
                        fresh,
                        predicate=pred,
                        ts_column="ts",
                        value_column="value",
                        series_column="tsid",
                        series_ids=series_ids,
                        t0=rng.start,
                        bucket_ms=bucket_ms,
                        num_buckets=num_buckets,
                        # data-table pk is (metric_id, tsid, field_id, ts):
                        # metric_id is eq-pinned in `pred`, field_id is
                        # constant 0 — the packed (sid, ts) dedup is exact
                        packed_ok=True,
                    ),
                )
            # the fold is synchronous (no awaits): safe on the event loop.
            # A vanished segment (TTL) reports None so the reorder buffer
            # still advances past its index.
            fold(idx, part)
            if part is None:
                return
            scanstats.note("raw_segments")
            prov["raw_segments"] = prov.get("raw_segments", 0) + 1

        from horaedb_tpu.storage.types import Timestamp

        async with TaskGroup() as tg:
            for idx, seg in enumerate(segments):
                seg_start = Timestamp(
                    seg[0].meta.time_range.start
                ).truncate_by(self._segment_duration).value
                rec = plan.get(seg_start)
                if rec is not None:
                    tg.create_task(one_rollup(rec, seg, idx))
                else:
                    tg.create_task(one_segment(seg, idx))
        if acc is None or acc["count"].sum() == 0:
            return None
        with np.errstate(invalid="ignore", divide="ignore"):
            acc["mean"] = acc["sum"] / acc["count"]
        return [int(x) for x in series_ids], acc

    @staticmethod
    def _fold_rollup(
        lanes: dict, metric_id: int, series_ids: np.ndarray,
        rng: TimeRange, bucket_ms: int, num_buckets: int,
    ) -> tuple[dict | None, int]:
        """Scatter one rollup artifact's pre-aggregated rows into a query
        grid partial. Rows are unique per (series, bucket) by
        construction, and alignment was proven at plan time, so the
        scatter-adds combine exactly like raw-row partials. Returns
        (partial grids or None, rows folded)."""
        ts = np.asarray(lanes["ts"], dtype=np.int64)
        tsid = np.asarray(lanes["tsid"], dtype=np.uint64)
        mid = np.asarray(lanes["metric_id"], dtype=np.uint64)
        m = (
            (mid == np.uint64(metric_id))
            & (ts >= rng.start) & (ts < rng.end)
        )
        pos = np.searchsorted(series_ids, tsid)
        pos_c = np.clip(pos, 0, max(0, len(series_ids) - 1))
        m &= series_ids[pos_c] == tsid
        rows = int(np.count_nonzero(m))
        if not rows:
            return None, 0
        sel = np.flatnonzero(m)
        b = ((ts[sel] - rng.start) // bucket_ms).astype(np.int64)
        p = pos_c[sel]
        part = {
            "sum": np.zeros((len(series_ids), num_buckets)),
            "count": np.zeros((len(series_ids), num_buckets)),
            "min": np.full((len(series_ids), num_buckets), np.inf),
            "max": np.full((len(series_ids), num_buckets), -np.inf),
        }
        np.add.at(part["sum"], (p, b), np.asarray(lanes["sum"])[sel])
        np.add.at(part["count"], (p, b),
                  np.asarray(lanes["count"], dtype=np.float64)[sel])
        np.minimum.at(part["min"], (p, b), np.asarray(lanes["min"])[sel])
        np.maximum.at(part["max"], (p, b), np.asarray(lanes["max"])[sel])
        return part, rows

    async def _query_downsample_materialized(
        self,
        metric_id: int,
        tsids: list[int] | None,
        rng: TimeRange,
        bucket_ms: int,
    ) -> tuple[list[int], dict[str, np.ndarray]] | None:
        """High-cardinality fallback: materialize rows and size the output
        grid by np.unique of the series present in range (the sorted-scan
        fast path still applies: scan output is pk-ordered). Uses the COLD
        raw scan — the downsample result is what the choke point caches;
        nesting a second cache entry under the raw key would double-store
        the same bytes."""
        from horaedb_tpu.ops import aggregate as agg_ops

        table = await self._query_raw_cold(metric_id, tsids, rng)
        if table is None or table.num_rows == 0:
            return None
        t = table.column("ts").to_numpy()
        v = table.column("value").to_numpy()
        uniq, sid_dense = np.unique(table.column("tsid").to_numpy(), return_inverse=True)
        num_buckets = int(-(-(rng.end - rng.start) // bucket_ms))
        out = agg_ops.downsample_sorted(
            t, sid_dense.astype(np.int32), v, rng.start, bucket_ms,
            num_series=len(uniq), num_buckets=num_buckets,
        )
        return [int(x) for x in uniq], {k: np.asarray(val) for k, val in out.items()}
