"""SampleManager: sample persistence + the device-side query pipeline.

Implements the reference's `SampleManager::persist` skeleton
(src/metric_engine/src/data/mod.rs:34-41, dead code in the snapshot): raw
sample rows land in the `data` table bucketed per time segment (a storage
write must not cross a segment, storage.rs:307-316), and queries run the
storage scan with (metric_id eq + TSID set-membership + time range)
predicates followed by on-device aggregation.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from horaedb_tpu.engine.tables import DATA_SCHEMA
from horaedb_tpu.ops import aggregate as agg_ops
from horaedb_tpu.ops import filter as F
from horaedb_tpu.storage.read import ScanRequest, WriteRequest
from horaedb_tpu.storage.types import TimeRange


# Above this series cardinality the dense pushdown grid (num_series x
# num_buckets x 4 stats) and the device membership probe stop paying off;
# fall back to materializing + np.unique sizing by the rows actually in range.
MAX_PUSHDOWN_SERIES = 65_536

# Resolution guard: a downsample query must not demand an absurd number of
# buckets (start=0, bucket=1m would be a ~30M-bucket grid per series) —
# reject loudly, like Prometheus's max-resolution limit.
MAX_BUCKETS = 100_000


class SampleManager:
    def __init__(self, storage, segment_duration_ms: int):
        self._storage = storage
        self._segment_duration = segment_duration_ms

    async def persist(
        self,
        metric_ids: np.ndarray,  # u64 per sample
        tsids: np.ndarray,       # u64 per sample
        ts: np.ndarray,          # i64 ms per sample
        values: np.ndarray,      # f64 per sample
    ) -> None:
        """One storage write per touched segment, rows sorted on device by
        the write path."""
        if len(ts) == 0:
            return
        seg = ts - (ts % self._segment_duration)
        for seg_start in np.unique(seg):
            m = seg == seg_start
            batch = pa.RecordBatch.from_pydict(
                {
                    "metric_id": metric_ids[m].astype(np.uint64),
                    "tsid": tsids[m].astype(np.uint64),
                    "field_id": np.zeros(int(m.sum()), dtype=np.uint64),
                    "ts": ts[m],
                    "value": values[m],
                },
                schema=DATA_SCHEMA,
            )
            lo = int(ts[m].min())
            hi = int(ts[m].max()) + 1
            await self._storage.write(WriteRequest(batch, TimeRange(lo, hi)))

    # -- queries ---------------------------------------------------------------
    def _predicate(self, metric_id: int, tsids: list[int] | None, rng: TimeRange):
        parts = [
            F.Compare("metric_id", "eq", metric_id),
            F.Compare("ts", "ge", rng.start),
            F.Compare("ts", "lt", rng.end),
        ]
        if tsids is not None:
            parts.append(F.InSet("tsid", tuple(tsids)))
        return F.And(*parts)

    async def query_raw(
        self, metric_id: int, tsids: list[int] | None, rng: TimeRange
    ) -> pa.Table | None:
        """Materialized (merged, deduped) sample rows."""
        batches = []
        async for b in self._storage.scan(
            ScanRequest(range=rng, predicate=self._predicate(metric_id, tsids, rng))
        ):
            batches.append(b)
        return pa.Table.from_batches(batches) if batches else None

    async def query_downsample(
        self,
        metric_id: int,
        tsids: list[int],
        rng: TimeRange,
        bucket_ms: int,
        filtered: bool = True,
    ) -> tuple[list[int], dict[str, np.ndarray]] | None:
        """Per-(series, bucket) sum/count/min/max/mean grids via aggregate
        PUSHDOWN: each segment reduces on device inside the scan (raw rows
        never return to host); per-segment partial grids combine trivially
        because the data-table pk includes the timestamp, so duplicates
        cannot span segments. Returns (tsid order, grids).

        `filtered=False` means `tsids` is just the metric's full series set
        (no tag filter): the TSID membership predicate is skipped, and very
        high cardinalities fall back to the materializing path whose output
        is sized by the series actually present in range."""
        from horaedb_tpu.common.error import ensure

        n_buckets = -(-(rng.end - rng.start) // bucket_ms)
        ensure(
            n_buckets <= MAX_BUCKETS,
            f"downsample resolution too high: {n_buckets} buckets "
            f"(max {MAX_BUCKETS}); narrow the range or coarsen bucket_ms",
        )
        ssts = self._storage.manifest.find_ssts(rng)
        if not ssts or not tsids:
            return None
        if len(tsids) > MAX_PUSHDOWN_SERIES:
            return await self._query_downsample_materialized(
                metric_id, tsids if filtered else None, rng, bucket_ms
            )
        series_ids = np.asarray(sorted(tsids), dtype=np.uint64)
        num_buckets = int(-(-(rng.end - rng.start) // bucket_ms))
        pred = self._predicate(
            metric_id, list(series_ids) if filtered else None, rng
        )
        acc: dict[str, np.ndarray] | None = None
        for seg in self._storage.group_by_segment(ssts):
            part = await self._storage.parquet_reader.scan_segment_downsample(
                seg,
                predicate=pred,
                ts_column="ts",
                value_column="value",
                series_column="tsid",
                series_ids=series_ids,
                t0=rng.start,
                bucket_ms=bucket_ms,
                num_buckets=num_buckets,
            )
            if acc is None:
                acc = part
            else:
                acc["sum"] = acc["sum"] + part["sum"]
                acc["count"] = acc["count"] + part["count"]
                acc["min"] = np.minimum(acc["min"], part["min"])
                acc["max"] = np.maximum(acc["max"], part["max"])
        if acc is None or acc["count"].sum() == 0:
            return None
        with np.errstate(invalid="ignore", divide="ignore"):
            acc["mean"] = acc["sum"] / acc["count"]
        return [int(x) for x in series_ids], acc

    async def _query_downsample_materialized(
        self,
        metric_id: int,
        tsids: list[int] | None,
        rng: TimeRange,
        bucket_ms: int,
    ) -> tuple[list[int], dict[str, np.ndarray]] | None:
        """High-cardinality fallback: materialize rows and size the output
        grid by np.unique of the series present in range (the sorted-scan
        fast path still applies: scan output is pk-ordered)."""
        from horaedb_tpu.ops import aggregate as agg_ops

        table = await self.query_raw(metric_id, tsids, rng)
        if table is None or table.num_rows == 0:
            return None
        t = table.column("ts").to_numpy()
        v = table.column("value").to_numpy()
        uniq, sid_dense = np.unique(table.column("tsid").to_numpy(), return_inverse=True)
        num_buckets = int(-(-(rng.end - rng.start) // bucket_ms))
        out = agg_ops.downsample_sorted(
            t, sid_dense.astype(np.int32), v, rng.start, bucket_ms,
            num_series=len(uniq), num_buckets=num_buckets,
        )
        return [int(x) for x in uniq], {k: np.asarray(val) for k, val in out.items()}
