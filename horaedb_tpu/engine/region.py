"""Region partitioning: the RFC's distributed design, implemented.

Reference: docs/rfcs/20240827-metric-engine.md:28-76 — one `root`
super-table range-partitioned by `hash(metric + sorted tags)` into
Regions, routed by a meta plane, single writer per region over shared
object storage, with split rules. The snapshot ships no implementation
(SURVEY §2.5 "inter-node: ABSENT"); this module provides a working one:

- `RangeRouter` (descriptor v2, the default): explicit ranges of the
  64-bit hash space at SERIES granularity — the route hash is the tsid,
  which IS seahash(canonical series key) = hash(metric + sorted tags),
  exactly the RFC's partition key. One metric's series spread across
  regions; reads fan out and merge. `split_region` halves a region's
  range: the daughter takes ownership of the upper half for new writes
  (descriptor rewrite = the meta-plane ownership migration); history
  stays in the parent and the fan-out merge covers it — the
  HBase-daughter-reference shape, no data rewrite on split.
- `RegionRouter` (descriptor v1, legacy): metric-granularity multiply-
  shift assignment; every query resolves in exactly ONE region. Stores
  created by earlier builds keep working unchanged.
- `RegionedEngine`: independent `MetricEngine` instances over sub-roots
  `{root}/region-{id}` of one shared object store. Writes split per
  region (vectorized on the parser's hash lanes); queries route (v1) or
  fan out + merge (v2). Each region is a separate LSM with its own
  manifest — the single-writer-per-region invariant the reference states
  at types.rs:135.

Known v2 semantics at splits: ownership moves for NEW writes only. A
re-write of a pre-split timestamp for a migrated series lands in the
daughter while the original row stays in the parent; the raw read path
deduplicates (owner region wins), bucketed aggregates do not (grids
cannot be deduplicated post-hoc) — append-mostly workloads (the
remote-write shape) never hit this.

Multi-node deployment shape: run each region's engine in its own process
(or host) against the same object store — benchmarks/shared_store_dryrun.py
validates the cross-process story; this module adds the routing fabric.
"""

from __future__ import annotations

import numpy as np

from horaedb_tpu.common.hash import seahash
from horaedb_tpu.engine.engine import MetricEngine, QueryRequest
from horaedb_tpu.ingest.cardinality import CardinalityLimited
from horaedb_tpu.ingest.types import ParsedWriteRequest

_TOP = 1 << 64


class RegionRouter:
    """Deterministic metric->region map: regions own equal slices of the
    64-bit seahash space (range partition, RFC :28-76)."""

    def __init__(self, num_regions: int):
        self.num_regions = num_regions

    def region_of_name(self, metric_name: bytes) -> int:
        return self.region_of_id(seahash(metric_name))

    def region_of_id(self, metric_id: int) -> int:
        # multiply-shift over the TOP 32 id bits: identical math in the
        # scalar and vectorized paths (u64-safe in numpy — a full 128-bit
        # product is not), so writes and queries can never disagree on a
        # metric's region
        return ((metric_id >> 32) * self.num_regions) >> 32

    def regions_of_ids(self, metric_ids: np.ndarray) -> np.ndarray:
        """Vectorized routing over a u64 id lane (same formula as
        region_of_id, element-wise)."""
        ids = metric_ids.astype(np.uint64, copy=False)
        return (
            ((ids >> np.uint64(32)) * np.uint64(self.num_regions))
            >> np.uint64(32)
        ).astype(np.int64)


class RangeRouter:
    """Descriptor-v2 routing: region `ids[i]` owns hashes in
    `[starts[i], starts[i+1])` (last region up to 2^64). Scalar and
    vectorized paths share the same boundary array, so writes and queries
    can never disagree."""

    def __init__(self, starts: list[int], ids: list[int], granularity: str):
        from horaedb_tpu.common.error import ensure

        ensure(len(starts) == len(ids) and starts and starts[0] == 0,
               "malformed region ranges")
        ensure(all(a < b for a, b in zip(starts, starts[1:])),
               "region range starts must be strictly increasing")
        ensure(granularity in ("series", "metric"),
               f"unknown region granularity: {granularity!r}")
        self.starts = list(starts)
        self._starts_u64 = np.asarray(starts, dtype=np.uint64)
        self.ids = list(ids)
        self._ids_arr = np.asarray(ids, dtype=np.int64)
        self.granularity = granularity

    @property
    def num_regions(self) -> int:
        return len(self.ids)

    def slot_of_hash(self, h: int) -> int:
        return int(np.searchsorted(self._starts_u64, np.uint64(h),
                                   side="right")) - 1

    def region_of_hash(self, h: int) -> int:
        return self.ids[self.slot_of_hash(h)]

    def region_of_name(self, metric_name: bytes) -> int:
        """Owner of the METRIC hash — the metadata/advisory routing surface
        (at series granularity data routing uses tsids, not this)."""
        return self.region_of_hash(seahash(metric_name))

    def regions_of_lanes(
        self, metric_ids: np.ndarray, tsids: np.ndarray
    ) -> np.ndarray:
        """Vectorized region-id per series from the parser's hash lanes."""
        lane = tsids if self.granularity == "series" else metric_ids
        lane = lane.astype(np.uint64, copy=False)
        slots = np.searchsorted(self._starts_u64, lane, side="right") - 1
        return self._ids_arr[slots]

    def split(self, region_id: int) -> "tuple[RangeRouter, int, int]":
        """Halve `region_id`'s range; returns (new router, daughter id,
        split point). The daughter id is fresh (max+1) — region ids are
        never recycled, they name on-disk sub-roots."""
        from horaedb_tpu.common.error import ensure

        ensure(region_id in self.ids, f"unknown region {region_id}")
        slot = self.ids.index(region_id)
        lo = self.starts[slot]
        hi = self.starts[slot + 1] if slot + 1 < len(self.starts) else _TOP
        ensure(hi - lo >= 2, f"region {region_id} range too small to split")
        mid = lo + ((hi - lo) >> 1)
        new_id = max(self.ids) + 1
        starts = self.starts[: slot + 1] + [mid] + self.starts[slot + 1:]
        ids = self.ids[: slot + 1] + [new_id] + self.ids[slot + 1:]
        return RangeRouter(starts, ids, self.granularity), new_id, mid

    def to_descriptor(self, initial_num_regions: int) -> dict:
        return {
            "version": 2,
            "granularity": self.granularity,
            "initial_num_regions": initial_num_regions,
            "regions": [
                {"id": i, "start": s} for i, s in zip(self.ids, self.starts)
            ],
        }

    @classmethod
    def from_descriptor(cls, desc: dict) -> "RangeRouter":
        regions = sorted(desc["regions"], key=lambda r: r["start"])
        return cls(
            [r["start"] for r in regions],
            [r["id"] for r in regions],
            desc.get("granularity", "series"),
        )


def _subset_request(req: ParsedWriteRequest, series_idx: np.ndarray) -> ParsedWriteRequest:
    """Build a per-region view of a FULLY-PARSED request containing only
    `series_idx` (sorted), with sample/exemplar lanes filtered and their
    series indices remapped to the subset's ordering."""
    remap = np.full(req.n_series, -1, dtype=np.int64)
    remap[series_idx] = np.arange(len(series_idx))
    smask = remap[req.sample_series] >= 0
    emask = (
        remap[req.exemplar_series] >= 0
        if len(req.exemplar_series)
        else np.zeros(0, dtype=bool)
    )
    # samples stay grouped by (remapped, ascending) series, so the subset's
    # per-series sample ranges are the cumsum of the filtered counts — the
    # buffered ingest path consumes these
    sub_counts = req.series_sample_count[series_idx]
    sub_starts = np.concatenate(([0], np.cumsum(sub_counts)[:-1])).astype(np.int64)
    # exemplar labels stay aligned with the (filtered) exemplar rows via
    # their per-exemplar start/count ranges — keep the flat ex-label lanes
    # whole and only filter the per-exemplar rows (ranges still index into
    # the shared flat lanes).
    return ParsedWriteRequest(
        payload=req.payload,
        series_label_start=req.series_label_start[series_idx],
        series_label_count=req.series_label_count[series_idx],
        series_sample_start=sub_starts,
        series_sample_count=sub_counts,
        label_name_off=req.label_name_off,
        label_name_len=req.label_name_len,
        label_value_off=req.label_value_off,
        label_value_len=req.label_value_len,
        sample_value=req.sample_value[smask],
        sample_ts=req.sample_ts[smask],
        sample_series=remap[req.sample_series[smask]],
        exemplar_value=req.exemplar_value[emask],
        exemplar_ts=req.exemplar_ts[emask],
        exemplar_series=remap[req.exemplar_series[emask]]
        if len(req.exemplar_series) else req.exemplar_series,
        exemplar_label_start=req.exemplar_label_start[emask],
        exemplar_label_count=req.exemplar_label_count[emask],
        ex_label_name_off=req.ex_label_name_off,
        ex_label_name_len=req.ex_label_name_len,
        ex_label_value_off=req.ex_label_value_off,
        ex_label_value_len=req.ex_label_value_len,
        # meta lanes deliberately STRIPPED: RegionedEngine.write_parsed
        # routes metadata by family name exactly once; letting delegated
        # engines re-record it would duplicate entries across regions and
        # let stale copies mask later updates in the metadata() union
        meta_type=req.meta_type[:0],
        meta_name_off=req.meta_name_off[:0],
        meta_name_len=req.meta_name_len[:0],
        series_metric_id=None if req.series_metric_id is None
        else req.series_metric_id[series_idx],
        series_tsid=None if req.series_tsid is None else req.series_tsid[series_idx],
        series_name_off=None if req.series_name_off is None
        else req.series_name_off[series_idx],
        series_name_len=None if req.series_name_len is None
        else req.series_name_len[series_idx],
        series_key_off=None if req.series_key_off is None
        else req.series_key_off[series_idx],
        series_key_len=None if req.series_key_len is None
        else req.series_key_len[series_idx],
        key_arena=req.key_arena,
    )


class RegionedEngine:
    """N region engines over one shared object store + the router."""

    def __init__(self) -> None:
        raise RuntimeError("use RegionedEngine.open")

    @classmethod
    async def open(
        cls,
        root: str,
        store,
        num_regions: int,
        parser_pool=None,
        granularity: str = "series",
        writable_regions: "set[int] | None" = None,
        **engine_kwargs,
    ) -> "RegionedEngine":
        """`writable_regions`: cluster partial-writer mode (the
        assignment map splits regions across writer processes,
        cluster/assignment.py) — regions IN the set open as writers
        (fenced when fence_node_id is configured), every other region
        opens as a read-only replica view, so this process can still
        serve full fan-out reads while writes to non-owned regions raise
        ReplicaReadOnlyError for the HTTP router to forward. None = all
        regions writable (the single-writer deployment). Passing
        read_only=True in engine_kwargs makes EVERY region a view (the
        replica role)."""
        import asyncio
        import json

        from horaedb_tpu.common.error import ensure
        from horaedb_tpu.objstore import NotFound

        # The initial region count and granularity are part of the on-disk
        # layout: the router maps series by them, so reopening with a
        # different N would silently make existing data invisible. The
        # REGIONS descriptor pins them; mismatches fail loudly. Splits grow
        # the live region set BEYOND the initial count — the descriptor is
        # the meta plane and always wins on the live set.
        desc_path = f"{root}/REGIONS"
        self = object.__new__(cls)
        self._root = root
        self._store = store
        self._desc_path = desc_path
        self._pool = parser_pool
        self._initial_num_regions = num_regions
        try:
            desc = json.loads((await store.get(desc_path)).decode())
            if desc.get("version") == 2:
                ensure(
                    desc.get("initial_num_regions") == num_regions,
                    f"store at {root!r} was created with "
                    f"num_regions={desc.get('initial_num_regions')}; "
                    f"reopening with {num_regions} would strand data — "
                    f"repartitioning requires a split or a rewrite, not a "
                    f"config change",
                )
                ensure(
                    desc.get("granularity", "series") == granularity,
                    f"store at {root!r} was created with granularity="
                    f"{desc.get('granularity')!r}; reopening with "
                    f"{granularity!r} would reroute series away from their "
                    f"data",
                )
                self.router = RangeRouter.from_descriptor(desc)
            else:
                # v1 legacy store: metric granularity, multiply-shift
                ensure(
                    desc.get("num_regions") == num_regions,
                    f"store at {root!r} was created with "
                    f"num_regions={desc.get('num_regions')}; reopening with "
                    f"{num_regions} would strand data — repartitioning "
                    f"requires a rewrite, not a config change",
                )
                self.router = RegionRouter(num_regions)
        except NotFound:
            if engine_kwargs.get("read_only"):
                # a replica cannot mint the meta-plane descriptor: the
                # writer owns the layout; surface NotFound so the caller
                # (cluster/replica.py) retries until the writer booted
                raise
            self.router = RangeRouter(
                [i * _TOP // num_regions for i in range(num_regions)],
                list(range(num_regions)),
                granularity,
            )
            # jaxlint: disable=J008 one-time REGIONS descriptor create at open (control plane)
            await store.put(
                desc_path,
                json.dumps(self.router.to_descriptor(num_regions)).encode(),
            )

        self._engine_kwargs = engine_kwargs
        self._writable_regions = (
            None if writable_regions is None else set(writable_regions)
        )
        self._split_lock = asyncio.Lock()
        region_ids = (self.router.ids if isinstance(self.router, RangeRouter)
                      else list(range(num_regions)))
        self.engines: dict[int, MetricEngine] = {}
        try:
            for i in region_ids:
                self.engines[i] = await MetricEngine.open(
                    f"{root}/region-{i}", store,
                    **self._region_kwargs(i),
                )
        except BaseException:
            # close the regions that did open — a retry loop must not leak
            # their tables/flush state
            await asyncio.gather(
                *(e.close() for e in self.engines.values()),
                return_exceptions=True,
            )
            raise
        return self

    @property
    def _legacy(self) -> bool:
        return not isinstance(self.router, RangeRouter)

    def _region_kwargs(self, region_id: int) -> dict:
        """Per-region open kwargs: non-owned regions under a partial
        writer open as read-only views (no fence claimed — the owning
        writer holds it)."""
        kw = dict(self._engine_kwargs)
        if (self._writable_regions is not None
                and region_id not in self._writable_regions
                and not kw.get("read_only")):
            kw["read_only"] = True
            kw.pop("fence_node_id", None)
            kw.pop("fence_validate_interval_s", None)
        return kw

    @property
    def read_only(self) -> bool:
        """True when EVERY region is a read-only view (the replica role)."""
        return all(e.read_only for e in self.engines.values())

    def writable_region_ids(self) -> list[int]:
        return sorted(i for i, e in self.engines.items() if not e.read_only)

    def manifest_epoch(self) -> int:
        """Max manifest epoch across regions (the cluster catch-up token)."""
        return max(e.manifest_epoch() for e in self.engines.values())

    def region_epochs(self) -> "dict[int, int]":
        """Per-region manifest epochs (/api/v1/cluster/status payload)."""
        return {i: e.manifest_epoch() for i, e in self.engines.items()}

    async def promote_region(self, region_id: int, fence_node_id: str) -> int:
        """Cluster takeover: reopen a read-only region as a WRITER. The
        fresh open acquires a new (higher) epoch fence on the region
        root — the acquisition IS the deposing step for whatever process
        last owned it (storage/fence.py). Returns the claimed epoch."""
        from horaedb_tpu.common.error import ensure

        # state checks INSIDE the lock: a concurrent refresh/promote must
        # not race this one to a double-swap (the loser would close an
        # engine the winner just installed)
        async with self._split_lock:
            old = self.engines.get(region_id)
            ensure(old is not None, f"unknown region {region_id}")
            ensure(old.read_only, f"region {region_id} is already writable")
            if self._writable_regions is not None:
                self._writable_regions.add(region_id)
            kw = dict(self._engine_kwargs)
            kw.pop("read_only", None)
            kw["fence_node_id"] = fence_node_id
            fresh = await MetricEngine.open(
                f"{self._root}/region-{region_id}", self._store, **kw,
            )
            self.engines[region_id] = fresh
            await old.close()
            return fresh._fence.epoch if fresh._fence is not None else 0

    async def refresh_region(self, region_id: int) -> int:
        """Cluster snapshot swap for ONE read-only region: open a fresh
        view over the shared store and atomically swap it in (in-flight
        queries keep the old engine via their own references; read-only
        engines hold no background state, so closing the old one after
        the swap is safe). Returns the fresh region epoch. Only valid on
        read-only regions — a writable region's state is already live.
        Serialized with promote/split: a refresh racing a promotion must
        not revert the freshly-claimed writer to a stale view."""
        from horaedb_tpu.common.error import ensure

        async with self._split_lock:
            old = self.engines.get(region_id)
            ensure(old is not None, f"unknown region {region_id}")
            ensure(old.read_only,
                   f"region {region_id} is writable; refresh is a replica op")
            fresh = await MetricEngine.open(
                f"{self._root}/region-{region_id}", self._store,
                **self._region_kwargs(region_id),
            )
            self.engines[region_id] = fresh
            await old.close()
            return fresh.manifest_epoch()

    async def split_region(self, region_id: int) -> int:
        """Halve `region_id`'s hash range; returns the daughter region id.

        The descriptor rewrite IS the ownership migration (meta plane):
        new writes in the upper half route to the daughter immediately.
        Existing SSTs stay in the parent's manifests — the fan-out read
        path merges them, so nothing is rewritten at split time (RFC
        :28-76 split rules; HBase-daughter-reference shape)."""
        import json

        from horaedb_tpu.common.error import ensure

        ensure(not self._legacy,
               "legacy v1 region stores cannot split; recreate with the "
               "range-partitioned layout")
        ensure(not self._engine_kwargs.get("read_only"),
               "a replica cannot split regions (meta-plane writes belong "
               "to the writer)")
        parent = self.engines.get(region_id)
        ensure(parent is not None and not parent.read_only,
               f"region {region_id} is not writable by this process; the "
               "owning writer must run the split")
        # serialized: concurrent splits reading the same router would mint
        # the same daughter id and open two engines on one sub-root
        async with self._split_lock:
            new_router, new_id, _mid = self.router.split(region_id)
            if self._writable_regions is not None:
                # the daughter inherits the parent's ownership
                self._writable_regions.add(new_id)
            self.engines[new_id] = await MetricEngine.open(
                f"{self._root}/region-{new_id}", self._store,
                **self._region_kwargs(new_id),
            )
            # engine first, descriptor second: a crash between the two
            # leaves an empty unreferenced sub-root (harmless), never a
            # referenced region with no engine state
            # jaxlint: disable=J008 split-time descriptor rewrite (meta plane), not the append path
            await self._store.put(
                self._desc_path,
                json.dumps(
                    new_router.to_descriptor(self._initial_num_regions)
                ).encode(),
            )
            self.router = new_router
            return new_id

    def sub_engines(self) -> dict[str, MetricEngine]:
        """Uniform enumeration for observability surfaces (prefix -> engine);
        MetricEngine exposes the same shape."""
        return {f"region-{i}/": e for i, e in self.engines.items()}

    async def close(self) -> None:
        import asyncio

        await asyncio.gather(*(e.close() for e in self.engines.values()))

    async def flush(self) -> None:
        import asyncio

        # regions are isolated engines over disjoint sub-roots: fan out
        await asyncio.gather(*(e.flush() for e in self.engines.values()))

    # -- write path ----------------------------------------------------------
    async def write_payload(self, payload: bytes) -> int:
        """Parse + route one wire payload. Regioned ingest always uses the
        full parse (hash lanes included): the zero-copy accumulator light
        path is single-engine-only since its samples bypass Python."""
        from horaedb_tpu.ingest import ParserPool

        if self._pool is None:
            self._pool = ParserPool()
        parsed = await self._pool.decode(payload)
        return await self.write_parsed(parsed)

    async def write_parsed(self, req: ParsedWriteRequest) -> int:
        """Split per region on the hash lanes and delegate. Requests whose
        series all route to one region (the common scrape shape) delegate
        without any copying."""
        # metadata records route by family name (advisory, in-memory)
        for i in range(len(req.meta_type)):
            name = req.meta_name(i)
            self.engines[self.router.region_of_name(name)] \
                .metric_mgr.record_metadata(name, int(req.meta_type[i]))
        if req.n_series == 0:
            return 0
        if self._legacy:
            if req.series_metric_id is not None:
                regions = self.router.regions_of_ids(req.series_metric_id)
            else:
                regions = self.router.regions_of_ids(
                    self._hash_lanes(req, need_tsids=False)[0]
                )
        else:
            need_tsids = self.router.granularity == "series"
            if req.series_metric_id is not None and (
                not need_tsids or req.series_tsid is not None
            ):
                mids = req.series_metric_id
                tsids = req.series_tsid if need_tsids else mids
            else:
                mids, tsids = self._hash_lanes(req, need_tsids)
            regions = self.router.regions_of_lanes(mids, tsids)
        uniq = np.unique(regions)
        if len(uniq) == 1:
            if len(req.meta_type):
                # strip meta lanes: recorded above by family routing (see
                # _subset_request for the same rule on the split path)
                import dataclasses

                req = dataclasses.replace(
                    req,
                    meta_type=req.meta_type[:0],
                    meta_name_off=req.meta_name_off[:0],
                    meta_name_len=req.meta_name_len[:0],
                )
            return await self.engines[int(uniq[0])].write_parsed(req)
        import asyncio

        results = await asyncio.gather(*(
            self.engines[r].write_parsed(
                _subset_request(req, np.flatnonzero(regions == r))
            )
            for r in uniq.tolist()
        ), return_exceptions=True)
        # return_exceptions: every region's write SETTLES before the
        # response — a bare gather would send the 503 while sibling
        # regions are still writing, and its accounting would name one
        # region's numbers as the whole request's
        limited = [r for r in results
                   if isinstance(r, CardinalityLimited)]
        other = [r for r in results
                 if isinstance(r, BaseException)
                 and not isinstance(r, CardinalityLimited)]
        if other:
            raise other[0]
        if limited:
            # combine the per-region partial-accepts into one request-level
            # accounting (accepted counts include fully-accepted regions)
            accepted = sum(r for r in results if isinstance(r, int))
            accepted += sum(e.accepted_samples for e in limited)
            raise CardinalityLimited(
                table=limited[0].table,
                limit=limited[0].limit,
                estimate=max(e.estimate for e in limited),
                accepted_samples=accepted,
                rejected_samples=sum(e.rejected_samples for e in limited),
                rejected_series=sum(e.rejected_series for e in limited),
            )
        return sum(results)

    def _hash_lanes(
        self, req: ParsedWriteRequest, need_tsids: bool
    ) -> tuple[np.ndarray, np.ndarray]:
        """Python-parse fallback: recompute the hash lanes the native parser
        would have supplied (differentially tested against it)."""
        from horaedb_tpu.engine.engine import NAME_LABEL
        from horaedb_tpu.engine.types import series_id_of, series_key_of

        mids = np.empty(req.n_series, dtype=np.uint64)
        tsids = np.empty(req.n_series, dtype=np.uint64)
        for s in range(req.n_series):
            labels = list(req.series_labels(s))
            name = b""
            for k, v in labels:
                if k == NAME_LABEL:
                    name = v
            mids[s] = seahash(name)
            if need_tsids:
                tsids[s] = series_id_of(series_key_of(labels))
        return mids, (tsids if need_tsids else mids)

    # -- read path -------------------------------------------------------------
    # v1 routes each metric to its single owner region. v2 fans out and
    # merges: at series granularity a metric's series span regions by
    # design, and after any split a migrated series' history lives in the
    # parent while new samples land in the daughter.

    def _engine_for(self, metric: bytes) -> MetricEngine:
        return self.engines[self.router.region_of_name(metric)]

    async def query(self, req: QueryRequest):
        from horaedb_tpu.common import deadline as deadline_ctx
        from horaedb_tpu.storage import scanstats

        if self._legacy:
            scanstats.note_max("regions_fanout", 1)
            return await self._engine_for(req.metric).query(req)
        import asyncio

        # cooperative deadline at the fan-out point: an expired query
        # must not launch one scan per region (each per-region query
        # re-checks on its own path, so a mid-fan-out expiry dies at the
        # next natural yield point instead of finishing every region)
        deadline_ctx.check("region_fanout")
        ids = list(self.engines)
        # EXPLAIN provenance: how many regions this query fanned out to
        # (max, not sum: a multi-selector PromQL expression queries the
        # engine several times under one collector)
        scanstats.note_max("regions_fanout", len(ids))
        results = await asyncio.gather(
            *(self.engines[i].query(req) for i in ids)
        )
        tagged = [(i, r) for i, r in zip(ids, results) if r is not None]
        if not tagged:
            return None
        if req.bucket_ms is None:
            return _merge_raw_tables(tagged, self.router, req.limit)
        return _merge_grids([r for _, r in tagged])

    async def query_partial_grids(self, req: QueryRequest):
        """Distributed scatter-gather leaf: run the normal per-region
        downsample scan for the regions `req.regions` names (None = all
        owned here) and return UNMERGED [(region_id, tsids, grids)] —
        the coordinator folds fragments from every computing node in
        this engine's canonical region order (`list(self.engines)`), so
        splitting a query across nodes reproduces the single-node
        `query` result bit-for-bit."""
        import asyncio

        from horaedb_tpu.common import deadline as deadline_ctx
        from horaedb_tpu.common.error import ensure
        from horaedb_tpu.storage import scanstats

        ensure(req.bucket_ms is not None,
               "query_partial_grids requires a bucketed (grid) query")
        deadline_ctx.check("region_fanout")
        if self._legacy:
            # v1 routes each metric to one owner region; a restriction
            # either includes it (full answer) or misses (empty)
            rid = self.router.region_of_name(req.metric)
            if req.regions is not None and int(rid) not in [
                int(r) for r in req.regions
            ]:
                return []
            out = await self.engines[rid].query(req)
            return [] if out is None else [(int(rid), out[0], out[1])]
        ids = list(self.engines)
        if req.regions is not None:
            want = {int(r) for r in req.regions}
            ids = [i for i in ids if int(i) in want]
        scanstats.note_max("regions_fanout", len(ids))
        results = await asyncio.gather(
            *(self.engines[i].query(req) for i in ids)
        )
        return [
            (int(i), r[0], r[1])
            for i, r in zip(ids, results) if r is not None
        ]

    async def query_exemplars(self, req: QueryRequest):
        if self._legacy:
            return await self._engine_for(req.metric).query_exemplars(req)
        import asyncio

        import pyarrow as pa

        results = await asyncio.gather(
            *(e.query_exemplars(req) for e in self.engines.values())
        )
        results = [r for r in results if r is not None]
        if not results:
            return None
        merged = pa.concat_tables(results)
        if req.limit is not None:
            merged = merged.slice(0, req.limit)
        return merged

    def label_values(self, metric: bytes, key: bytes) -> list[bytes]:
        if self._legacy:
            return self._engine_for(metric).label_values(metric, key)
        out: set[bytes] = set()
        for e in self.engines.values():
            out.update(e.label_values(metric, key))
        return sorted(out)

    def series(self, metric: bytes):
        if self._legacy:
            return self._engine_for(metric).series(metric)
        # dedup by tsid: a split-migrated series is registered in both the
        # parent and the daughter
        by_tsid: dict[str, dict] = {}
        for e in self.engines.values():
            for row in e.series(metric):
                by_tsid.setdefault(row.get("__tsid__", repr(row)), row)
        # numeric order, matching the single engine's sorted(per_tsid)
        return [by_tsid[k] for k in sorted(
            by_tsid, key=lambda k: (0, int(k)) if k.isdigit() else (1, 0, k)
        )]

    def metric_names(self) -> list[bytes]:
        """Fan-out union (cross-region read surface)."""
        out: list[bytes] = []
        for e in self.engines.values():
            out.extend(e.metric_names())
        return sorted(set(out))

    def series_count(self, metric: bytes) -> int:
        """Fan-out sum of per-region registered series (a split-migrated
        series registered in parent AND daughter counts twice — an
        acceptable over-estimate for the admission cost model)."""
        if self._legacy:
            return self._engine_for(metric).series_count(metric)
        return sum(e.series_count(metric) for e in self.engines.values())

    def label_names(self) -> list[bytes]:
        """Fan-out union of per-region label keys (mirrors match_series:
        the /api/v1/labels no-match[] branch runs unchanged on regioned
        deployments)."""
        out: set[bytes] = set()
        for e in self.engines.values():
            out.update(e.label_names())
        return sorted(out)

    def series_labels_map(
        self, metric: bytes, tsids: "list[int] | None" = None
    ) -> dict[int, dict[bytes, bytes]]:
        """Fan-out union of per-region tsid -> label maps (a split-migrated
        series registered in parent and daughter resolves to one entry —
        same labels either way)."""
        if self._legacy:
            return self._engine_for(metric).series_labels_map(metric, tsids)
        out: dict[int, dict[bytes, bytes]] = {}
        for e in self.engines.values():
            for t, labs in e.series_labels_map(metric, tsids).items():
                out.setdefault(t, labs)
        return out

    async def match_series(
        self, metric: bytes, filters, matchers
    ) -> dict[int, dict[bytes, bytes]]:
        """Fan-out union of per-region match[] resolution (PromQL and the
        discovery endpoints run unchanged on regioned deployments)."""
        if self._legacy:
            return await self._engine_for(metric).match_series(
                metric, filters, matchers
            )
        out: dict[int, dict[bytes, bytes]] = {}
        for e in self.engines.values():
            for t, labs in (await e.match_series(metric, filters, matchers)).items():
                out.setdefault(t, labs)
        return out

    def metadata(self) -> "dict[bytes, str]":
        """Fan-out union of per-region metric-family metadata."""
        out: dict[bytes, str] = {}
        for e in self.engines.values():
            out.update(e.metadata())
        return out

    async def compact(self, time_range=None) -> None:
        import asyncio

        await asyncio.gather(
            *(e.compact(time_range=time_range) for e in self.engines.values())
        )

    async def delete_series(
        self, metric: bytes, filters=None, matchers=None,
        start_ms: int = 0, end_ms: "int | None" = None,
    ) -> dict:
        """Fan-out tombstone delete: a metric's series hash across regions
        (and a pre-split series may live in parent AND daughter manifests),
        so every region evaluates the matchers independently. The NOW cap
        for the all-time form resolves HERE so every region shares one
        bound (see MetricEngine.delete_series)."""
        import asyncio

        from horaedb_tpu.common.time_ext import now_ms

        if end_ms is None:
            end_ms = now_ms() + 1

        results = await asyncio.gather(*(
            e.delete_series(metric, filters=filters, matchers=matchers,
                            start_ms=start_ms, end_ms=end_ms)
            for e in self.engines.values()
        ))
        return {
            "matched_series": sum(r["matched_series"] for r in results),
            "tombstones": sum(r["tombstones"] for r in results),
            "tombstone_ids": [
                i for r in results for i in r.get("tombstone_ids", [])
            ],
            "start_ms": start_ms,
            "end_ms": end_ms,
        }


def _merge_raw_tables(tagged: list, router: RangeRouter, limit: int | None):
    """Concat per-region raw-row tables, order by (tsid, field_id, ts), and
    drop cross-region duplicates of one sample key: a pre-split row
    re-written post-split exists in both parent and daughter — the row from
    the region that currently OWNS the series' hash wins (it holds the
    newest write), matching single-engine upsert semantics."""
    import pyarrow as pa

    parts = []
    for region_id, table in tagged:
        lane_col = "tsid" if router.granularity == "series" else "metric_id"
        if lane_col in table.column_names:
            lane = table.column(lane_col).to_numpy().astype(np.uint64,
                                                            copy=False)
            owner = router._ids_arr[
                np.searchsorted(router._starts_u64, lane, side="right") - 1
            ]
            prio = (owner != region_id).astype(np.int8)
        else:
            prio = np.ones(table.num_rows, np.int8)
        parts.append(table.append_column("__prio__", pa.array(prio)))
    merged = pa.concat_tables(parts)
    sort_keys = [(c, "ascending") for c in ("tsid", "field_id", "ts")
                 if c in merged.column_names]
    merged = merged.sort_by(sort_keys + [("__prio__", "ascending")])
    if len(sort_keys) == 3 and len(tagged) > 1 and merged.num_rows:
        cols = [merged.column(c).to_numpy() for c, _ in sort_keys]
        keep = np.ones(merged.num_rows, dtype=bool)
        # owner sorts first within a duplicate run, so keep-first keeps it
        keep[1:] = ~np.logical_and.reduce(
            [c[1:] == c[:-1] for c in cols]
        )
        if not keep.all():
            merged = merged.filter(pa.array(keep))
    merged = merged.drop_columns(["__prio__"])
    if limit is not None:
        merged = merged.slice(0, limit)
    return merged


def _merge_grids(results: list):
    """Combine per-region (tsids, grids) downsample outputs — delegates
    to the ONE fold implementation in cluster/partial.py (jaxlint J023):
    the distributed coordinator folds remote fragments with the same
    code in the same region order, which is what makes a split query
    bit-exact vs this single-node merge."""
    from horaedb_tpu.cluster.partial import merge_grids

    return merge_grids(results)
