"""Region partitioning: the RFC's distributed design, implemented.

Reference: docs/rfcs/20240827-metric-engine.md:28-76 — one `root`
super-table partitioned by hash into Regions, routed by a meta service,
single writer per region over shared object storage. The snapshot ships no
implementation (SURVEY §2.5 "inter-node: ABSENT"); this module provides a
working one:

- `RegionRouter`: deterministic metric -> region assignment by seahash
  range (metric granularity, so every query resolves in exactly ONE region
  — no cross-region merge on the read path; the RFC's series-hash
  partitioning is a sharper-grained variant of the same scheme).
- `RegionedEngine`: N independent `MetricEngine` instances over sub-roots
  `{root}/region-{i}` of one shared object store. Writes split per region
  (vectorized on the parser's hash lanes); queries route. Each region is a
  separate LSM with its own manifest — the single-writer-per-region
  invariant the reference states at types.rs:135.

Multi-node deployment shape: run each region's engine in its own process
(or host) against the same object store — benchmarks/shared_store_dryrun.py
validates the cross-process story; this module adds the routing fabric.
"""

from __future__ import annotations

import numpy as np

from horaedb_tpu.common.hash import seahash
from horaedb_tpu.engine.engine import MetricEngine, QueryRequest
from horaedb_tpu.ingest.types import ParsedWriteRequest


class RegionRouter:
    """Deterministic metric->region map: regions own equal slices of the
    64-bit seahash space (range partition, RFC :28-76)."""

    def __init__(self, num_regions: int):
        self.num_regions = num_regions

    def region_of_name(self, metric_name: bytes) -> int:
        return self.region_of_id(seahash(metric_name))

    def region_of_id(self, metric_id: int) -> int:
        # multiply-shift over the TOP 32 id bits: identical math in the
        # scalar and vectorized paths (u64-safe in numpy — a full 128-bit
        # product is not), so writes and queries can never disagree on a
        # metric's region
        return ((metric_id >> 32) * self.num_regions) >> 32

    def regions_of_ids(self, metric_ids: np.ndarray) -> np.ndarray:
        """Vectorized routing over a u64 id lane (same formula as
        region_of_id, element-wise)."""
        ids = metric_ids.astype(np.uint64, copy=False)
        return (
            ((ids >> np.uint64(32)) * np.uint64(self.num_regions))
            >> np.uint64(32)
        ).astype(np.int64)


def _subset_request(req: ParsedWriteRequest, series_idx: np.ndarray) -> ParsedWriteRequest:
    """Build a per-region view of a FULLY-PARSED request containing only
    `series_idx` (sorted), with sample/exemplar lanes filtered and their
    series indices remapped to the subset's ordering."""
    remap = np.full(req.n_series, -1, dtype=np.int64)
    remap[series_idx] = np.arange(len(series_idx))
    smask = remap[req.sample_series] >= 0
    emask = (
        remap[req.exemplar_series] >= 0
        if len(req.exemplar_series)
        else np.zeros(0, dtype=bool)
    )
    # samples stay grouped by (remapped, ascending) series, so the subset's
    # per-series sample ranges are the cumsum of the filtered counts — the
    # buffered ingest path consumes these
    sub_counts = req.series_sample_count[series_idx]
    sub_starts = np.concatenate(([0], np.cumsum(sub_counts)[:-1])).astype(np.int64)
    # exemplar labels stay aligned with the (filtered) exemplar rows via
    # their per-exemplar start/count ranges — keep the flat ex-label lanes
    # whole and only filter the per-exemplar rows (ranges still index into
    # the shared flat lanes).
    return ParsedWriteRequest(
        payload=req.payload,
        series_label_start=req.series_label_start[series_idx],
        series_label_count=req.series_label_count[series_idx],
        series_sample_start=sub_starts,
        series_sample_count=sub_counts,
        label_name_off=req.label_name_off,
        label_name_len=req.label_name_len,
        label_value_off=req.label_value_off,
        label_value_len=req.label_value_len,
        sample_value=req.sample_value[smask],
        sample_ts=req.sample_ts[smask],
        sample_series=remap[req.sample_series[smask]],
        exemplar_value=req.exemplar_value[emask],
        exemplar_ts=req.exemplar_ts[emask],
        exemplar_series=remap[req.exemplar_series[emask]]
        if len(req.exemplar_series) else req.exemplar_series,
        exemplar_label_start=req.exemplar_label_start[emask],
        exemplar_label_count=req.exemplar_label_count[emask],
        ex_label_name_off=req.ex_label_name_off,
        ex_label_name_len=req.ex_label_name_len,
        ex_label_value_off=req.ex_label_value_off,
        ex_label_value_len=req.ex_label_value_len,
        # meta lanes deliberately STRIPPED: RegionedEngine.write_parsed
        # routes metadata by family name exactly once; letting delegated
        # engines re-record it would duplicate entries across regions and
        # let stale copies mask later updates in the metadata() union
        meta_type=req.meta_type[:0],
        meta_name_off=req.meta_name_off[:0],
        meta_name_len=req.meta_name_len[:0],
        series_metric_id=None if req.series_metric_id is None
        else req.series_metric_id[series_idx],
        series_tsid=None if req.series_tsid is None else req.series_tsid[series_idx],
        series_name_off=None if req.series_name_off is None
        else req.series_name_off[series_idx],
        series_name_len=None if req.series_name_len is None
        else req.series_name_len[series_idx],
        series_key_off=None if req.series_key_off is None
        else req.series_key_off[series_idx],
        series_key_len=None if req.series_key_len is None
        else req.series_key_len[series_idx],
        key_arena=req.key_arena,
    )


class RegionedEngine:
    """N region engines over one shared object store + the router."""

    def __init__(self) -> None:
        raise RuntimeError("use RegionedEngine.open")

    @classmethod
    async def open(
        cls, root: str, store, num_regions: int, parser_pool=None, **engine_kwargs
    ) -> "RegionedEngine":
        import asyncio
        import json

        from horaedb_tpu.common.error import ensure
        from horaedb_tpu.objstore import NotFound

        # The region count is part of the on-disk layout: the router maps
        # metrics by it, so reopening with a different N would silently make
        # existing data invisible (or never open some regions at all). A
        # REGIONS descriptor pins it; mismatches fail loudly.
        desc_path = f"{root}/REGIONS"
        try:
            desc = json.loads((await store.get(desc_path)).decode())
            ensure(
                desc.get("num_regions") == num_regions,
                f"store at {root!r} was created with "
                f"num_regions={desc.get('num_regions')}; reopening with "
                f"{num_regions} would strand data — repartitioning requires "
                f"a rewrite, not a config change",
            )
        except NotFound:
            await store.put(
                desc_path, json.dumps({"num_regions": num_regions}).encode()
            )

        self = object.__new__(cls)
        self.router = RegionRouter(num_regions)
        self._pool = parser_pool
        self.engines = []
        try:
            for i in range(num_regions):
                self.engines.append(
                    await MetricEngine.open(
                        f"{root}/region-{i}", store, **engine_kwargs
                    )
                )
        except BaseException:
            # close the regions that did open — a retry loop must not leak
            # their tables/flush state
            await asyncio.gather(
                *(e.close() for e in self.engines), return_exceptions=True
            )
            raise
        return self

    def sub_engines(self) -> dict[str, MetricEngine]:
        """Uniform enumeration for observability surfaces (prefix -> engine);
        MetricEngine exposes the same shape."""
        return {f"region-{i}/": e for i, e in enumerate(self.engines)}

    async def close(self) -> None:
        import asyncio

        await asyncio.gather(*(e.close() for e in self.engines))

    async def flush(self) -> None:
        import asyncio

        # regions are isolated engines over disjoint sub-roots: fan out
        await asyncio.gather(*(e.flush() for e in self.engines))

    # -- write path ----------------------------------------------------------
    async def write_payload(self, payload: bytes) -> int:
        """Parse + route one wire payload. Regioned ingest always uses the
        full parse (hash lanes included): the zero-copy accumulator light
        path is single-engine-only since its samples bypass Python."""
        from horaedb_tpu.ingest import ParserPool

        if self._pool is None:
            self._pool = ParserPool()
        parsed = await self._pool.decode(payload)
        return await self.write_parsed(parsed)

    async def write_parsed(self, req: ParsedWriteRequest) -> int:
        """Split per region on the hash lanes and delegate. Requests whose
        series all route to one region (the common scrape shape) delegate
        without any copying."""
        # metadata records route by family name (advisory, in-memory)
        for i in range(len(req.meta_type)):
            name = req.meta_name(i)
            self.engines[self.router.region_of_name(name)] \
                .metric_mgr.record_metadata(name, int(req.meta_type[i]))
        if req.n_series == 0:
            return 0
        if req.series_metric_id is not None:
            regions = self.router.regions_of_ids(req.series_metric_id)
        else:
            from horaedb_tpu.engine.engine import NAME_LABEL

            ids = np.empty(req.n_series, dtype=np.uint64)
            for s in range(req.n_series):
                name = b""
                for k, v in req.series_labels(s):
                    if k == NAME_LABEL:
                        name = v
                ids[s] = seahash(name)
            regions = self.router.regions_of_ids(ids)
        uniq = np.unique(regions)
        if len(uniq) == 1:
            if len(req.meta_type):
                # strip meta lanes: recorded above by family routing (see
                # _subset_request for the same rule on the split path)
                import dataclasses

                req = dataclasses.replace(
                    req,
                    meta_type=req.meta_type[:0],
                    meta_name_off=req.meta_name_off[:0],
                    meta_name_len=req.meta_name_len[:0],
                )
            return await self.engines[int(uniq[0])].write_parsed(req)
        import asyncio

        counts = await asyncio.gather(*(
            self.engines[r].write_parsed(
                _subset_request(req, np.flatnonzero(regions == r))
            )
            for r in uniq.tolist()
        ))
        return sum(counts)

    # -- read path -------------------------------------------------------------
    def _engine_for(self, metric: bytes) -> MetricEngine:
        return self.engines[self.router.region_of_name(metric)]

    async def query(self, req: QueryRequest):
        return await self._engine_for(req.metric).query(req)

    async def query_exemplars(self, req: QueryRequest):
        return await self._engine_for(req.metric).query_exemplars(req)

    def label_values(self, metric: bytes, key: bytes) -> list[bytes]:
        return self._engine_for(metric).label_values(metric, key)

    def series(self, metric: bytes):
        return self._engine_for(metric).series(metric)

    def metric_names(self) -> list[bytes]:
        """Fan-out union (the one cross-region read surface)."""
        out: list[bytes] = []
        for e in self.engines:
            out.extend(e.metric_names())
        return sorted(set(out))

    def metadata(self) -> "dict[bytes, str]":
        """Fan-out union of per-region metric-family metadata."""
        out: dict[bytes, str] = {}
        for e in self.engines:
            out.update(e.metadata())
        return out

    async def compact(self) -> None:
        import asyncio

        await asyncio.gather(*(e.compact() for e in self.engines))
