"""Background flush executor: the overlapped ingest->flush pipeline.

ROOFLINE §7 measured the ingest wall directly: pure ingest runs at
14.2 M samples/s but collapses to 4.5 M once flushes fire, because flush
work (accumulator drain, parquet encode, object-store upload) ran inline
on the append path. This module is the producer/consumer decoupling the
HoraeDB metric-engine RFC's LSM design gets from immutable memtables +
a background flusher:

- ``SealedMemtable``: an immutable snapshot of the SampleManager's
  active buffers (python per-segment chunks, the zero-copy column
  arrays, the C++ accumulator's pk-sorted lanes), sealed atomically on
  the event loop with its dedup sequence pinned. Appends after the seal
  land in a fresh active buffer — the double-buffer swap.
- ``FlushExecutor``: a bounded queue + bounded worker pool draining
  sealed memtables through the SampleManager's write-out. Appends never
  block on drain/encode/upload while the queue has room; when it is
  full they block on a condition variable with a deadline (recorded in
  ``horaedb_ingest_stall_seconds``) and fail loudly past it — bounded
  memory, never a silent drop.
- Crash-consistency: a failed write-out converts the sealed memtable's
  un-landed rows into pinned-seq replay groups and PARKS it (nothing is
  dropped); the next flush trigger or barrier re-queues it. Manifest
  visibility still commits only after the SST upload (storage layer),
  and shutdown drains the queue before the engine closes.

Workers are per-item tasks bounded by ``workers`` (no idle long-lived
loops to leak across event loops); all state is event-loop-confined.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Awaitable, Callable

import numpy as np

from horaedb_tpu.common import tracing
from horaedb_tpu.common.error import UnavailableError
from horaedb_tpu.server.metrics import GLOBAL_METRICS

logger = logging.getLogger(__name__)

FLUSH_QUEUE_DEPTH = GLOBAL_METRICS.gauge(
    "horaedb_flush_queue_depth",
    help="Sealed memtables awaiting a background flush worker (queued + "
         "parked-after-failure; excludes the one being written), by table.",
    labelnames=("table",),
)
INGEST_STALL_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_ingest_stall_seconds",
    help="Time appends spent blocked on a full flush queue (backpressure "
         "stalls on the condition variable), by table. A fat tail means "
         "flush bandwidth — not parse — is the ingest ceiling.",
    labelnames=("table",),
)
# storage.py observes the encode/upload stages of flush-profile SST writes
# into this same family (the registry is idempotent by name); the drain
# stage is observed by the SampleManager's seal/sort.
FLUSH_STAGE_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_flush_stage_seconds",
    help="Per-stage flush cost: drain (memtable -> pk-sorted column "
         "lanes), encode (parquet), upload (object-store PUT).",
    labelnames=("table", "stage"),
    # OpenMetrics exemplars: a slow flush stage names the trace that
    # paid it (telemetry package wires the source)
    exemplars=True,
)
FLUSH_FAILURES_TOTAL = GLOBAL_METRICS.counter(
    "horaedb_flush_failures_total",
    help="Failed flush write-outs; the sealed memtable re-queues with its "
         "sequence pinned (zero rows lost) and a later trigger retries.",
    labelnames=("table",),
)
FLUSH_OVERLAP_RATIO = GLOBAL_METRICS.histogram(
    "horaedb_flush_overlap_ratio",
    help="Rows appended to the ACTIVE memtable while a flush write-out ran, "
         "over the rows in that write-out — 0 means ingest sat idle during "
         "the flush (no overlap), ~1 means full producer/consumer overlap.",
    labelnames=("table",),
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 1.0, 1.5, 2.5, 5.0),
)


@dataclass(eq=False)  # identity semantics: memtables live in queues/sets
class SealedMemtable:
    """One immutable flush unit. ``seq`` is the dedup sequence pinned at
    seal time, so a delayed/retried replay can never beat writes acked
    after it. After a failed attempt the un-landed state lives in
    ``groups`` (per-segment pinned-seq lane tuples) and the fresh fields
    are empty — the same object retries until it lands."""

    seq: int
    rows: int
    # persist()-path python buffers: segment start -> list of lane tuples
    buf: dict[int, list[tuple[np.ndarray, ...]]] = field(default_factory=dict)
    # buffer_request()-path zero-copy column views: (dense, ts, value)
    cols: tuple[np.ndarray, np.ndarray, np.ndarray] | None = None
    keys: list[tuple[int, int]] = field(default_factory=list)
    # full backing arrays behind `cols` — recycled into the spare pool
    # after a successful write-out (arena reuse across flushes)
    cols_backing: tuple[np.ndarray, ...] | None = None
    # C++ accumulator drain: (mid, tsid, ts, value) pk-sorted lanes
    lanes: tuple[np.ndarray, ...] | None = None
    # the sealed rows as ONE frozen column block (common/colblock.py):
    # `cols` above are its read-only lane views — consumers that need the
    # whole hand-off (drain, replay grouping) pass the block by reference
    # (block.share()) instead of re-materializing lanes
    block: "object | None" = None
    # pinned-seq replay groups from failed attempts:
    # (seq, segment_start, (mid, tsid, ts, value), presorted)
    groups: list[tuple[int, int, tuple, bool]] = field(default_factory=list)
    attempts: int = 0
    # the last write-out failure, kept WITH the memtable so retry policy
    # can classify it (common/error.py): retryable failures re-queue on
    # the next trigger; persistent/fatal ones surface at the barrier
    # instead of parking forever
    last_error: BaseException | None = None


class FlushExecutor:
    """Bounded background flush pool for ONE SampleManager.

    ``writeout`` is the manager's async write-out (one attempt; on
    failure it must convert the sealed memtable's remaining rows into
    pinned-seq ``groups`` before raising, so parking it loses nothing).
    """

    def __init__(
        self,
        writeout: Callable[[SealedMemtable], Awaitable[None]],
        table_id: str,
        workers: int = 2,
        queue_max: int = 4,
        stall_deadline_s: float = 30.0,
    ) -> None:
        self._writeout = writeout
        self._table = table_id
        self._workers = max(1, int(workers))
        self._queue_max = max(1, int(queue_max))
        self._deadline = float(stall_deadline_s)
        self._queue: deque[SealedMemtable] = deque()
        self._parked: list[SealedMemtable] = []
        self._inflight: set[SealedMemtable] = set()
        self._running = 0          # live worker tasks
        self._active_rows = 0      # rows inside in-flight write-outs
        self._cond: asyncio.Condition | None = None
        self._last_error: BaseException | None = None
        # pre-register every family child so /metrics shows the zero
        # state from boot (the PR2 convention)
        self._depth = FLUSH_QUEUE_DEPTH.labels(table_id)
        self._stall = INGEST_STALL_SECONDS.labels(table_id)
        FLUSH_FAILURES_TOTAL.labels(table_id)
        FLUSH_OVERLAP_RATIO.labels(table_id)
        for stage in ("drain", "encode", "upload"):
            FLUSH_STAGE_SECONDS.labels(table_id, stage)
        self._depth.set(0)

    # -- state ---------------------------------------------------------------
    @property
    def backlog(self) -> int:
        """Sealed memtables not yet being worked (the queue-bound unit)."""
        return len(self._queue) + len(self._parked)

    @property
    def busy(self) -> bool:
        return bool(self._queue) or self._running > 0

    @property
    def pending_rows(self) -> int:
        """Rows sealed but not yet durable (queued + parked + in-flight)."""
        return (
            sum(s.rows for s in self._queue)
            + sum(s.rows for s in self._parked)
            + self._active_rows
        )

    @property
    def last_error(self) -> BaseException | None:
        return self._last_error

    def _condition(self) -> asyncio.Condition:
        if self._cond is None:  # lazy: binds the running loop
            self._cond = asyncio.Condition()
        return self._cond

    def _set_depth(self) -> None:
        self._depth.set(self.backlog)

    def _notify_soon(self) -> None:
        """Wake waiters from a sync call site (single-loop state change)."""
        if self._cond is None:
            return

        async def _n() -> None:
            async with self._cond:
                self._cond.notify_all()

        asyncio.get_running_loop().create_task(_n())

    # -- submission ----------------------------------------------------------
    async def submit(self, sealed: SealedMemtable, urgent: bool = False) -> None:
        """Queue a sealed memtable for background write-out.

        When the queue (including parked failures) is full, BLOCK on the
        condition variable until a worker frees a slot — the backpressure
        that bounds ingest memory at ~(queue_max + workers + 1) buffers —
        and raise past the stall deadline so the writer sees a retryable
        error instead of silently-dropped rows. ``urgent`` (the flush
        barrier) bypasses the bound: the caller drains immediately."""
        if not urgent and self.backlog >= self._queue_max:
            cond = self._condition()
            self._kick()  # workers must be running for a slot to ever free
            t0 = time.perf_counter()
            try:
                async with cond:
                    await asyncio.wait_for(
                        cond.wait_for(lambda: self.backlog < self._queue_max),
                        timeout=self._deadline,
                    )
            except asyncio.TimeoutError:
                stalled = time.perf_counter() - t0
                self._stall.observe(stalled)
                err = self._last_error
                # typed overload signal: the HTTP layer sheds this as
                # 503 + Retry-After (server/errors.py) instead of a 500 —
                # the sender's retry IS the backpressure release valve
                raise UnavailableError(
                    f"ingest stalled {stalled:.1f}s: flush queue full "
                    f"({self.backlog} sealed memtables, table={self._table})"
                    + (f"; last flush error: {err}" if err else ""),
                    retry_after_s=min(self._deadline, 5.0),
                )
            self._stall.observe(time.perf_counter() - t0)
        self._queue.append(sealed)
        self._set_depth()
        self._kick()

    def kick_parked(self) -> None:
        """Re-queue parked (failed) memtables at the FRONT — their pinned
        seqs are the oldest and a retry should land before fresh work.

        Classification gate (common/error.py): only RETRYABLE failures
        re-queue here. A memtable whose last failure was persistent or
        fatal stays parked — background workers re-attempting a
        deterministic failure on every trigger would burn store budget
        forever without ever surfacing it; the flush barrier owns
        raising those (SampleManager.flush)."""
        if not self._parked:
            return
        from horaedb_tpu.common.error import classify

        keep: list[SealedMemtable] = []
        while self._parked:
            s = self._parked.pop()
            if s.last_error is not None and classify(s.last_error) != "retryable":
                keep.append(s)
                continue
            self._queue.appendleft(s)
        keep.reverse()
        self._parked = keep
        self._set_depth()
        self._kick()

    def take_parked(self) -> SealedMemtable | None:
        """Pop one parked memtable for an inline (barrier) retry."""
        if not self._parked:
            return None
        s = self._parked.pop(0)
        self._set_depth()
        self._notify_soon()
        return s

    def park(self, sealed: SealedMemtable) -> None:
        """Park a memtable whose write-out failed (rows preserved)."""
        self._parked.append(sealed)
        self._set_depth()

    # -- workers -------------------------------------------------------------
    def _kick(self) -> None:
        while self._running < self._workers and self._queue:
            self._running += 1
            asyncio.get_running_loop().create_task(
                self._run(), name=f"flush-{self._table}"
            )

    async def _run(self) -> None:
        """One worker: drain queued memtables until the queue is empty,
        then exit (per-item tasks — nothing lingers at loop teardown)."""
        from horaedb_tpu.common import deadline as deadline_ctx

        # background durability work must NOT inherit a request deadline:
        # this task was possibly created from a query's flush barrier
        # (tasks copy the spawning context), and killing a half-done SST
        # upload because a dashboard panel's budget expired would turn a
        # slow query into parked memtables
        deadline_ctx.detach()
        cond = self._condition()
        try:
            while self._queue:
                item = self._queue.popleft()
                self._inflight.add(item)
                self._set_depth()
                self._active_rows += item.rows
                item.attempts += 1
                try:
                    with tracing.span(
                        "flush_task", table=self._table, rows=item.rows,
                        seq=item.seq, attempt=item.attempts,
                    ):
                        await self._writeout(item)
                    self._last_error = None
                except asyncio.CancelledError:
                    self.park(item)  # loop teardown: nothing is dropped
                    raise
                except BaseException as e:  # noqa: BLE001 — parked for retry
                    self._last_error = e
                    item.last_error = e
                    self.park(item)
                    logger.error(
                        "background flush failed (table=%s, rows=%d, "
                        "attempt %d); memtable re-queued",
                        self._table, item.rows, item.attempts, exc_info=e,
                    )
                finally:
                    self._active_rows -= item.rows
                    self._inflight.discard(item)
                async with cond:
                    cond.notify_all()
        finally:
            self._running -= 1
            try:
                async with cond:
                    cond.notify_all()
            except BaseException:  # noqa: BLE001 — teardown already raising
                pass

    # -- barriers ------------------------------------------------------------
    def snapshot_pending(self) -> "list[SealedMemtable]":
        """The memtables queued or in flight RIGHT NOW — the work a flush
        barrier must wait out. Deliberately excludes anything submitted
        after this call, so a barrier is never starved by sustained
        ingest that keeps the queue non-empty."""
        return list(self._queue) + list(self._inflight)

    async def wait_settled(self, items: "list[SealedMemtable]") -> None:
        """Wait until every memtable in `items` has SETTLED: written
        durably, or parked after a failure (the barrier then retries
        parked ones inline and surfaces the error — a background worker
        never spins on a broken store)."""
        self._kick()
        cond = self._condition()

        def pending(i: SealedMemtable) -> bool:
            return i in self._inflight or i in self._queue

        async with cond:
            await cond.wait_for(lambda: not any(pending(i) for i in items))
