"""Metric-engine id types and the seahash function.

Reference: src/metric_engine/src/types.rs:18-41 — `name_id = hash(name)`,
`series_id = hash(sorted labels)`, hash = seahash. This is a from-scratch
Python implementation of the public SeaHash algorithm (the portable,
seed-fixed variant the seahash crate's `hash()` uses); conformance is pinned
by the crate's documented test vector in tests/test_engine.py.
"""

from __future__ import annotations

import struct

MetricId = int  # u64
SeriesId = int  # u64 (a.k.a. TSID)

_MASK = (1 << 64) - 1
_P = 0x6EED_0E9D_A4D9_4A4F
# Default seeds of seahash::hash (crate src: lib.rs).
_SEEDS = (
    0x16F1_1FE8_9B0D_677C,
    0xB480_A793_D8E6_C86C,
    0x6FE2_E5AA_F078_EBC9,
    0x14F9_94A4_C525_9381,
)


def _diffuse(x: int) -> int:
    x = (x * _P) & _MASK
    x ^= (x >> 32) >> (x >> 60)
    return (x * _P) & _MASK


def seahash(data: bytes) -> int:
    """SeaHash of `data` with the default seeds."""
    a, b, c, d = _SEEDS
    n = len(data)
    # full 8-byte little-endian chunks, round-robin over the four lanes
    full = n & ~7
    lanes = [a, b, c, d]
    i = 0
    lane = 0
    while i < full:
        (chunk,) = struct.unpack_from("<Q", data, i)
        lanes[lane] = _diffuse(lanes[lane] ^ chunk)
        lane = (lane + 1) & 3
        i += 8
    if i < n:
        tail = data[i:] + b"\x00" * (8 - (n - i))
        (chunk,) = struct.unpack_from("<Q", tail, 0)
        lanes[lane] = _diffuse(lanes[lane] ^ chunk)
    a, b, c, d = lanes
    a ^= b
    c ^= d
    a ^= c
    a ^= n
    return _diffuse(a)


def metric_id_of(name: bytes) -> MetricId:
    return seahash(name)


def series_id_of(sorted_label_kvs: bytes) -> SeriesId:
    return seahash(sorted_label_kvs)


def series_key_of(labels: list[tuple[bytes, bytes]]) -> bytes:
    """Canonical series key: sorted `k=v` pairs joined with 0x01 (a byte that
    cannot appear in valid UTF-8 label names per Prometheus rules is not
    guaranteed, so the pairing also length-prefixes to stay injective)."""
    parts = []
    for k, v in sorted(labels):
        parts.append(struct.pack("<I", len(k)) + k + struct.pack("<I", len(v)) + v)
    return b"".join(parts)


def tag_hash_of(key: bytes, value: bytes) -> int:
    """Posting-list key for one tag KV in the inverted index."""
    return seahash(struct.pack("<I", len(key)) + key + value)


def decode_series_key(data: bytes) -> list[tuple[bytes, bytes]]:
    """Inverse of series_key_of (length-prefixed sorted KV pairs)."""
    out = []
    i = 0
    n = len(data)
    while i + 4 <= n:
        (kl,) = struct.unpack_from("<I", data, i)
        i += 4
        k = data[i : i + kl]
        i += kl
        if i + 4 > n:
            break
        (vl,) = struct.unpack_from("<I", data, i)
        i += 4
        v = data[i : i + vl]
        i += vl
        out.append((k, v))
    return out
