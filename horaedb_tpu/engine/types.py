"""Metric-engine id types and the seahash function.

Reference: src/metric_engine/src/types.rs:18-41 — `name_id = hash(name)`,
`series_id = hash(sorted labels)`, hash = seahash. This is a from-scratch
Python implementation of the public SeaHash algorithm (the portable,
seed-fixed variant the seahash crate's `hash()` uses); conformance is pinned
by the crate's documented test vector in tests/test_engine.py.
"""

from __future__ import annotations

import struct

from horaedb_tpu.common.hash import seahash

__all__ = [
    "MetricId", "SeriesId", "seahash", "metric_id_of", "series_id_of",
    "series_key_of", "tag_hash_of", "decode_series_key",
]

MetricId = int  # u64
SeriesId = int  # u64 (a.k.a. TSID)


def metric_id_of(name: bytes) -> MetricId:
    return seahash(name)


def series_id_of(sorted_label_kvs: bytes) -> SeriesId:
    return seahash(sorted_label_kvs)


def series_key_of(labels: list[tuple[bytes, bytes]]) -> bytes:
    """Canonical series key: sorted `k=v` pairs joined with 0x01 (a byte that
    cannot appear in valid UTF-8 label names per Prometheus rules is not
    guaranteed, so the pairing also length-prefixes to stay injective)."""
    parts = []
    for k, v in sorted(labels):
        parts.append(struct.pack("<I", len(k)) + k + struct.pack("<I", len(v)) + v)
    # jaxlint: disable=J018 bounded by one series' label count, not a streaming accumulation
    return b"".join(parts)


def tag_hash_of(key: bytes, value: bytes) -> int:
    """Posting-list key for one tag KV in the inverted index."""
    return seahash(struct.pack("<I", len(key)) + key + value)


def decode_series_key(data: bytes) -> list[tuple[bytes, bytes]]:
    """Inverse of series_key_of (length-prefixed sorted KV pairs)."""
    out = []
    i = 0
    n = len(data)
    while i + 4 <= n:
        (kl,) = struct.unpack_from("<I", data, i)
        i += 4
        k = data[i : i + kl]
        i += kl
        if i + 4 > n:
            break
        (vl,) = struct.unpack_from("<I", data, i)
        i += 4
        v = data[i : i + vl]
        i += vl
        out.append((k, v))
    return out
