"""Query-path admission control: a bounded scheduler in front of the engine.

PRs 5-7 made the WRITE path bounded and degradable; until this module,
every query walked the scan path alone and unbounded — N concurrent
dashboard panels meant N unthrottled kernel dispatches and, under
pressure, an OOM or a hang instead of a 429-class answer. This is the
read-path counterpart of the PR 5 backpressure pattern (Taurus NDP,
arXiv:2506.20010, is the reference for shedding at the serving tier
rather than letting storage/compute absorb unbounded fan-in):

- **Bounded concurrency.** A global in-flight cap plus a per-tenant cap
  (tenant = the `X-Horaedb-Tenant` header; absent = "default"). Excess
  queries wait in per-tenant FIFO queues bounded by `queue_max`; a full
  queue sheds immediately with `UnavailableError` -> 503 + Retry-After.
- **Weighted-fair dequeue.** Start-time fair queuing (stride scheduling):
  each grant advances the tenant's virtual time by 1/weight, and the
  waiter with the smallest virtual time runs next — a heavy tenant's
  burst cannot starve light tenants, and `[metric_engine.query]`
  `tenant_weights` skews capacity deliberately.
- **Stall deadline.** A query queued past `queue_deadline` sheds with
  `UnavailableError` (the PR 5 condition-variable/stall pattern on the
  read side); one queued past its OWN end-to-end deadline raises
  `DeadlineExceeded` -> 504 without ever occupying a slot.
- **Cost-aware gate.** `CostModel` estimates a query's device cost
  before admission: an EWMA of measured per-grid-cell seconds fed back
  by finished queries, plus — for a grid shape this process has not
  compiled yet — the measured mean compile cost of the scan kernels
  from the PR 4 xprof kernel catalog. The estimate rides the admission
  verdict into EXPLAIN; `max_cost_s > 0` turns it into a hard gate
  (shed reason="cost").
- **Cancellation.** A client disconnect raises CancelledError into the
  handler (aiohttp `handler_cancellation`); the slot frees itself —
  queued OR running — marks the trace cancelled, and counts
  `horaedb_query_shed_total{reason="client_disconnect"}`.

Observability: `horaedb_query_inflight` / `horaedb_query_queued` gauges,
`horaedb_query_shed_total{reason}`, `horaedb_query_deadline_exceeded_
total`, queue wait as `stage="queue_wait"` in the scan-stage histogram
(and therefore in EXPLAIN's `stages_s` and the slow-query flight
recorder), and the full admission verdict in EXPLAIN.

jaxlint J011 enforces the funnel: server handlers reach `engine.query` /
`engine.query_exemplars` ONLY through :func:`run_query` /
:func:`run_query_exemplars` here — a handler calling the engine directly
would silently bypass every bound above.

Event-loop-confined like the flush executor: no locks, all state mutates
between awaits.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque

from horaedb_tpu.common import deadline as deadline_ctx
from horaedb_tpu.common import tracing, xprof
from horaedb_tpu.common.error import DeadlineExceeded, UnavailableError
from horaedb_tpu.server.metrics import GLOBAL_METRICS
from horaedb_tpu.storage import scanstats
# per-tenant usage accounting rides the J015 metering funnel — the
# admission scheduler is the layer that KNOWS the tenant, so queue
# waits, sheds, deadline hits, and admitted-query counts meter here
from horaedb_tpu.telemetry.metering import GLOBAL_METER

QUERY_INFLIGHT = GLOBAL_METRICS.gauge(
    "horaedb_query_inflight",
    help="Queries currently holding an admission slot (running a scan). "
         "Bounded by [metric_engine.query] max_concurrent.",
)
QUERY_QUEUED = GLOBAL_METRICS.gauge(
    "horaedb_query_queued",
    help="Queries waiting in the admission queue for a slot. Bounded by "
         "[metric_engine.query] queue_max; a full queue sheds 503s.",
)
QUERY_SHED = GLOBAL_METRICS.counter(
    "horaedb_query_shed_total",
    help="Queries shed by the admission scheduler, by reason: queue_full "
         "(bounded queue at capacity), stall (queued past queue_deadline), "
         "client_disconnect (caller went away — queued or mid-scan), cost "
         "(estimated device cost above max_cost_s), forced (admin hook).",
    labelnames=("reason",),
)
QUERY_DEADLINE_EXCEEDED = GLOBAL_METRICS.counter(
    "horaedb_query_deadline_exceeded_total",
    help="Queries that ran out of their end-to-end deadline "
         "(common/deadline.py) — queued or mid-scan — and answered 504.",
)

SHED_REASONS = ("queue_full", "stall", "client_disconnect", "cost", "forced")
for _r in SHED_REASONS:
    QUERY_SHED.labels(_r)
del _r
# queue wait is a first-class scan stage: /metrics histogram, EXPLAIN
# stages_s, and the flight recorder all see it without extra plumbing
scanstats.STAGE_SECONDS.labels("queue_wait")

# the scan-path kernels whose catalog entries (common/xprof.py) feed the
# cost model's compile-cost prior
SCAN_KERNELS = (
    "sharded_downsample", "multisegment_downsample", "scan_kernel",
    "packed_merge", "block_sum_count",
)


class CostModel:
    """Device-cost estimator for the admission gate.

    Two measured signals, no magic constants in steady state:

    - *execute*: an EWMA of observed seconds-per-grid-cell, fed back by
      every finished admitted query (`observe`), seeded with a
      conservative 50 M cells/s cold-start rate;
    - *compile*: a grid shape (power-of-two cell-count class — the same
      granularity XLA retraces at) this process has not run yet will pay
      an XLA compile on top; the xprof kernel catalog (PR 4) supplies
      the measured mean compile seconds of the scan kernels.

    `estimate_s(None)` (raw/unsized queries) returns None — the gate
    only prices the grid-shaped queries whose cost is predictable.

    Batched-sample attribution (server/batching.py): a query that rode a
    stacked launch of N measured ~the GROUP's wall — observing that as a
    solo sample would feed the EWMA N amortized walls per launch and
    bias the cost gate optimistic for future SOLO queries. `observe`
    with `batched_with=N > 1` therefore records cost/N into a SEPARATE
    batched EWMA (observability: how cheap does coalescing make a cell)
    and leaves the solo EWMA, the gate's input, and the compiled-shape
    set untouched (the stacked kernel compiled a stacked shape, not this
    query's solo shape)."""

    PER_CELL_SEED = 2e-8  # 50M cells/s
    MAX_SHAPES = 1024

    def __init__(self, alpha: float = 0.2):
        self._alpha = float(alpha)
        self._per_cell = self.PER_CELL_SEED
        self._per_cell_batched: float | None = None
        self._shapes: set[int] = set()

    @staticmethod
    def _shape_class(cells: int) -> int:
        return max(1, int(cells)).bit_length()

    @staticmethod
    def compile_cost_s() -> float:
        """Measured mean compile seconds of the scan kernels (0.0 until
        the catalog has seen one compile)."""
        entries = xprof.kernel_entries(SCAN_KERNELS)
        compiles = sum(e.get("compiles", 0) for e in entries)
        if not compiles:
            return 0.0
        return sum(e.get("compile_seconds", 0.0) for e in entries) / compiles

    @property
    def per_cell_s(self) -> float:
        return self._per_cell

    @property
    def per_cell_batched_s(self) -> "float | None":
        """Amortized per-cell seconds under stacked launches (None until
        the first batched sample). Observability only — the admission
        gate prices SOLO execution, the pessimistic bound."""
        return self._per_cell_batched

    def estimate_s(self, cells: int | None) -> float | None:
        if not cells or cells <= 0:
            return None
        est = cells * self._per_cell
        if self._shape_class(cells) not in self._shapes:
            est += self.compile_cost_s()
        return est

    def observe(self, cells: int | None, seconds: float,
                batched_with: int = 1) -> None:
        """Feed one finished query's measured wall (excluding queue wait)
        back into the EWMA. `batched_with > 1` = the wall covers a
        stacked launch shared by that many queries: the amortized share
        (seconds / batched_with) feeds the batched EWMA only — the solo
        EWMA and the compiled-shape set stay unpolluted (class
        docstring)."""
        if not cells or cells <= 0 or seconds <= 0:
            return
        if batched_with > 1:
            share = (seconds / batched_with) / cells
            if self._per_cell_batched is None:
                self._per_cell_batched = share
            else:
                self._per_cell_batched += self._alpha * (
                    share - self._per_cell_batched
                )
            return
        if len(self._shapes) >= self.MAX_SHAPES:
            self._shapes.clear()
        self._shapes.add(self._shape_class(cells))
        self._per_cell += self._alpha * (seconds / cells - self._per_cell)


class _Waiter:
    __slots__ = ("tenant", "fut", "enq_t")

    def __init__(self, tenant: str, fut: asyncio.Future, enq_t: float):
        self.tenant = tenant
        self.fut = fut
        self.enq_t = enq_t


class AdmissionSlot:
    """One query's admission: `async with controller.slot(...)`.

    After exit the verdict fields stay readable — the handler embeds
    them in EXPLAIN (`verdict()`)."""

    __slots__ = ("_ctl", "tenant", "cells", "cost_estimate_s",
                 "queue_wait_s", "queued", "_granted", "_t_run")

    def __init__(self, ctl: "AdmissionController", tenant: str,
                 cells: int | None):
        self._ctl = ctl
        self.tenant = tenant
        self.cells = cells
        self.cost_estimate_s: float | None = None
        self.queue_wait_s = 0.0
        self.queued = False
        self._granted = False
        self._t_run: float | None = None

    async def __aenter__(self) -> "AdmissionSlot":
        await self._ctl._acquire(self)
        self._granted = True
        self._t_run = self._ctl._clock()
        return self

    async def __aexit__(self, et, e, tb) -> bool:
        if self._granted:
            self._granted = False
            if (
                et is None and self.cells and self._t_run is not None
            ):
                # stacked-launch attribution: batched_with rides the scan
                # collector (server/batching.py notes it) — amortized
                # samples must not pollute the solo EWMA the gate prices
                self._ctl.cost_model.observe(
                    self.cells, self._ctl._clock() - self._t_run,
                    batched_with=scanstats.get_note("batched_with") or 1,
                )
            self._ctl._do_release(self.tenant)
        if et is not None and issubclass(et, asyncio.CancelledError):
            # client disconnect mid-scan: the slot is already freed above;
            # mark the trace and count the shed before the cancellation
            # unwinds the handler
            QUERY_SHED.labels("client_disconnect").inc()
            GLOBAL_METER.account(self.tenant, sheds=1)
            tracing.add_attr(cancelled=True)
        elif e is not None and isinstance(e, DeadlineExceeded):
            QUERY_DEADLINE_EXCEEDED.inc()
            GLOBAL_METER.account(self.tenant, deadline_hits=1)
        return False

    def verdict(self) -> dict:
        """The admission story EXPLAIN embeds (and the flight recorder
        spools): was this query queued, for how long, at what estimated
        cost, against what load."""
        return {
            "admitted": True,
            "tenant": self.tenant,
            "queued": self.queued,
            "queue_wait_s": round(self.queue_wait_s, 6),
            "cost_estimate_s": (
                round(self.cost_estimate_s, 9)
                if self.cost_estimate_s is not None else None
            ),
            "inflight": self._ctl.inflight,
            "queued_now": self._ctl.queued,
        }


class AdmissionController:
    """The bounded query scheduler (module docstring has the contract)."""

    def __init__(
        self,
        max_concurrent: int = 8,
        max_per_tenant: int = 0,
        queue_max: int = 64,
        queue_deadline_s: float = 5.0,
        max_cost_s: float = 0.0,
        weights: dict | None = None,
        cost_model: CostModel | None = None,
        clock=time.monotonic,
    ):
        self.max_concurrent = max(1, int(max_concurrent))
        # 0 = per-tenant cap equals the global cap (no extra restriction)
        self.max_per_tenant = max(0, int(max_per_tenant))
        self.queue_max = max(0, int(queue_max))
        self.queue_deadline_s = float(queue_deadline_s)
        self.max_cost_s = float(max_cost_s)
        self.cost_model = cost_model or CostModel()
        self._weights = {str(k): float(v) for k, v in (weights or {}).items()}
        self._clock = clock
        self._inflight = 0
        self._inflight_by: dict[str, int] = {}
        self._queues: dict[str, deque[_Waiter]] = {}
        self._queued = 0
        # start-time fair queuing state: per-tenant virtual time + the
        # global virtual clock (the vtime of the last grant)
        self._vtime: dict[str, float] = {}
        self._vclock = 0.0
        self._forced_full = False
        QUERY_INFLIGHT.set(0)
        QUERY_QUEUED.set(0)

    # -- introspection / admin ----------------------------------------------
    @property
    def inflight(self) -> int:
        return self._inflight

    @property
    def queued(self) -> int:
        return self._queued

    def weight(self, tenant: str) -> float:
        return max(self._weights.get(tenant, 1.0), 1e-6)

    def force_full(self, on: bool = True) -> None:
        """Admin/test hook (the smoke gate uses it to prove the 503
        shedding path without generating real overload): while forced,
        every admission sheds immediately with reason="forced"."""
        self._forced_full = bool(on)

    def reset_forced(self) -> None:
        self.force_full(False)

    # -- the slot protocol ---------------------------------------------------
    def slot(self, tenant: str = "default", cells: int | None = None) -> AdmissionSlot:
        """An async context manager admitting one query. `cells` sizes the
        cost estimate (grid cells for downsample/PromQL-range shapes;
        None for raw queries — unpriced)."""
        return AdmissionSlot(self, tenant, cells)

    def _tenant_cap(self) -> int:
        return self.max_per_tenant or self.max_concurrent

    def _headroom(self, tenant: str) -> bool:
        if self._inflight >= self.max_concurrent:
            return False
        return self._inflight_by.get(tenant, 0) < self._tenant_cap()

    async def _acquire(self, slot: AdmissionSlot) -> None:
        if self._forced_full:
            QUERY_SHED.labels("forced").inc()
            GLOBAL_METER.account(slot.tenant, sheds=1)
            raise UnavailableError(
                "query admission forced full (admin hook)",
                retry_after_s=1.0,
            )
        est = self.cost_model.estimate_s(slot.cells)
        slot.cost_estimate_s = est
        if self.max_cost_s > 0 and est is not None and est > self.max_cost_s:
            QUERY_SHED.labels("cost").inc()
            GLOBAL_METER.account(slot.tenant, sheds=1)
            raise UnavailableError(
                f"query estimated device cost {est:.3f}s exceeds "
                f"max_cost_s={self.max_cost_s:g} "
                f"({slot.cells} grid cells); narrow the range or coarsen "
                f"the step",
                retry_after_s=1.0,
            )
        d = deadline_ctx.current()
        if d is not None and d.expired():
            # arrived already out of budget: 504 without touching a slot
            QUERY_DEADLINE_EXCEEDED.inc()
            GLOBAL_METER.account(slot.tenant, deadline_hits=1)
            d.check("admission")
        if self._queued == 0 and self._headroom(slot.tenant):
            self._grant_counts(slot.tenant)
            GLOBAL_METER.account(slot.tenant, queries=1)
            return
        if self._queued >= self.queue_max:
            QUERY_SHED.labels("queue_full").inc()
            GLOBAL_METER.account(slot.tenant, sheds=1)
            raise UnavailableError(
                f"query queue full ({self._queued} queued, "
                f"{self._inflight} in flight, cap {self.max_concurrent})",
                retry_after_s=max(min(self.queue_deadline_s, 5.0), 1.0),
            )
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        w = _Waiter(slot.tenant, fut, self._clock())
        self._queues.setdefault(slot.tenant, deque()).append(w)
        self._queued += 1
        QUERY_QUEUED.set(self._queued)
        # headroom may already exist (per-tenant cap freed, or the queue
        # was empty a moment ago): dispatch now, never rely on a release
        self._dispatch()
        timeout = self.queue_deadline_s
        rem = deadline_ctx.remaining_s()
        if rem is not None:
            timeout = min(timeout, max(rem, 0.0))
        try:
            await asyncio.wait_for(fut, timeout=timeout)
        except asyncio.TimeoutError:
            if not (fut.done() and not fut.cancelled()
                    and fut.exception() is None):
                # not granted: leave the queue and shed
                self._remove_waiter(w)
                wait = self._clock() - w.enq_t
                scanstats.record("queue_wait", wait)
                GLOBAL_METER.account(slot.tenant, queue_wait_seconds=wait)
                if d is not None and d.expired():
                    QUERY_DEADLINE_EXCEEDED.inc()
                    GLOBAL_METER.account(slot.tenant, deadline_hits=1)
                    d.check("admission_queue")
                QUERY_SHED.labels("stall").inc()
                GLOBAL_METER.account(slot.tenant, sheds=1)
                raise UnavailableError(
                    f"query stalled {wait:.2f}s in the admission queue "
                    f"({self._inflight} in flight, cap "
                    f"{self.max_concurrent}); shedding",
                    retry_after_s=max(min(self.queue_deadline_s, 5.0), 1.0),
                ) from None
            # granted in the timeout race: fall through and use the slot
        except asyncio.CancelledError:
            # client went away while queued (or while granted-but-not-
            # observed): free whatever we hold, count, and unwind
            if fut.done() and not fut.cancelled() and fut.exception() is None:
                self._do_release(slot.tenant)
            else:
                self._remove_waiter(w)
            QUERY_SHED.labels("client_disconnect").inc()
            GLOBAL_METER.account(slot.tenant, sheds=1)
            tracing.add_attr(cancelled=True)
            raise
        slot.queued = True
        slot.queue_wait_s = self._clock() - w.enq_t
        scanstats.record("queue_wait", slot.queue_wait_s)
        scanstats.note("admission_queued")
        GLOBAL_METER.account(slot.tenant, queries=1,
                             queue_wait_seconds=slot.queue_wait_s)

    def _remove_waiter(self, w: _Waiter) -> None:
        q = self._queues.get(w.tenant)
        if q is not None:
            try:
                q.remove(w)
            except ValueError:
                return  # already dispatched/cleaned
            self._queued -= 1
            QUERY_QUEUED.set(self._queued)
            if not q:
                del self._queues[w.tenant]

    def _grant_counts(self, tenant: str) -> None:
        self._inflight += 1
        self._inflight_by[tenant] = self._inflight_by.get(tenant, 0) + 1
        QUERY_INFLIGHT.set(self._inflight)
        # stride accounting: lagging/new tenants start at the virtual
        # clock (no banked credit), each grant costs 1/weight
        vt = max(self._vtime.get(tenant, 0.0), self._vclock)
        self._vclock = vt
        self._vtime[tenant] = vt + 1.0 / self.weight(tenant)
        if len(self._vtime) > 4096:  # bounded tenant-state memory
            self._vtime = {
                t: v for t, v in self._vtime.items()
                if t in self._queues or t in self._inflight_by
            }

    def _do_release(self, tenant: str) -> None:
        self._inflight -= 1
        n = self._inflight_by.get(tenant, 1) - 1
        if n <= 0:
            self._inflight_by.pop(tenant, None)
        else:
            self._inflight_by[tenant] = n
        QUERY_INFLIGHT.set(self._inflight)
        self._dispatch()

    def _dispatch(self) -> None:
        """Grant queued waiters while headroom exists: weighted-fair
        across tenants (smallest virtual time first; ties break on the
        tenant name for determinism), FIFO within a tenant."""
        while self._queued:
            best = None
            for tenant in sorted(self._queues):
                q = self._queues[tenant]
                while q and q[0].fut.done():
                    # abandoned (timed out / cancelled) head: drop it
                    q.popleft()
                    self._queued -= 1
                if not q:
                    continue
                if not self._headroom(tenant):
                    continue
                vt = max(self._vtime.get(tenant, 0.0), self._vclock)
                if best is None or vt < best[0]:
                    best = (vt, tenant, q)
            if best is None:
                break
            _, tenant, q = best
            w = q.popleft()
            self._queued -= 1
            if not q:
                del self._queues[tenant]
            self._grant_counts(tenant)
            w.fut.set_result(None)
        # empty-queue cleanup for tenants whose abandoned heads drained
        for t in [t for t, q in self._queues.items() if not q]:
            del self._queues[t]
        QUERY_QUEUED.set(self._queued)


# ---------------------------------------------------------------------------
# the sanctioned engine entry points (jaxlint J011 funnel)
# ---------------------------------------------------------------------------


async def run_query(controller: AdmissionController, engine, req, *,
                    tenant: str = "default", cells: int | None = None):
    """Admit, then run `engine.query(req)` under the slot. Returns
    (result, slot) — the slot's verdict feeds EXPLAIN. The ONLY route
    from a server handler to the engine's query surface (jaxlint J011)."""
    slot = controller.slot(tenant, cells=cells)
    async with slot:
        result = await engine.query(req)
    return result, slot


async def run_query_exemplars(controller: AdmissionController, engine, req, *,
                              tenant: str = "default"):
    """Admitted `engine.query_exemplars(req)` (see run_query)."""
    slot = controller.slot(tenant)
    async with slot:
        result = await engine.query_exemplars(req)
    return result, slot


async def run_query_partials(controller: AdmissionController, engine, req, *,
                             tenant: str = "default",
                             cells: int | None = None):
    """Admitted `engine.query_partial_grids(req)` (see run_query) — the
    distributed scatter-gather leaf: every node computing a fragment
    admits it through its OWN scheduler, so a split query costs each
    node a slot sized to its region subset, exactly like a local one."""
    slot = controller.slot(tenant, cells=cells)
    async with slot:
        result = await engine.query_partial_grids(req)
    return result, slot


def parse_timeout_s(raw, default_s: float, max_s: float) -> float:
    """Prometheus-style per-request deadline override: `timeout=` as
    float seconds ("2.5") or a duration string ("30s", "1m30s").
    Clamped to (0, max_s]; absent/empty -> the config default (itself
    clamped, so a misconfigured default cannot exceed the cap).
    Raises ValueError on garbage (the handlers' 400 path)."""
    import math

    if raw is None or raw == "":
        return min(default_s, max_s)
    s = str(raw)
    try:
        secs = float(s)
    except ValueError:
        from horaedb_tpu.promql import parse_duration_ms

        secs = parse_duration_ms(s) / 1000.0
    # non-finite values must be rejected, not clamped: NaN compares False
    # against everything, so it would slip past BOTH this check and every
    # downstream `elapsed >= budget` — a never-expiring deadline holding
    # an admission slot forever
    if not math.isfinite(secs) or secs <= 0:
        raise ValueError(f"timeout must be a positive finite duration, "
                         f"got {raw!r}")
    return min(secs, max_s)
