"""Server metrics registry.

The reference has logging but NO metrics endpoint (SURVEY §5.5 — DataFusion's
metrics set is accepted but unused); the survey explicitly tells the TPU
build to do better. Minimal dependency-free counters exposed in Prometheus
text format at /metrics.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict


class Metrics:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._start = time.time()

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._counters[name] = value

    def render(self) -> str:
        with self._lock:
            lines = [
                "# TYPE horaedb_uptime_seconds gauge",
                f"horaedb_uptime_seconds {time.time() - self._start:.1f}",
            ]
            for name in sorted(self._counters):
                lines.append(f"{name} {self._counters[name]:g}")
        return "\n".join(lines) + "\n"


GLOBAL_METRICS = Metrics()
