"""Metrics registry: counters, gauges, and fixed-bucket histograms.

The reference has logging but NO metrics endpoint (SURVEY §5.5 —
DataFusion's metrics set is accepted but unused); the survey explicitly
tells the TPU build to do better. This module is the process-wide registry
every layer (ingest, flush, scan, compaction, HTTP) reports into, rendered
in the Prometheus text exposition format at /metrics.

Dependency-free by design: storage/, engine/, and parallel/ import it, so
it must never pull in aiohttp, jax, or anything above common/.

API:

    H = GLOBAL_METRICS.histogram("horaedb_scan_stage_seconds",
                                 help="per-stage scan time",
                                 labelnames=("stage",))
    H.labels("io_decode").observe(0.012)

    C = GLOBAL_METRICS.counter("horaedb_queries_total")
    C.inc()

Legacy string API (`METRICS.inc('name{label="v"}')`) keeps working: the
embedded label form parses into a labeled child so the seed's call sites
render with correct `# TYPE` metadata and escaped label values.
"""

from __future__ import annotations

import bisect
import re
import threading
import time

__all__ = [
    "Metrics", "CounterFamily", "GaugeFamily", "HistogramFamily",
    "GLOBAL_METRICS", "DEFAULT_BUCKETS", "set_exemplar_source",
    "OPENMETRICS_CONTENT_TYPE",
]

OPENMETRICS_CONTENT_TYPE = "application/openmetrics-text"

# Exemplar source: a zero-arg callable returning the active trace id (or
# None outside a trace). Injected — NOT imported — so this module stays
# dependency-free (storage/ and parallel/ import it); the telemetry
# package wires it to common/tracing.current_trace_id at import.
_exemplar_source = None


def set_exemplar_source(fn) -> None:
    global _exemplar_source
    _exemplar_source = fn

# Prometheus' classic latency buckets (seconds); wide enough to cover a
# sub-ms device dispatch and a multi-second compaction in one family.
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)

# Size buckets (bytes): 4 KiB .. 4 GiB in 8x steps.
BYTES_BUCKETS = tuple(float(4096 * 8 ** i) for i in range(7))

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LEGACY_RE = re.compile(r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
                        r"(?:\{(?P<labels>.*)\})?$")
_LEGACY_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def escape_label_value(v: str) -> str:
    """Escape a label value per the Prometheus text format: backslash,
    double-quote, and newline (in that order — backslash first)."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


_UNESCAPE_RE = re.compile(r"\\(.)")


def _unescape_label_value(v: str) -> str:
    """Single-pass inverse of escape_label_value: sequential .replace()
    calls would let an escaped backslash donate its second character to a
    following escape (`a\\\\nb` — literal backslash + n — must not decode
    to a newline)."""
    return _UNESCAPE_RE.sub(
        lambda m: "\n" if m.group(1) == "n" else m.group(1), v
    )


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    """Sample value formatting: integers render bare (1 not 1.0)."""
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if isinstance(v, float) and v.is_integer() and abs(v) < 2 ** 53:
        return str(int(v))
    return repr(v)


def _label_str(items: tuple[tuple[str, str], ...]) -> str:
    if not items:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"' for k, v in items)
    return "{" + inner + "}"


class _Family:
    """One metric family: a name, a type, and children keyed by their
    label items tuple ``((name, value), ...)``."""

    TYPE = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple[str, ...]):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name: {name!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}

    def labels(self, *values, **kw):
        if kw:
            if values:
                raise ValueError("pass label values positionally OR by name")
            values = tuple(kw[n] for n in self.labelnames)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {values!r}"
            )
        key = tuple(zip(self.labelnames, (str(v) for v in values)))
        return self._child(key)

    def _child(self, key: tuple):
        with self._lock:
            c = self._children.get(key)
            if c is None:
                c = self._make_child()
                self._children[key] = c
            return c

    def _make_child(self):
        raise NotImplementedError

    # -- label-less convenience: the family IS its only child ---------------
    def _default(self):
        if self.labelnames:
            raise ValueError(f"{self.name} requires labels {self.labelnames}")
        return self._child(())

    def samples(self) -> list[tuple[str, tuple, float]]:
        """(suffix, label items, value) triples for render()."""
        out = []
        with self._lock:
            items = sorted(self._children.items())
        for key, child in items:
            out.extend(child.rows(key))
        return out


class _CounterChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, value: float = 1.0) -> None:
        with self._lock:
            self._value += value

    @property
    def value(self) -> float:
        return self._value

    def rows(self, key):
        return [("", key, self._value)]


class _GaugeChild(_CounterChild):
    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def dec(self, value: float = 1.0) -> None:
        self.inc(-value)


class CounterFamily(_Family):
    TYPE = "counter"

    def _make_child(self):
        return _CounterChild()

    def inc(self, value: float = 1.0) -> None:
        self._default().inc(value)

    @property
    def value(self) -> float:
        return self._default().value


class GaugeFamily(_Family):
    TYPE = "gauge"

    def _make_child(self):
        return _GaugeChild()

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, value: float = 1.0) -> None:
        self._default().inc(value)

    def dec(self, value: float = 1.0) -> None:
        self._default().dec(value)

    @property
    def value(self) -> float:
        return self._default().value


class _HistogramChild:
    __slots__ = ("_bounds", "_counts", "_sum", "_lock", "_ex")

    def __init__(self, bounds: tuple[float, ...], exemplars: bool = False):
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last slot = +Inf
        self._sum = 0.0
        self._lock = threading.Lock()
        # per-bucket latest exemplar (labels, value, unix seconds) — only
        # allocated on exemplar-enabled families (route/scan/flush latency)
        self._ex: "list | None" = [None] * (len(bounds) + 1) if exemplars \
            else None

    def time(self) -> "_Timer":
        """Context manager observing the block's wall time."""
        return _Timer(self)

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self._bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
        if self._ex is not None and _exemplar_source is not None:
            tid = _exemplar_source()
            if tid:
                # one tuple store under the GIL; rendering snapshots the
                # tuple, never the mutating list slot
                self._ex[i] = ({"trace_id": str(tid)}, value, time.time())

    def exemplars(self) -> "list":
        """Per-bucket (labels, value, ts) snapshot, index-aligned with
        the bounds (+Inf last); empty when the family is not
        exemplar-enabled."""
        return list(self._ex) if self._ex is not None else []

    @property
    def count(self) -> int:
        return sum(self._counts)

    @property
    def sum(self) -> float:
        return self._sum

    def _snapshot(self) -> tuple[list[int], float]:
        """Counts + sum under ONE lock acquisition: a render racing an
        observe must never emit `_count` != the +Inf bucket (the validator
        — and Prometheus quantile math — treat that as corruption)."""
        with self._lock:
            return list(self._counts), self._sum

    def cumulative(self) -> list[tuple[float, int]]:
        """(upper bound, cumulative count) pairs, ending at +Inf."""
        counts, _ = self._snapshot()
        out, acc = [], 0
        for b, c in zip(self._bounds, counts):
            acc += c
            out.append((b, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out

    def rows(self, key):
        counts, total_sum = self._snapshot()
        out, acc = [], 0
        for b, c in zip(self._bounds, counts):
            acc += c
            out.append(("_bucket", key + (("le", _fmt(float(b))),), float(acc)))
        total = acc + counts[-1]
        out.append(("_bucket", key + (("le", "+Inf"),), float(total)))
        out.append(("_sum", key, total_sum))
        out.append(("_count", key, float(total)))
        return out


class HistogramFamily(_Family):
    TYPE = "histogram"

    def __init__(self, name, help, labelnames, buckets, exemplars=False):
        super().__init__(name, help, labelnames)
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError(f"{name}: histogram needs at least one bucket")
        if bounds and bounds[-1] == float("inf"):
            bounds = bounds[:-1]  # +Inf is implicit
        self.buckets = bounds
        self.exemplars_enabled = bool(exemplars)

    def _make_child(self):
        return _HistogramChild(self.buckets, exemplars=self.exemplars_enabled)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def time(self) -> "_Timer":
        """Context manager observing the block's wall time (label-less)."""
        return self._default().time()


class _Timer:
    __slots__ = ("_child", "_t0")

    def __init__(self, child: _HistogramChild):
        self._child = child

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._child.observe(time.perf_counter() - self._t0)
        return False


class Metrics:
    """Process-wide registry. Families register once (idempotent: the same
    (name, type) returns the existing family; a type conflict raises)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}
        self._start = time.time()

    # -- registration --------------------------------------------------------
    def _register(self, cls, name, help, labelnames, eager_default=True,
                  **kw) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {fam.TYPE}"
                    )
                return fam
            fam = cls(name, help, tuple(labelnames), **kw)
            self._families[name] = fam
        if eager_default and not fam.labelnames:
            # a label-less family has exactly one child: create it now so
            # the family renders its zero state from boot (scrapers see the
            # series exist before the first event). Legacy LABELED names
            # suppress this — their family is declared label-less but every
            # real series carries labels, and an eager () child would be a
            # phantom unlabeled 0 series on /metrics.
            fam._child(())
        return fam

    def counter(self, name: str, help: str = "",
                labelnames: tuple[str, ...] = ()) -> CounterFamily:
        return self._register(CounterFamily, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: tuple[str, ...] = ()) -> GaugeFamily:
        return self._register(GaugeFamily, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: tuple[str, ...] = (),
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                  exemplars: bool = False) -> HistogramFamily:
        """`exemplars=True` stores the latest (trace_id, value, ts) per
        bucket when an exemplar source is wired (set_exemplar_source) —
        rendered only in the OpenMetrics exposition."""
        return self._register(HistogramFamily, name, help, labelnames,
                              buckets=buckets, exemplars=exemplars)

    def get(self, name: str) -> _Family | None:
        with self._lock:
            return self._families.get(name)

    # -- legacy string API ---------------------------------------------------
    def _legacy_child(self, cls, name: str):
        m = _LEGACY_RE.match(name)
        if m is None:
            raise ValueError(f"invalid metric name: {name!r}")
        fam_name = m.group("name")
        raw = m.group("labels")
        pairs: tuple[tuple[str, str], ...] = ()
        if raw:
            pairs = tuple(
                (k, _unescape_label_value(v))
                for k, v in _LEGACY_PAIR_RE.findall(raw)
            )
        fam = self._register(cls, fam_name, "", (),
                             eager_default=not pairs)
        # legacy children bypass labelnames: key directly by the pairs, so
        # one family may hold heterogeneous label sets (table gauges do)
        return fam._child(pairs)

    def inc(self, name: str, value: float = 1.0) -> None:
        """Legacy: counter increment; `name` may embed `{k="v"}` labels."""
        self._legacy_child(CounterFamily, name).inc(value)

    def set(self, name: str, value: float) -> None:
        """Legacy: gauge set; `name` may embed `{k="v"}` labels."""
        self._legacy_child(GaugeFamily, name).set(value)

    # -- snapshots (self-scrape collector) -----------------------------------
    def snapshot_samples(self) -> list[tuple[str, str, str, tuple, float]]:
        """(family, type, sample_name, label items, value) for every
        sample the text exposition would render (histograms exploded to
        _bucket/_sum/_count, cumulative counts, `le` formatted exactly as
        render() prints it). The self-scrape collector's source of truth:
        a PromQL query over a self-written series must return values
        bit-equal to this snapshot at the scrape timestamp."""
        with self._lock:
            fams = sorted(self._families.items())
        out = []
        for name, fam in fams:
            for suffix, key, value in fam.samples():
                out.append((name, fam.TYPE, name + suffix, key,
                            float(value)))
        return out

    def federation_snapshot(self) -> list:
        """The JSON-serializable wire shape of snapshot_samples for
        `GET /api/v1/telemetry/snapshot` — what a fleet-telemetry peer
        pulls: [[sample_name, [[label, value]...], value]...]. Family
        and type drop out (the puller relabels and writes through its
        own ingest path; the text exposition keeps the typed view)."""
        return [
            [sample, [[k, v] for k, v in key], value]
            for _family, _type, sample, key, value in
            self.snapshot_samples()
        ]

    # -- rendering -----------------------------------------------------------
    def render(self) -> str:
        lines = [
            "# HELP horaedb_uptime_seconds Seconds since process start.",
            "# TYPE horaedb_uptime_seconds gauge",
            f"horaedb_uptime_seconds {time.time() - self._start:.1f}",
        ]
        with self._lock:
            fams = sorted(self._families.items())
        for name, fam in fams:
            if fam.help:
                lines.append(f"# HELP {name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {name} {fam.TYPE}")
            for suffix, key, value in fam.samples():
                lines.append(f"{name}{suffix}{_label_str(key)} {_fmt(value)}")
        return "\n".join(lines) + "\n"

    def render_openmetrics(self) -> str:
        """OpenMetrics 1.0 exposition (content-negotiated on /metrics):
        counter family names drop the `_total` suffix (the sample keeps
        it), exemplar-enabled histograms append `# {trace_id="..."} v ts`
        to their bucket lines, and the body terminates with `# EOF`. A
        counter whose registered name lacks `_total` cannot be spelled as
        an OpenMetrics counter — it renders as `unknown` (tools/
        promcheck.py --openmetrics enforces the grammar)."""
        lines = [
            "# TYPE horaedb_uptime_seconds gauge",
            f"horaedb_uptime_seconds {time.time() - self._start:.1f}",
        ]
        with self._lock:
            fams = sorted(self._families.items())
        for name, fam in fams:
            if fam.TYPE == "counter":
                conformant = name.endswith("_total")
                base = name[:-len("_total")] if conformant else name
                om_type = "counter" if conformant else "unknown"
            else:
                base, om_type = name, fam.TYPE
            if fam.help:
                lines.append(f"# HELP {base} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {base} {om_type}")
            if fam.TYPE != "histogram":
                for suffix, key, value in fam.samples():
                    lines.append(
                        f"{name}{suffix}{_label_str(key)} {_fmt(value)}"
                    )
                continue
            with fam._lock:
                items = sorted(fam._children.items())
            for key, child in items:
                counts, total_sum = child._snapshot()
                exs = child.exemplars()
                bounds = list(child._bounds) + [float("inf")]
                acc = 0
                for j, b in enumerate(bounds):
                    acc += counts[j]
                    line = (
                        f"{name}_bucket"
                        f"{_label_str(key + (('le', _fmt(float(b))),))} "
                        f"{_fmt(float(acc))}"
                    )
                    ex = exs[j] if j < len(exs) else None
                    if ex is not None:
                        line += _exemplar_str(ex)
                    lines.append(line)
                lines.append(f"{name}_sum{_label_str(key)} {_fmt(total_sum)}")
                lines.append(
                    f"{name}_count{_label_str(key)} {_fmt(float(acc))}"
                )
        lines.append("# EOF")
        return "\n".join(lines) + "\n"


def _exemplar_str(ex: tuple) -> str:
    """` # {trace_id="..."} value timestamp` (OpenMetrics exemplar)."""
    labels, value, ts = ex
    items = tuple((str(k), str(v)) for k, v in labels.items())
    return f" # {_label_str(items)} {_fmt(float(value))} {ts:.3f}"


GLOBAL_METRICS = Metrics()
