"""HTTP shedding for the failure taxonomy (common/error.py).

The graceful-degradation contract: when the object store is down
(circuit breaker open, retry budget exhausted) or this process is
overloaded (flush queue stalled past its deadline), HTTP writes and
queries answer **503 + Retry-After** with bounded latency — never a
hang, never a silent drop, and never a 4xx (remote-write senders retry
5xx but permanently DROP batches on 4xx, so the status code IS the
durability contract).

`Retry-After` comes from the error itself when the breaker knows how
long it stays open (`UnavailableError.retry_after_s`), else a small
default — enough to decorrelate a sender fleet without stalling it.
"""

from __future__ import annotations

import math

from aiohttp import web

from horaedb_tpu.common.error import UnavailableError

# fallback Retry-After when the error carries no hint (seconds)
DEFAULT_RETRY_AFTER_S = 1


def retry_after_seconds(e: BaseException) -> int:
    """Integer Retry-After for an unavailable-class error (>= 1: a 0
    would tell well-behaved clients to hammer immediately)."""
    hint = getattr(e, "retry_after_s", None)
    if hint is None or hint <= 0:
        return DEFAULT_RETRY_AFTER_S
    return max(1, math.ceil(hint))


def unavailable_response(
    e: UnavailableError | BaseException, extra: dict | None = None
) -> web.Response:
    """503 + Retry-After for a store-down / overloaded request. `extra`
    merges into the JSON body (e.g. partial-result provenance / EXPLAIN
    for a scan that could not read a required SST)."""
    body = {"error": str(e), "unavailable": True}
    if extra:
        body.update(extra)
    return web.json_response(
        body,
        status=503,
        headers={"Retry-After": str(retry_after_seconds(e))},
    )


def deadline_response(
    e: BaseException,
    progress: dict | None = None,
    extra: dict | None = None,
) -> web.Response:
    """504 for an expired end-to-end query deadline (common/deadline.py).

    Distinct from the 503 shed on purpose: a 503 says "the server is
    overloaded, back off and resend", a 504 says "YOUR budget ran out —
    widen `timeout=` or narrow the query". `progress` carries the
    partial-progress provenance (regions fanned out, SSTs selected/read,
    stage seconds) so the caller sees how far the scan got before the
    budget died; the cooperative checks name WHERE it expired (`at`)."""
    body = {"error": str(e), "deadline_exceeded": True}
    budget = getattr(e, "budget_s", None)
    if budget is not None:
        body["budget_s"] = round(budget, 3)
    elapsed = getattr(e, "elapsed_s", None)
    if elapsed is not None:
        body["elapsed_s"] = round(elapsed, 3)
    at = getattr(e, "at", "")
    if at:
        body["at"] = at
    if progress:
        body["progress"] = progress
    if extra:
        body.update(extra)
    return web.json_response(body, status=504)
