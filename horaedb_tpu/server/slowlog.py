"""Slow-query flight recorder: the N slowest requests, spooled to disk.

The trace ring (/debug/traces) answers "what just happened" but is
bounded and churns under load: by the time an operator investigates last
night's p99 spike, the offending trace is long evicted. This module keeps
the N SLOWEST query requests — full span tree + scanstats + EXPLAIN
payload — as individual JSON files under `<data-root>/slowlog/`, with
bounded rotation (admission = being among the N slowest), served at
GET /debug/slowlog.

Design:
- one file per entry, named `<duration_ms padded>-<trace_id>.json`, so
  the duration ordering is recoverable from the DIRECTORY LISTING alone —
  restart rebuilds the index without parsing a single body, and a corrupt
  body can never corrupt admission;
- admission under a lock: below capacity everything >= min_duration is
  admitted; at capacity a new entry must beat the current fastest kept
  entry, which is evicted (files deleted) — "keeps exactly N";
- reads are forgiving: a corrupt spool file is skipped LOUDLY (WARNING
  log + `horaedb_slowlog_corrupt_total`) and reported in the response
  meta, never a 500 — the flight recorder must stay readable after a
  partial write or a disk hiccup.

Writes happen on the serving path but are one small JSON dump amortized
over requests that were, by admission, already slow; operators who need
zero disk writes set capacity 0 (disabled).
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
from pathlib import Path

from horaedb_tpu.server.metrics import GLOBAL_METRICS

logger = logging.getLogger(__name__)

SLOWLOG_RECORDS = GLOBAL_METRICS.counter(
    "horaedb_slowlog_records_total",
    help="Requests admitted to the slow-query flight recorder.",
)
SLOWLOG_CORRUPT = GLOBAL_METRICS.counter(
    "horaedb_slowlog_corrupt_total",
    help="Unreadable slowlog spool entries skipped on read.",
)
SLOWLOG_ENTRIES = GLOBAL_METRICS.gauge(
    "horaedb_slowlog_entries",
    help="Entries currently kept by the slow-query flight recorder.",
)

# `<duration_ms, zero-padded 12>-<trace_id hex>.json`
_NAME_RE = re.compile(r"^(\d{12})-([0-9a-f]+)\.json$")


def _fname(duration_s: float, trace_id: str) -> str:
    ms = max(0, min(int(duration_s * 1000.0), 10 ** 12 - 1))
    return f"{ms:012d}-{trace_id}.json"


class SlowLog:
    """Bounded slowest-N spool over one directory. Thread-safe; safe to
    share between the event loop and worker threads (the JSON dump is
    small and admission already implies the request was slow)."""

    def __init__(self, directory: str | Path, capacity: int = 32,
                 min_duration_s: float = 0.0):
        self._dir = Path(directory)
        self.capacity = max(0, int(capacity))
        self.min_duration_s = float(min_duration_s)
        self._lock = threading.Lock()
        # trace_id -> (duration_ms, Path); rebuilt from filenames alone
        self._index: dict[str, tuple[int, Path]] = {}
        if self.capacity:
            self._dir.mkdir(parents=True, exist_ok=True)
            self._load()

    # -- startup -----------------------------------------------------------
    def _load(self) -> None:
        """Rebuild the index from filenames (no body parses) and prune to
        capacity — a restart with a smaller configured N keeps the N
        slowest survivors, deleting the rest."""
        for p in self._dir.iterdir():
            m = _NAME_RE.match(p.name)
            if m is None:
                if p.suffix == ".tmp":
                    # a crash between write_text and rename orphans the
                    # temp file; reclaim it instead of leaking one per crash
                    try:
                        p.unlink()
                    except OSError:
                        pass
                elif p.suffix == ".json":
                    logger.warning("slowlog: unrecognized spool file %s "
                                   "(ignored)", p)
                continue
            self._index[m.group(2)] = (int(m.group(1)), p)
        while len(self._index) > self.capacity:
            self._evict_fastest_locked()
        SLOWLOG_ENTRIES.set(len(self._index))

    def _evict_fastest_locked(self) -> None:
        victim = min(self._index, key=lambda t: self._index[t][0])
        _, path = self._index.pop(victim)
        try:
            path.unlink()
        except OSError:
            logger.warning("slowlog: could not delete evicted entry %s", path)

    # -- write path --------------------------------------------------------
    def admit(self, duration_s: float) -> bool:
        """Cheap pre-check: would a request this slow be kept? Takes the
        lock for the index scan — record() in a worker thread mutates the
        dict, and an unlocked iteration could raise mid-scan."""
        if not self.capacity or duration_s < self.min_duration_s:
            return False
        with self._lock:
            if len(self._index) < self.capacity:
                return True
            fastest = min(d for d, _ in self._index.values())
        return duration_s * 1000.0 > fastest

    def record(self, trace_id: str, duration_s: float, entry: dict) -> bool:
        """Admit one finished request. `entry` must be JSON-serializable
        (trace tree + explain payload). Returns whether it was kept."""
        if not self.capacity or duration_s < self.min_duration_s:
            return False
        ms = int(duration_s * 1000.0)
        with self._lock:
            if len(self._index) >= self.capacity:
                fastest = min(d for d, _ in self._index.values())
                if ms <= fastest:
                    return False
            path = self._dir / _fname(duration_s, trace_id)
            try:
                body = json.dumps(entry)
                tmp = path.with_suffix(".tmp")
                tmp.write_text(body)
                tmp.rename(path)  # atomic: readers never see a torn body
            except Exception:  # noqa: BLE001 — a non-serializable attr or a
                # disk error must degrade to "not recorded", never fail the
                # request the middleware is finishing
                logger.warning("slowlog: could not spool entry %s", path,
                               exc_info=True)
                return False
            # same trace_id re-recorded (should not happen — ids are
            # random) keeps the newer file
            old = self._index.pop(trace_id, None)
            if old is not None and old[1] != path:
                try:
                    old[1].unlink()
                except OSError:
                    pass
            self._index[trace_id] = (ms, path)
            while len(self._index) > self.capacity:
                self._evict_fastest_locked()
            SLOWLOG_ENTRIES.set(len(self._index))
        SLOWLOG_RECORDS.inc()
        return True

    # -- read path ---------------------------------------------------------
    def entries(self, limit: int | None = None) -> tuple[list[dict], int]:
        """(entries slowest-first, corrupt-skipped count). Each entry is
        the recorded dict plus `trace_id`/`duration_ms` recovered from the
        filename (authoritative even if the body lies)."""
        with self._lock:
            items = sorted(
                self._index.items(), key=lambda kv: -kv[1][0]
            )
        if limit is not None:
            items = items[:limit]
        out: list[dict] = []
        corrupt = 0
        for trace_id, (ms, path) in items:
            try:
                body = json.loads(path.read_text())
                if not isinstance(body, dict):
                    raise ValueError("spool entry is not a JSON object")
            except FileNotFoundError:
                # a concurrent record() evicted this entry between the
                # index snapshot and the read — healthy churn, not
                # corruption
                continue
            except (OSError, ValueError) as e:
                corrupt += 1
                SLOWLOG_CORRUPT.inc()
                logger.warning("slowlog: skipping corrupt spool entry %s: %s",
                               path, e)
                continue
            body.setdefault("trace_id", trace_id)
            body["duration_ms"] = ms
            out.append(body)
        return out, corrupt

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)


def _span_nodes(span: dict, out: set) -> None:
    attrs = span.get("attrs")
    if isinstance(attrs, dict) and attrs.get("node"):
        out.add(str(attrs["node"]))
    for child in span.get("children") or ():
        if isinstance(child, dict):
            _span_nodes(child, out)


def build_entry(trace_dict: dict, explain: dict | None) -> dict:
    """The spooled payload for one slow request: the full span tree (whose
    root attrs carry the scanstats stages) plus the EXPLAIN plan. The
    plan also sits in the trace ROOT's attrs (the handler attached it
    there for /debug/traces); drop that copy — it is byte-identical to
    the top-level `explain` and would double the spool size.

    `nodes` lists the peer nodes whose grafted span subtrees appear in
    the tree (cross-node traces: router funnel spans and remote spans
    both carry a `node` attr) — "was this slow request slow because of
    a forward" is answerable from the listing without opening the tree."""
    root = trace_dict.get("root")
    if isinstance(root, dict) and isinstance(root.get("attrs"), dict):
        root["attrs"].pop("explain", None)
    nodes: set = set()
    if isinstance(root, dict):
        _span_nodes(root, nodes)
    return {
        "trace_id": trace_dict.get("trace_id"),
        "name": trace_dict.get("name"),
        "duration_s": trace_dict.get("duration_s"),
        "recorded_unix_ms": int(time.time() * 1000),
        "nodes": sorted(nodes),
        # the memory verdict at top level: "was this slow request slow
        # because it copied" answers from the listing without opening
        # the full plan (same payload as explain["memory"], one level up)
        "memory": explain.get("memory") if isinstance(explain, dict) else None,
        "explain": explain,
        "trace": trace_dict,
    }
