"""HTTP server + config (reference: src/server)."""

from horaedb_tpu.server.config import Config

__all__ = ["Config"]
