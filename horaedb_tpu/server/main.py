"""Server entrypoint (reference: src/server/src/main.rs:87-233).

Bootstrap mirrors the reference: structured logging with file/line/time
(tracing-subscriber analog), `--config <toml>`, LocalFileSystem object store,
an ObjectBasedStorage on the hardcoded demo schema (pk1,pk2,pk3,value Int64,
num_primary_keys=3, main.rs:178-185), the optional self-write load generator
(bench_write, main.rs:187-233), and the HTTP surface:

    GET  /                 greeting/health
    GET  /toggle           flip the load generator (main.rs:59-80)
    GET  /compact          manual compaction trigger
    GET  /metrics          Prometheus text metrics (beyond the reference)
    GET  /debug/traces     recent request traces; /debug/traces/{id} is the
                           span tree for the X-Horaedb-Trace-Id a query
                           response echoed (common/tracing.py)

plus the ingest/query endpoints the reference defines but never wired
(remote_write "NOT yet wired into server", SURVEY L5):

    POST /api/v1/write     Prometheus remote-write (snappy or raw protobuf)
    POST /api/v1/query     JSON query -> rows or downsample grids
    GET  /api/v1/query     query-string form (filters = leftover params)
    GET  /api/v1/labels    label values via the inverted index
    GET  /api/v1/metrics   metric-name listing
    GET  /api/v1/series    per-metric series listing
    GET  /api/v1/metadata  metric-family metadata (Prometheus shape)

plus the streaming rule engine (horaedb_tpu/rules):

    POST /api/v1/rules        register one recording/alert rule (durable)
    GET  /api/v1/rules        registered rules, Prometheus groups shape
    DELETE /api/v1/rules/{n}  unregister
    GET  /api/v1/alerts       active alerts (+ ?transitions=<rule> tail)
    POST /api/v1/rules/tick   force one evaluator tick (admin/debug)

Run: python -m horaedb_tpu.server.main --config docs/example.toml
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys
import time

import numpy as np
import pyarrow as pa
from aiohttp import web

from horaedb_tpu.common import deadline as deadline_ctx
from horaedb_tpu.common import memtrace, tracing, xprof
from horaedb_tpu.common.bytebudget import GLOBAL_POOLS, rss_bytes
from horaedb_tpu.common.error import (
    DeadlineExceeded,
    HoraeError,
    UnavailableError,
)
from horaedb_tpu.common.time_ext import now_ms
from horaedb_tpu.engine import MetricEngine, QueryRequest
from horaedb_tpu.ingest import ParserPool
from horaedb_tpu.ingest.cardinality import CardinalityLimited
from horaedb_tpu.objstore import LocalStore
from horaedb_tpu.objstore.resilient import ResilientStore
from horaedb_tpu.server import admission
from horaedb_tpu.server.admission import AdmissionController
from horaedb_tpu.server.config import Config
from horaedb_tpu.server.errors import deadline_response, unavailable_response
from horaedb_tpu.server.metrics import GLOBAL_METRICS as METRICS
from horaedb_tpu.server.slowlog import SlowLog, build_entry
from horaedb_tpu.storage import scanstats
from horaedb_tpu.storage.read import CompactRequest, WriteRequest
from horaedb_tpu.storage.storage import ObjectBasedStorage
from horaedb_tpu.storage.types import TimeRange
from horaedb_tpu.telemetry.metering import GLOBAL_METER as _METER

logger = logging.getLogger("horaedb_tpu.server")

STATE_KEY = web.AppKey("state", object)

# canonical spellings live in common/tracing.py (the cluster router
# funnel injects them; this tier adopts + echoes them)
TRACE_HEADER = tracing.TRACE_HEADER

HTTP_SECONDS = METRICS.histogram(
    "horaedb_http_request_seconds",
    help="HTTP request latency by route template and method.",
    labelnames=("endpoint", "method"),
    # OpenMetrics exemplars: route-latency buckets carry the trace id of
    # their latest observation (rendered under content negotiation)
    exemplars=True,
)
HTTP_REQUESTS = METRICS.counter(
    "horaedb_http_requests_total",
    help="HTTP requests by route template, method, and status.",
    labelnames=("endpoint", "method", "status"),
)
INGEST_BATCH_SAMPLES = METRICS.histogram(
    "horaedb_remote_write_batch_samples",
    help="Samples per accepted remote-write request.",
    buckets=(1.0, 10.0, 100.0, 1000.0, 10_000.0, 100_000.0, 1_000_000.0),
)


# Routes whose finished traces feed the slow-query flight recorder (the
# diagnosis surfaces themselves never spool).
QUERY_ENDPOINTS = frozenset((
    "/api/v1/query", "/api/v1/query_range", "/api/v1/query_exemplars",
))


def _record_slow_query(slowlog: "SlowLog | None", t) -> None:
    """Feed one FINISHED query trace to the flight recorder. The root
    span's attrs already carry the EXPLAIN payload and scanstats stages
    the handler attached, so the spooled entry is the full diagnosis the
    operator would have gotten live with ?explain=1."""
    if slowlog is None:
        return
    root = t.root
    if root is None or root.duration_s is None:
        return
    if not slowlog.admit(root.duration_s):
        return  # cheap pre-check; record() re-validates under its lock
    entry = build_entry(t.as_dict(), root.attrs.get("explain"))
    slowlog.record(t.trace_id, root.duration_s, entry)


def _remote_trace_context(request: web.Request):
    """(remote trace id, remote parent span id) when this request arrived
    through a peer's traced client funnel; (None, None) otherwise. The
    parent-span header is the gate: only the funnel sends it, so a client
    replaying an X-Horaedb-Trace-Id from a previous response cannot make
    this node adopt (and clobber) an old ring entry."""
    parent_raw = request.headers.get(tracing.PARENT_SPAN_HEADER)
    if parent_raw is None:
        return None, None
    remote_id = request.headers.get(TRACE_HEADER)
    try:
        parent = int(parent_raw)
    except ValueError:
        parent = None
    return remote_id, parent


@web.middleware
async def observability_middleware(request: web.Request, handler):
    """Every request (except the observability surfaces themselves) gets a
    trace (subject to sampling) and a latency histogram sample; the trace
    id is echoed in the X-Horaedb-Trace-Id response header so a caller can
    fetch its span tree from /debug/traces/{id}. Finished traces of query
    endpoints feed the slow-query flight recorder (including failed
    requests — a slow 500 is exactly what the recorder exists for).

    Cross-node plumbing: a request carrying the router funnel's trace
    headers ADOPTS the origin's trace id instead of minting one, and the
    finished span subtree ships back in the response's SPANS_HEADER so
    the origin grafts it into one stitched, node-labeled tree."""
    resource = request.match_info.route.resource
    endpoint = resource.canonical if resource is not None else "unmatched"
    if request.path.startswith(("/metrics", "/debug")):
        return await handler(request)
    remote_id, remote_parent = _remote_trace_context(request)
    t0 = time.perf_counter()
    status = 500
    finished = None
    try:
        with tracing.trace(
            f"{request.method} {endpoint}", remote_id=remote_id,
            remote_parent=remote_parent, method=request.method,
            path=request.path,
        ) as t:
            finished = t
            try:
                resp = await handler(request)
                status = resp.status
            except web.HTTPException as e:
                status = e.status
                if t is not None:
                    e.headers[TRACE_HEADER] = t.trace_id
                raise
            finally:
                tracing.add_attr(status=status)
                HTTP_SECONDS.labels(endpoint, request.method).observe(
                    time.perf_counter() - t0
                )
                HTTP_REQUESTS.labels(endpoint, request.method, str(status)).inc()
    except web.HTTPException as e:
        # the trace finished when the with-block unwound: a forwarded
        # request's error response still ships its span subtree home
        if finished is not None and remote_id == finished.trace_id:
            e.headers[tracing.SPANS_HEADER] = tracing.export_spans(finished)
        raise
    finally:
        # the trace context exited above, so duration_s is final here
        if finished is not None and endpoint in QUERY_ENDPOINTS:
            state: ServerState = request.app[STATE_KEY]
            try:
                _record_slow_query(state.slowlog, finished)
            except Exception:  # noqa: BLE001 — the flight recorder must
                # never fail the request it is observing
                logger.exception("slowlog record failed")
    if finished is not None:
        resp.headers[TRACE_HEADER] = finished.trace_id
        if remote_id == finished.trace_id:
            # adopted context: the callee's half of the cross-node tree
            # rides home in one bounded header (export degrades under
            # budget instead of overflowing aiohttp's field cap)
            resp.headers[tracing.SPANS_HEADER] = tracing.export_spans(finished)
    return resp


# Read endpoints the cluster router may offload from a writer to a
# healthy replica (the expensive query surface; discovery endpoints are
# index-cheap and always serve locally).
CLUSTER_READ_ROUTES = frozenset((
    "/api/v1/query", "/api/v1/query_range", "/api/v1/query_exemplars",
))


@web.middleware
async def cluster_middleware(request: web.Request, handler):
    """Cluster routing in the HTTP tier (horaedb_tpu/cluster/router.py):

    - On a WRITER with healthy replicas (`route_reads`), query requests
      forward to the rendezvous-picked replica (one panel's repeats keep
      hitting one replica's caches); a replica failure fails over to the
      local engine — hedged, never user-visible.
    - On a REPLICA (or standby), every query response carries the
      bounded-staleness token as `X-Horaedb-Staleness-Ms`.
    - `X-Horaedb-Forwarded` marks proxied requests; they are never
      re-routed (loop guard). Write forwarding lives in the write
      handler (it needs the body + partial-ownership split)."""
    from horaedb_tpu.cluster.router import FORWARD_HEADER, STALENESS_HEADER

    state: ServerState = request.app[STATE_KEY]
    cl = state.cluster
    if cl is None:
        return await handler(request)
    failed_peer = None
    if (
        cl.role == "writer" and not cl.standby
        and cl.config.route_reads
        and FORWARD_HEADER not in request.headers
        and request.path in CLUSTER_READ_ROUTES
        and request.method in ("GET", "POST")
    ):
        key = request.path_qs.encode()
        body = None
        if request.method == "POST":
            body = await request.read()  # cached: the handler re-reads
            key += body
        # a split-eligible grid query is worth more than one replica's
        # caches: fall through to the local handler, which scatters
        # region shards across the computing nodes instead
        peer = (None if _split_eligible(state, request, body)
                else cl.router.pick_read_peer(key))
        if peer is not None:
            res = await cl.router.forward(
                peer.node, request.method, request.path_qs,
                request.headers, body, "read",
            )
            if res is not None and res[0] < 500:
                status, hdrs, out = res
                out = _fleet_merge_body(state, out, remote_node=peer.node,
                                        wire_bytes=len(out))
                resp = web.Response(status=status, body=out)
                resp.headers["Content-Type"] = hdrs.get(
                    "Content-Type", "application/json"
                )
                if STALENESS_HEADER in hdrs:
                    resp.headers[STALENESS_HEADER] = hdrs[STALENESS_HEADER]
                return resp
            # replica error / unreachable: hedged failover to local
            cl.router.note_failover()
            failed_peer = peer.node
    resp = await handler(request)
    if failed_peer is not None and resp.body:
        # the dead peer's EXPLAIN fragment degrades to a counted partial
        # on the locally-served answer — the fleet verdict never hangs
        # on (or silently forgets) a replica that failed mid-route
        local_body = bytes(resp.body)
        merged = _fleet_merge_body(state, local_body,
                                   remote_node=None, partial=1)
        if merged is not local_body:
            fresh = web.Response(status=resp.status, body=merged)
            fresh.headers["Content-Type"] = resp.headers.get(
                "Content-Type", "application/json"
            )
            resp = fresh
    if (cl.replica is not None
            and request.path.startswith("/api/v1/")
            and request.path != "/api/v1/cluster/status"):
        from horaedb_tpu.cluster.router import STALENESS_HEADER as _SH

        resp.headers[_SH] = str(round(cl.replica.staleness_ms(), 1))
    return resp


def _cluster_verdict(state: "ServerState") -> dict:
    """EXPLAIN `cluster` verdict: who served this query and how stale
    its view may be. Standalone deployments report the role alone."""
    cl = state.cluster
    if cl is None:
        return {"role": "standalone"}
    out = {"role": "replica" if (cl.replica is not None) else cl.role,
           "node": cl.node_id}
    try:
        if cl.replica is not None:
            out.update(cl.replica.staleness())
        else:
            out["manifest_epoch"] = state.engine.manifest_epoch()
            out["staleness_ms"] = 0.0
    except Exception:  # noqa: BLE001 — verdict must never fail a query
        pass
    return out


def _fleet_merge_body(state: "ServerState", out: bytes,
                      remote_node: "str | None", partial: int = 0,
                      wire_bytes: "int | None" = None) -> bytes:
    """Splice the federated `fleet` verdict into a JSON query response
    carrying an EXPLAIN payload. `remote_node` names the peer whose
    engine produced the response (read offload); None means this node
    executed it (local serve / hedged failover). `partial` counts
    fragments lost to dead peers. Returns `out` UNCHANGED (same object —
    callers compare identity) when there is no EXPLAIN to merge into or
    the body isn't parseable; the cheap substring gate keeps the
    non-EXPLAIN forwarded path at zero parse cost."""
    cl = state.cluster
    if cl is None or not out or b'"explain"' not in out:
        return out
    from horaedb_tpu import cluster as cluster_mod

    try:
        body = json.loads(out)
        explain = body.get("explain") if isinstance(body, dict) else None
        if not isinstance(explain, dict):
            return out
        executed_by = remote_node if remote_node is not None else cl.node_id
        frags = []
        frag = cluster_mod.fleet_fragment(executed_by, explain)
        if frag is None:
            partial += 1
        if remote_node is not None:
            # the origin routed but did not execute: it contributes its
            # identity + freshness token, so the merged verdict names
            # BOTH halves of the hop (the scatter-gather shape)
            origin_frag = cluster_mod.fleet_fragment(
                cl.node_id, {"cluster": _cluster_verdict(state)}
            )
            if origin_frag is not None:
                frags.append(origin_frag)
        if frag is not None:
            frags.append(frag)
        explain["fleet"] = cluster_mod.fleet_verdict(
            cl.node_id, frags, partial, wire_bytes=wire_bytes
        )
        return json.dumps(body).encode()
    except Exception:  # noqa: BLE001 — the merge must never turn a good
        # answer into a 500; the un-merged body is still correct
        logger.exception("fleet EXPLAIN merge failed")
        return out


async def _cluster_forward_write(state: "ServerState", request: web.Request,
                                 raw_body: bytes) -> "web.Response | None":
    """Whole-payload write forwarding: a replica (or standby writer)
    routes every write to the owning writer, raw body + headers intact
    (snappy stays snappy). None = handle locally."""
    from horaedb_tpu.cluster.router import FORWARD_HEADER

    cl = state.cluster
    if cl is None or FORWARD_HEADER in request.headers:
        return None
    if cl.role != "replica" and not cl.standby:
        return None
    targets = cl.router.write_targets(0)
    if not targets:
        return unavailable_response(UnavailableError(
            "replica knows no healthy writer to forward the write to"
        ))
    res = None
    for node in targets:
        res = await cl.router.forward(
            node, "POST", request.path_qs, request.headers, raw_body,
            "write",
        )
        if res is not None:
            break
    if res is None:
        return unavailable_response(UnavailableError(
            f"no reachable writer (tried {targets!r})"
        ))
    status, hdrs, out = res
    resp = web.Response(status=status, body=out)
    resp.headers["Content-Type"] = hdrs.get("Content-Type",
                                            "application/json")
    return resp


async def _cluster_split_write(
    state: "ServerState", body: bytes, tenant: str,
) -> "tuple[int, int]":
    """Partial-writer write path: split the (decompressed) payload per
    region owner — the local subset lands through the normal parsed
    write, non-owned subsets re-encode and forward to their owners WITH
    the caller's tenant identity (the owner meters its own subset; the
    origin meters only the local one — the J015 ledger must neither
    double-count nor misattribute forwarded rows to "default").
    Returns (total accepted, locally landed); raises on a failed
    forward (the sender retries the whole batch; local writes are
    LWW-idempotent)."""
    from horaedb_tpu.cluster.router import split_by_owner

    cl = state.cluster
    tenant_hdr = state.config.metric_engine.query.tenant_header
    parsed = await state.parser_pool.decode(body)
    local, remote = split_by_owner(
        parsed, state.engine.router, cl.router.assignment, cl.node_id,
    )
    total = local_n = 0
    if local is not None:
        local_n = await state.engine.write_parsed(local)
        total += local_n
    for node, payload in remote.items():
        res = await cl.router.forward(
            node, "POST", "/api/v1/write", {tenant_hdr: tenant}, payload,
            "write",
        )
        if res is None or res[0] >= 300:
            raise UnavailableError(
                f"forwarded write subset to {node!r} failed "
                f"(status {res[0] if res else 'unreachable'})"
            )
        try:
            import json as _json

            total += int(_json.loads(res[2]).get("samples", 0))
        except Exception:  # noqa: BLE001 — body shape is ours, but be safe
            pass
    return total, local_n


def _split_eligible(state: "ServerState", request: web.Request,
                    body: "bytes | None") -> bool:
    """Cheap pre-parse gate for the scatter-gather read path: is this a
    native grid query this node could SPLIT across computing nodes
    instead of forwarding whole? False negatives only cost the split
    (the query still answers, whole-forwarded); a false positive (e.g.
    `"bucket_ms": null` in the body) just serves locally — the full
    eligibility check re-runs on the parsed request in `_scatter_plan`.
    """
    cl = state.cluster
    if cl is None or not cl.config.distributed.enabled:
        return False
    if request.path != "/api/v1/query":
        return False
    if "query" in request.query:  # PromQL rides the whole-forward path
        return False
    if request.method == "POST":
        if not body or b"bucket_ms" not in body or b'"query"' in body:
            return False
    elif "bucket_ms" not in request.query:
        return False
    engines = getattr(state.engine, "engines", None)
    if not engines or getattr(state.engine, "_legacy", True):
        return False
    if len(engines) < max(2, cl.config.distributed.min_regions):
        return False
    return bool(cl.router.compute_nodes())


def _scatter_plan(state: "ServerState", request: web.Request, req):
    """Full split eligibility on the PARSED query + the shard plan:
    {node: [region ids]} across self + healthy computing peers, or None
    (execute the single-node way). Only a non-standby regioned writer
    coordinates; forwarded requests never re-split (loop guard, same as
    the whole-forward path)."""
    from horaedb_tpu.cluster.router import FORWARD_HEADER

    cl = state.cluster
    if (cl is None or req.bucket_ms is None
            or FORWARD_HEADER in request.headers
            or cl.role != "writer" or cl.standby
            or not cl.config.distributed.enabled):
        return None
    engines = getattr(state.engine, "engines", None)
    if not engines or getattr(state.engine, "_legacy", True):
        return None
    regions = [int(r) for r in engines]
    if len(regions) < max(2, cl.config.distributed.min_regions):
        return None
    return cl.router.plan_scatter(
        regions, max_fanout=cl.config.distributed.max_fanout
    )


async def _run_distributed(state: "ServerState", req, q: dict, tenant: str,
                           cells: "int | None", plan: dict):
    """Drive one scatter-gather query: local shards compute through the
    normal admitted engine path while remote fragments are in flight;
    any failed fragment's shards re-run locally (counted in the fleet
    `partial`, never waited on past the fragment timeout); all
    per-region partials fold in canonical region order
    (cluster/partial.py) — bit-exact vs the single-node merge.

    Returns (merged out | None, admission slot, dist provenance dict).
    """
    from dataclasses import replace

    from horaedb_tpu import cluster as cluster_mod
    from horaedb_tpu.cluster import partial as partial_mod
    from horaedb_tpu.parallel.mesh import active_mesh
    from horaedb_tpu.server import admission

    cl = state.cluster
    dcfg = cl.config.distributed
    order = [int(r) for r in state.engine.engines]
    total = max(1, len(order))
    my_regions = list(plan.get(cl.node_id, []))
    remote_plan = {n: rs for n, rs in plan.items() if n != cl.node_id}
    tenant_hdr = state.config.metric_engine.query.tenant_header

    def _frag_body(regions: "list[int]") -> bytes:
        body = {k: v for k, v in q.items()
                if k not in ("explain", "partial_grids", "regions")}
        body["partial_grids"] = True
        body["regions"] = [int(r) for r in regions]
        return json.dumps(body).encode()

    def _cells_for(regions: "list[int]") -> "int | None":
        if cells is None:
            return None
        return max(1, cells * len(regions) // total)

    async def _local(regions: "list[int]"):
        lreq = replace(req, regions=[int(r) for r in regions])
        return await admission.run_query_partials(
            state.admission, state.engine, lreq, tenant=tenant,
            cells=_cells_for(regions),
        )

    remote_tasks = {
        node: asyncio.create_task(cl.router.fetch_partials(
            node, _frag_body(regions), headers={tenant_hdr: tenant},
            timeout_s=dcfg.fragment_timeout.seconds,
        ))
        for node, regions in remote_plan.items()
    }
    try:
        parts, slot = await _local(my_regions)
    except BaseException:
        for t in remote_tasks.values():
            t.cancel()
        raise
    parts = list(parts)
    frags: list[dict] = []
    memory_frags: list[dict] = []
    failed: list[int] = []
    partial_count = 0
    wire_bytes = 0
    for node, task in remote_tasks.items():
        payload = await task
        decoded = None
        if payload is not None:
            try:
                decoded = partial_mod.decode_partials(payload)
            except Exception:  # noqa: BLE001 — a garbled fragment is a
                # dead fragment; its shards re-run locally below
                logger.warning("undecodable partial-grid fragment from %s",
                               node, exc_info=True)
        if decoded is None:
            failed.extend(remote_plan[node])
            partial_count += 1
            continue
        header, remote_parts = decoded
        wire_bytes += len(payload)
        parts.extend(remote_parts)
        prov = dict(header.get("provenance") or {})
        prov.setdefault("regions", remote_plan[node])
        prov["wire_bytes"] = len(payload)
        mem_frag = prov.pop("memory", None)
        if isinstance(mem_frag, dict):
            memory_frags.append(mem_frag)
        frag = cluster_mod.fleet_fragment(
            header.get("node", node), {"cluster": prov}
        )
        if frag is not None:
            if isinstance(mem_frag, dict):
                frag["memory"] = mem_frag
            frags.append(frag)
    if failed:
        # degrade ladder rung 2: the coordinator owns every region
        # locally (shared store), so dead fragments re-run here through
        # a fresh admission slot — exact answer, degraded parallelism
        rerun_parts, slot = await _local(sorted(failed))
        parts.extend(rerun_parts)
        my_regions = sorted(set(my_regions) | set(failed))
    out = partial_mod.merge_partials(
        parts, order=order, device_mesh=active_mesh(),
    )
    dist = {
        "fragments": frags,
        "memory_fragments": memory_frags,
        "partial": partial_count,
        "wire_bytes": wire_bytes,
        "regions_local": my_regions,
        "plan": {n: [int(r) for r in rs] for n, rs in plan.items()},
    }
    return out, slot, dist


def init_logging() -> None:
    """file:line + local time + env filter (main.rs:88-94 analog; level from
    the standard logging env var style: HORAEDB_LOG=DEBUG)."""
    import os

    level = os.environ.get("HORAEDB_LOG", "INFO").upper()
    logging.basicConfig(
        level=getattr(logging, level, logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s %(filename)s:%(lineno)d %(message)s",
        stream=sys.stderr,
    )


def build_demo_schema() -> pa.Schema:
    """Hardcoded demo schema (main.rs:178-185)."""
    return pa.schema(
        [
            ("pk1", pa.int64()),
            ("pk2", pa.int64()),
            ("pk3", pa.int64()),
            ("value", pa.int64()),
        ]
    )


# Largest decompressed remote-write payload the server will materialize; a
# hostile leading uvarint must not drive an arbitrary allocation.
MAX_DECOMPRESSED = 256 * 1024 * 1024


def snappy_decompress(buf: bytes) -> bytes:
    """Raw-snappy decompress via pyarrow's codec (no python-snappy in the
    image): the uncompressed length is the stream's leading uvarint."""
    size, shift, i = 0, 0, 0
    while True:
        b = buf[i]
        size |= (b & 0x7F) << shift
        i += 1
        if not (b & 0x80):
            break
        shift += 7
    if size > MAX_DECOMPRESSED:
        raise ValueError(f"decompressed size {size} exceeds limit")
    return bytes(pa.Codec("snappy").decompress(buf, decompressed_size=size))


class ClusterState:
    """This node's cluster identity + routing fabric (horaedb_tpu/cluster):
    the rendezvous router over the peer table, the replica handle when
    role = "replica" (or a standby writer), and the partial-ownership
    flag that turns on write splitting."""

    def __init__(self, config, node_id: str, router, replica=None,
                 standby: bool = False, partial: bool = False,
                 store=None, cluster_root: str = "metrics/cluster",
                 engine_root: str = "metrics",
                 engine_kwargs: "dict | None" = None):
        self.config = config          # cluster.ClusterConfig
        self.node_id = node_id
        self.router = router          # cluster.router.ClusterRouter
        self.replica = replica        # cluster.replica.ReplicaEngine | None
        self.role = config.role
        # a writer-role process that owns no regions yet (serves reads as
        # a replica; /api/v1/cluster/takeover promotes it)
        self.standby = standby
        # a regioned writer owning a strict subset of regions (the
        # assignment map split them): non-owned writes forward per owner
        self.partial = partial
        # takeover needs to reopen engines over the shared store
        self.store = store
        self.cluster_root = cluster_root
        self.engine_root = engine_root
        self.engine_kwargs = dict(engine_kwargs or {})


class ServerState:
    def __init__(self, config: Config, storage, engine: MetricEngine,
                 parser_pool=None, slowlog: "SlowLog | None" = None,
                 admission_controller: "AdmissionController | None" = None,
                 rules=None, telemetry=None, cluster: "ClusterState | None" = None):
        self.config = config
        self.storage = storage       # demo ColumnarStorage (reference parity)
        self.engine = engine         # metric engine (remote-write path)
        self.parser_pool = parser_pool or ParserPool()
        self.slowlog = slowlog       # slow-query flight recorder (or None)
        # bounded query scheduler (server/admission.py): every query
        # handler routes through it (jaxlint J011)
        self.admission = admission_controller or AdmissionController()
        # streaming rule engine (horaedb_tpu/rules), None = disabled
        self.rules = rules
        # self-scrape collector (horaedb_tpu/telemetry), None = disabled
        # (config or the HORAEDB_TELEMETRY=off kill switch)
        self.telemetry = telemetry
        # cluster layer (horaedb_tpu/cluster), None = standalone
        self.cluster = cluster
        self.write_enabled = asyncio.Event()
        self.write_workers: list[asyncio.Task] = []


# ---------------------------------------------------------------------------
# handlers
# ---------------------------------------------------------------------------


async def shield_mutation(coro):
    """Run a state-MUTATING engine/storage call to completion even when
    the client disconnects. `handler_cancellation` exists so abandoned
    QUERIES free their admission slot — but it aborts every handler task,
    and a write/admin mutation cancelled between its internal awaits
    would commit half an operation (e.g. delete_series lands the
    data-table tombstone but not the exemplars one). Shielding keeps the
    mutation atomic: the inner task runs to completion, the cancellation
    re-raises AFTER it settles, and a failure after disconnect is logged
    (nobody is left to receive it)."""
    task = asyncio.ensure_future(coro)
    try:
        return await asyncio.shield(task)
    except asyncio.CancelledError:
        try:
            await task
        except Exception:  # noqa: BLE001 — no caller left to tell
            logger.exception("shielded mutation failed after client "
                             "disconnect")
        raise


async def handle_root(request: web.Request) -> web.Response:
    return web.json_response({"status": "ok", "engine": "horaedb-tpu"})


async def handle_toggle(request: web.Request) -> web.Response:
    state: ServerState = request.app[STATE_KEY]
    if state.write_enabled.is_set():
        state.write_enabled.clear()
        flag = False
    else:
        state.write_enabled.set()
        flag = True
    return web.json_response({"enable_write": flag})


async def handle_compact(request: web.Request) -> web.Response:
    """Manual compaction. Optional `start`/`end` (epoch ms) scope the pick
    to SSTs overlapping that window (reference /compact is global-only)."""
    state: ServerState = request.app[STATE_KEY]
    rng = None
    if "start" in request.query or "end" in request.query:
        try:
            start = int(request.query.get("start", 0))
            end = int(request.query.get("end", 1 << 62))
        except ValueError:
            return web.json_response(
                {"error": "start/end must be integer epoch ms"}, status=400
            )
        if start > end:
            return web.json_response(
                {"error": f"start ({start}) must be <= end ({end})"}, status=400
            )
        rng = TimeRange(start, end)
    try:
        # the demo root may be a read-only view under cluster mode (its
        # writer is whichever process runs the load generator); the admin
        # op still compacts the METRIC engine below
        if not getattr(state.storage, "read_only", False):
            await shield_mutation(
                state.storage.compact(CompactRequest(time_range=rng))
            )
        await shield_mutation(state.engine.compact(time_range=rng))
    except UnavailableError as e:
        # transient store trouble stays the retryable 503 contract
        return unavailable_response(e)
    except HoraeError as e:
        # ONLY the deployment-shaped refusals are client errors:
        # read-only replica views and disabled schedulers. Anything
        # else (corrupt snapshot, FencedError mid-compaction) is a real
        # internal fault and must keep its 5xx signal for monitoring.
        from horaedb_tpu.common.error import ReplicaReadOnlyError

        if isinstance(e, ReplicaReadOnlyError) \
                or "compaction scheduler disabled" in str(e):
            return web.json_response({"error": str(e)}, status=400)
        raise
    METRICS.inc("horaedb_compactions_triggered_total")
    return web.json_response({
        "compaction": "triggered",
        **({"scope": [rng.start, rng.end]} if rng is not None else {}),
    })


async def handle_split_region(request: web.Request) -> web.Response:
    """Meta-plane split op (RFC :28-76 split rules): halves a region's hash
    range; the daughter owns the upper half for new writes. 400 on a
    non-regioned deployment or an unknown/unsplittable region."""
    from horaedb_tpu.engine.region import RegionedEngine

    state: ServerState = request.app[STATE_KEY]
    if not isinstance(state.engine, RegionedEngine):
        return web.json_response(
            {"error": "not a regioned deployment"}, status=400
        )
    try:
        region = int(request.query["region"])
    except (KeyError, ValueError):
        return web.json_response(
            {"error": "query param ?region=<id> required"}, status=400
        )
    try:
        daughter = await shield_mutation(state.engine.split_region(region))
    except HoraeError as e:
        return web.json_response({"error": str(e)}, status=400)
    METRICS.inc("horaedb_region_splits_total")
    return web.json_response({
        "split": region,
        "daughter": daughter,
        "regions": sorted(state.engine.engines),
    })


async def handle_metrics(request: web.Request) -> web.Response:
    state: ServerState = request.app[STATE_KEY]
    pool = state.parser_pool.status
    METRICS.set("horaedb_parser_pool_size", pool["size"])
    METRICS.set("horaedb_parser_pool_available", pool["available"])
    # storage/engine gauges: live SSTs and un-merged manifest deltas per
    # table (the backpressure signals, manifest/mod.rs:248-262), buffered
    # ingest rows awaiting flush
    tables: dict = {"demo": state.storage}
    buffered = 0
    for prefix, e in state.engine.sub_engines().items():
        tables.update({
            f"{prefix}metrics": e.metrics_table,
            f"{prefix}series": e.series_table,
            f"{prefix}index": e.index_table,
            f"{prefix}tags": e.tags_table,
            f"{prefix}data": e.data_table,
            f"{prefix}exemplars": e.exemplars_table,
        })
        buffered += e.sample_mgr.buffered_rows
    for name, table in tables.items():
        METRICS.set(
            f'horaedb_ssts_live{{table="{name}"}}', len(table.manifest.all_ssts())
        )
        METRICS.set(
            f'horaedb_manifest_deltas{{table="{name}"}}',
            table.manifest.deltas_num,
        )
    METRICS.set("horaedb_ingest_buffered_rows", buffered)
    # unified pool registry: pull occupancy from the live cache owners
    # right before render, so horaedb_pool_* gauges are scrape-fresh
    GLOBAL_POOLS.refresh()
    # content negotiation: OpenMetrics (with # EOF + trace-id exemplars
    # on the latency histograms) when the scraper asks for it; classic
    # Prometheus text otherwise
    from horaedb_tpu.server.metrics import OPENMETRICS_CONTENT_TYPE

    if OPENMETRICS_CONTENT_TYPE in request.headers.get("Accept", ""):
        return web.Response(
            text=METRICS.render_openmetrics(),
            content_type=OPENMETRICS_CONTENT_TYPE,
        )
    return web.Response(text=METRICS.render(), content_type="text/plain")


async def handle_remote_write(request: web.Request) -> web.Response:
    state: ServerState = request.app[STATE_KEY]
    body = await request.read()
    # cluster write routing: a replica / standby forwards the RAW body
    # to the owning writer (before any decompression — bytes stay bytes)
    forwarded = await _cluster_forward_write(state, request, body)
    if forwarded is not None:
        return forwarded
    if request.headers.get("Content-Encoding", "").lower() == "snappy":
        try:
            with tracing.span("snappy_decompress", bytes=len(body)):
                body = snappy_decompress(body)
        except Exception:  # noqa: BLE001
            return web.json_response({"error": "bad snappy payload"}, status=400)
    cl = state.cluster
    try:
        with tracing.span("ingest", bytes=len(body)):
            if cl is not None and cl.partial:
                # assignment-split regions: local subset + per-owner
                # forwards (cluster/router.py split_by_owner)
                n, n_local = await shield_mutation(
                    _cluster_split_write(state, body, _tenant_of(request))
                )
            else:
                n = await shield_mutation(state.engine.write_payload(body))
                n_local = n
    except CardinalityLimited as e:
        # series-cardinality partial-accept: existing-series samples WERE
        # accepted and are durable per the normal ack contract; only new
        # series (and their samples) were rejected. 503 + Retry-After so
        # senders back off; the body carries the exact accounting.
        logger.warning("remote write cardinality-limited: %s", e)
        _METER.account(_tenant_of(request),
                       rows_ingested=e.accepted_samples,
                       samples_rejected=e.rejected_samples)
        return unavailable_response(e, extra={
            "partial_accept": True,
            "accepted_samples": e.accepted_samples,
            "rejected_samples": e.rejected_samples,
            "rejected_series": e.rejected_series,
            "cardinality_limit": e.limit,
            "series_estimate": round(e.estimate),
        })
    except UnavailableError as e:
        # overload / store-down shedding: 503 + Retry-After with bounded
        # latency (breaker open fails fast; a stalled flush queue already
        # waited out its deadline) — the sender retries, nothing is lost
        logger.warning("remote write shed (unavailable): %s", e)
        return unavailable_response(e)
    except HoraeError as e:
        # client-shaped errors (malformed wire bytes, missing __name__)
        # stay 4xx
        msg = str(e)
        if "missing __name__" in msg or "malformed" in msg:
            return web.json_response({"error": msg}, status=400)
        logger.exception("remote write failed")
        return web.json_response({"error": msg}, status=500)
    except Exception as e:  # noqa: BLE001
        # internal failures must be 5xx: remote-write senders retry 5xx but
        # permanently DROP the batch on 4xx
        logger.exception("remote write failed")
        return web.json_response({"error": str(e)}, status=500)
    METRICS.inc("horaedb_remote_write_requests_total")
    METRICS.inc("horaedb_remote_write_samples_total", n)
    INGEST_BATCH_SAMPLES.observe(n)
    # per-tenant usage (telemetry/metering.py, the J015 funnel): only
    # LOCALLY-landed rows — a split-forwarded subset is metered by its
    # owning writer under the propagated tenant, never twice
    _METER.account(_tenant_of(request), rows_ingested=n_local)
    return web.json_response({"samples": n}, status=200)


def _raw_table_response(table, limit: int, explain: dict | None = None) -> web.Response:
    """Shared raw-row serialization (samples and exemplars): bounded by
    `limit` with a truncated flag; exemplar label blobs decode to dicts."""
    from horaedb_tpu.engine.types import decode_series_key

    truncated = table.num_rows > limit
    view = table.slice(0, limit)
    body = {
        "rows": view.num_rows,
        "truncated": truncated,
        "tsid": [str(x) for x in view.column("tsid").to_pylist()],
        "ts": view.column("ts").to_pylist(),
        "value": view.column("value").to_pylist(),
    }
    if "labels" in view.schema.names:
        body["labels"] = [
            {
                k.decode(errors="replace"): v.decode(errors="replace")
                for k, v in decode_series_key(blob or b"")
            }
            for blob in view.column("labels").to_pylist()
        ]
    if explain is not None:
        body["explain"] = explain
    return web.json_response(body)


# ---------------------------------------------------------------------------
# query admission plumbing (server/admission.py)
# ---------------------------------------------------------------------------


def _tenant_of(request: web.Request) -> str:
    """Fairness-accounting tenant: the configured header, else "default"."""
    state: ServerState = request.app[STATE_KEY]
    hdr = state.config.metric_engine.query.tenant_header
    return request.headers.get(hdr, "") or "default"


def _meter_scan(request: web.Request, st) -> None:
    """Fold one finished (or deadline-killed / shed — the caller paid for
    the partial scan too) query's byte provenance into the tenant's usage
    ledger (telemetry/metering.py)."""
    if st is None:
        return
    b = st.counts.get("bytes_scanned", 0)
    if b:
        _METER.account(_tenant_of(request), bytes_scanned=b)


def _query_deadline(state: "ServerState", raw_timeout) -> "deadline_ctx.Deadline":
    """End-to-end deadline for one query: Prometheus-style `timeout=`
    override, clamped to [metric_engine.query] max_timeout; absent ->
    default_timeout. Raises ValueError on garbage (the 400 path)."""
    qcfg = state.config.metric_engine.query
    secs = admission.parse_timeout_s(
        raw_timeout, qcfg.default_timeout.seconds, qcfg.max_timeout.seconds
    )
    return deadline_ctx.Deadline(secs)


def _promql_cells(state: "ServerState", expr, n_steps: int) -> int | None:
    """Grid-cell estimate for the admission cost model: steps x the
    matched-series count of every selector in the expression. Index
    lookups only — no scan, no IO."""
    from dataclasses import fields as dc_fields, is_dataclass

    from horaedb_tpu.promql import Selector

    names: list[str] = []
    stack = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, Selector):
            names.append(node.name)
        elif is_dataclass(node) and not isinstance(node, type):
            for f in dc_fields(node):
                v = getattr(node, f.name)
                if isinstance(v, (list, tuple)):
                    stack.extend(v)
                else:
                    stack.append(v)
    if not names:
        return None
    series = sum(state.engine.series_count(n.encode()) for n in names)
    return max(n_steps, 1) * max(series, 1)


def _progress_payload(st) -> dict | None:
    """Partial-progress provenance for a deadline-killed query's 504
    body: how far the scan got before the budget died (the caller paid
    for these numbers; naming them beats a bare timeout)."""
    if st is None:
        return None
    counts = dict(st.counts)
    return {
        "regions": counts.get("regions_fanout", 0),
        "ssts_selected": counts.get("ssts_selected", 0),
        "ssts_read": counts.get("ssts_read", 0),
        "ssts_bloom_pruned": counts.get("ssts_bloom_pruned", 0),
        "stages_s": {k: round(v, 6) for k, v in st.seconds.items()},
    }


# ---------------------------------------------------------------------------
# query EXPLAIN
# ---------------------------------------------------------------------------

_TRUTHY = ("1", "true", "yes", "on")


def _want_explain(request: web.Request, params: dict | None = None) -> bool:
    """`?explain=1` (query string, or merged PromQL form/JSON params)."""
    v = request.query.get("explain", "")
    if params is not None and not v:
        v = str(params.get("explain", ""))
    return v.lower() in _TRUTHY


def _explain_payload(st, mode: str, admission_verdict: dict | None = None) -> dict:
    """Assemble the plan a finished query leaves behind: what was touched
    (regions, SSTs, bloom prunes), which routes/kernels served it
    (scan path, dispatcher impl, instrumented-kernel envelopes), and where
    the time went (per-lane stage seconds, compile vs steady split, the
    roofline `bound` verdict). Pure dict assembly over the scanstats
    collector — the query already paid for every number in here."""
    att = st.attribution()
    counts = dict(st.counts)
    agg_impls = sorted(
        k[len("agg_impl_"):] for k in counts if k.startswith("agg_impl_")
    )
    if not agg_impls and mode == "downsample":
        # pushdowns that rode the sharded mesh path report via the
        # process-global dispatcher provenance instead of a collector note
        from horaedb_tpu.ops import agg_registry

        last = agg_registry.last_choice()
        if last:
            agg_impls = [last]
    scan_paths = sorted(
        k[len("path_"):] for k in counts if k.startswith("path_")
    )
    # compressed-domain scan provenance (storage/encoding.py +
    # ops/decode.py): which lanes scanned encoded and under which codec,
    # the wire-vs-materialized byte split (compression ratio), what the
    # zone maps / rle run skipping pruned before any decode, and which
    # decode funnel the calibrated dispatcher ran
    enc_lanes = {}
    for k in counts:
        if k.startswith("enclane_") and "=" in k:
            lane, _, codec = k[len("enclane_"):].partition("=")
            enc_lanes[lane] = codec
    encoding = {
        "lanes": enc_lanes,
        "ssts_encoded": counts.get("ssts_encoded", 0),
        "encoded_bytes": counts.get("encoded_bytes", 0),
        "decoded_bytes": counts.get("decoded_bytes", 0),
        "pages_pruned": counts.get("pages_pruned", 0),
        "runs_skipped": counts.get("runs_skipped", 0),
        "decode_impls": sorted(
            k[len("decode_impl_"):] for k in counts
            if k.startswith("decode_impl_")
        ),
    }
    # serving-tier verdict (horaedb_tpu/serving): did the result cache
    # serve this query (hit), was it computed + stored (miss), or was the
    # tier off/bypassed (bypass / None when the query never reached the
    # choke point); which rollup resolution(s) substituted for raw
    # segment scans; and the residency split of the blocks touched.
    if counts.get("serving_cache_hit"):
        cache_verdict = "hit"
    elif counts.get("serving_cache_miss"):
        cache_verdict = "miss"
    elif counts.get("serving_cache_bypass"):
        cache_verdict = "bypass"
    else:
        cache_verdict = None
    rollup_res = sorted(
        k[len("rollup_res_"):] for k in counts if k.startswith("rollup_res_")
    )
    serving_verdict = {
        "cache": cache_verdict,
        "rollup": (
            "none" if not rollup_res
            else rollup_res[0] if len(rollup_res) == 1
            else "mixed"
        ),
        "rollup_resolutions": rollup_res,
        "rollup_segments": counts.get("rollup_segments", 0),
        "rollup_rows_read": counts.get("rollup_rows_read", 0),
        "raw_segments": counts.get("raw_segments", 0),
        "blocks_resident": counts.get("blocks_resident", 0),
        "blocks_fetched": counts.get("blocks_fetched", 0),
    }
    # query-batcher verdict (server/batching.py): how many compatible
    # grid queries shared this query's stacked kernel launch (1 = ran
    # solo; None = never reached the batching decision point, e.g. raw
    # mode or a cache hit replay), the padded-buffer waste of that
    # launch, the shape class it coalesced under, and the time spent
    # holding in the coalescing window.
    batch_classes = sorted(
        k[len("batch_class_"):] for k in counts
        if k.startswith("batch_class_")
    )
    batching_verdict = {
        "batched_with": counts.get("batched_with"),
        "pad_waste_pct": counts.get("batch_pad_waste_pct", 0),
        "shape_class": batch_classes[0] if batch_classes else None,
        "window_wait_s": round(st.seconds.get("batch_window", 0.0), 6),
    }
    compile_s = st.seconds.get("compile", 0.0)
    total_s = sum(att["lanes_s"].values())
    kernels = []
    for entry in xprof.kernel_entries(st.kernels):
        entry["calls"] = st.kernels.get(entry["kernel"], 0)
        # the full signature map is catalog detail; EXPLAIN keeps the size
        entry.pop("signatures", None)
        kernels.append(entry)
    return {
        "mode": mode,
        "regions": counts.get("regions_fanout", 1),
        "ssts": {
            "selected": counts.get("ssts_selected", 0),
            "read": counts.get("ssts_read", 0),
            "bloom_pruned": counts.get("ssts_bloom_pruned", 0),
            # retention provenance: SSTs wholly past the horizon the
            # selection dropped before any IO (storage.select_ssts)
            "retention_pruned": counts.get("ssts_retention_pruned", 0),
            # partial-result provenance: SSTs a degraded store could not
            # serve (the query answered 503; this names what was missing)
            "unavailable": counts.get("ssts_unavailable", 0),
        },
        # tombstone provenance (storage/visibility.py): delete records
        # that masked rows in this scan, and how many rows they masked
        "tombstones_applied": counts.get("tombstones_applied", 0),
        "tombstone_rows_masked": counts.get("tombstone_rows_masked", 0),
        "scan_paths": scan_paths,
        "agg_impl": agg_impls[0] if agg_impls else None,
        "agg_impls": agg_impls,
        "stages_s": {k: round(v, 6) for k, v in st.seconds.items()},
        "lanes_s": att["lanes_s"],
        "bound": att["bound"],
        "compile_s": round(compile_s, 6),
        "steady_s": round(max(0.0, total_s - compile_s), 6),
        # admission verdict (server/admission.py): queued?, queue-wait
        # seconds, estimated device cost, load at admission. None when the
        # query never reached admission (e.g. shed before a slot).
        "admission": admission_verdict,
        "encoding": encoding,
        "serving": serving_verdict,
        "batching": batching_verdict,
        # memory provenance (common/memtrace.py): the buffer-lineage
        # verdict — bytes allocated/copied per stage, copies vs views,
        # device staging bytes, peak-delta + top sites under deep mode.
        # Pinned schema (memtrace.VERDICT_KEYS); zeros when tracing off.
        "memory": memtrace.verdict(getattr(st, "mem", None)),
        "counts": counts,
        "kernels": kernels,
    }


def _finish_explain(state: "ServerState", st, mode: str,
                    want: bool,
                    admission_verdict: dict | None = None) -> dict | None:
    """Build the plan and attach it to the request's trace root so the
    slow-query flight recorder (and /debug/traces/{id}) carries it even
    when the caller did not ask for ?explain=1. Skipped entirely — zero
    assembly cost on the hot path — when the caller didn't ask AND the
    flight recorder is disabled (nobody would ever read it)."""
    if not want and state.slowlog is None:
        return None
    explain = _explain_payload(st, mode, admission_verdict=admission_verdict)
    # cluster verdict (horaedb_tpu/cluster): who served this and how
    # stale its view may be — the staleness token EXPLAIN carries
    explain["cluster"] = _cluster_verdict(state)
    tracing.add_attr(explain=explain, scanstats=st.as_dict())
    return explain if want else None


async def _promql_params(request: web.Request) -> dict:
    """Merge query-string and form/JSON body params (Prometheus clients
    send either; Grafana's POST mode uses form bodies). Malformed bodies
    raise ValueError so callers answer the Prometheus 400 shape."""
    out = dict(request.query)
    if request.method == "POST":
        if request.content_type == "application/json":
            try:
                body = await request.json()
            except Exception as e:  # noqa: BLE001 — client data
                raise ValueError(f"bad JSON body: {e}") from None
            if not isinstance(body, dict):
                raise ValueError("JSON body must be an object")
            out.update({k: str(v) for k, v in body.items()})
        else:
            body = await request.post()
            out.update({k: v for k, v in body.items() if isinstance(v, str)})
    return out


def _promql_error(e: Exception) -> web.Response:
    return web.json_response(
        {"status": "error", "errorType": "bad_data", "error": str(e)},
        status=400,
    )


async def handle_query_range(request: web.Request) -> web.Response:
    """Prometheus-compatible /api/v1/query_range: PromQL over the engine
    (the subset in horaedb_tpu/promql — *_over_time/aggregations ride the
    device pushdown). The reference has no query language at all."""
    from horaedb_tpu.promql import PromQLError, parse, parse_duration_ms
    from horaedb_tpu.promql.eval import RangeEvaluator, to_prometheus_matrix

    state: ServerState = request.app[STATE_KEY]
    st = None
    try:
        p = await _promql_params(request)
        expr = parse(p["query"])
        start_ms = int(float(p["start"]) * 1000)
        end_ms = int(float(p["end"]) * 1000)
        step_ms = parse_duration_ms(p["step"])
        dl = _query_deadline(state, p.get("timeout"))
        ev = RangeEvaluator(state.engine, start_ms, end_ms, step_ms)
        cells = _promql_cells(state, expr, len(ev.steps))
        # scan_stats outermost so the admission queue wait lands in the
        # collector (stage="queue_wait"); the deadline covers queue wait
        # AND the scan — end-to-end means end-to-end
        with scanstats.scan_stats() as st, \
                deadline_ctx.deadline_scope(dl):
            slot = state.admission.slot(_tenant_of(request), cells=cells)
            async with slot:
                series = await ev.eval(expr)
    except DeadlineExceeded as e:
        _meter_scan(request, st)
        return deadline_response(e, progress=_progress_payload(st))
    except UnavailableError as e:
        _meter_scan(request, st)
        return unavailable_response(e)
    except (PromQLError, HoraeError, KeyError, ValueError) as e:
        # post-scan PromQL errors exist (e.g. many-to-one vector
        # matching rejects AFTER both operands scanned) — the caller
        # paid for those bytes too
        _meter_scan(request, st)
        return _promql_error(e)
    METRICS.inc("horaedb_queries_total")
    _meter_scan(request, st)
    explain = _finish_explain(state, st, "promql_range",
                              _want_explain(request, p),
                              admission_verdict=slot.verdict())
    _attach_rule_provenance(state, explain, _selector_names(expr))
    body = {"status": "success", "data": to_prometheus_matrix(series, ev.steps)}
    if explain is not None:
        body["explain"] = explain
    return web.json_response(body)


async def handle_promql_instant(
    request: web.Request, params: dict
) -> web.Response:
    """Prometheus-compatible instant query (the `query` param form of
    /api/v1/query; requests without `query` fall through to the native
    JSON query API below)."""
    from horaedb_tpu.common.time_ext import now_ms
    from horaedb_tpu.promql import PromQLError, parse
    from horaedb_tpu.promql.eval import (
        LOOKBACK_MS,
        RangeEvaluator,
        to_prometheus_vector,
    )

    state: ServerState = request.app[STATE_KEY]
    st = None
    try:
        expr = parse(params["query"])
        at_ms = int(float(params.get("time", now_ms() / 1000.0)) * 1000)
        dl = _query_deadline(state, params.get("timeout"))
        # instant = a one-step range ending at `time` (window functions need
        # a left context; LOOKBACK covers bare selectors)
        ev = RangeEvaluator(state.engine, at_ms - LOOKBACK_MS, at_ms, LOOKBACK_MS)
        cells = _promql_cells(state, expr, 1)
        with scanstats.scan_stats() as st, \
                deadline_ctx.deadline_scope(dl):
            slot = state.admission.slot(_tenant_of(request), cells=cells)
            async with slot:
                series = await ev.eval(expr)
    except DeadlineExceeded as e:
        _meter_scan(request, st)
        return deadline_response(e, progress=_progress_payload(st))
    except UnavailableError as e:
        _meter_scan(request, st)
        return unavailable_response(e)
    except (PromQLError, HoraeError, ValueError) as e:
        _meter_scan(request, st)  # post-scan eval errors paid for bytes
        return _promql_error(e)
    METRICS.inc("horaedb_queries_total")
    _meter_scan(request, st)
    explain = _finish_explain(state, st, "promql_instant",
                              _want_explain(request, params),
                              admission_verdict=slot.verdict())
    _attach_rule_provenance(state, explain, _selector_names(expr))
    body = {"status": "success", "data": to_prometheus_vector(series, at_ms)}
    if explain is not None:
        body["explain"] = explain
    return web.json_response(body)


async def handle_query(request: web.Request) -> web.Response:
    state: ServerState = request.app[STATE_KEY]
    # PromQL routing: `query` in the URL, or in a form-encoded POST body
    # (Grafana's POST mode). JSON POST bodies stay on the native API — its
    # own `query` key never existed, so there is no ambiguity.
    if "query" in request.query:
        return await handle_promql_instant(request, dict(request.query))
    if (
        request.method == "POST"
        and request.content_type in (
            "application/x-www-form-urlencoded", "multipart/form-data"
        )
    ):
        form = await request.post()
        if "query" in form:
            params = dict(request.query)
            params.update({k: v for k, v in form.items() if isinstance(v, str)})
            return await handle_promql_instant(request, params)
        return web.json_response(
            {"error": "form body without `query`; use the JSON API"},
            status=400,
        )
    try:
        if request.method == "GET":
            # curl/Grafana-style convenience: scalar params in the query
            # string (metric, start_ms, end_ms, bucket_ms, limit,
            # exemplars); tag filters as every remaining key. Matchers need
            # the JSON POST form.
            qs = dict(request.query)
            if len(request.query) != len(qs):
                # a duplicated key (e.g. &host=a&host=b) would silently drop
                # values; two equality filters on one key can never both
                # match — the caller wants the JSON matcher form
                raise ValueError(
                    "duplicate query parameter; use POST with matchers for "
                    "multiple constraints on one label"
                )
            qs.pop("explain", None)  # EXPLAIN flag, never a tag filter
            q = {
                k: qs.pop(k)
                for k in ("metric", "start_ms", "end_ms", "bucket_ms",
                          "limit", "exemplars", "timeout")
                if k in qs
            }
            if "bucket_ms" in q:
                q["bucket_ms"] = int(q["bucket_ms"])
            if "exemplars" in q:
                q["exemplars"] = q["exemplars"].lower() not in (
                    "0", "false", "no", "off", ""
                )
            q["filters"] = qs
        else:
            q = await request.json()
        if q.get("bucket_ms") is not None and int(q["bucket_ms"]) <= 0:
            raise ValueError("bucket_ms must be > 0")
        matchers = []
        raw_matchers = q.get("matchers", [])
        if isinstance(raw_matchers, dict):
            # convenience form {"host": {"op": "re", "pattern": "web.*"}} —
            # one matcher per key only
            raw_matchers = [
                {"key": k, **spec} for k, spec in raw_matchers.items()
            ]
        for spec in raw_matchers:
            # canonical list form supports several matchers on one label:
            # [{"key": "host", "op": "re", "pattern": "web.*"}, ...]
            matchers.append(
                (spec["key"].encode(), spec["op"], spec["pattern"].encode())
            )
        limit = min(int(q.get("limit", 100_000)), 1_000_000)
        if limit < 0:
            raise ValueError("limit must be >= 0")
        req = QueryRequest(
            metric=q["metric"].encode(),
            start_ms=int(q["start_ms"]),
            end_ms=int(q["end_ms"]),
            filters=[(k.encode(), v.encode()) for k, v in q.get("filters", {}).items()],
            matchers=matchers,
            bucket_ms=q.get("bucket_ms"),
            # +1 so the response can report `truncated` without paying for
            # unbounded materialization
            limit=limit + 1,
        )
    except Exception as e:  # noqa: BLE001
        return web.json_response({"error": f"bad query: {e}"}, status=400)
    try:
        dl = _query_deadline(state, q.get("timeout"))
    except ValueError as e:
        return web.json_response({"error": f"bad query: {e}"}, status=400)
    METRICS.inc("horaedb_queries_total")
    want_explain = _want_explain(request, q)
    mode = (
        "exemplars" if q.get("exemplars")
        else "raw" if req.bucket_ms is None else "downsample"
    )
    # cost-model sizing: only grid-shaped queries are predictable enough
    # to price (buckets x registered series of the metric — index lookup)
    cells = None
    if mode == "downsample":
        n_buckets = -(-(req.end_ms - req.start_ms) // req.bucket_ms)
        cells = int(n_buckets) * max(state.engine.series_count(req.metric), 1)
    tenant = _tenant_of(request)
    # distributed scatter-gather leaf: a coordinator asked THIS node to
    # compute a region-shard subset and answer compact partial grids
    # (cluster/partial.py wire) instead of a merged JSON response
    partial_wire = bool(q.get("partial_grids")) and mode == "downsample"
    if partial_wire and q.get("regions") is not None:
        try:
            req.regions = [int(r) for r in q["regions"]]
        except (TypeError, ValueError):
            return web.json_response(
                {"error": "bad query: regions must be a list of ints"},
                status=400,
            )
    dist = None
    st = None
    try:
        with scanstats.scan_stats() as st, \
                deadline_ctx.deadline_scope(dl):
            if q.get("exemplars"):
                table, slot = await admission.run_query_exemplars(
                    state.admission, state.engine, req, tenant=tenant
                )
            elif partial_wire:
                parts, slot = await admission.run_query_partials(
                    state.admission, state.engine, req, tenant=tenant,
                    cells=cells,
                )
            else:
                plan = (_scatter_plan(state, request, req)
                        if mode == "downsample" else None)
                if plan is not None:
                    out, slot, dist = await _run_distributed(
                        state, req, q, tenant, cells, plan
                    )
                else:
                    out, slot = await admission.run_query(
                        state.admission, state.engine, req, tenant=tenant,
                        cells=cells,
                    )
    except DeadlineExceeded as e:
        # end-to-end budget spent (queued or mid-scan): 504 with the
        # partial-progress provenance of what the scan HAD done
        _meter_scan(request, st)
        extra = (
            {"explain": _explain_payload(st, mode)} if want_explain else None
        )
        return deadline_response(e, progress=_progress_payload(st),
                                 extra=extra)
    except UnavailableError as e:
        # a required SST (or the flush barrier before the scan) hit a
        # down store — or the admission scheduler shed (queue full /
        # stalled / cost gate): typed 503 + Retry-After, with the
        # partial-result provenance of what WAS reached when the caller
        # asked for the plan
        _meter_scan(request, st)
        extra = (
            {"explain": _explain_payload(st, mode)} if want_explain else None
        )
        return unavailable_response(e, extra=extra)
    except HoraeError as e:
        _meter_scan(request, st)  # post-scan errors paid for bytes
        return web.json_response({"error": str(e)}, status=400)
    _meter_scan(request, st)
    explain = _finish_explain(state, st, mode, want_explain,
                              admission_verdict=slot.verdict())
    _attach_rule_provenance(state, explain, [q["metric"]])
    if partial_wire:
        from horaedb_tpu.cluster import WIRE_BYTES
        from horaedb_tpu.cluster.partial import (
            WIRE_CONTENT_TYPE,
            encode_partials,
        )

        cl = state.cluster
        prov = _cluster_verdict(state)
        prov["regions"] = sorted(
            {int(p[0]) for p in parts}
            | set(req.regions if req.regions is not None else ())
        )
        # leaf memory verdict rides the fragment header so the
        # coordinator can graft it into the federated memory verdict
        prov["memory"] = memtrace.verdict(getattr(st, "mem", None))
        payload = encode_partials(
            cl.node_id if cl is not None else "local", parts,
            provenance=prov,
        )
        WIRE_BYTES.labels("partial_grid", "tx").inc(len(payload))
        return web.Response(body=payload, content_type=WIRE_CONTENT_TYPE)
    if dist is not None and explain is not None:
        from horaedb_tpu import cluster as cluster_mod

        cl = state.cluster
        origin = cluster_mod.fleet_fragment(cl.node_id, explain)
        frags = []
        if origin is not None:
            origin["regions"] = [int(r) for r in dist["regions_local"]]
            frags.append(origin)
        explain["fleet"] = cluster_mod.fleet_verdict(
            cl.node_id, frags + dist["fragments"],
            partial=dist["partial"], wire_bytes=dist["wire_bytes"],
        )
        explain["fleet"]["distributed"] = {"plan": dist["plan"]}
        # graft remote leaf memory verdicts into the coordinator's own:
        # scalars add, peaks max — the fleet-wide copy tax of this query
        for mem_frag in dist.get("memory_fragments", ()):
            explain["memory"] = memtrace.verdict_merge(
                explain["memory"], mem_frag
            )
    if q.get("exemplars"):
        if table is None:
            return web.json_response(
                {"series": [], **({"explain": explain} if explain else {})}
            )
        return _raw_table_response(table, limit, explain=explain)
    if out is None:
        return web.json_response(
            {"series": [], **({"explain": explain} if explain else {})}
        )
    if req.bucket_ms is None:
        return _raw_table_response(out, limit, explain=explain)
    tsids, grids = out
    # limit bounds the series dimension of bucketed responses too
    truncated = len(tsids) > limit
    tsids = tsids[:limit]
    mean = grids["mean"][:limit]
    count = grids["count"][:limit]
    body = {
        "tsids": [str(t) for t in tsids],
        "buckets": grids["mean"].shape[1],
        "truncated": truncated,
        "mean": np.where(np.isnan(mean), None, mean).tolist(),
        "count": count.tolist(),
    }
    if explain is not None:
        body["explain"] = explain
    return web.json_response(body)


async def handle_delete_series(request: web.Request) -> web.Response:
    """Prometheus-admin-shaped tombstone delete
    (POST /api/v1/admin/tsdb/delete_series): `match[]` instant selectors
    plus optional `start`/`end` (epoch seconds; default = all time).
    Deletes are visible to queries immediately (scan-time masking via the
    shared visibility helper) and physically applied when compaction
    rewrites the matched SSTs; samples written AFTER the delete survive."""
    from horaedb_tpu.promql import PromQLError, Selector, parse
    from horaedb_tpu.promql.eval import _to_query

    state: ServerState = request.app[STATE_KEY]
    try:
        p = await _promql_params(request)
    except ValueError as e:
        return _promql_error(e)
    # match[] is multi-valued in BOTH carriers (query string and form
    # body) — _promql_params' dict collapse would silently drop all but
    # the last selector, a silent under-delete on a GDPR surface
    match_exprs = list(request.query.getall("match[]", []))
    if request.method == "POST" and request.content_type in (
        "application/x-www-form-urlencoded", "multipart/form-data"
    ):
        form = await request.post()
        match_exprs += [v for v in form.getall("match[]", [])
                        if isinstance(v, str)]
    if not match_exprs and "match[]" in p:
        match_exprs = [p["match[]"]]  # JSON body: single selector
    if not match_exprs:
        return _promql_error(ValueError("match[] selector(s) required"))
    try:
        start_ms = int(float(p["start"]) * 1000) if "start" in p else 0
        # no end = "up to now": rows written after the delete survive by
        # sequence anyway, and an unbounded range would make the
        # tombstone permanently un-GC-able (it would overlap every live
        # SST forever)
        end_ms = (int(float(p["end"]) * 1000) + 1 if "end" in p
                  else now_ms() + 1)
        results = []
        for expr in match_exprs:
            node = parse(expr)
            if not isinstance(node, Selector) or node.range_ms is not None:
                raise PromQLError(
                    f"match[] must be an instant selector: {expr!r}"
                )
            q = _to_query(node, start_ms, end_ms)
            with tracing.span("delete_series", metric=node.name):
                r = await shield_mutation(state.engine.delete_series(
                    q.metric, filters=q.filters, matchers=q.matchers,
                    start_ms=start_ms, end_ms=end_ms,
                ))
            r["match"] = expr
            results.append(r)
    except UnavailableError as e:
        return unavailable_response(e)
    except (PromQLError, HoraeError, KeyError, ValueError) as e:
        return _promql_error(e)
    METRICS.inc("horaedb_delete_series_requests_total")
    return web.json_response({"status": "success", "data": results})


async def handle_metrics_list(request: web.Request) -> web.Response:
    state: ServerState = request.app[STATE_KEY]
    names = state.engine.metric_names()
    return web.json_response({"metrics": [n.decode(errors="replace") for n in names]})


async def _match_series(state: ServerState, match_exprs: list[str]) -> list[dict]:
    """Resolve Prometheus `match[]` selectors to label maps (discovery
    surface behind /api/v1/series, /labels and /label/:name/values). Goes
    through the engines' public match_series — regex matchers evaluate off
    the event loop and regioned deployments fan out."""
    from horaedb_tpu.promql import PromQLError, Selector, parse
    from horaedb_tpu.promql.eval import _to_query

    out, seen = [], set()
    for expr in match_exprs:
        node = parse(expr)
        if not isinstance(node, Selector) or node.range_ms is not None:
            raise PromQLError(f"match[] must be an instant selector: {expr!r}")
        q = _to_query(node, 0, 1)
        matched = await state.engine.match_series(q.metric, q.filters, q.matchers)
        for t, labs in matched.items():
            if (node.name, t) in seen:
                continue
            seen.add((node.name, t))
            d = {k.decode(errors="replace"): v.decode(errors="replace")
                 for k, v in labs.items()}
            d["__name__"] = node.name
            out.append(d)
    return out


async def handle_series(request: web.Request) -> web.Response:
    state: ServerState = request.app[STATE_KEY]
    if "match[]" in request.query:
        # Prometheus-shaped series discovery (Grafana variables)
        from horaedb_tpu.promql import PromQLError

        try:
            data = await _match_series(state, request.query.getall("match[]"))
        except (PromQLError, HoraeError) as e:
            return _promql_error(e)
        return web.json_response({"status": "success", "data": data})
    metric = request.query.get("metric", "").encode()
    return web.json_response({"series": state.engine.series(metric)})


async def _all_label_names(
    state: ServerState, match_exprs: list[str] | None
) -> list[str]:
    names: set[str] = {"__name__"}
    if match_exprs:
        for d in await _match_series(state, match_exprs):
            names.update(d.keys())
        return sorted(names)
    # engines' public surface (NOT metric_mgr/index_mgr: RegionedEngine
    # has neither — it answers via fan-out, mirroring match_series)
    names.update(k.decode(errors="replace") for k in state.engine.label_names())
    return sorted(names)


async def handle_labels(request: web.Request) -> web.Response:
    state: ServerState = request.app[STATE_KEY]
    if "metric" in request.query or "key" in request.query:
        # native surface: values of one key under one metric
        metric = request.query.get("metric", "").encode()
        key = request.query.get("key", "").encode()
        vals = state.engine.label_values(metric, key)
        return web.json_response(
            {"values": [v.decode(errors="replace") for v in vals]}
        )
    # Prometheus-shaped label-NAME listing (optional match[] scope)
    from horaedb_tpu.promql import PromQLError

    try:
        match = (request.query.getall("match[]")
                 if "match[]" in request.query else None)
        data = await _all_label_names(state, match)
    except (PromQLError, HoraeError) as e:
        return _promql_error(e)
    return web.json_response({"status": "success", "data": data})


async def handle_label_values(request: web.Request) -> web.Response:
    """Prometheus /api/v1/label/{name}/values — Grafana's autocomplete
    surface. `__name__` lists metrics; other labels union their values
    across metrics (scoped by match[] when given)."""
    from horaedb_tpu.promql import PromQLError

    state: ServerState = request.app[STATE_KEY]
    name = request.match_info["name"]
    try:
        if "match[]" in request.query:
            rows = await _match_series(state, request.query.getall("match[]"))
            vals = sorted({d[name] for d in rows if name in d})
            return web.json_response({"status": "success", "data": vals})
        if name == "__name__":
            vals = sorted(
                m.decode(errors="replace") for m in state.engine.metric_names()
            )
            return web.json_response({"status": "success", "data": vals})
        out: set[str] = set()
        for metric in state.engine.metric_names():
            for v in state.engine.label_values(metric, name.encode()):
                out.add(v.decode(errors="replace"))
        return web.json_response({"status": "success", "data": sorted(out)})
    except (PromQLError, HoraeError) as e:
        return _promql_error(e)


async def handle_debug_traces(request: web.Request) -> web.Response:
    """Recent traces, newest first (summaries; span trees via /{id}).
    `?limit=N` bounds the count; `?min_ms=X` keeps only traces at least
    that slow — together they serve the operator's "last 10 slow traces"
    pull without scraping the whole ring."""
    try:
        limit = int(request.query.get("limit", 50))
    except ValueError:
        return web.json_response({"error": "limit must be an int"}, status=400)
    min_ms = None
    if "min_ms" in request.query:
        try:
            min_ms = float(request.query["min_ms"])
        except ValueError:
            return web.json_response(
                {"error": "min_ms must be a number"}, status=400
            )
    return web.json_response({
        "sampling": tracing.sampling_enabled(),
        "traces": tracing.recent(limit, min_ms=min_ms),
    })


async def handle_debug_trace(request: web.Request) -> web.Response:
    """One trace's span tree by id (the X-Horaedb-Trace-Id header value)."""
    t = tracing.get(request.match_info["id"])
    if t is None:
        return web.json_response(
            {"error": "unknown trace id (evicted from the ring, or never "
                      "sampled)"},
            status=404,
        )
    return web.json_response(t)


async def handle_debug_kernels(request: web.Request) -> web.Response:
    """Process-wide instrumented-kernel catalog (common/xprof.py): per
    kernel, the compile/retrace history, distinct arg-signatures, and —
    where the backend supports cost/memory analysis — the predicted
    FLOPs/bytes envelope with its arithmetic intensity. The static half of
    the roofline story; /metrics' stage histograms are the measured half."""
    try:
        import jax

        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — catalog must render without a backend
        backend = None
    return web.json_response({
        "backend": backend,
        "totals": xprof.snapshot(),
        "kernels": xprof.catalog(),
    })


async def handle_debug_slowlog(request: web.Request) -> web.Response:
    """Slow-query flight recorder contents, slowest first: each entry is
    one recorded request's full span tree + EXPLAIN payload. `?limit=N`
    bounds the response; corrupt spool entries are skipped (logged +
    counted in `corrupt_skipped`), never a 500."""
    state: ServerState = request.app[STATE_KEY]
    if state.slowlog is None:
        return web.json_response({
            "enabled": False, "capacity": 0, "entries": [],
        })
    limit = None
    if "limit" in request.query:
        try:
            limit = int(request.query["limit"])
        except ValueError:
            return web.json_response(
                {"error": "limit must be an int"}, status=400
            )
    entries, corrupt = state.slowlog.entries(limit=limit)
    return web.json_response({
        "enabled": True,
        "capacity": state.slowlog.capacity,
        "min_duration_s": state.slowlog.min_duration_s,
        "corrupt_skipped": corrupt,
        "entries": entries,
    })


async def handle_debug_memory(request: web.Request) -> web.Response:
    """`GET /debug/memory`: the data-plane memory observatory on one
    page — unified pool occupancy (all five byte-budgeted caches through
    the common/bytebudget registry), process RSS, the per-stage copy-tax
    table accumulated since boot, and the memtrace mode. Every number is
    a read-back of state the process already keeps; the handler computes
    nothing new."""
    pools = GLOBAL_POOLS.refresh()
    return web.json_response({
        "memtrace_mode": memtrace.mode() or "default",
        "rss_bytes": rss_bytes(),
        "pools": pools,
        # since-boot lineage aggregate, sorted by bytes moved: the
        # fleet-independent face of the per-query EXPLAIN verdict
        "copy_tax": memtrace.copy_tax_table(),
    })


async def handle_buildinfo(request: web.Request) -> web.Response:
    """Minimal Prometheus buildinfo (datasource health checks probe it)."""
    return web.json_response({
        "status": "success",
        "data": {"version": "2.45.0", "application": "horaedb-tpu"},
    })


async def handle_query_exemplars(request: web.Request) -> web.Response:
    """Prometheus /api/v1/query_exemplars (Grafana's trace-integration
    surface): instant-selector `query` + start/end seconds -> exemplars
    grouped per series with their trace labels."""
    from horaedb_tpu.engine.types import decode_series_key
    from horaedb_tpu.promql import PromQLError, Selector, parse
    from horaedb_tpu.promql.eval import _to_query

    state: ServerState = request.app[STATE_KEY]
    st = None
    try:
        p = await _promql_params(request)
        node = parse(p["query"])
        if not isinstance(node, Selector) or node.range_ms is not None:
            raise PromQLError("query must be an instant vector selector")
        start_ms = int(float(p["start"]) * 1000)
        end_ms = int(float(p["end"]) * 1000)
        dl = _query_deadline(state, p.get("timeout"))
        req = _to_query(node, start_ms, end_ms + 1)
        req.limit = 10_000
        with scanstats.scan_stats() as st, \
                deadline_ctx.deadline_scope(dl):
            table, _slot = await admission.run_query_exemplars(
                state.admission, state.engine, req,
                tenant=_tenant_of(request),
            )
    except DeadlineExceeded as e:
        _meter_scan(request, st)
        return deadline_response(e, progress=_progress_payload(st))
    except UnavailableError as e:
        _meter_scan(request, st)
        return unavailable_response(e)
    except (PromQLError, HoraeError, KeyError, ValueError) as e:
        _meter_scan(request, st)  # post-scan errors paid for bytes
        return _promql_error(e)
    METRICS.inc("horaedb_queries_total")
    _meter_scan(request, st)
    if table is None or table.num_rows == 0:
        return web.json_response({"status": "success", "data": []})
    matched = await state.engine.match_series(req.metric, req.filters, req.matchers)
    by_tsid: dict[int, list] = {}
    tsids = table.column("tsid").to_pylist()
    tss = table.column("ts").to_pylist()
    vals = table.column("value").to_pylist()
    blobs = table.column("labels").to_pylist()
    for t, ts, v, blob in zip(tsids, tss, vals, blobs):
        by_tsid.setdefault(int(t), []).append({
            "labels": {
                k.decode(errors="replace"): val.decode(errors="replace")
                for k, val in decode_series_key(blob or b"")
            },
            "value": str(v),
            "timestamp": ts / 1000.0,
        })
    data = []
    for t, exemplars in sorted(by_tsid.items()):
        labs = matched.get(t, {})
        series_labels = {
            k.decode(errors="replace"): v.decode(errors="replace")
            for k, v in labs.items()
        }
        series_labels["__name__"] = node.name
        data.append({"seriesLabels": series_labels, "exemplars": exemplars})
    return web.json_response({"status": "success", "data": data})


async def handle_metadata(request: web.Request) -> web.Response:
    """Prometheus-shaped /api/v1/metadata: metric family -> [{"type": t}],
    from remote-write METADATA records (advisory, in-memory)."""
    state: ServerState = request.app[STATE_KEY]
    meta = state.engine.metadata()
    return web.json_response({
        "status": "success",
        "data": {
            name.decode(errors="replace"): [{"type": t}]
            for name, t in sorted(meta.items())
        },
    })


# ---------------------------------------------------------------------------
# self-telemetry surface (horaedb_tpu/telemetry)
# ---------------------------------------------------------------------------


async def handle_usage(request: web.Request) -> web.Response:
    """Per-tenant usage summary (telemetry/metering.py, the J015 funnel):
    `?tenant=X` for one tenant (since-boot + `?window=5m` trailing view);
    without `tenant`, every known tenant's since-boot totals. Serving
    this never touches the query path — it reads the in-memory ledger."""
    window_s = None
    raw_window = request.query.get("window")
    if raw_window:
        try:
            # the admission parser is the one float-or-duration reader
            # (and the one that rejects NaN/inf — a NaN window would
            # silently sum nothing). Clamped to the ledger's actual ring
            # horizon (1 h): a wider window CANNOT be answered here —
            # the clamp is visible in the response's `seconds`, and
            # `coverage_seconds` marks any further truncation (short
            # uptime). Longer ranges are a PromQL query over the
            # self-scraped horaedb_tenant_* series.
            from horaedb_tpu.telemetry.metering import UsageMeter

            window_s = admission.parse_timeout_s(
                raw_window, 300.0, UsageMeter.horizon_s()
            )
        except Exception as e:  # noqa: BLE001 — client data
            return web.json_response(
                {"status": "error", "errorType": "bad_data",
                 "error": f"bad window: {e}"},
                status=400,
            )
    tenant = request.query.get("tenant")
    if tenant:
        data = _METER.summary(tenant, window_s=window_s)
    else:
        data = {
            "tenants": [
                _METER.summary(t, window_s=window_s)
                for t in _METER.tenants()
            ],
        }
    return web.json_response({"status": "success", "data": data})


def _telemetry_unavailable() -> web.Response:
    return web.json_response(
        {"status": "error", "errorType": "unavailable",
         "error": "self-telemetry disabled ([metric_engine.telemetry] "
                  "enabled = false, or HORAEDB_TELEMETRY=off)"},
        status=501,
    )


async def handle_telemetry_scrape(request: web.Request) -> web.Response:
    """Force one self-scrape tick NOW (admin/debug; the smoke gate uses
    it instead of waiting out the interval). `?include=<prefix>` echoes
    the written samples whose __name__ starts with the prefix — the
    bit-equality oracle for range-query checks."""
    state: ServerState = request.app[STATE_KEY]
    if state.telemetry is None:
        return _telemetry_unavailable()
    # a forced tick also forces a federation sweep (when configured):
    # the operator probing "is telemetry flowing" means the FLEET view
    summary = await shield_mutation(
        state.telemetry.tick(force_federation=True)
    )
    if summary.get("error"):
        # the background loop retries silently; the FORCED tick is an
        # operator probe, and a probe must not dress a failed write as
        # success (automation keys on the status)
        return web.json_response(
            {"status": "error", "errorType": "internal",
             "error": "self-scrape tick failed (see server log)",
             "data": summary},
            status=503,
        )
    samples = summary.pop("samples_list", [])
    include = request.query.get("include")
    if include:
        summary["matched"] = [
            {"name": n, "labels": dict(k), "value": v}
            for n, k, v in samples if n.startswith(include)
        ]
    return web.json_response({"status": "success", "data": summary})


async def handle_telemetry_snapshot(request: web.Request) -> web.Response:
    """`GET /api/v1/telemetry/snapshot`: the registry's JSON twin of
    /metrics — [[sample name, [[label, value]...], value]...] — what a
    peer's federation sweep pulls through the traced client funnel.
    Served regardless of the local collector (a read-only replica never
    WRITES its own telemetry, but the fleet still scrapes it)."""
    state: ServerState = request.app[STATE_KEY]
    cl = state.cluster
    node = (cl.node_id if cl is not None
            else state.config.metric_engine.telemetry.instance)
    return web.json_response({"status": "success", "data": {
        "node": node,
        "samples": METRICS.federation_snapshot(),
    }})


# ---------------------------------------------------------------------------
# streaming rule engine surface (horaedb_tpu/rules)
# ---------------------------------------------------------------------------


def _selector_names(expr) -> tuple:
    """Metric names a parsed PromQL expression reads (EXPLAIN rule
    provenance for the PromQL handlers) — the shared promql walker."""
    from horaedb_tpu.promql.eval import selector_metrics

    return selector_metrics(expr)


def _rule_provenance(state: "ServerState", metrics) -> dict | None:
    """EXPLAIN provenance for rule-produced series: which of the queried
    metrics are recording-rule outputs, and the producing rule's body —
    so a dashboard reading `cpu:rate5m` can see it is materialized, by
    what, from what."""
    if state.rules is None:
        return None
    hit = sorted(set(metrics) & state.rules.output_metrics())
    if not hit:
        return None
    produced = {}
    for m in hit:
        rule = state.rules.rule_for_metric(m)
        if rule is not None:
            produced[m] = {"rule": rule.name, "expr": rule.expr,
                           "interval_ms": rule.interval_ms}
    return {"rule_produced": produced}


def _attach_rule_provenance(state, explain, metrics) -> None:
    if explain is None:
        return
    prov = _rule_provenance(state, metrics)
    if prov is not None:
        explain["rules"] = prov


def _rules_unavailable() -> web.Response:
    return web.json_response(
        {"status": "error", "errorType": "unavailable",
         "error": "rule engine disabled ([metric_engine.rules] "
                  "enabled = false)"},
        status=501,
    )


async def handle_rules_get(request: web.Request) -> web.Response:
    """Registered rules, Prometheus /api/v1/rules groups shape (one
    implicit group per kind), with live alert state folded in."""
    state: ServerState = request.app[STATE_KEY]
    if state.rules is None:
        return _rules_unavailable()
    recording, alerting = [], []
    # named rule GROUPS (shared interval, ordered in-tick evaluation):
    # each renders as its own Prometheus group; ungrouped recording
    # rules keep the implicit "recording" group
    named_groups: dict[str, list] = {}
    active = {}
    for a in state.rules.alerts():
        active.setdefault(a["labels"]["alertname"], []).append(a)
    for rule in state.rules.list_rules():
        if rule.kind == "recording":
            entry = {
                "type": "recording", "name": rule.name,
                "query": rule.expr, "labels": rule.labels,
                "interval": rule.interval_ms / 1000.0,
            }
            if getattr(rule, "group", ""):
                entry["group_order"] = rule.group_order
                named_groups.setdefault(rule.group, []).append(
                    (rule.group_order, rule.name, entry)
                )
            else:
                recording.append(entry)
        else:
            alerts = active.get(rule.name, [])
            worst = "inactive"
            if any(a["state"] == "firing" for a in alerts):
                worst = "firing"
            elif alerts:
                worst = "pending"
            alerting.append({
                "type": "alerting", "name": rule.name,
                "query": rule.expr, "duration": rule.for_ms / 1000.0,
                "labels": rule.labels, "annotations": rule.annotations,
                "state": worst, "alerts": alerts,
            })
    groups = []
    if recording:
        groups.append({"name": "recording", "rules": recording})
    for g in sorted(named_groups):
        members = [e for _o, _n, e in sorted(named_groups[g],
                                             key=lambda t: t[:2])]
        groups.append({
            "name": g,
            # the group-shared interval (registration enforces equality)
            "interval": members[0]["interval"],
            "rules": members,
        })
    if alerting:
        groups.append({"name": "alerting", "rules": alerting})
    return web.json_response({"status": "success",
                              "data": {"groups": groups}})


async def handle_rules_post(request: web.Request) -> web.Response:
    """Register (or replace, by name) one rule. Body: {"kind":
    "recording"|"alert", "name", "expr", "interval"|"for", "labels",
    "annotations"}. The PUT of the durable record is the registration's
    durability point — a 200 means the rule survives restarts."""
    from horaedb_tpu.promql import PromQLError
    from horaedb_tpu.rules import rule_from_dict

    state: ServerState = request.app[STATE_KEY]
    if state.rules is None:
        return _rules_unavailable()
    try:
        body = await request.json()
    except Exception as e:  # noqa: BLE001 — client data
        return _promql_error(ValueError(f"bad JSON body: {e}"))
    try:
        rule = rule_from_dict(body, now_ms=now_ms())
        # idempotent like the boot path: re-POSTing an UNCHANGED
        # definition (config-sync reconciliation) must not reset the
        # watermark or wipe the alert state machine / transition log
        changed = await shield_mutation(state.rules.ensure_registered(rule))
    except UnavailableError as e:
        return unavailable_response(e)
    except (PromQLError, HoraeError, KeyError, TypeError, ValueError) as e:
        return _promql_error(e)
    METRICS.inc("horaedb_rules_api_registrations_total")
    return web.json_response({
        "status": "success",
        "data": {"kind": rule.kind, "name": rule.name, "expr": rule.expr,
                 "updated": changed},
    })


async def handle_rules_delete(request: web.Request) -> web.Response:
    state: ServerState = request.app[STATE_KEY]
    if state.rules is None:
        return _rules_unavailable()
    name = request.match_info["name"]
    try:
        known = await shield_mutation(state.rules.delete(name))
    except UnavailableError as e:
        return unavailable_response(e)
    if not known:
        return web.json_response(
            {"status": "error", "errorType": "bad_data",
             "error": f"unknown rule {name!r}"},
            status=404,
        )
    return web.json_response({"status": "success", "data": {"deleted": name}})


async def handle_alerts(request: web.Request) -> web.Response:
    """Active alerts (Prometheus /api/v1/alerts shape). The optional
    `?transitions=<rule>` debug view returns that rule's durable
    transition-log tail (the exactly-once record the runbooks and the
    chaos oracle read)."""
    state: ServerState = request.app[STATE_KEY]
    if state.rules is None:
        return _rules_unavailable()
    name = request.query.get("transitions")
    if name:
        return web.json_response({
            "status": "success",
            "data": {"rule": name,
                     "transitions": state.rules.transitions(name)},
        })
    return web.json_response({
        "status": "success", "data": {"alerts": state.rules.alerts()},
    })


async def handle_rules_tick(request: web.Request) -> web.Response:
    """Force one evaluator tick NOW (admin/debug; the smoke gate and
    stuck-pending runbooks use it instead of waiting out the interval).
    Serialized with the background loop by the engine's tick lock."""
    state: ServerState = request.app[STATE_KEY]
    if state.rules is None:
        return _rules_unavailable()
    try:
        summary = await shield_mutation(state.rules.tick())
    except UnavailableError as e:
        return unavailable_response(e)
    return web.json_response({"status": "success", "data": summary})


# ---------------------------------------------------------------------------
# cluster surface (horaedb_tpu/cluster)
# ---------------------------------------------------------------------------


def _cluster_regions_view(state: "ServerState") -> dict:
    """{region_id: {"owned", "epoch"}} for the status payload — works for
    a single engine, a regioned engine, and a replica facade alike."""
    eng = state.engine
    engines = getattr(eng, "engines", None)
    if engines is None:
        return {"0": {
            "owned": not getattr(eng, "read_only", False),
            "epoch": eng.manifest_epoch(),
        }}
    return {
        str(i): {"owned": not sub.read_only, "epoch": sub.manifest_epoch()}
        for i, sub in sorted(engines.items())
    }


_BREAKER_STATES = {0: "closed", 1: "half_open", 2: "open"}


def _load_view() -> dict:
    """This node's load in one dict, read ENTIRELY from the metric
    registry (no reach into admission/resilient internals — the metrics
    are the stable contract): admission inflight/queued, object-store
    breaker states, shed totals by reason. Rides the cluster status
    payload, so peers' probe loops carry every node's load to every
    /debug/cluster page within one probe interval."""
    view: dict = {"inflight": 0, "queued": 0, "breakers": {}, "sheds": {}}
    for family, _type, _sample, key, value in METRICS.snapshot_samples():
        if family == "horaedb_query_inflight":
            view["inflight"] = int(value)
        elif family == "horaedb_query_queued":
            view["queued"] = int(value)
        elif family == "horaedb_objstore_breaker_state":
            store = dict(key).get("store", "?")
            view["breakers"][store] = _BREAKER_STATES.get(
                int(value), str(value)
            )
        elif family == "horaedb_query_shed_total" and value:
            view["sheds"][dict(key).get("reason", "?")] = value
    return view


async def handle_cluster_status(request: web.Request) -> web.Response:
    """`/api/v1/cluster/status`: this node's role, per-region ownership +
    manifest epochs, the staleness token (replicas), the assignment-map
    view, and peer health — the router's probe target AND the operator's
    catch-up check (writer epoch == replica epoch means caught up)."""
    state: ServerState = request.app[STATE_KEY]
    cl = state.cluster
    if cl is None:
        return web.json_response({"status": "success", "data": {
            "enabled": False, "role": "standalone",
            "manifest_epoch": state.engine.manifest_epoch(),
        }})
    data = {
        "enabled": True,
        "role": cl.role,
        "node": cl.node_id,
        "standby": cl.standby,
        "partial": cl.partial,
        "manifest_epoch": state.engine.manifest_epoch(),
        "regions": _cluster_regions_view(state),
        "peers": cl.router.peer_status(),
        "load": _load_view(),
    }
    if cl.replica is not None:
        st = cl.replica.staleness()
        data["manifest_epoch"] = st["manifest_epoch"]
        data["staleness_ms"] = st["staleness_ms"]
        data["stale"] = (
            st["staleness_ms"] / 1000.0
            > cl.config.max_staleness.seconds
        )
    asg = cl.router.assignment
    if asg is not None:
        data["assignment"] = {
            "version": asg.version,
            "regions": {str(r): n for r, n in sorted(asg.regions.items())},
        }
    return web.json_response({"status": "success", "data": data})


async def handle_cluster_refresh(request: web.Request) -> web.Response:
    """Force one watch probe NOW (admin/debug; smoke gates and tests use
    it instead of waiting out the watch interval). On a replica this
    swaps in any fresh snapshots; on a partial writer it refreshes the
    non-owned (read-only) region views. Either way one peer-probe round
    runs first, so a peer that was down at boot (and got marked
    unhealthy by the initial probe) rejoins the routable set without
    waiting out the probe interval."""
    state: ServerState = request.app[STATE_KEY]
    cl = state.cluster
    if cl is None:
        return web.json_response(
            {"status": "error", "errorType": "unavailable",
             "error": "cluster layer disabled ([metric_engine.cluster])"},
            status=501,
        )
    if cl.router.peers:
        try:
            await cl.router.probe_once()
        except Exception:  # noqa: BLE001 — health converges on the loop
            logger.warning("forced peer probe failed", exc_info=True)
    if cl.replica is not None:
        try:
            outcome = await shield_mutation(cl.replica.watch_once())
        except Exception as e:  # noqa: BLE001 — faulted store
            return unavailable_response(UnavailableError(
                f"refresh probe failed: {e}"
            ))
        return web.json_response({"status": "success", "data": {
            "outcome": outcome, **cl.replica.staleness(),
        }})
    engines = getattr(state.engine, "engines", None)
    refreshed = []
    if engines is not None:
        for rid, sub in sorted(engines.items()):
            if sub.read_only:
                await shield_mutation(state.engine.refresh_region(rid))
                refreshed.append(rid)
    return web.json_response({"status": "success", "data": {
        "outcome": "refreshed" if refreshed else "noop",
        "regions": refreshed,
        "manifest_epoch": state.engine.manifest_epoch(),
    }})


async def handle_cluster_takeover(request: web.Request) -> web.Response:
    """Writer takeover (`?region=all` or `?region=<id>`): rewrite the
    assignment map to name THIS node the owner, then reopen the region
    as a writer — the fresh epoch-fence acquisition deposes the lapsed
    writer regardless of what it believes (storage/fence.py). The
    operator runbook for a dead writer (docs/operations.md "Scale-out");
    background rule/telemetry loops resume on the next boot."""
    from horaedb_tpu.cluster import TAKEOVERS
    from horaedb_tpu.cluster import assignment as asg_mod

    state: ServerState = request.app[STATE_KEY]
    cl = state.cluster
    if cl is None or cl.role != "writer":
        return web.json_response(
            {"error": "takeover requires cluster role = writer"}, status=400
        )
    raw = request.query.get("region", "all")
    asg = cl.router.assignment or await asg_mod.load_assignment(
        cl.store, cl.cluster_root
    )
    # the regions this deployment actually has: the engine's live set,
    # plus anything the assignment map names (a split elsewhere)
    engines = getattr(state.engine, "engines", None)
    known = set(asg.regions) | (set(engines) if engines is not None
                                else {0})
    if raw == "all":
        targets = sorted(known - set(asg.regions_of(cl.node_id)))
    else:
        try:
            targets = [int(raw)]
        except ValueError:
            return web.json_response(
                {"error": "?region= must be an int or 'all'"}, status=400
            )
        unknown = [r for r in targets if r not in known]
        if unknown:
            # never commit an assignment version (a permanent audit-log
            # record) for a region that does not exist
            return web.json_response(
                {"error": f"unknown region(s) {unknown}; known: "
                          f"{sorted(known)}"},
                status=400,
            )
    taken = []
    for rid in targets:
        def mutate(regions, rid=rid):
            regions[int(rid)] = cl.node_id
            return regions

        asg = await shield_mutation(asg_mod.propose_assignment(
            cl.store, cl.cluster_root, cl.node_id, mutate
        ))
        engines = getattr(state.engine, "engines", None)
        if engines is not None and rid in engines:
            if engines[rid].read_only:
                await shield_mutation(
                    state.engine.promote_region(rid, cl.node_id)
                )
        elif cl.replica is not None or cl.standby:
            # single-engine standby: swap the replica facade for a real
            # writer engine (the open's fence acquisition deposes)
            new_engine = await shield_mutation(MetricEngine.open(
                cl.engine_root, cl.store,
                **{**cl.engine_kwargs, "fence_node_id": cl.node_id},
            ))
            old = state.engine
            state.engine = new_engine
            if cl.replica is not None:
                await cl.replica.close()
            else:
                await old.close()
            cl.replica = None
            cl.standby = False
        TAKEOVERS.inc()
        taken.append(rid)
    cl.router.set_assignment(asg)
    if getattr(state.engine, "engines", None) is not None:
        cl.partial = any(
            sub.read_only for sub in state.engine.engines.values()
        )
    return web.json_response({"status": "success", "data": {
        "taken": taken,
        "assignment_version": asg.version,
        "regions": _cluster_regions_view(state),
        # rule evaluation / self-telemetry were sized for the boot-time
        # role; a restart picks them up under the new ownership
        "restart_recommended": bool(taken) and (state.rules is None),
    }})


async def handle_debug_cluster(request: web.Request) -> web.Response:
    """`GET /debug/cluster`: the fleet on one page — this node's role,
    epoch, staleness/watch posture and load, plus every peer as the
    router sees it (health, probe-reported role/epoch/staleness/load)
    and the telemetry-federation posture. Everything here is already
    in memory (registry reads + the router's probe cache): rendering
    the page costs no cluster traffic."""
    state: ServerState = request.app[STATE_KEY]
    cl = state.cluster
    self_view: dict = {
        "node": (cl.node_id if cl is not None
                 else state.config.metric_engine.telemetry.instance),
        "role": cl.role if cl is not None else "standalone",
        "manifest_epoch": state.engine.manifest_epoch(),
        "load": _load_view(),
    }
    if cl is not None:
        self_view["standby"] = cl.standby
        self_view["partial"] = cl.partial
        if cl.replica is not None:
            self_view["replica"] = cl.replica.watch_stats()
    federation = (state.telemetry.federation_status()
                  if state.telemetry is not None else {"enabled": False})
    data = {
        "enabled": cl is not None,
        "self": self_view,
        "peers": cl.router.peer_detail() if cl is not None else {},
        "federation": federation,
    }
    if cl is not None and cl.router.assignment is not None:
        asg = cl.router.assignment
        data["assignment"] = {
            "version": asg.version,
            "regions": {str(r): n
                        for r, n in sorted(asg.regions.items())},
        }
    return web.json_response({"status": "success", "data": data})


# ---------------------------------------------------------------------------
# self-write load generator (main.rs:187-233)
# ---------------------------------------------------------------------------


async def bench_write_worker(state: ServerState, worker_id: int) -> None:
    interval = state.config.test.write_interval.seconds
    rng = np.random.default_rng(worker_id)
    schema = build_demo_schema()
    while True:
        await state.write_enabled.wait()
        t = now_ms()
        batch = pa.RecordBatch.from_pydict(
            {
                "pk1": rng.integers(0, 1000, 1000),
                "pk2": rng.integers(0, 1000, 1000),
                "pk3": rng.integers(0, 1000, 1000),
                "value": rng.integers(0, 1_000_000, 1000),
            },
            schema=schema,
        )
        try:
            await state.storage.write(
                WriteRequest(batch, TimeRange(t, t + 1), enable_check=True)
            )
            METRICS.inc("horaedb_bench_writes_total")
        except Exception:  # noqa: BLE001
            logger.exception("bench write failed")
        await asyncio.sleep(interval)


# ---------------------------------------------------------------------------
# bootstrap
# ---------------------------------------------------------------------------


async def build_app(config: Config, store=None) -> web.Application:
    """`store`: optional pre-built ObjectStore overriding the config's
    store selection — the chaos gate (tools/chaos_smoke.py) boots the
    real server over a ChaosStore this way. Callers injecting a store
    own its resilience wrapping; config-built stores are always wrapped
    in a ResilientStore here, so every component (engine flush,
    manifest, fence, compaction, scan reads) inherits the retry/breaker
    policy."""
    from concurrent.futures import ThreadPoolExecutor

    config.validate()
    # memory observatory mode ([metric_engine.memory] memtrace, default
    # from HORAEDB_MEMTRACE — the config never clobbers an env override)
    memtrace.configure(config.metric_engine.memory.memtrace)
    store_cfg = config.metric_engine.storage.object_store
    # imported at boot so horaedb_agg_impl_total renders on /metrics even
    # before the first aggregate dispatch
    from horaedb_tpu.ops import agg_registry

    # same contract for the horaedb_jit_* families (lazy by module
    # layering; forced here so scrapers see the zero state from boot)
    xprof.register_metrics()

    res = store_cfg.resilience
    if store is not None:
        pass  # injected store: caller owns wrapping (see docstring)
    elif store_cfg.type.lower() == "s3like":
        from horaedb_tpu.objstore.s3 import S3LikeStore

        store = ResilientStore(
            S3LikeStore(store_cfg.to_s3_config()),
            retry=res.retry, breaker=res.breaker, name="s3like",
        )
    else:
        store = ResilientStore(
            LocalStore(store_cfg.data_dir),
            retry=res.retry, breaker=res.breaker, name="local",
        )
        # aggregation + decode calibration caches live under the data root
        # (an S3 deployment keeps the tmpdir default — the caches are
        # per-BOX measurement, not shared state)
        agg_registry.configure_cache_dir(store_cfg.data_dir)
        from horaedb_tpu.ops import decode as decode_ops

        decode_ops.configure_cache_dir(store_cfg.data_dir)
    segment_ms = config.test.segment_duration.as_millis()
    # ThreadConfig sizes the dedicated executor for CPU-heavy SST work —
    # the analog of the reference's named multi-thread runtimes
    # (main.rs:102-119): heavy compaction encodes no longer compete with
    # ingest for the event loop's default pool.
    sst_executor = ThreadPoolExecutor(
        max_workers=config.metric_engine.threads.sst_thread_num,
        thread_name_prefix="sst",
    )
    manifest_executor = ThreadPoolExecutor(
        max_workers=config.metric_engine.threads.manifest_thread_num,
        thread_name_prefix="manifest",
    )
    cluster_cfg = config.metric_engine.cluster
    replica_role = cluster_cfg.enabled and cluster_cfg.role == "replica"
    # The demo root has no epoch fence: in ANY cluster topology (writer +
    # standby included) a second process running its merger/compaction/GC
    # would be an unfenced concurrent mutator on the shared bucket. It
    # opens writable only when this process actually drives it (the
    # self-write load generator, single-process by config validation).
    demo_read_only = cluster_cfg.enabled and (
        replica_role or not config.test.enable_write
    )
    storage = await ObjectBasedStorage.try_new(
        root="demo",
        store=store,
        arrow_schema=build_demo_schema(),
        num_primary_keys=3,
        segment_duration_ms=segment_ms,
        config=config.metric_engine.storage.time_merge_storage,
        sst_executor=sst_executor,
        manifest_executor=manifest_executor,
        read_only=demo_read_only,
    )
    # one shared parser pool: the /metrics pool telemetry must reflect the
    # pool the engine's ingest actually borrows from
    pool = ParserPool()
    engine_kwargs = dict(
        segment_duration_ms=segment_ms,
        config=config.metric_engine.storage.time_merge_storage,
        sst_executor=sst_executor,
        manifest_executor=manifest_executor,
        ingest_buffer_rows=config.metric_engine.ingest_buffer_rows,
        # overlapped ingest->flush pipeline sizing ([metric_engine.ingest])
        flush_workers=config.metric_engine.ingest.flush_workers,
        flush_queue_max=config.metric_engine.ingest.flush_queue_max,
        flush_stall_deadline_s=config.metric_engine.ingest.stall_deadline.seconds,
        # dirty-traffic knobs: retention horizon ([metric_engine.retention])
        # and the series-cardinality limit ([metric_engine.limits])
        retention_period_ms=config.metric_engine.retention.period_ms(),
        max_series=config.metric_engine.limits.max_series,
        # serving tier ([metric_engine.serving]): rollups + result cache +
        # device residency, bit-exact vs HORAEDB_SERVING=off
        serving=config.metric_engine.serving,
        parser_pool=pool,
    )
    if config.metric_engine.node_id:
        # multi-process shared store: claim per-region write ownership
        engine_kwargs["fence_node_id"] = config.metric_engine.node_id
    num_regions = config.metric_engine.num_regions
    granularity = config.metric_engine.region_granularity
    cluster_state: "ClusterState | None" = None
    if cluster_cfg.enabled:
        from horaedb_tpu.cluster import assignment as asg_mod
        from horaedb_tpu.cluster.replica import ReplicaEngine
        from horaedb_tpu.cluster.router import ClusterRouter

        node_id = config.metric_engine.node_id
        router = ClusterRouter(cluster_cfg, node_id)
        cluster_root = "metrics/cluster"
        replica_kwargs = {
            k: v for k, v in engine_kwargs.items()
            if k not in ("fence_node_id",)
        }
        if replica_role:
            replica = await ReplicaEngine.open(
                "metrics", store,
                num_regions=num_regions, granularity=granularity,
                watch_interval_s=cluster_cfg.watch_interval.seconds,
                watch_backoff_cap_s=cluster_cfg.watch_backoff_cap.seconds,
                engine_kwargs=replica_kwargs,
                # a racing boot waits for the writer's store layout
                open_retries=40, open_retry_delay_s=0.5,
            )
            engine = replica
            try:
                router.set_assignment(
                    await asg_mod.load_assignment(store, cluster_root)
                )
            except Exception:  # noqa: BLE001 — routing converges on probes
                logger.warning("assignment map unreadable at replica boot")
            cluster_state = ClusterState(
                cluster_cfg, node_id, router, replica=replica,
                store=store, cluster_root=cluster_root,
                engine_kwargs=replica_kwargs,
            )
        else:
            # writer: claim regions per the assignment map (never steals;
            # takeover is the explicit /api/v1/cluster/takeover op).
            # Unowned regions claim to SELF — first writer to boot owns
            # them; a later writer finds them taken and serves as a
            # standby. Rendezvous-splitting regions across several LIVE
            # writers is a deliberate operator action (the assignment
            # API's writer_nodes bootstrap / per-region takeover), never
            # an inference from the peer table: a configured-but-down
            # peer must not be handed regions nobody can write.
            region_ids = list(range(num_regions))
            asg = await asg_mod.claim_regions(
                store, cluster_root, node_id, region_ids, [node_id],
            )
            owned = set(asg.regions_of(node_id))
            router.set_assignment(asg)
            standby = False
            replica = None
            if num_regions > 1:
                from horaedb_tpu.engine.region import RegionedEngine

                engine = await RegionedEngine.open(
                    "metrics", store, num_regions,
                    granularity=granularity,
                    writable_regions=(None if owned == set(region_ids)
                                      else owned),
                    **engine_kwargs,
                )
            elif 0 in owned:
                engine = await MetricEngine.open(
                    "metrics", store, **engine_kwargs,
                )
            else:
                # standby writer: another writer owns the region — serve
                # reads as a replica until takeover promotes this node
                standby = True
                replica = await ReplicaEngine.open(
                    "metrics", store,
                    num_regions=num_regions, granularity=granularity,
                    watch_interval_s=cluster_cfg.watch_interval.seconds,
                    watch_backoff_cap_s=cluster_cfg.watch_backoff_cap.seconds,
                    engine_kwargs=replica_kwargs,
                    open_retries=40, open_retry_delay_s=0.5,
                )
                engine = replica
            cluster_state = ClusterState(
                cluster_cfg, node_id, router, replica=replica,
                standby=standby,
                partial=(num_regions > 1 and owned != set(region_ids)),
                store=store, cluster_root=cluster_root,
                engine_kwargs=replica_kwargs,
            )
    elif num_regions > 1:
        from horaedb_tpu.engine.region import RegionedEngine

        engine = await RegionedEngine.open(
            "metrics", store, num_regions,
            granularity=granularity,
            **engine_kwargs,
        )
    else:
        engine = await MetricEngine.open("metrics", store, **engine_kwargs)
    engine_read_only = bool(getattr(engine, "read_only", False))
    slow = None
    if config.slowlog.capacity > 0:
        import os as _os

        # the spool is per-box diagnostic state, like the agg-calib cache:
        # it lives under the LOCAL data dir even for S3 deployments
        slow = SlowLog(
            _os.path.join(store_cfg.data_dir, "slowlog"),
            capacity=config.slowlog.capacity,
            min_duration_s=config.slowlog.min_duration.seconds,
        )
    qcfg = config.metric_engine.query
    rcfg = config.metric_engine.rules
    tcfg = config.metric_engine.telemetry
    # rule evaluations run as a distinct weighted-fair tenant; its LOW
    # default share means a rule storm queues behind dashboards, never
    # ahead of them (an explicit tenant_weights entry wins). The
    # self-scrape `_system` tenant gets the same treatment.
    weights = dict(qcfg.tenant_weights)
    weights.setdefault(rcfg.tenant, rcfg.tenant_weight)
    weights.setdefault(tcfg.tenant, tcfg.tenant_weight)
    adm = AdmissionController(
        max_concurrent=qcfg.max_concurrent,
        max_per_tenant=qcfg.max_per_tenant,
        queue_max=qcfg.queue_max,
        queue_deadline_s=qcfg.queue_deadline.seconds,
        max_cost_s=qcfg.max_cost_s,
        weights=weights,
    )
    # query batcher ([metric_engine.query.batching], server/batching.py):
    # process-global like the serving caches — the planner rides the
    # engine's cold downsample path, so configuring it here covers every
    # read surface (native JSON, PromQL, rules, regioned fan-out)
    from horaedb_tpu.server import batching as batching_mod

    batching_mod.GLOBAL_BATCHER.configure(qcfg.batching)
    from horaedb_tpu import telemetry as telemetry_mod

    rules_engine = None
    if rcfg.enabled and engine_read_only:
        # rules materialize output through the ingest path and checkpoint
        # fenced state — writer-only work; replicas serve the rule OUTPUT
        # series like any other data with bounded staleness
        logger.info("rule engine disabled on a read-only replica")
    elif rcfg.enabled:
        from horaedb_tpu.rules import rule_from_dict
        from horaedb_tpu.rules.engine import RuleEngine

        rules_engine = await RuleEngine.open(
            engine, store, root="metrics/rules",
            # single-writer discipline rides the engine's fence when one
            # is configured (regioned deployments fence per region root;
            # the rule store then relies on deployment discipline)
            fence=getattr(engine, "_fence", None),
            admission=adm, tenant=rcfg.tenant,
        )
        # config-declared rules: asserted idempotently (an unchanged
        # definition keeps its watermark / alert states across restarts).
        # SLO burn-rate templates (telemetry/slo.py) expand into the same
        # idempotent path — an unchanged [[metric_engine.slo]] block
        # keeps its rules' watermarks and alert states.
        declared = (
            list(rcfg.recording) + list(rcfg.alerting)
            + telemetry_mod.expand_slos(config.metric_engine.slo)
        )
        for entry in declared:
            await rules_engine.ensure_registered(
                rule_from_dict(entry, now_ms=now_ms())
            )
    collector = None
    if telemetry_mod.telemetry_enabled(tcfg.enabled) and engine_read_only:
        logger.info("self-telemetry collector disabled on a read-only "
                    "replica (its writes belong to the writer)")
    elif telemetry_mod.telemetry_enabled(tcfg.enabled):
        collector = telemetry_mod.SelfScrapeCollector(
            engine,
            tenant=tcfg.tenant,
            max_series=tcfg.max_series,
            exclude=tuple(tcfg.exclude),
            retention_ms=tcfg.retention_ms(),
            instance=tcfg.instance,
            # fleet federation: pull peers' snapshots through the cluster
            # router's traced client funnel (no cluster layer, no fleet)
            federation=tcfg.federation,
            router=(cluster_state.router
                    if cluster_state is not None else None),
        )
        if tcfg.federation.enabled and cluster_state is None:
            logger.warning(
                "[metric_engine.telemetry.federation] enabled without the "
                "cluster layer; there are no peers to scrape"
            )
    state = ServerState(config, storage, engine, parser_pool=pool,
                        slowlog=slow, admission_controller=adm,
                        rules=rules_engine, telemetry=collector,
                        cluster=cluster_state)
    if config.test.enable_write:
        state.write_enabled.set()
    for i in range(config.test.write_worker_num):
        state.write_workers.append(
            asyncio.create_task(bench_write_worker(state, i), name=f"bench-write-{i}")
        )
    if config.metric_engine.ingest_buffer_rows > 0 and not engine_read_only:
        # periodic flush bounds the buffered-ingest data-loss window
        interval = config.metric_engine.ingest_flush_interval.seconds

        async def flush_loop():
            while True:
                await asyncio.sleep(interval)
                try:
                    with tracing.trace("periodic_ingest_flush"):
                        await engine.flush()
                except Exception:  # noqa: BLE001 — keep flushing; writes retry
                    logger.exception("periodic ingest flush failed")

        state.write_workers.append(
            asyncio.create_task(flush_loop(), name="ingest-flush")
        )
    if rules_engine is not None:
        # the evaluator tick loop: dirty-set driven, so a quiet tick
        # costs ~nothing; failures log and retry next interval (the
        # dirty sets only clear on success, so nothing is lost)
        rules_interval = rcfg.eval_interval.seconds

        async def rules_loop():
            while True:
                await asyncio.sleep(rules_interval)
                try:
                    await rules_engine.tick()
                except Exception:  # noqa: BLE001 — keep ticking
                    logger.exception("rule evaluator tick failed")

        state.write_workers.append(
            asyncio.create_task(rules_loop(), name="rule-evaluator")
        )
    if collector is not None:
        # the self-scrape loop: the registry becomes first-class series
        # on this interval; tick failures log and retry (the collector
        # is stateless between ticks beyond its series budget)
        scrape_interval = tcfg.scrape_interval.seconds

        async def telemetry_loop():
            while True:
                await asyncio.sleep(scrape_interval)
                try:
                    await collector.tick()
                except Exception:  # noqa: BLE001 — keep scraping
                    logger.exception("self-scrape tick failed")

        state.write_workers.append(
            asyncio.create_task(telemetry_loop(), name="telemetry-scrape")
        )

    if cluster_state is not None:
        # background cluster fabric: the replica watch/swap loop and the
        # peer health probes (both tasks die with their owners' close)
        if cluster_state.replica is not None:
            cluster_state.replica.start_watch()
        cluster_state.router.start_probes()

    tracing.configure(
        sample=config.tracing.sample,
        slow_s=config.tracing.slow_threshold.seconds,
        ring=config.tracing.ring_capacity,
    )
    app = web.Application(
        client_max_size=64 * 1024 * 1024,
        middlewares=[observability_middleware, cluster_middleware],
    )
    app[STATE_KEY] = state
    app.add_routes(
        [
            web.get("/", handle_root),
            web.get("/toggle", handle_toggle),
            web.get("/compact", handle_compact),
            web.post("/admin/split_region", handle_split_region),
            web.get("/metrics", handle_metrics),
            web.post("/api/v1/write", handle_remote_write),
            web.post("/api/v1/query", handle_query),
            web.get("/api/v1/query", handle_query),
            web.get("/api/v1/query_range", handle_query_range),
            web.post("/api/v1/query_range", handle_query_range),
            web.get("/api/v1/query_exemplars", handle_query_exemplars),
            web.post("/api/v1/query_exemplars", handle_query_exemplars),
            web.get("/api/v1/labels", handle_labels),
            web.get("/api/v1/label/{name}/values", handle_label_values),
            web.get("/api/v1/metrics", handle_metrics_list),
            web.get("/api/v1/series", handle_series),
            web.get("/api/v1/metadata", handle_metadata),
            web.get("/api/v1/rules", handle_rules_get),
            web.post("/api/v1/rules", handle_rules_post),
            web.delete("/api/v1/rules/{name}", handle_rules_delete),
            web.get("/api/v1/alerts", handle_alerts),
            web.post("/api/v1/rules/tick", handle_rules_tick),
            web.get("/api/v1/usage", handle_usage),
            web.get("/api/v1/cluster/status", handle_cluster_status),
            web.post("/api/v1/cluster/refresh", handle_cluster_refresh),
            web.post("/api/v1/cluster/takeover", handle_cluster_takeover),
            web.post("/api/v1/telemetry/scrape", handle_telemetry_scrape),
            web.get("/api/v1/telemetry/snapshot", handle_telemetry_snapshot),
            web.post("/api/v1/admin/tsdb/delete_series", handle_delete_series),
            web.get("/api/v1/status/buildinfo", handle_buildinfo),
            web.get("/debug/traces", handle_debug_traces),
            web.get("/debug/traces/{id}", handle_debug_trace),
            web.get("/debug/kernels", handle_debug_kernels),
            web.get("/debug/slowlog", handle_debug_slowlog),
            web.get("/debug/memory", handle_debug_memory),
            web.get("/debug/cluster", handle_debug_cluster),
        ]
    )

    async def on_cleanup(app):
        for t in state.write_workers:
            t.cancel()
        # wait for in-flight writes before closing storage under them
        await asyncio.gather(*state.write_workers, return_exceptions=True)
        if state.rules is not None:
            await state.rules.close()
        if state.cluster is not None:
            await state.cluster.router.close()
        await state.storage.close()
        await state.engine.close()
        closer = getattr(store, "close", None)
        if closer is not None:  # S3LikeStore owns an HTTP session
            await closer()

    app.on_cleanup.append(on_cleanup)
    return app


def main() -> None:
    init_logging()
    # Escape hatch for CPU-only deployments and CI: force the jax platform
    # BEFORE the backend initializes (some images pre-register an accelerator
    # platform that wins over JAX_PLATFORMS).
    import os

    platform = os.environ.get("HORAEDB_JAX_PLATFORM")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    ap = argparse.ArgumentParser(description="horaedb-tpu server")
    ap.add_argument("--config", help="toml config path")
    args = ap.parse_args()
    config = Config.from_file(args.config) if args.config else Config()
    logger.info("starting horaedb-tpu server on 127.0.0.1:%d", config.port)

    async def run():
        app = await build_app(config)
        # handler_cancellation: a client disconnect raises CancelledError
        # into the handler, so an abandoned query frees its admission
        # slot and stops scanning instead of finishing work nobody reads
        # (counted in horaedb_query_shed_total{reason="client_disconnect"})
        runner = web.AppRunner(app, handler_cancellation=True)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", config.port)
        await site.start()
        await asyncio.Event().wait()  # serve forever

    asyncio.run(run())


if __name__ == "__main__":
    main()
