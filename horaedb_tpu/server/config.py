"""Server configuration (reference: src/server/src/config.rs:21-175).

Same tree: port, test-write knobs, engine threads, object-store selection
(tagged enum Local | S3-like), nested StorageConfig. TOML via tomllib,
deny_unknown_fields semantics throughout, ReadableDuration/Size strings
accepted anywhere a duration/size appears (docs/example.toml analog below).

Example:

    port = 5000

    [test]
    enable_write = true
    write_worker_num = 2
    write_interval = "500ms"
    segment_duration = "12h"

    [metric_engine.storage.object_store]
    type = "Local"
    data_dir = "/tmp/horaedb-tpu"

    [metric_engine.storage.time_merge_storage]
    update_mode = "Overwrite"
"""

from __future__ import annotations

try:
    import tomllib  # Python >= 3.11
except ImportError:  # 3.10 images ship the API-identical backport
    import tomli as tomllib
from dataclasses import dataclass, field

from horaedb_tpu.common import memtrace as _memtrace_mod
from horaedb_tpu.common import tracing as _tracing_mod
from horaedb_tpu.common.error import ensure
from horaedb_tpu.common.time_ext import ReadableDuration
from horaedb_tpu.objstore.s3 import HttpOptions, S3LikeConfig, TimeoutOptions
from horaedb_tpu.storage.config import StorageConfig, _from_dict


def _default_retry():
    # deferred: objstore.resilient registers metric families, whose
    # registry module lives under server/ — a top-level import here would
    # close the server.__init__ -> config -> resilient -> server.metrics
    # cycle while server is still partially initialized
    from horaedb_tpu.objstore.resilient import RetryPolicy

    return RetryPolicy()


def _default_breaker():
    from horaedb_tpu.objstore.resilient import BreakerPolicy

    return BreakerPolicy()


def _serving_mod():
    # deferred for the same cycle reason as the resilience defaults:
    # serving registers metric families on the server-side registry
    from horaedb_tpu import serving

    return serving


def _telemetry_mod():
    # deferred: telemetry registers the horaedb_tenant_*/_telemetry_*
    # families and wires the exemplar source
    from horaedb_tpu import telemetry

    return telemetry


def _batching_mod():
    # deferred: batching registers the horaedb_batch_* families
    from horaedb_tpu.server import batching

    return batching


def _cluster_mod():
    # deferred: cluster registers the horaedb_cluster_* families
    from horaedb_tpu import cluster

    return cluster


@dataclass
class TestConfig:
    """Self-write load generator (reference config.rs TestConfig)."""

    enable_write: bool = False
    write_worker_num: int = 1
    write_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.millis(500)
    )
    segment_duration: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.hours(12)
    )

    @classmethod
    def from_dict(cls, d: dict | None) -> "TestConfig":
        return _from_dict(cls, d)


@dataclass
class ThreadConfig:
    """Background executor sizing (reference: tokio runtime thread counts;
    here: bounded concurrency for manifest/compaction work)."""

    manifest_thread_num: int = 2
    sst_thread_num: int = 2

    @classmethod
    def from_dict(cls, d: dict | None) -> "ThreadConfig":
        return _from_dict(cls, d)


@dataclass
class ResilienceConfig:
    """Fault-tolerance knobs for the object-store boundary
    (objstore/resilient.py): the server wraps whichever store it builds
    in a ResilientStore with this retry ladder and circuit breaker.
    `[metric_engine.storage.object_store.resilience.retry]` /
    `[...resilience.breaker]` in TOML. There is no off switch — set
    `retry.max_attempts = 1` and `breaker.failure_threshold = 0` to get
    single-attempt semantics with classification/metrics kept."""

    retry: object = field(default_factory=_default_retry)
    breaker: object = field(default_factory=_default_breaker)

    @classmethod
    def from_dict(cls, d: dict | None) -> "ResilienceConfig":
        return _from_dict(cls, d)


@dataclass
class ObjectStoreConfig:
    """Tagged store selection: `type = "Local"` (data_dir) or
    `type = "S3Like"` with the reference's full knob tree
    (config.rs:104-130). Divergence from the reference, documented: its
    main.rs:112 panics 'S3 not support yet' even though the config parses;
    here S3Like actually boots (objstore/s3.py)."""

    type: str = "Local"
    data_dir: str = "/tmp/horaedb-tpu"
    # S3-like knobs (objstore/s3.py::S3LikeConfig)
    region: str = ""
    endpoint: str = ""
    bucket: str = ""
    key_id: str = ""
    key_secret: str = ""
    prefix: str = ""
    max_retries: int = 3
    http: HttpOptions = field(default_factory=HttpOptions)
    timeout: TimeoutOptions = field(default_factory=TimeoutOptions)
    # retry/backoff/breaker policy applied by the server's ResilientStore
    # wrapper around EITHER store type (objstore/resilient.py)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)

    @classmethod
    def from_dict(cls, d: dict | None) -> "ObjectStoreConfig":
        return _from_dict(cls, d)

    def to_s3_config(self) -> "S3LikeConfig":
        return S3LikeConfig(
            region=self.region, key_id=self.key_id,
            key_secret=self.key_secret, endpoint=self.endpoint,
            bucket=self.bucket, prefix=self.prefix,
            max_retries=self.max_retries, http=self.http,
            timeout=self.timeout,
        )


@dataclass
class EngineStorageConfig:
    object_store: ObjectStoreConfig = field(default_factory=ObjectStoreConfig)
    time_merge_storage: StorageConfig = field(default_factory=StorageConfig)

    @classmethod
    def from_dict(cls, d: dict | None) -> "EngineStorageConfig":
        return _from_dict(cls, d)


@dataclass
class IngestConfig:
    """Overlapped ingest->flush pipeline knobs (engine/flush_executor.py).

    `flush_workers` background write-out workers drain a queue of at most
    `flush_queue_max` sealed memtables; when the queue is full, appends
    block (backpressure, horaedb_ingest_stall_seconds) and fail with a
    retryable error past `stall_deadline`. Bounded ingest memory is
    roughly (flush_queue_max + flush_workers + 1) x ingest_buffer_rows."""

    flush_workers: int = 2
    flush_queue_max: int = 4
    stall_deadline: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(30)
    )

    @classmethod
    def from_dict(cls, d: dict | None) -> "IngestConfig":
        return _from_dict(cls, d)


@dataclass
class QueryConfig:
    """Query-path admission control knobs (`[metric_engine.query]`,
    server/admission.py): a bounded scheduler in front of the engine so
    a dashboard burst degrades to 503s + Retry-After instead of
    unbounded concurrent scans, and every query carries an end-to-end
    deadline (504 past it). See docs/operations.md "Query admission &
    deadlines"."""

    # Global in-flight query cap (scans running concurrently).
    max_concurrent: int = 8
    # Per-tenant in-flight cap; 0 = same as max_concurrent.
    max_per_tenant: int = 0
    # Bounded admission queue; a full queue sheds 503 immediately. 0
    # disables queuing entirely (at-capacity queries shed at once).
    queue_max: int = 64
    # A query queued longer than this sheds 503 (the stall deadline).
    queue_deadline: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(5)
    )
    # Default end-to-end query deadline; per-request override via
    # Prometheus-style `timeout=` (clamped to max_timeout).
    default_timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(30)
    )
    max_timeout: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(300)
    )
    # Hard cost gate: shed (503) queries whose ESTIMATED device cost
    # (server/admission.py CostModel, seeded from the xprof kernel
    # catalog) exceeds this many seconds. 0 disables the gate — the
    # estimate still rides EXPLAIN's admission verdict.
    max_cost_s: float = 0.0
    # Header naming the tenant for fairness accounting.
    tenant_header: str = "X-Horaedb-Tenant"
    # Weighted-fair shares per tenant (default weight 1.0):
    # [metric_engine.query.tenant_weights] dashboards = 2.0
    tenant_weights: dict = field(default_factory=dict)
    # Query batcher ([metric_engine.query.batching], server/batching.py):
    # compatible cache-MISS grid queries arriving within max_delay
    # coalesce into ONE stacked kernel launch; HORAEDB_BATCH=off is the
    # runtime honesty switch. See docs/operations.md "Query batching".
    batching: object = field(
        default_factory=lambda: _batching_mod().BatchingConfig()
    )

    @classmethod
    def from_dict(cls, d: dict | None) -> "QueryConfig":
        return _from_dict(cls, d)


@dataclass
class RetentionConfig:
    """Per-table retention horizon (`[metric_engine.retention]`): samples
    older than now - period stop existing. Row-exact at scan time via the
    shared visibility mask (storage/visibility.py), whole SSTs expire
    physically through the compaction scheduler's TTL (including
    expired-only delete tasks on quiet tables). Applies to the data +
    exemplars tables of every region; registration tables never expire.
    period = "0s" / absent keeps samples forever."""

    period: ReadableDuration | None = None

    @classmethod
    def from_dict(cls, d: dict | None) -> "RetentionConfig":
        if d is None:
            return cls()
        unknown = set(d) - {"period"}
        ensure(not unknown,
               f"unknown config keys for RetentionConfig: {sorted(unknown)}")
        p = d.get("period")
        if p in (None, "", 0, "0s"):
            return cls()
        return cls(period=ReadableDuration.parse(p))

    def period_ms(self) -> int | None:
        if self.period is None:
            return None
        ms = self.period.as_millis()
        return ms if ms > 0 else None


@dataclass
class LimitsConfig:
    """Dirty-traffic limits (`[metric_engine.limits]`).

    `max_series`: per-engine series-cardinality cap enforced by the
    ingest-path HLL sketch (ingest/cardinality.py): at the limit, NEW
    series are rejected with a 503/Retry-After partial-accept while
    existing-series samples keep landing. On regioned deployments the
    limit applies PER REGION (series hash-partition evenly, so the
    effective global cap is ~num_regions x max_series). 0 = unlimited
    (the sketch still runs and exports horaedb_series_cardinality)."""

    max_series: int = 0

    @classmethod
    def from_dict(cls, d: dict | None) -> "LimitsConfig":
        return _from_dict(cls, d)


@dataclass
class RulesConfig:
    """Streaming rule engine knobs (`[metric_engine.rules]`,
    horaedb_tpu/rules): recording rules materialized incrementally at
    flush time + alert rules with exactly-once transitions. See
    docs/operations.md "Rules"."""

    enabled: bool = True
    # evaluator tick spacing (the server's background loop; rules are
    # dirty-set driven, so a quiet tick costs ~nothing)
    eval_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(30)
    )
    # admission-fairness identity for rule evaluations, and its
    # weighted-fair share (merged into query.tenant_weights; low by
    # default so a rule storm queues behind dashboards, not ahead)
    tenant: str = "rules"
    tenant_weight: float = 0.25
    # rules declared in TOML ([[metric_engine.rules.recording]] /
    # [[metric_engine.rules.alerting]] arrays of tables); validated and
    # durably registered at boot (by name — a restart re-asserts them)
    recording: list = field(default_factory=list)
    alerting: list = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict | None) -> "RulesConfig":
        # kind-tagging of the recording/alerting arrays lives in the
        # generic loader (_from_dict), which is ALSO what runs when this
        # config nests under MetricEngineConfig — one path, no drift
        return _from_dict(cls, d)


@dataclass
class MetricEngineConfig:
    threads: ThreadConfig = field(default_factory=ThreadConfig)
    ingest: IngestConfig = field(default_factory=IngestConfig)
    query: QueryConfig = field(default_factory=QueryConfig)
    retention: RetentionConfig = field(default_factory=RetentionConfig)
    limits: LimitsConfig = field(default_factory=LimitsConfig)
    # Streaming rule engine ([metric_engine.rules], horaedb_tpu/rules):
    # recording rules evaluated incrementally off the invalidation
    # funnel's dirty sets, alert rules with fenced exactly-once
    # transitions, both admission-controlled as a low-weight tenant.
    rules: RulesConfig = field(default_factory=RulesConfig)
    # Serving tier for repeated dashboard traffic ([metric_engine.serving],
    # horaedb_tpu/serving): compaction-time rollups, the invalidation-
    # correct result cache, hot-block device residency. ON by default —
    # answers are bit-exact vs forced-cold scans (HORAEDB_SERVING=off).
    serving: "ServingTierConfig" = field(
        default_factory=lambda: _serving_mod().ServingTierConfig()
    )
    # Self-telemetry ([metric_engine.telemetry], horaedb_tpu/telemetry):
    # the self-scrape loop writing the registry's families back through
    # the normal ingest path as first-class series, per-tenant usage
    # metering, and the HORAEDB_TELEMETRY=off kill switch.
    telemetry: "TelemetryConfig" = field(
        default_factory=lambda: _telemetry_mod().TelemetryConfig()
    )
    # SLO burn-rate templates ([[metric_engine.slo]] array of tables,
    # telemetry/slo.py): each expands into recording + alert rules over
    # the self-scraped series at boot (requires rules.enabled).
    slo: list = field(default_factory=list)
    # Cluster layer ([metric_engine.cluster], horaedb_tpu/cluster):
    # stateless read replicas over the shared object store, the
    # region-assignment map, and the rendezvous query router. Disabled =
    # the single-process behavior, byte-identical.
    cluster: "ClusterConfig" = field(
        default_factory=lambda: _cluster_mod().ClusterConfig()
    )
    storage: EngineStorageConfig = field(default_factory=EngineStorageConfig)
    # Data-plane memory observatory ([metric_engine.memory],
    # common/memtrace.py): per-query buffer-lineage tracing mode.
    memory: "MemoryConfig" = field(default_factory=lambda: MemoryConfig())
    # Ingest buffering (engine/data.py SampleManager): 0 = every write is
    # immediately durable (reference write==SST semantics); > 0 buffers up
    # to that many rows (flushed at the threshold, on the flush interval,
    # before every query, and on shutdown). Higher throughput, bounded
    # data-loss window on crash.
    ingest_buffer_rows: int = 0
    ingest_flush_interval: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.secs(1)
    )
    # Region partitioning (RFC :28-76): > 1 runs N independent region
    # engines over the shared store, series routed by seahash range
    # (engine/region.py). 1 = a single unpartitioned engine.
    num_regions: int = 1
    # "series" = hash(metric + sorted tags) range partition (the RFC
    # design; one metric spans regions, reads fan out + merge, regions can
    # split). "metric" = coarse metric-granularity routing.
    region_granularity: str = "series"
    # Non-empty = claim exclusive write ownership of each region root via
    # epoch fencing (storage/fence.py): required when several server
    # processes share one object store; a later claimant deposes this one
    # and its writes fail with FencedError instead of corrupting manifests.
    node_id: str = ""

    @classmethod
    def from_dict(cls, d: dict | None) -> "MetricEngineConfig":
        return _from_dict(cls, d)


@dataclass
class MemoryConfig:
    """Data-plane memory observatory knobs ([metric_engine.memory],
    common/memtrace.py). The default comes from HORAEDB_MEMTRACE (via
    memtrace.env_default), so build_app applying this config never
    clobbers an env override set without a config section; an explicit
    config value wins over both."""

    # "" (default: cheap per-query lineage ledger), "deep" (adds
    # tracemalloc peak-delta + top allocation sites per query — debug
    # only), "off" (no-op collectors; the funnels still perform their
    # array ops, so the data path is byte-identical).
    memtrace: str = field(default_factory=lambda: _memtrace_mod.env_default())

    @classmethod
    def from_dict(cls, d: dict | None) -> "MemoryConfig":
        return _from_dict(cls, d)


@dataclass
class TracingConfig:
    """Request tracing knobs (common/tracing.py). Field defaults come from
    the HORAEDB_TRACE_* env vars (via tracing.env_defaults), so build_app
    applying this config never clobbers an env override the operator set
    without a [tracing] section; an explicit config value wins over both."""

    # Sample rate in [0, 1]: 1 traces every request, 0 disables tracing
    # entirely (span() collapses to one contextvar get — the overhead
    # budget the bench acceptance bar holds).
    sample: float = field(
        default_factory=lambda: _tracing_mod.env_defaults()[0]
    )
    # Traces slower than this log a WARNING with the trace id.
    slow_threshold: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.millis(
            int(_tracing_mod.env_defaults()[1] * 1000)
        )
    )
    # Bounded in-memory ring of recent traces served at /debug/traces.
    ring_capacity: int = field(
        default_factory=lambda: _tracing_mod.env_defaults()[2]
    )

    @classmethod
    def from_dict(cls, d: dict | None) -> "TracingConfig":
        return _from_dict(cls, d)


@dataclass
class SlowlogConfig:
    """Slow-query flight recorder knobs (server/slowlog.py): the
    `capacity` slowest query requests spool — full trace tree + EXPLAIN —
    to `<object_store.data_dir>/slowlog/`, served at GET /debug/slowlog."""

    # How many entries to keep (the N in "N slowest"); 0 disables the
    # recorder entirely (no directory is created, no writes happen).
    capacity: int = 32
    # Requests faster than this never spool, even below capacity — keeps
    # a cold server from burning disk writes on its first N fast queries.
    min_duration: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.millis(0)
    )

    @classmethod
    def from_dict(cls, d: dict | None) -> "SlowlogConfig":
        return _from_dict(cls, d)


@dataclass
class Config:
    port: int = 5000
    test: TestConfig = field(default_factory=TestConfig)
    metric_engine: MetricEngineConfig = field(default_factory=MetricEngineConfig)
    tracing: TracingConfig = field(default_factory=TracingConfig)
    slowlog: SlowlogConfig = field(default_factory=SlowlogConfig)

    @classmethod
    def from_dict(cls, d: dict | None) -> "Config":
        return _from_dict(cls, d)

    @classmethod
    def from_toml(cls, text: str) -> "Config":
        return cls.from_dict(tomllib.loads(text))

    @classmethod
    def from_file(cls, path: str) -> "Config":
        with open(path, "rb") as f:
            return cls.from_dict(tomllib.load(f))

    def validate(self) -> None:
        ensure(
            0.0 <= self.tracing.sample <= 1.0,
            f"tracing.sample must be in [0, 1], got {self.tracing.sample}",
        )
        ensure(
            self.tracing.ring_capacity > 0,
            "tracing.ring_capacity must be positive",
        )
        ensure(
            self.slowlog.capacity >= 0,
            "slowlog.capacity must be >= 0 (0 disables the recorder)",
        )
        ing = self.metric_engine.ingest
        ensure(ing.flush_workers >= 1, "ingest.flush_workers must be >= 1")
        ensure(ing.flush_queue_max >= 1, "ingest.flush_queue_max must be >= 1")
        q = self.metric_engine.query
        ensure(q.max_concurrent >= 1, "query.max_concurrent must be >= 1")
        ensure(q.max_per_tenant >= 0,
               "query.max_per_tenant must be >= 0 (0 = the global cap)")
        ensure(q.queue_max >= 0, "query.queue_max must be >= 0")
        ensure(q.queue_deadline.seconds > 0,
               "query.queue_deadline must be positive")
        ensure(q.default_timeout.seconds > 0,
               "query.default_timeout must be positive")
        ensure(q.max_timeout.seconds >= q.default_timeout.seconds,
               "query.max_timeout must be >= query.default_timeout")
        ensure(q.max_cost_s >= 0, "query.max_cost_s must be >= 0")
        ensure(
            all(isinstance(v, (int, float)) and v > 0
                for v in q.tenant_weights.values()),
            "query.tenant_weights values must be positive numbers",
        )
        b = q.batching
        ensure(b.max_delay.seconds > 0,
               "query.batching.max_delay must be positive")
        ensure(b.max_group >= 2,
               "query.batching.max_group must be >= 2 (a group of one "
               "is the solo path; disable with batching.enabled=false)")
        ensure(b.max_stacked_cells >= 1,
               "query.batching.max_stacked_cells must be >= 1")
        ensure(b.max_rows >= 1, "query.batching.max_rows must be >= 1")
        ensure(
            self.metric_engine.limits.max_series >= 0,
            "limits.max_series must be >= 0 (0 disables the limit)",
        )
        rules = self.metric_engine.rules
        ensure(rules.eval_interval.seconds > 0,
               "rules.eval_interval must be positive")
        ensure(rules.tenant_weight > 0,
               "rules.tenant_weight must be positive")
        ensure(bool(rules.tenant), "rules.tenant must be non-empty")
        ensure(
            self.metric_engine.memory.memtrace in _memtrace_mod.MODES,
            f"memory.memtrace must be one of {sorted(_memtrace_mod.MODES)}, "
            f"got {self.metric_engine.memory.memtrace!r}",
        )
        tel = self.metric_engine.telemetry
        ensure(tel.scrape_interval.seconds > 0,
               "telemetry.scrape_interval must be positive")
        ensure(tel.max_series >= 0,
               "telemetry.max_series must be >= 0 (0 = unbudgeted)")
        ensure(bool(tel.tenant), "telemetry.tenant must be non-empty")
        ensure(tel.tenant_weight > 0,
               "telemetry.tenant_weight must be positive")
        fed = tel.federation
        ensure(fed.scrape_interval.seconds > 0,
               "telemetry.federation.scrape_interval must be positive")
        ensure(fed.timeout.seconds > 0,
               "telemetry.federation.timeout must be positive")
        ensure(fed.max_series >= 0,
               "telemetry.federation.max_series must be >= 0 "
               "(0 = unbudgeted)")
        if fed.enabled:
            ensure(self.metric_engine.cluster.enabled,
                   "telemetry.federation requires metric_engine.cluster "
                   "(peer scrapes pull from the cluster peer table)")
        if self.metric_engine.slo:
            ensure(rules.enabled,
                   "[[metric_engine.slo]] requires metric_engine.rules "
                   "enabled (the templates expand into rules)")
            # validate every block NOW: a typo'd SLO must fail boot, not
            # the first evaluator tick
            _telemetry_mod().expand_slos(self.metric_engine.slo)
        cl = self.metric_engine.cluster
        ensure(cl.role in ("writer", "replica"),
               f"cluster.role must be writer|replica, got {cl.role!r}")
        ensure(cl.watch_interval.seconds > 0,
               "cluster.watch_interval must be positive")
        ensure(cl.probe_interval.seconds > 0,
               "cluster.probe_interval must be positive")
        ensure(cl.watch_backoff_cap.seconds >= cl.watch_interval.seconds,
               "cluster.watch_backoff_cap must be >= watch_interval")
        if cl.enabled:
            ensure(bool(self.metric_engine.node_id),
                   "cluster.enabled requires metric_engine.node_id (the "
                   "node's identity in the assignment map and peer table)")
            if cl.role == "replica":
                ensure(
                    not self.test.enable_write,
                    "a replica cannot run the self-write load generator",
                )
        store = self.metric_engine.storage.object_store
        kind = store.type.lower()
        ensure(
            kind in ("local", "s3like"),
            f"unknown object_store type: {store.type!r} (Local | S3Like)",
        )
        if kind == "s3like":
            ensure(
                bool(store.endpoint and store.bucket),
                "S3Like object_store requires endpoint and bucket",
            )
        res = store.resilience
        ensure(
            res.retry.max_attempts >= 1,
            "object_store.resilience.retry.max_attempts must be >= 1",
        )
        ensure(
            res.breaker.failure_threshold >= 0,
            "object_store.resilience.breaker.failure_threshold must be "
            ">= 0 (0 disables the breaker)",
        )
