"""Query batcher: coalesce compatible grid queries into ONE stacked launch.

The engine was one-query-per-kernel-launch: a dashboard of N panels paid
N times the per-query dispatch/H2D/retrace overhead ROOFLINE §4 puts at
~95% of on-chip wall. The decode-throughput law (arXiv:2606.22423) says
the kernels only go bandwidth-bound once those per-launch fixed costs are
amortized away — and the admission queue (PR 8) already holds compatible
queries waiting together, while the serving tier (PR 10) guarantees only
cache-MISS queries ever reach this point, so the coalescing window sees
exactly the expensive distinct shapes.

This module is the coalescing planner riding that dispatch point:

- **Shape classes.** Grid queries grouped by (bucket_ms, num_buckets,
  power-of-two series class) — the same step/window shape at the same
  power-of-two cell class the CostModel retraces at. Members differ only
  in their series sets (and start offsets — `t0` travels as a dynamic
  operand), so padding the series axis to the shared class makes every
  group member layout-identical.
- **Hold-for-coalescing window.** The FIRST member of a class arms a
  `max_delay` timer; compatible arrivals join until the window closes or
  `max_group` fills. A query with no concurrent batchable company
  launches solo IMMEDIATELY (`batched_with=1`, zero window penalty — the
  1-client p50 contract), and a query whose end-to-end deadline cannot
  cover the window never waits (it launches solo and keeps its budget).
- **One stacked launch.** The group's scans run concurrently (the same
  merged/deduped row materialization a solo query uses), rows pad to a
  power-of-two row bucket, queries pad to a power-of-two batch axis, and
  ONE vmapped kernel (ops/aggregate.stacked_downsample, xjit'd so padded
  buckets share compiled shapes and retraces stay caught) reduces every
  member's grid in a single dispatch. Results de-multiplex per member,
  bit-exact vs solo execution: each member's cells sum exactly its own
  surviving rows in scan order, padding contributes masked zeros.
- **Fairness and deadlines survive.** Members hold their OWN admission
  slots while coalescing — per-tenant weighted fairness, caps, the cost
  gate, and metering are untouched. Group execution runs detached
  (deadline_ctx.detach, its own scanstats collector, serving-cache
  single-flight style): a member whose deadline dies mid-batch 504s
  individually while the rest of the group completes.

Honesty: `HORAEDB_BATCH=off` (read per query, like HORAEDB_SERVING)
forces every query down the solo path — the A/B oracle the parity tests
and the bench lane assert against. EXPLAIN carries `batched_with=N`,
pad-waste, the shape class, and the window wait; /metrics carries the
`horaedb_batch_*` families below.

jaxlint J016 keeps the lane honest the other way: stacking/padding
primitives over query result lanes anywhere OUTSIDE this module and the
sanctioned stacked kernels is a finding — a second stacking path would
dodge the padded-shape discipline and the pad-waste accounting.
"""

from __future__ import annotations

import asyncio
import os
import time
from dataclasses import dataclass, field

import numpy as np

from horaedb_tpu.common import deadline as deadline_ctx
from horaedb_tpu.common.error import DeadlineExceeded
from horaedb_tpu.common.time_ext import ReadableDuration
from horaedb_tpu.server.metrics import GLOBAL_METRICS
from horaedb_tpu.storage import scanstats

BATCH_GROUP_SIZE = GLOBAL_METRICS.histogram(
    "horaedb_batch_group_size",
    help="Queries per stacked kernel launch (1 never lands here — lone "
         "queries run the solo path without a launch; the window knob "
         "trades p50 hold time for bigger groups).",
    buckets=(1, 2, 4, 8, 16, 32, 64),
)
BATCH_PAD_WASTE = GLOBAL_METRICS.histogram(
    "horaedb_batch_pad_waste_ratio",
    help="Padded-but-dead fraction of each stacked launch's row buffer "
         "(batch x row x series padding to shared power-of-two buckets). "
         "Sustained high waste means the shape classes are too coarse "
         "for the traffic mix — see docs/operations.md 'Query batching'.",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99),
)
BATCH_WINDOW_WAIT = GLOBAL_METRICS.histogram(
    "horaedb_batch_window_wait_seconds",
    help="Time a coalesced query spent holding in the batching window "
         "before its group launched (bounded by "
         "[metric_engine.query.batching] max_delay).",
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1),
)
BATCH_QUERIES = GLOBAL_METRICS.counter(
    "horaedb_batch_queries_total",
    help="Grid queries through the batching decision point, by mode: "
         "batched (rode a stacked launch), solo_lone (no concurrent "
         "batchable company — immediate solo, no window), solo_window "
         "(held the window but no co-runner arrived), solo_deadline "
         "(budget could not cover the window), solo_ineligible (shape "
         "outside the stacked lane's caps), solo_overflow (scan larger "
         "than max_rows — demoted after materialization), solo_off "
         "(batching disabled or HORAEDB_BATCH=off).",
    labelnames=("mode",),
)
BATCH_LAUNCHES = GLOBAL_METRICS.counter(
    "horaedb_batch_launches_total",
    help="Stacked kernel launches (each covers >= 2 coalesced queries).",
)

BATCH_MODES = ("batched", "solo_lone", "solo_window", "solo_deadline",
               "solo_ineligible", "solo_overflow", "solo_off")
for _m in BATCH_MODES:
    BATCH_QUERIES.labels(_m)
del _m
# window wait is a first-class scan stage (EXPLAIN stages_s, /metrics,
# the flight recorder) — same plumbing as the admission queue_wait stage
scanstats.STAGE_SECONDS.labels("batch_window")

# Sentinel: the caller owns execution (run the normal solo path).
SOLO = object()

# row-bucket floor: shapes below this pad up to one compiled shape, so
# tiny dashboard queries share a single XLA executable per (B, S, T).
# Kept small on purpose: the stacked scatter's cost scales with PADDED
# rows (measured ~linear on CPU), so a big floor taxes every tiny panel;
# at 64 the distinct-row-shape count stays <= log2(max_rows/64) anyway.
MIN_ROW_BUCKET = 64


def batch_env_off() -> bool:
    """The honesty switch: HORAEDB_BATCH=off forces every grid query down
    the solo path so batched answers can be asserted bit-exact (and the
    QPS lane A/B-measured) against unbatched execution. Read per query,
    not at import, so tests and operators flip it live."""
    return os.environ.get("HORAEDB_BATCH", "").lower() in (
        "off", "0", "false", "no",
    )


def pow2ceil(n: int) -> int:
    """Smallest power of two >= n (>= 1)."""
    return 1 << max(0, int(n) - 1).bit_length()


@dataclass
class BatchingConfig:
    """Knobs of the query batcher (`[metric_engine.query.batching]`).

    Defaults are ON: coalesced results are bit-exact vs solo execution
    by construction (regression- and property-tested), and the lone-query
    fast path means a 1-client workload never pays the window."""

    enabled: bool = True
    # hold-for-coalescing window: how long the first member of a shape
    # class waits for company before launching. The p50 floor at high
    # concurrency, the p50 ceiling for unlucky non-coalescible bursts.
    # 2 ms rides just above one event-loop turn: a concurrent burst's
    # co-runners arrive within microseconds of each other, so a longer
    # hold only ever taxes the unlucky.
    max_delay: ReadableDuration = field(
        default_factory=lambda: ReadableDuration.millis(2)
    )
    # queries per stacked launch (a full group launches early)
    max_group: int = 16
    # ceiling on the stacked output grid (batch x padded series x
    # buckets); shapes that cannot fit two members run solo
    max_stacked_cells: int = 4 << 20
    # total padded-row budget of ONE stacked buffer (batch x row-bucket
    # after power-of-two padding, ~21 bytes/row); members whose scans
    # would blow it demote to the solo path, largest first
    max_rows: int = 1 << 20

    @classmethod
    def from_dict(cls, d: dict | None) -> "BatchingConfig":
        from horaedb_tpu.storage.config import _from_dict

        return _from_dict(cls, d)


class _Member:
    __slots__ = ("scan", "series_ids", "filtered", "share_key", "fut",
                 "enq_t")

    def __init__(self, scan, series_ids: np.ndarray, filtered: bool,
                 share_key, fut: asyncio.Future, enq_t: float):
        self.scan = scan
        self.series_ids = series_ids
        self.filtered = filtered
        self.share_key = share_key
        self.fut = fut
        self.enq_t = enq_t

    @property
    def n_series(self) -> int:
        return len(self.series_ids)


class _Group:
    __slots__ = ("key", "bucket_ms", "num_buckets", "spad", "members",
                 "t0s", "launched", "handle", "loop", "launch_t")

    def __init__(self, key, bucket_ms: int, num_buckets: int, spad: int,
                 loop):
        self.key = key
        self.bucket_ms = bucket_ms
        self.num_buckets = num_buckets
        self.spad = spad
        self.members: list[_Member] = []
        self.t0s: list[int] = []
        self.launched = False
        self.handle = None
        self.loop = loop
        self.launch_t = 0.0


class QueryBatcher:
    """The coalescing planner (module docstring has the contract).

    Event-loop-confined like the admission scheduler: all state mutates
    between awaits; groups remember their loop so a stale group from a
    finished test loop can never capture a live query."""

    def __init__(self, config: "BatchingConfig | None" = None,
                 clock=time.monotonic):
        self.config = config or BatchingConfig()
        self._clock = clock
        self._groups: dict[tuple, _Group] = {}
        # concurrent batchable CLIENTS between begin()/end(), keyed by
        # scanstats collector identity — the signal that a window is
        # worth holding at all. Collector-keyed (not a bare counter) so
        # a regioned query's own N fan-out sub-queries count as ONE
        # client: a lone regioned query keeps the no-window fast path
        # instead of its sub-queries holding windows for each other.
        self._active: dict[object, int] = {}
        self._tasks: set[asyncio.Task] = set()

    def configure(self, config: BatchingConfig) -> None:
        self.config = config

    def active(self) -> bool:
        return self.config.enabled and not batch_env_off()

    # -- concurrency tracking (the lone-query fast path's signal) ------------
    def begin(self) -> object:
        """A batchable grid query entered the cold execution path.
        Returns the token end() takes; sub-queries sharing a scanstats
        collector share a token (one client)."""
        st = scanstats.current()
        tok = id(st) if st is not None else object()
        self._active[tok] = self._active.get(tok, 0) + 1
        return tok

    def end(self, tok: object) -> None:
        n = self._active.get(tok, 0) - 1
        if n <= 0:
            self._active.pop(tok, None)
        else:
            self._active[tok] = n

    def note_ineligible(self) -> None:
        """Count a grid query the dispatch point could not batch (grid
        not segment-aligned, or a rollup plan covers it — the solo
        pushdown is strictly better there) without it entering the
        concurrency signal: company that can never join a group must
        not make other queries hold windows."""
        if not self.active():
            BATCH_QUERIES.labels("solo_off").inc()
            return
        BATCH_QUERIES.labels("solo_ineligible").inc()
        scanstats.note_max("batched_with", 1)

    # -- the coalescing protocol ---------------------------------------------
    def shape_key(self, bucket_ms: int, num_buckets: int,
                  n_series: int) -> tuple:
        """(step, window, power-of-two series class): members of one key
        are layout-identical after padding — the CostModel's power-of-two
        cell class (num_buckets x spad) in key form."""
        return (int(bucket_ms), int(num_buckets), pow2ceil(n_series))

    def _max_group_for(self, spad: int, num_buckets: int) -> int:
        cells = spad * num_buckets
        if cells <= 0:
            return 0
        return min(self.config.max_group,
                   self.config.max_stacked_cells // cells)

    async def coalesce(self, *, bucket_ms: int, num_buckets: int,
                       series_ids: np.ndarray, t0: int, filtered: bool,
                       share_key, scan):
        """One grid query's batching decision. Returns SOLO (the caller
        runs the un-batched path; `batched_with=1` already noted) or
        `(grids | None, notes)` from a stacked group launch — `grids` has
        the solo return contract (dense [n_series, num_buckets] arrays
        for sum/count/min/max/mean over the caller's sorted `series_ids`;
        None = no surviving rows), `notes` is the group's provenance for
        the caller's collector.

        `scan(tsids | None)` is a coroutine materializing merged/deduped
        row lanes (ts i64, tsid u64, values f64) for a series set — or
        None when nothing is in range — and runs in the group's detached
        context. Members sharing `share_key` (same table, metric, and
        time range — the N-panels-one-dashboard case) are scanned ONCE
        with the union of their series sets and de-multiplexed, so the
        group pays one read where solo execution pays N."""
        n_series = len(series_ids)
        if not self.active():
            BATCH_QUERIES.labels("solo_off").inc()
            return SOLO
        key = self.shape_key(bucket_ms, num_buckets, n_series)
        if n_series < 1 or num_buckets < 1 \
                or self._max_group_for(key[2], num_buckets) < 2:
            BATCH_QUERIES.labels("solo_ineligible").inc()
            scanstats.note_max("batched_with", 1)
            return SOLO
        window = self.config.max_delay.seconds
        rem = deadline_ctx.remaining_s()
        if rem is not None and rem < 4.0 * window:
            # the budget cannot cover the hold + a stacked execution:
            # keep every remaining millisecond for the solo scan
            BATCH_QUERIES.labels("solo_deadline").inc()
            scanstats.note_max("batched_with", 1)
            return SOLO
        loop = asyncio.get_running_loop()
        group = self._groups.get(key)
        if group is not None and group.loop is not loop:
            # stale group parked by a finished event loop (test harness
            # churn): unreachable timers can never fire — drop it
            self._groups.pop(key, None)
            group = None
        if group is None and len(self._active) <= 1:
            # lone query: no batchable company is even executing, so no
            # co-runner can arrive inside the window — solo NOW, no hold
            BATCH_QUERIES.labels("solo_lone").inc()
            scanstats.note_max("batched_with", 1)
            return SOLO
        if group is None or group.launched \
                or len(group.members) >= self._max_group_for(
                    key[2], num_buckets):
            group = _Group(key, int(bucket_ms), int(num_buckets), key[2],
                           loop)
            self._groups[key] = group
            group.handle = loop.call_later(
                window, self._launch, key, group
            )
        m = _Member(scan, series_ids, filtered, share_key,
                    loop.create_future(), self._clock())
        group.members.append(m)
        group.t0s.append(int(t0))
        if len(group.members) >= self._max_group_for(key[2], num_buckets):
            self._launch(key, group)  # full group: no reason to wait
        try:
            rem = deadline_ctx.remaining_s()
            if rem is None:
                res, notes = await asyncio.shield(m.fut)
            else:
                res, notes = await asyncio.wait_for(
                    asyncio.shield(m.fut), timeout=max(rem, 0.0)
                )
        except asyncio.TimeoutError:
            # mid-batch deadline expiry: leave the group (pre-launch:
            # the scan is never run; post-launch: the result is dropped)
            # and 504 with the standard deadline machinery. The explicit
            # raise covers the clock-edge race where wait_for fired a
            # hair before check() agrees — a bare TimeoutError must
            # never escape as a 500.
            self._abandon(group, m)
            deadline_ctx.check("batch_window")
            raise DeadlineExceeded(
                "query budget expired while coalescing",
                at="batch_window",
            ) from None
        except asyncio.CancelledError:
            # client disconnect while coalescing: same cleanup, then let
            # the cancellation unwind (admission counts the shed)
            self._abandon(group, m)
            raise
        wait = group.launch_t - m.enq_t
        scanstats.record("batch_window", max(wait, 0.0))
        BATCH_WINDOW_WAIT.observe(max(wait, 0.0))
        if res is SOLO:
            # held the window but everyone else left (or never came), or
            # the scan overflowed the stacked buffer: caller runs solo
            scanstats.note_max("batched_with", 1)
            return SOLO
        return res, notes

    def _abandon(self, group: _Group, m: _Member) -> None:
        if not group.launched:
            try:
                i = group.members.index(m)
            except ValueError:
                return
            group.members.pop(i)
            group.t0s.pop(i)
            if not group.members:
                if group.handle is not None:
                    group.handle.cancel()
                if self._groups.get(group.key) is group:
                    del self._groups[group.key]
        if not m.fut.done():
            m.fut.cancel()
        elif not m.fut.cancelled():
            # the group resolved in the abandon race: consume the result
            # so an unretrieved exception never warns at GC
            m.fut.exception()

    def _launch(self, key, group: _Group) -> None:
        """Close the window: detach the group from the pending map and
        hand it to a planner-owned execution task (no member's deadline
        or cancellation can kill the shared work)."""
        if group.launched:
            return
        group.launched = True
        group.launch_t = self._clock()
        if group.handle is not None:
            group.handle.cancel()
        if self._groups.get(key) is group:
            del self._groups[key]
        if not group.members:
            return
        if len(group.members) == 1:
            # the co-runners the window bet on never arrived (or all
            # abandoned): release the survivor to the solo path
            BATCH_QUERIES.labels("solo_window").inc()
            m = group.members[0]
            if not m.fut.done():
                m.fut.set_result((SOLO, None))
            return
        task = group.loop.create_task(self._execute(group))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _execute(self, group: _Group) -> None:
        """Scan every member (one union scan per share_key cluster),
        stack, launch ONE kernel, de-multiplex."""
        deadline_ctx.detach()  # no member budget owns the shared work
        members = group.members
        try:
            with scanstats.scan_stats() as st:
                lanes = await self._scan_members(members)
                results = self._stack_and_launch(group, lanes)
            notes = dict(st.counts)
        except Exception as e:  # noqa: BLE001 — fan the failure out
            for m in members:
                if not m.fut.done():
                    m.fut.set_exception(e)
            return
        live = [
            i for i, r in enumerate(results)
            if not isinstance(r, BaseException) and r is not SOLO
        ]
        pct = notes.pop("_pad_waste_pct", 0)
        cls = f"batch_class_b{group.bucket_ms}" \
              f"_t{group.num_buckets}_s{group.spad}"
        batched_n = 0
        for i, m in enumerate(members):
            if m.fut.done():
                continue
            r = results[i]
            if isinstance(r, BaseException):
                m.fut.set_exception(r)
            elif r is SOLO:
                BATCH_QUERIES.labels("solo_overflow").inc()
                m.fut.set_result((SOLO, None))
            else:
                # empty (None) results count as batched too: the query
                # rode the group's shared scan — sum-over-modes of
                # horaedb_batch_queries_total must cover every decision
                batched_n += 1
                m.fut.set_result((r, {
                    **notes,
                    "batched_with": len(live),
                    "batch_pad_waste_pct": pct,
                    cls: 1,
                }))
        if batched_n:
            BATCH_QUERIES.labels("batched").inc(batched_n)

    async def _scan_members(self, members: list) -> list:
        """Materialize every member's row lanes, sharing one union scan
        across members whose share_key matches (same table + metric +
        time range, the dashboard-panel case). Returns one entry per
        member: (ts, dense sid, values) | None | BaseException."""
        clusters: dict = {}
        for i, m in enumerate(members):
            clusters.setdefault(m.share_key, []).append(i)
        lanes: list = [None] * len(members)

        async def one_cluster(idxs: list[int]) -> None:
            ms = [members[i] for i in idxs]
            try:
                if len(ms) == 1:
                    m = ms[0]
                    rows = await m.scan(
                        [int(x) for x in m.series_ids]
                        if m.filtered else None
                    )
                elif not all(m.filtered for m in ms):
                    # an unfiltered member's series set IS the metric's
                    # full set: scanning without the membership predicate
                    # covers every member (each demuxes to its own set)
                    scanstats.note("batch_shared_scans", len(ms) - 1)
                    rows = await ms[0].scan(None)
                else:
                    scanstats.note("batch_shared_scans", len(ms) - 1)
                    union = ms[0].series_ids
                    for m in ms[1:]:
                        union = np.union1d(union, m.series_ids)
                    rows = await ms[0].scan([int(x) for x in union])
            except BaseException as e:  # noqa: BLE001 — per-member fate
                for i in idxs:
                    lanes[i] = e
                return
            for i in idxs:
                lanes[i] = self._demux_rows(members[i], rows)

        await asyncio.gather(*(one_cluster(v) for v in clusters.values()))
        return lanes

    @staticmethod
    def _demux_rows(m: _Member, rows):
        """One member's lanes out of a (possibly shared) scan: rows whose
        tsid is in the member's set, dense-indexed against its sorted
        series_ids. Selection preserves the scan's (tsid, ts) order, so
        each cell still accumulates its rows exactly as a member-only
        scan would deliver them."""
        if rows is None:
            return None
        ts, tsid, vals = rows
        pos = np.searchsorted(m.series_ids, tsid)
        pos_c = np.clip(pos, 0, max(0, len(m.series_ids) - 1))
        hit = m.series_ids[pos_c] == tsid
        if bool(hit.all()):
            return ts, pos_c.astype(np.int32), vals
        sel = np.flatnonzero(hit)
        if not len(sel):
            return None
        return ts[sel], pos_c[sel].astype(np.int32), vals[sel]

    def _stack_and_launch(self, group: _Group, lanes: list) -> list:
        """Pad member row lanes to shared power-of-two buckets, run ONE
        stacked kernel, slice per-member grids back out. Synchronous (no
        awaits): runs on the event loop like the solo fold path. Returns
        one entry per member: grids dict | None | SOLO (overflow) |
        BaseException (that member's scan failed)."""
        members = group.members
        results: list = [None] * len(members)
        stack_idx: list[int] = []
        for i, lane in enumerate(lanes):
            if isinstance(lane, BaseException):
                results[i] = lane
            elif lane is not None:
                stack_idx.append(i)
            # lane None: nothing in range — results[i] stays None
        # fit the padded buffer inside the max_rows budget: demote the
        # largest members to the solo path until Bpad x Rpad fits (a
        # stacked launch must never allocate an unbounded buffer just
        # because one member's scan came back huge). A sole fitting
        # member still launches stacked (B=1): its scan is already paid
        # — demoting it would re-run the whole read on the solo path.
        while stack_idx:
            bpad = pow2ceil(len(stack_idx))
            rpad = max(
                MIN_ROW_BUCKET,
                pow2ceil(max(len(lanes[i][0]) for i in stack_idx)),
            )
            if bpad * rpad <= self.config.max_rows:
                break
            big = max(stack_idx, key=lambda i: len(lanes[i][0]))
            stack_idx.remove(big)
            results[big] = SOLO
        if not stack_idx:
            return results
        from horaedb_tpu.ops import aggregate as agg_ops

        bsz = len(stack_idx)
        spad = group.spad
        nb = group.num_buckets
        ts_b = np.zeros((bpad, rpad), dtype=np.int64)
        sid_b = np.zeros((bpad, rpad), dtype=np.int32)
        val_b = np.zeros((bpad, rpad), dtype=np.float64)
        ok_b = np.zeros((bpad, rpad), dtype=bool)
        t0_b = np.zeros((bpad,), dtype=np.int64)
        rows = 0
        for j, i in enumerate(stack_idx):
            ts, sid, vals = lanes[i]
            n = len(ts)
            rows += n
            ts_b[j, :n] = ts
            sid_b[j, :n] = sid
            val_b[j, :n] = vals
            ok_b[j, :n] = True
            t0_b[j] = group.t0s[i]
        waste = 1.0 - rows / float(bpad * rpad)
        with scanstats.stage("device_agg"):
            out = agg_ops.stacked_downsample(
                ts_b, sid_b, val_b, ok_b, t0_b, group.bucket_ms,
                num_series=spad, num_buckets=nb,
            )
        grids = {k: np.asarray(v) for k, v in out.items()}
        BATCH_LAUNCHES.inc()
        BATCH_GROUP_SIZE.observe(bsz)
        BATCH_PAD_WASTE.observe(waste)
        scanstats.note("batch_stacked_rows", rows)
        # ride the waste ratio out through the group collector's notes
        # (int percent; _execute pops it into the per-member notes)
        scanstats.note("_pad_waste_pct", int(round(waste * 100)))
        for j, i in enumerate(stack_idx):
            s = members[i].n_series
            # contiguous copies: a sliced view would pin the whole padded
            # stacked grid alive in the result cache for every member
            g = {
                k: np.ascontiguousarray(grids[k][j, :s, :])
                for k in ("sum", "count", "min", "max", "mean")
            }
            # match the solo contract: an all-empty grid is None
            results[i] = g if g["count"].sum() != 0 else None
        return results


# The process-global planner (server boot configures it from
# [metric_engine.query.batching]; engine-level tests/benches use the
# defaults, exactly like the serving tier's process-global caches).
GLOBAL_BATCHER = QueryBatcher()
