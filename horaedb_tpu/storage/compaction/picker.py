"""Time-window compaction strategy.

Reference: src/columnar_storage/src/compaction/picker.rs. Policy preserved
exactly:
- files already marked in_compaction are skipped; TTL-expired files are
  collected separately (picker.rs:117-134);
- remaining files bucket by segment (`time_range.start.truncate_by`), and
  segments are scanned NEWEST first (picker.rs:155-188);
- a segment qualifies with >= input_sst_min_num files; files sort size-asc
  (smallest first) and accumulate up to input_sst_max_num files while total
  size stays <= 1.1 x new_sst_max_size;
- quirk preserved: expired files only ride along when some segment qualifies
  (pick_compaction_files returning None aborts the pick entirely,
  picker.rs:92-95);
- the picker must run sequentially so an SST is never picked twice
  (picker.rs:52-55) — here it only ever runs inside the scheduler's single
  generate-task loop.
"""

from __future__ import annotations

import logging

from horaedb_tpu.storage.compaction import Task
from horaedb_tpu.storage.sst import SstFile
from horaedb_tpu.storage.types import Timestamp

logger = logging.getLogger(__name__)


class TimeWindowCompactionStrategy:
    def __init__(
        self,
        segment_duration_ms: int,
        new_sst_max_size: int,
        input_sst_max_num: int,
        input_sst_min_num: int,
    ):
        self._segment_duration = segment_duration_ms
        self._new_sst_max_size = new_sst_max_size
        self._input_sst_max_num = input_sst_max_num
        self._input_sst_min_num = input_sst_min_num

    def pick_candidate(
        self,
        ssts: list[SstFile],
        expire_before_ms: int | None,
    ) -> Task | None:
        uncompacted, expired = self._find_uncompacted_and_expired(ssts, expire_before_ms)
        by_segment = self._files_by_segment(uncompacted)
        picked = self._pick_compaction_files(by_segment)
        if picked is None:
            return None
        if not picked and not expired:
            return None
        for f in picked:
            f.mark_compaction()
        for f in expired:
            f.mark_compaction()
        task = Task(inputs=picked, expireds=expired)
        logger.debug(
            "picked compaction task: inputs=%d expireds=%d size=%d",
            len(picked), len(expired), task.input_size(),
        )
        return task

    @staticmethod
    def _find_uncompacted_and_expired(
        files: list[SstFile], expire_before_ms: int | None
    ) -> tuple[list[SstFile], list[SstFile]]:
        uncompacted, expired = [], []
        for f in files:
            if f.is_compaction():
                continue
            (expired if f.is_expired(expire_before_ms) else uncompacted).append(f)
        return uncompacted, expired

    def _files_by_segment(self, files: list[SstFile]) -> dict[int, list[SstFile]]:
        out: dict[int, list[SstFile]] = {}
        for f in files:
            seg = Timestamp(f.meta.time_range.start).truncate_by(self._segment_duration)
            out.setdefault(seg.value, []).append(f)
        return out

    def _pick_compaction_files(
        self, by_segment: dict[int, list[SstFile]]
    ) -> list[SstFile] | None:
        for seg in sorted(by_segment, reverse=True):  # newest first
            files = by_segment[seg]
            if len(files) < self._input_sst_min_num:
                continue
            files = sorted(files, key=lambda f: f.meta.size)  # smallest first
            # Suppose compaction reduces size by ~10% (picker.rs:172-174).
            budget = int(self._new_sst_max_size * 1.1)
            picked: list[SstFile] = []
            total = 0
            for f in files[: self._input_sst_max_num]:
                total += f.meta.size
                if total > budget:
                    break
                picked.append(f)
            if len(picked) >= self._input_sst_min_num:
                return picked
        return None
