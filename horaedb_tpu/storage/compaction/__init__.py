"""Compaction: task, picker, executor, scheduler.

Reference: src/columnar_storage/src/compaction/. The merge+dedup of k input
SSTs runs on device through the same fused scan pipeline as queries
(BASELINE config 5 / SURVEY C12); policy and orchestration are host control
plane with the reference's exact semantics (memory gating, in_compaction
marking, manifest-commit-before-physical-delete).
"""

from dataclasses import dataclass, field

from typing import TYPE_CHECKING

from horaedb_tpu.storage.sst import SstFile

if TYPE_CHECKING:
    from horaedb_tpu.storage.types import TimeRange  # noqa: F401


@dataclass
class Task:
    """One compaction unit (compaction/mod.rs:26-36)."""

    inputs: list[SstFile] = field(default_factory=list)
    expireds: list[SstFile] = field(default_factory=list)
    # Set by Executor.pre_check once the memory budget is charged, so the
    # release paths never refund a reservation that was never taken.
    mem_reserved: bool = field(default=False, compare=False)
    # The time-range scope of the pick that produced this task (None =
    # global). The executor's more-work ping re-picks under the SAME scope,
    # so a window-scoped manual compaction drains its window instead of
    # cascading into a global one; background ticks stay global.
    scope: "TimeRange | None" = field(default=None, compare=False)

    def input_size(self) -> int:
        return sum(f.meta.size for f in self.inputs)
