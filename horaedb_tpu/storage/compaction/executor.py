"""Compaction executor: k-way merge+dedup on device, then manifest commit.

Reference: src/columnar_storage/src/compaction/executor.rs. Semantics kept:
- memory gate: in-use bytes + task input size must stay under the limit or
  the task is rejected before running (executor.rs:93-114);
- each admitted task immediately pings the trigger channel so the picker
  looks for more work (executor.rs:147-151);
- the k inputs merge through the SAME pipeline as scans with
  keep_builtin=True (original __seq__ values survive into the output SST);
- the manifest update (add new, delete inputs+expireds) is the commit point:
  after it, physical deletes are best-effort and never fail the task
  ("From now on, no error should be returned", executor.rs:218-219);
- failures before the commit release memory and unmark the SSTs so the
  picker can retry them (executor.rs:123-137).
"""

from __future__ import annotations

import asyncio
import logging

import pyarrow as pa

from horaedb_tpu.common import tracing
from horaedb_tpu.common.error import ensure
from horaedb_tpu.server.metrics import BYTES_BUCKETS, GLOBAL_METRICS
from horaedb_tpu.storage import scanstats
from horaedb_tpu.storage.compaction import Task
from horaedb_tpu.storage.sst import FileMeta, SstFile, allocate_id
from horaedb_tpu.storage.types import TimeRange

logger = logging.getLogger(__name__)

COMPACTION_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_compaction_seconds",
    help="One compaction task end to end (read inputs, device merge, "
         "encode shards, manifest commit, physical deletes).",
)
COMPACTION_BYTES = GLOBAL_METRICS.histogram(
    "horaedb_compaction_bytes",
    help="Input bytes per compaction task (the admitted task's SST sizes).",
    buckets=BYTES_BUCKETS,
)
COMPACTIONS = GLOBAL_METRICS.counter(
    "horaedb_compactions_total",
    help="Completed compaction tasks by result.",
    labelnames=("result",),
)


class Executor:
    def __init__(
        self,
        storage,  # ObjectBasedStorage (duck-typed to avoid an import cycle)
        manifest,
        mem_limit: int,
        trigger: "asyncio.Queue[None]",
    ):
        self._storage = storage
        self._manifest = manifest
        self._mem_limit = mem_limit
        self._inused_memory = 0
        self._trigger = trigger
        self._inflight: set[asyncio.Task] = set()

    # -- admission (executor.rs:93-114) -------------------------------------
    def pre_check(self, task: Task) -> None:
        # expired-only tasks (retention enforcement: delete-only commit, no
        # merge) are legal; a task with neither inputs nor expireds is not
        ensure(bool(task.inputs) or bool(task.expireds),
               "compaction task must have inputs or expireds")
        ensure(
            all(f.is_compaction() for f in task.inputs + task.expireds),
            "compaction task files must be marked in_compaction",
        )
        task_size = task.input_size()
        ensure(
            self._inused_memory + task_size <= self._mem_limit,
            f"Compaction memory usage too high, inused:{self._inused_memory}, "
            f"task_size:{task_size}, limit:{self._mem_limit}",
        )
        self._inused_memory += task_size
        task.mem_reserved = True

    def _release(self, task: Task) -> None:
        if task.mem_reserved:
            self._inused_memory -= task.input_size()
            task.mem_reserved = False

    def on_success(self, task: Task) -> None:
        self._release(task)

    def on_failure(self, task: Task) -> None:
        """Release the budget (only if charged — a pre_check rejection must
        not drive the gate negative) and unmark SSTs for re-pick."""
        self._release(task)
        for sst in task.inputs + task.expireds:
            sst.unmark_compaction()

    def _trigger_more_task(self, scope=None) -> None:
        """Ping the picker for more work (executor.rs:147-151), re-picking
        under the admitted task's scope (None = global)."""
        try:
            self._trigger.put_nowait(scope)
        except asyncio.QueueFull:
            pass

    # -- submission (executor.rs:139-151, 261-272) ---------------------------
    def submit(self, task: Task) -> asyncio.Task:
        async def _run() -> None:
            try:
                with tracing.trace(
                    "compaction", inputs=len(task.inputs),
                    input_bytes=task.input_size(),
                ), COMPACTION_SECONDS.time():
                    await self.do_compaction(task)
            except Exception:  # noqa: BLE001
                logger.exception("Do compaction failed")
                COMPACTIONS.labels("error").inc()
                self.on_failure(task)
            else:
                COMPACTIONS.labels("ok").inc()
                self.on_success(task)

        t = asyncio.create_task(_run(), name="compaction-task")
        self._inflight.add(t)
        t.add_done_callback(self._inflight.discard)
        return t

    async def drain(self) -> None:
        """Wait for in-flight compactions (tests & shutdown)."""
        while self._inflight:
            await asyncio.gather(*list(self._inflight), return_exceptions=True)

    # -- the compaction itself (executor.rs:155-222) --------------------------
    async def do_compaction(self, task: Task) -> None:
        from horaedb_tpu.serving.cache import RESULT_CACHE
        from horaedb_tpu.storage import visibility as vis_mod

        self.pre_check(task)
        self._trigger_more_task(task.scope)
        COMPACTION_BYTES.observe(task.input_size())
        logger.debug("Start do compaction, input_len=%d", len(task.inputs))

        if not task.inputs:
            # expired-only task (retention enforcement): delete-only commit,
            # no merge — the horizon already proved every row out of range
            to_deletes = [f.id for f in task.expireds]
            await self._manifest.update([], to_deletes)
            RESULT_CACHE.serving_invalidate(
                self._storage._root, "compact",
                TimeRange.union_of([f.meta.time_range for f in task.expireds]),
            )
            await self._delete_ssts(to_deletes)
            await self._gc_tombstones()
            await self._gc_rollups()
            return

        time_range = TimeRange.union_of([f.meta.time_range for f in task.inputs])
        # Tombstones whose masking the merge below WILL include — captured
        # BEFORE the read so the rollup record can never claim a delete it
        # did not apply (a tombstone landing mid-task compares newer than
        # this set and forces raw until the next compaction re-emits).
        applied_tombs = tuple(sorted(
            t.id for t in self._manifest.all_tombstones()
        ))
        # Same merge pipeline as the scan path, on device, builtins kept.
        # Memory bound: device memory is O(scan_block_rows) (hierarchical
        # chunked scan), the parquet ENCODE streams to the store at
        # O(row group + chunk) (write_sst), and the merged host columns are
        # O(task rows) — admitted only under the memory_limit gate
        # (pre_check, default 2 GiB), the same bound the reference's
        # streamed plan enforces via its task budget (executor.rs:93-114).
        # The reads funnel through the shared visibility mask under the
        # "compact" context (storage/visibility.py): tombstoned/expired
        # rows are PHYSICALLY absent from the rewritten output — this is
        # where a delete reclaims bytes.
        with vis_mod.mask_context("compact"):
            batches = await self._storage.parquet_reader.scan_segment(
                task.inputs,
                predicate=None,
                projections=None,
                keep_builtin=True,
                # a compaction reads every row group of soon-deleted inputs
                # exactly once — caching them would evict the hot query entries
                use_block_cache=False,
            )
        if not batches:
            # All inputs were empty SSTs (or every row was tombstoned/
            # expired): commit a delete-only update instead of erroring (an
            # error would unmark + re-pick the same files in an infinite
            # retry loop).
            to_deletes = [f.id for f in task.expireds] + [f.id for f in task.inputs]
            await self._manifest.update([], to_deletes)
            RESULT_CACHE.serving_invalidate(
                self._storage._root, "compact",
                TimeRange.union_of(
                    [f.meta.time_range for f in task.inputs + task.expireds]
                ),
            )
            await self._delete_ssts(to_deletes)
            await self._gc_tombstones()
            await self._gc_rollups()
            return
        table = pa.Table.from_batches(batches)

        # Output sharding (divergence from the reference's single output,
        # executor.rs:173-191, shared with the flush path's shard design):
        # a large merged output splits into pk-contiguous slices whose
        # parquet encodes run CONCURRENTLY on worker threads — the encode
        # was the pipeline's serial tail (VERDICT r02 #3). Shard count is
        # capped below the picker's input_sst_min_num so a fully-compacted
        # segment can never re-pick its own output in a churn loop; each
        # shard is a sorted, pk-disjoint run, so later scans take the
        # presorted O(n) merge path instead of re-sorting.
        cfg = self._storage._config.scheduler
        max_shards = max(1, cfg.input_sst_min_num - 1)
        shard_rows = max(1, cfg.output_shard_rows)
        n_shards = min(max_shards, -(-table.num_rows // shard_rows))
        per = -(-table.num_rows // n_shards)
        slices = [table.slice(i * per, per) for i in range(n_shards)]
        slices = [s for s in slices if s.num_rows > 0]
        ids = [allocate_id() for _ in slices]
        with scanstats.stage("encode"):
            # all-settle semantics: a failed shard encode must not leave its
            # siblings running detached (they would race close/teardown);
            # gather with return_exceptions, then re-raise the first failure
            results = await asyncio.gather(
                *(self._storage.write_sst(fid, s) for fid, s in zip(ids, slices)),
                return_exceptions=True,
            )
            # compaction outputs carry the encoding descriptor of their
            # fresh sidecar (pop_enc_meta): rewriting v1 inputs under an
            # encoding-enabled config naturally upgrades the tree to
            # format v2. Popped BEFORE the failure re-raise so successful
            # siblings of a failed shard never strand their entries (the
            # orphan objects themselves are GC'd at next open).
            enc_metas = [self._storage.pop_enc_meta(fid) for fid in ids]
            for r in results:
                if isinstance(r, BaseException):
                    raise r
            sizes = results
        new_files = [
            SstFile(
                id=fid,
                meta=FileMeta(
                    max_sequence=fid,
                    num_rows=s.num_rows,
                    size=size,
                    time_range=time_range,
                    format_version=fmt,
                    encodings=encodings,
                ),
            )
            for fid, s, size, (fmt, encodings) in zip(ids, slices, sizes, enc_metas)
        ]
        logger.debug(
            "Compact output %d sst shard(s): ids=%s rows=%d",
            len(new_files), ids, table.num_rows,
        )

        # Commit point: add new THEN delete inputs+expireds, atomically in one
        # manifest delta (executor.rs:206-216).
        to_deletes = [f.id for f in task.expireds] + [f.id for f in task.inputs]
        await self._manifest.update(new_files, to_deletes)
        # serving-tier invalidation funnel (jaxlint J013): the sealed-SST
        # set just changed; cached results over the old set are dead
        RESULT_CACHE.serving_invalidate(
            self._storage._root, "compact", time_range
        )
        # From now on, no error should be returned (executor.rs:218-219).
        try:
            # rollup emission rides the bytes compaction already rewrote:
            # the merged table IS the segment's exact LWW-resolved,
            # tombstone-applied content. Post-commit and best-effort — a
            # failed artifact costs speed on the next dashboard refresh,
            # never correctness (the planner scans raw without it).
            await self._emit_rollups(task, table, new_files, time_range,
                                     applied_tombs)
        except Exception:  # noqa: BLE001 — perf artifact only
            logger.warning("rollup emission failed (raw scans still exact)",
                           exc_info=True)
        await self._delete_ssts(to_deletes)
        await self._gc_tombstones()
        await self._gc_rollups()

    async def _emit_rollups(
        self, task: Task, table: pa.Table, new_files: list[SstFile],
        time_range: TimeRange, applied_tombs: tuple,
    ) -> None:
        """Emit one pre-aggregated SST + registry record per configured
        resolution for a FULL-segment compaction (storage/rollup.py holds
        the freshness contract the records carry).

        Emission is skipped — never wrong — when the contract cannot be
        exact: a partial-segment task (un-merged siblings would carry
        un-deduped duplicates), a racing flush that landed mid-task (the
        output set is no longer the segment's whole live set), a
        non-OVERWRITE schema, or a table without a trailing time-column
        primary key."""
        from horaedb_tpu.serving import ROLLUPS_BUILT, resolution_label
        from horaedb_tpu.storage import rollup as rollup_mod
        from horaedb_tpu.storage.config import UpdateMode
        from horaedb_tpu.storage.types import Timestamp

        storage = self._storage
        cfg = storage.rollup_config
        if not cfg.enabled or storage.time_column is None:
            return
        if storage.schema.update_mode != UpdateMode.OVERWRITE:
            return
        pks = storage.schema.primary_key_names
        names = storage.schema.arrow_schema.names
        if not pks or pks[-1] != storage.time_column:
            return
        if cfg.value_column not in names:
            return
        if table.num_rows < max(1, cfg.min_rows):
            return
        seg_ms = storage.segment_duration_ms
        segs = {
            Timestamp(f.meta.time_range.start).truncate_by(seg_ms).value
            for f in task.inputs
        }
        if len(segs) != 1:
            return
        seg_start = segs.pop()
        seg_range = TimeRange(seg_start, seg_start + seg_ms)
        live = {
            s.id for s in self._manifest.find_ssts(seg_range)
            if Timestamp(s.meta.time_range.start).truncate_by(seg_ms).value
            == seg_start
        }
        out_ids = {f.id for f in new_files}
        if live != out_ids:
            return  # partial-segment task or a flush raced the merge
        group_cols = list(pks[:-1])
        sources = tuple(sorted(out_ids))
        for res in cfg.resolutions:
            if res <= 0 or seg_ms % res != 0:
                continue
            rtab = await storage._run_sst(
                rollup_mod.compute_rollup, table, group_cols,
                storage.time_column, cfg.value_column, res,
            )
            blob = await storage._run_sst(rollup_mod.encode_rollup, rtab)
            rid = allocate_id()
            # artifact BEFORE record: a crash between the two leaves an
            # unreferenced object the rollup orphan GC reclaims at open
            await storage.store.put(
                storage.sst_path_gen.generate_rollup(rid), blob
            )
            old = self._manifest.rollup_records().get((seg_start, res))
            record = rollup_mod.RollupRecord(
                id=allocate_id(),
                resolution_ms=res,
                segment_start=seg_start,
                sst_id=rid,
                num_rows=rtab.num_rows,
                size=len(blob),
                time_range=time_range,
                source_sst_ids=sources,
                tombstone_ids=applied_tombs,
            )
            await self._manifest.add_rollup(record)
            if old is not None:
                await self._manifest.remove_rollups([old])
            ROLLUPS_BUILT.labels(resolution_label(res)).inc()
            logger.debug(
                "rollup emitted: seg=%d res=%d rows=%d size=%d sources=%s",
                seg_start, res, rtab.num_rows, len(blob), sources,
            )

    async def _gc_rollups(self) -> None:
        """Post-commit rollup-record GC, best-effort like tombstone GC:
        records whose sources are no longer live can never pass the
        freshness contract again."""
        try:
            await self._manifest.gc_rollups()
        except Exception as e:  # noqa: BLE001 — next compaction retries
            logger.warning("rollup gc failed: %s", e)

    async def _gc_tombstones(self) -> None:
        """Post-commit tombstone GC, best-effort like physical deletes:
        records whose time range no live SST overlaps are dead weight."""
        try:
            await self._manifest.gc_tombstones()
        except Exception as e:  # noqa: BLE001 — next compaction retries
            logger.warning("tombstone gc failed: %s", e)

    async def _delete_ssts(self, ids: list[int]) -> None:
        """Best-effort parallel physical deletes (executor.rs:224-253),
        including bloom sidecars (missing ones are expected: sidecars only
        exist when bloom filters were enabled at write time)."""
        path_gen = self._storage.parquet_reader._path_gen
        for i in ids:
            self._storage.parquet_reader.evict_cached(i)
        paths = [path_gen.generate(i) for i in ids]
        bloom_paths = [path_gen.generate_bloom(i) for i in ids]
        enc_paths = [path_gen.generate_enc(i) for i in ids]
        results = await asyncio.gather(
            *(self._storage._store.delete(p) for p in paths),
            *(self._storage._store.delete(p) for p in bloom_paths),
            *(self._storage._store.delete(p) for p in enc_paths),
            return_exceptions=True,
        )
        from horaedb_tpu.objstore import NotFound

        for p, r in zip(paths + bloom_paths + enc_paths, results):
            if isinstance(r, NotFound):
                continue
            if isinstance(r, BaseException):
                logger.error("Failed to delete sst object %s: %s", p, r)
