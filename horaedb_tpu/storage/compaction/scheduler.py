"""Compaction scheduler: the picker loop and the executor-submit loop.

Reference: src/columnar_storage/src/compaction/scheduler.rs. Shape preserved:
- generate_task_loop: select!(schedule_interval tick | manual trigger) ->
  pick_candidate over the manifest's SSTs -> push into a bounded task queue
  (scheduler.rs:121-159);
- recv_task_loop: pop tasks and hand them to the executor (scheduler.rs:114-119);
- `trigger_compaction()` is the `/compact` HTTP hook (scheduler.rs:106-112);
- TTL: expire horizon = now - ttl when a TTL is configured.
"""

from __future__ import annotations

import asyncio
import logging

from horaedb_tpu.common.time_ext import now_ms
from horaedb_tpu.server.metrics import GLOBAL_METRICS
from horaedb_tpu.storage.compaction import Task
from horaedb_tpu.storage.compaction.executor import Executor
from horaedb_tpu.storage.compaction.picker import TimeWindowCompactionStrategy
from horaedb_tpu.storage.config import SchedulerConfig
from horaedb_tpu.storage.types import TimeRange  # noqa: F401 — annotations

logger = logging.getLogger(__name__)

QUEUE_DEPTH = GLOBAL_METRICS.gauge(
    "horaedb_compaction_queue_depth",
    help="Compaction tasks picked but not yet handed to the executor "
         "(sustained depth means picking outpaces compaction bandwidth).",
)
PICKS = GLOBAL_METRICS.counter(
    "horaedb_compaction_picks_total",
    help="Picker outcomes per pick attempt.",
    labelnames=("outcome",),
)


class CompactionScheduler:
    def __init__(
        self,
        storage,  # ObjectBasedStorage
        manifest,
        config: SchedulerConfig,
        segment_duration_ms: int,
    ):
        self._config = config
        self._manifest = manifest
        # trigger payload = the pick scope: a TimeRange to restrict the
        # pick, or None for a global pick (ticks and plain /compact)
        self._trigger: "asyncio.Queue[TimeRange | None]" = asyncio.Queue(maxsize=4)
        self._tasks: asyncio.Queue[Task] = asyncio.Queue(
            maxsize=config.max_pending_compaction_tasks
        )
        self._picker = TimeWindowCompactionStrategy(
            segment_duration_ms=segment_duration_ms,
            new_sst_max_size=config.new_sst_max_size.as_bytes(),
            input_sst_max_num=config.input_sst_max_num,
            input_sst_min_num=config.input_sst_min_num,
        )
        self.executor = Executor(
            storage=storage,
            manifest=manifest,
            mem_limit=config.memory_limit.as_bytes(),
            trigger=self._trigger,
        )
        self._loops: list[asyncio.Task] = []

    def start(self) -> None:
        self._loops = [
            asyncio.create_task(self._generate_task_loop(), name="compaction-picker"),
            asyncio.create_task(self._recv_task_loop(), name="compaction-submit"),
        ]

    async def close(self) -> None:
        for t in self._loops:
            t.cancel()
        await asyncio.gather(*self._loops, return_exceptions=True)
        self._loops = []
        await self.executor.drain()

    def trigger_compaction(self, time_range=None) -> None:
        """Manual trigger, e.g. the `/compact` endpoint (scheduler.rs:106-112).

        `time_range` scopes the pick to SSTs overlapping it (the reference's
        CompactRequest is an empty struct and compacts globally; per-call
        scoping lets an operator target one hot window without queueing work
        for every segment). The scope rides the trigger channel; the
        periodic tick stays global."""
        try:
            self._trigger.put_nowait(time_range)
        except asyncio.QueueFull:
            logger.debug("compaction trigger channel full; pick already pending")

    # -- loops ---------------------------------------------------------------
    async def _generate_task_loop(self) -> None:
        interval = self._config.schedule_interval.seconds
        while True:
            sleep = asyncio.create_task(asyncio.sleep(interval))
            recv = asyncio.create_task(self._trigger.get())
            done, pending = await asyncio.wait(
                {sleep, recv}, return_when=asyncio.FIRST_COMPLETED
            )
            for t in pending:
                t.cancel()
            await asyncio.gather(*pending, return_exceptions=True)
            scope = None
            for t in done:
                if t is recv and not t.cancelled() and t.exception() is None:
                    scope = t.result()
            self.pick_once(time_range=scope)

    def pick_once(self, time_range=None) -> bool:
        """One sequential pick; returns True if a task was enqueued.
        `time_range` restricts candidates to overlapping SSTs."""
        expire_before = None
        if self._config.ttl is not None:
            expire_before = now_ms() - self._config.ttl.as_millis()
        ssts = self._manifest.all_ssts()
        if time_range is not None:
            ssts = [s for s in ssts if s.meta.time_range.overlaps(time_range)]
        task = self._picker.pick_candidate(ssts, expire_before)
        if task is None and expire_before is not None:
            # Retention enforcement: the reference picker only expires
            # files when some segment also qualifies for a merge (the
            # preserved quirk, picker.rs:92-95) — which would let expired
            # SSTs linger forever on a quiet table. A TTL deployment gets
            # an EXPIRED-ONLY task instead: delete-only commit, no merge.
            expired = [
                f for f in ssts
                if not f.is_compaction() and f.is_expired(expire_before)
            ]
            if expired:
                for f in expired:
                    f.mark_compaction()
                task = Task(inputs=[], expireds=expired)
                PICKS.labels("expired_only").inc()
        if task is not None:
            task.scope = time_range
        if task is None:
            PICKS.labels("empty").inc()
            return False
        try:
            self._tasks.put_nowait(task)
            PICKS.labels("queued").inc()
            QUEUE_DEPTH.set(self._tasks.qsize())
            return True
        except asyncio.QueueFull:
            # Task queue full: unmark so a later pick retries these files
            # (no memory to release — reservation happens in pre_check).
            logger.warning("compaction task queue full; dropping pick")
            PICKS.labels("dropped_full").inc()
            for f in task.inputs + task.expireds:
                f.unmark_compaction()
            return False

    async def _recv_task_loop(self) -> None:
        while True:
            task = await self._tasks.get()
            QUEUE_DEPTH.set(self._tasks.qsize())
            self.executor.submit(task)
