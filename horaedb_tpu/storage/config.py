"""Storage engine configuration (reference: src/columnar_storage/src/config.rs:24-172).

Same knob tree and defaults as the reference; values deserialize from TOML via
`from_dict`, with ReadableDuration/ReadableSize strings accepted anywhere a
duration/size appears.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, fields as dc_fields

from horaedb_tpu.common.error import HoraeError
from horaedb_tpu.common.size_ext import ReadableSize
from horaedb_tpu.common.time_ext import ReadableDuration


class UpdateMode(enum.Enum):
    """How duplicate primary keys merge at read/compact time (config.rs)."""

    OVERWRITE = "Overwrite"  # keep the row with max sequence
    APPEND = "Append"        # concatenate binary value columns

    @classmethod
    def parse(cls, v: "str | UpdateMode") -> "UpdateMode":
        if isinstance(v, UpdateMode):
            return v
        for m in cls:
            if m.value.lower() == str(v).lower():
                return m
        raise HoraeError(f"unknown update mode: {v!r}")


class ParquetCompression(enum.Enum):
    UNCOMPRESSED = "none"
    SNAPPY = "snappy"
    LZ4 = "lz4"
    ZSTD = "zstd"
    GZIP = "gzip"

    @classmethod
    def parse(cls, v: "str | ParquetCompression") -> "ParquetCompression":
        if isinstance(v, ParquetCompression):
            return v
        for m in cls:
            if m.value.lower() == str(v).lower() or m.name.lower() == str(v).lower():
                return m
        raise HoraeError(f"unknown compression: {v!r}")


def _from_dict(cls, d: dict):
    """Build a config dataclass from a (possibly partial) dict, recursing into
    nested config dataclasses and parsing human-readable value types —
    unknown keys are rejected like serde's deny_unknown_fields."""
    if d is None:
        return cls()
    known = {f.name: f for f in dc_fields(cls)}
    unknown = set(d) - set(known)
    if unknown:
        raise HoraeError(f"unknown config keys for {cls.__name__}: {sorted(unknown)}")
    kwargs = {}
    for name, value in d.items():
        default = getattr(cls(), name)
        if (value is not None and default is None
                and not isinstance(value, dict)
                and "ReadableDuration" in str(known[name].type)):
            # duration-or-None fields (`ReadableDuration | None = None`,
            # e.g. ttl/period/telemetry.retention): the None default gives
            # the generic `.parse` dispatch below nothing to go on, so
            # dispatch on the DECLARED field type — a name list here
            # already rotted once (the PR 11 RulesConfig lesson)
            kwargs[name] = ReadableDuration.parse(value)
        elif name in ("resolutions", "rollup_resolutions") and value is not None:
            # rollup resolutions: "1m"/"1h" strings or raw ms ints
            from horaedb_tpu.serving import parse_resolution

            kwargs[name] = [parse_resolution(v) for v in value]
        elif name in ("recording", "alerting") and value is not None:
            # rule arrays ([[metric_engine.rules.recording]] /
            # [[...alerting]]): tag each entry with its kind so the rule
            # engine's one validator (rules.rule_from_dict) serves both
            kind = "recording" if name == "recording" else "alert"
            kwargs[name] = [
                {**e, "kind": kind} if isinstance(e, dict) else e
                for e in value
            ]
        elif name == "peers" and value is not None:
            # cluster peer table ([[metric_engine.cluster.peers]]):
            # validated member records, not raw dicts
            from horaedb_tpu.cluster import ClusterPeer

            kwargs[name] = [
                ClusterPeer.from_dict(p) if isinstance(p, dict) else p
                for p in value
            ]
        elif name == "column_options" and value is not None:
            kwargs[name] = {
                col: _from_dict(ColumnOptions, opts) for col, opts in value.items()
            }
        elif hasattr(type(default), "parse") and not isinstance(value, dict):
            kwargs[name] = type(default).parse(value)
        elif hasattr(default, "__dataclass_fields__"):
            kwargs[name] = _from_dict(type(default), value)
        else:
            kwargs[name] = value
    return cls(**kwargs)


@dataclass
class ColumnOptions:
    """Per-column parquet overrides (config.rs WriteConfig column options)."""

    enable_dict: bool | None = None
    enable_bloom_filter: bool | None = None
    encoding: str | None = None
    compression: str | None = None


@dataclass
class WriteConfig:
    """Parquet writer knobs (config.rs, defaults preserved)."""

    max_row_group_size: int = 8192
    write_batch_size: int = 1024
    enable_sorting_columns: bool = True
    enable_dict: bool = False
    enable_bloom_filter: bool = False
    compression: ParquetCompression = ParquetCompression.SNAPPY
    column_options: dict | None = None
    # Fast-encode profile for ingest-flush SSTs (the LSM's L0): snappy +
    # plain encoding writes ~2x faster than the tuned profile at ~1.7x the
    # bytes; compaction re-encodes its outputs with the tuned profile, so
    # the size cost is transient. Statistics and sorting columns are kept
    # (row-group pruning and the presorted scan path must keep working).
    flush_fast_encode: bool = True

    @classmethod
    def from_dict(cls, d: dict | None) -> "WriteConfig":
        return _from_dict(cls, d)


@dataclass
class EncodingConfig:
    """Encoded-lane SST sidecars (storage/encoding.py) — the
    compressed-domain scan's write side (TPU-build extension).

    When enabled, every SST write also emits a `{id}.enc` sidecar holding
    per-lane columnar encodings (dict/rle/dod/xor) with per-page zone
    maps; readers evaluate predicates on the encoded form and ship
    qualifying lanes to the device encoded (ops/decode.py). Disabled
    tables write plain v1 SSTs; flipping the knob on upgrades the tree
    naturally as compaction rewrites old files. Per-lane codec choice is
    by measured encoded size, never configured."""

    enabled: bool = False
    # rows per encoded page (zone-map/pruning granule, shared across lanes)
    page_rows: int = 4096
    # dictionary-encoding cardinality ceiling per lane
    max_dict: int = 4096
    # SSTs below this row count skip the sidecar (the fixed header/page
    # overhead outweighs any decode win on tiny registration batches)
    min_rows: int = 256
    # explicit lane allowlist; None encodes every eligible numeric lane
    lanes: "list[str] | None" = None
    # reader-side decoded-sidecar cache budget (LRU by resident bytes,
    # like scan_cache for parquet row groups); 0 disables caching
    sidecar_cache: ReadableSize = field(default_factory=lambda: ReadableSize.mb(32))

    @classmethod
    def from_dict(cls, d: dict | None) -> "EncodingConfig":
        return _from_dict(cls, d)


@dataclass
class RollupConfig:
    """Compaction-time downsample rollups (storage/rollup.py — the
    serving tier's layer a, TPU-build extension).

    When enabled, a compaction that merges a FULL segment additionally
    emits one pre-aggregated SST per resolution (sum/count/min/max per
    series per bucket over `value_column`), recorded as a distinct
    manifest artifact kind (`manifest/rollup/{id}` records referencing
    `rollup/{id}.sst` objects — never listed among the data SSTs, so
    raw scans are oblivious). The planner substitutes a rollup tree for
    a raw segment scan only when the record's source SST set exactly
    matches the segment's live set and no newer tombstone overlaps —
    see plan_rollups for the full freshness contract. Requires a table
    with `time_column` (the engine's sample tables) and OVERWRITE
    update mode; resolutions must divide the segment duration."""

    enabled: bool = False
    resolutions: list = field(
        default_factory=lambda: [60_000, 3_600_000]  # 1m, 1h
    )
    value_column: str = "value"
    # merged segments below this row count skip rollup emission (the
    # artifact would not be meaningfully smaller than the raw rows)
    min_rows: int = 0

    @classmethod
    def from_dict(cls, d: dict | None) -> "RollupConfig":
        if d and "resolutions" in d:
            from horaedb_tpu.serving import parse_resolution

            d = dict(d)
            d["resolutions"] = [
                parse_resolution(v) for v in d["resolutions"]
            ]
        return _from_dict(cls, d)


@dataclass
class ManifestConfig:
    """Manifest merger thresholds (config.rs; semantics in manifest/mod.rs):
    - soft limit: schedule a background merge;
    - hard limit: REJECT writes until the merger catches up."""

    channel_size: int = 3
    merge_interval: ReadableDuration = field(default_factory=lambda: ReadableDuration.secs(5))
    min_merge_threshold: int = 10
    soft_merge_threshold: int = 50
    hard_merge_threshold: int = 90

    @classmethod
    def from_dict(cls, d: dict | None) -> "ManifestConfig":
        return _from_dict(cls, d)


@dataclass
class SchedulerConfig:
    """Compaction scheduler knobs (config.rs SchedulerConfig)."""

    schedule_interval: ReadableDuration = field(default_factory=lambda: ReadableDuration.secs(10))
    max_pending_compaction_tasks: int = 10
    memory_limit: ReadableSize = field(default_factory=lambda: ReadableSize.gb(2))
    ttl: ReadableDuration | None = None
    new_sst_max_size: ReadableSize = field(default_factory=lambda: ReadableSize.gb(1))
    input_sst_max_num: int = 30
    input_sst_min_num: int = 5
    # TPU-build extension: compaction outputs above this row count split
    # into up to (input_sst_min_num - 1) pk-contiguous shard SSTs whose
    # parquet encodes run CONCURRENTLY (the encode was the compaction
    # pipeline's serial tail). The shard cap keeps a fully-compacted
    # segment below the picker's min file count, so shards never re-pick
    # themselves in a churn loop.
    output_shard_rows: int = 8_000_000

    @classmethod
    def from_dict(cls, d: dict | None) -> "SchedulerConfig":
        return _from_dict(cls, d)


@dataclass
class StorageConfig:
    """Top-level storage config (config.rs StorageConfig).

    `scan_block_rows` is a TPU-build extension: the max rows one device pass
    materializes. Segments above it scan hierarchically (chunked device
    passes + merge tree) instead of one giant block — the blockwise-carry
    answer to HBM limits (SURVEY §5.7/§7 risk (a))."""

    write: WriteConfig = field(default_factory=WriteConfig)
    encoding: EncodingConfig = field(default_factory=EncodingConfig)
    rollup: RollupConfig = field(default_factory=RollupConfig)
    manifest: ManifestConfig = field(default_factory=ManifestConfig)
    scheduler: SchedulerConfig = field(default_factory=SchedulerConfig)
    update_mode: UpdateMode = UpdateMode.OVERWRITE
    scan_block_rows: int = 32 * 1024 * 1024
    # TPU-build extension: LRU cache of decoded SST column tables (the block
    # cache the reference lacks — repeated dashboard queries skip parquet
    # decode + object-store IO entirely; SSTs are immutable so entries never
    # go stale, deletes evict). ReadableSize string or bytes; 0 disables.
    scan_cache: ReadableSize = field(default_factory=lambda: ReadableSize.mb(64))

    @classmethod
    def from_dict(cls, d: dict | None) -> "StorageConfig":
        return _from_dict(cls, d)
