"""Scan pipeline: parquet SSTs -> device filter/merge/dedup -> record batches.

This module replaces the reference's DataFusion physical plan
(`build_df_plan`: ParquetExec -> FilterExec -> SortPreservingMergeExec ->
MergeExec, src/columnar_storage/src/read.rs:429-494) with a TPU execution
shape:

  1. host: row-group-pruned parquet reads per SST (the analog of the custom
     ParquetFileReaderFactory + pruning predicate, read.rs:66-93,459-463),
     fanned out concurrently;
  2. device: ONE fused XLA kernel per segment — predicate mask, k-way merge
     (sort over the concatenated block with rejected rows sunk to the tail),
     and last-value dedup mask (reference MergeExec semantics,
     read.rs:99-385);
  3. host: gather surviving rows, strip builtin columns unless keep_builtin,
     emit fixed-size record batches old->new.

Ordering contract preserved: output sorted by (pk..., __seq__), duplicates
collapsed per UpdateMode; filter runs BEFORE dedup exactly like the
reference's plan, so a newest-version row rejected by the predicate exposes
the older surviving version.

Append mode and binary value columns follow the hybrid path: the device
computes the sort permutation and group boundaries over the numeric key lanes
and the host applies pyarrow takes + BytesMergeOperator (SURVEY §7 risk (b)).
"""

from __future__ import annotations

import asyncio
import io
import logging
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from functools import lru_cache
import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from horaedb_tpu.common import colblock
from horaedb_tpu.common import deadline as deadline_ctx
from horaedb_tpu.common import memtrace
from horaedb_tpu.common import tracing
from horaedb_tpu.common.bytebudget import GLOBAL_POOLS
from horaedb_tpu.common.error import HoraeError, ensure
from horaedb_tpu.common.xprof import xjit
from horaedb_tpu.objstore import ObjectStore
from horaedb_tpu.server.metrics import GLOBAL_METRICS
from horaedb_tpu.ops import dedup as dedup_ops
from horaedb_tpu.ops import filter as filter_ops
from horaedb_tpu.ops.blocks import PACK_SENTINEL, Block, arrow_column_to_numpy
from horaedb_tpu.ops.filter import Predicate
from horaedb_tpu.storage import scanstats
from horaedb_tpu.storage.config import UpdateMode
from horaedb_tpu.storage.operator import BytesMergeOperator
from horaedb_tpu.storage.sst import SstFile, SstPathGenerator
from horaedb_tpu.storage.types import (
    RESERVED_COLUMN_NAME,
    SEQ_COLUMN_NAME,
    StorageSchema,
    TimeRange,
)

logger = logging.getLogger(__name__)

DEFAULT_SCAN_BATCH_SIZE = 8192

SCAN_PATH = GLOBAL_METRICS.counter(
    "horaedb_scan_path_total",
    help="Merge route the scan planner took (host SIMD, single-device "
         "kernel, or the cross-chip sharded merge).",
    labelnames=("path",),
)
# pre-register so the route split is visible on /metrics from boot
for _p in ("host", "device", "sharded"):
    SCAN_PATH.labels(_p)
del _p


def _is_binary_like(t: pa.DataType) -> bool:
    """The single definition of 'cannot ride a device lane'."""
    return pa.types.is_binary(t) or pa.types.is_large_binary(t) or pa.types.is_string(t)


@dataclass
class ScanRequest:
    """Reference: storage.rs ScanRequest — range prunes SSTs (row-exact time
    filtering is the caller's predicate, matching reference semantics)."""

    range: TimeRange
    predicate: Predicate | None = None
    projections: list[int] | None = None
    # Skip SST files with id <= min_sst_id (file granularity — an SST's id
    # IS its write sequence). The index sidecar replay scans only what
    # landed after its watermark; compacted outputs get fresh (larger) ids,
    # so their old rows may reappear — callers must replay idempotently.
    min_sst_id: int | None = None


@dataclass
class CompactRequest:
    """Manual-compaction request (storage.rs:372-374; the reference's is an
    empty struct). `time_range` scopes the pick to SSTs overlapping it —
    None keeps the reference's compact-everything behavior."""

    time_range: "TimeRange | None" = None


@dataclass
class WriteRequest:
    batch: pa.RecordBatch
    time_range: TimeRange
    # Whether to check the batch is within the same segment (storage.rs:307-316).
    enable_check: bool = True
    # Caller guarantees the batch is already pk-sorted (e.g. the metric
    # engine's accumulator flush): the write path skips the sort AND the
    # O(n) sortedness verification.
    presorted: bool = False
    # Explicit sequence for the __seq__ column / FileMeta (defaults to the
    # SST's file id). Concurrent flush snapshots allocate their sequence at
    # snapshot-detach time so last-value dedup follows buffering order even
    # when a later snapshot's encode finishes first.
    seq: int | None = None
    # Ingest-flush writes opt into the fast parquet encode profile (L0
    # trade: ~2x faster encode, ~1.7x bytes until compaction re-encodes);
    # honored only when WriteConfig.flush_fast_encode is on.
    fast_encode: bool = False


# ---------------------------------------------------------------------------
# host<->device link profile + scan-path cost model
# ---------------------------------------------------------------------------


class _LinkProfile:
    """Measured host<->device transfer characteristics (module singleton).

    The materializing-scan planner needs real numbers, not assumptions: on a
    production TPU host H2D rides PCIe (GB/s) and the device merge wins for
    any sizable scan, while a tunneled dev chip can move ~50 MB/s with
    ~50 ms dispatch latency, where host SIMD wins far longer. One lazy 8 MB
    probe per process keeps the planner honest on both (VERDICT r02 #1: the
    end-to-end configs were transfer-bound, not kernel-bound).

    The probe runs on a daemon thread with a bounded first wait
    (HORAEDB_LINK_PROBE_TIMEOUT_S, default 15 s): on a wedged remote-TPU
    tunnel `device_put` blocks indefinitely inside the runtime, and the old
    inline probe blocked the first scan with it (VERDICT r03 weak #5). On
    timeout the planner degrades to host-favoring numbers and every later
    scan re-checks (without blocking) whether the probe finally landed, so
    a recovered tunnel upgrades the plan mid-process.

    Probe-avoidance gates (common/linkprobe.py), both checked before any
    thread starts: `HORAEDB_LINK_PROFILE=host|skip` pins the host-favoring
    numbers and `device` pins PCIe-class numbers, paying nothing; a
    fresh cached WEDGED verdict (e.g. bench.py just proved the tunnel
    dead) short-circuits to the same host-favoring plan instead of
    re-paying the bounded wait per process."""

    _cached: dict | None = None
    _lock = threading.Lock()
    _thread: threading.Thread | None = None
    _done = threading.Event()
    _result: dict | None = None
    _deadline: float | None = None

    # pessimistic-link plan: ~1 MB/s and 1 s dispatch make every device
    # route lose the cost model, which is exactly right when the device
    # cannot be reached; host sort speed stays the local-CPU measurement
    _WEDGED = {"h2d_bw": 1e6, "d2h_bw": 1e6, "dispatch_s": 1.0,
               "sort_s_per_row": 1.2e-6}
    # production-host plan (HORAEDB_LINK_PROFILE=device): PCIe-class link,
    # accelerator sort rate — the operator vouches for the link, so the
    # planner must not strand scans on host SIMD waiting for a probe
    _TRUSTED = {"h2d_bw": 16e9, "d2h_bw": 16e9, "dispatch_s": 1e-4,
                "sort_s_per_row": 25e-9}

    @classmethod
    def get(cls) -> dict:
        if cls._cached is not None:
            return cls._cached
        from horaedb_tpu.common import linkprobe

        mode = linkprobe.override()
        if mode in ("host", "skip"):
            with cls._lock:
                cls._cached = dict(cls._WEDGED)
                return cls._cached
        if mode == "device":
            with cls._lock:
                cls._cached = dict(cls._TRUSTED)
                return cls._cached
        if cls._thread is None:
            cached = linkprobe.cached_verdict()
            if cached is not None and not cached[0]:
                # a fresh wedged verdict: don't start a probe that will
                # only burn the bounded wait; NOT memoized in _cached so a
                # later process-lifetime call re-reads the (TTL-bounded)
                # verdict and can upgrade once it expires
                return dict(cls._WEDGED)
        with cls._lock:
            if cls._cached is not None:
                return cls._cached
            if cls._thread is None:
                try:
                    timeout = float(
                        os.environ.get("HORAEDB_LINK_PROBE_TIMEOUT_S", "15")
                    )
                except ValueError:
                    timeout = 15.0
                cls._thread = threading.Thread(
                    target=cls._probe_worker, name="link-probe", daemon=True
                )
                cls._thread.start()
                cls._deadline = time.monotonic() + timeout
            # every caller waits only until the shared probe deadline:
            # concurrent first scans block for the REMAINDER (a healthy
            # probe lands in ~100 ms and they all get real numbers); once
            # the deadline passes, scans poll without blocking
            wait_s = max(0.0, cls._deadline - time.monotonic())
        cls._done.wait(wait_s)
        with cls._lock:
            if cls._result is not None:
                cls._cached = cls._result
                return cls._cached
        return dict(cls._WEDGED)

    @classmethod
    def _probe_worker(cls) -> None:
        res = cls._measure()
        with cls._lock:
            cls._result = res
        cls._done.set()

    @staticmethod
    def _measure() -> dict:
        try:
            dev = jax.devices()[0]
            if dev.platform == "cpu":
                # same memory space ("transfer" is a memcpy), but the XLA
                # multi-key stable sort is single-core and ~1.6 us/row —
                # an order slower than numpy's packed argsort (measured on
                # the quick-baseline shape), so it must carry its real cost
                return {"h2d_bw": 8e9, "d2h_bw": 8e9, "dispatch_s": 1e-4,
                        "sort_s_per_row": 1.2e-6}
            warm = jax.jit(lambda x: x.sum())
            small = jax.device_put(np.arange(128, dtype=np.float32))
            # jaxlint: disable=J001 one-time link calibration, off the query path
            warm(small).block_until_ready()  # compile outside the clock
            t0 = time.perf_counter()
            # jaxlint: disable=J001 one-time link calibration, off the query path
            warm(small).block_until_ready()
            dispatch = max(time.perf_counter() - t0, 1e-5)
            probe = np.empty(8 << 20, np.uint8)
            t0 = time.perf_counter()
            d = jax.device_put(probe)
            # jaxlint: disable=J001 one-time link calibration, off the query path
            d.block_until_ready()
            h2d = len(probe) / max(time.perf_counter() - t0 - dispatch, 1e-6)
            t0 = time.perf_counter()
            np.asarray(d)
            d2h = len(probe) / max(time.perf_counter() - t0 - dispatch, 1e-6)
            # a completed in-process device probe IS an accelerator-health
            # verdict: share it so bench.py skips its subprocess probe
            from horaedb_tpu.common import linkprobe

            linkprobe.store_verdict(True, "in-process link probe ok")
            # accelerator multi-key sort throughput (v5e measured ~4 ns/row
            # per key lane; 6 lanes on the scan shape)
            return {"h2d_bw": h2d, "d2h_bw": d2h, "dispatch_s": dispatch,
                    "sort_s_per_row": 25e-9}
        except Exception:  # noqa: BLE001 — no device: plan as if local
            return {"h2d_bw": 8e9, "d2h_bw": 8e9, "dispatch_s": 1e-4,
                    "sort_s_per_row": 1.2e-6}


# host merge cost priors (measured microbench on the CI shape): stable u64
# argsort + pack + dedup ≈ 150-250 ns per SURVIVING row; vectorized
# predicate eval ≈ 2 ns/row per term. These only steer the host/device
# choice — being 2x off moves the crossover, not correctness.
_HOST_SORT_S_PER_ROW = 200e-9
_HOST_EVAL_S_PER_ROW = 2e-9


class _HostCalib:
    """Self-calibrating host-cost estimates (VERDICT r04 #6).

    The static numbers above are PRIORS; on any other machine they are
    faith. Every real (non-presorted) host merge and host predicate eval is
    timed in place and folded into a per-process EWMA, so a mis-set prior
    converges to this host's true speed after a few sizable scans and the
    host/device routing crossover lands where it belongs.

    Learning is one-sided by construction: observations only arrive on the
    routes actually taken, so a prior that wrongly makes the host look
    EXPENSIVE routes everything to the device and never self-corrects (the
    device side is covered by the measured _LinkProfile instead). The
    dangerous direction — a prior that makes the host look cheap — corrects
    itself, because the mis-routed host work is exactly what gets measured.

    `HORAEDB_PLANNER_CALIB=off` freezes the priors (A/B and routing tests
    that pin expectations to the static constants)."""

    ALPHA = 0.25          # EWMA weight per observation
    MIN_ROWS = 50_000     # below this, timer noise dominates the signal
    _sort = _HOST_SORT_S_PER_ROW
    _eval = _HOST_EVAL_S_PER_ROW

    @staticmethod
    def enabled() -> bool:
        return os.environ.get("HORAEDB_PLANNER_CALIB", "on") != "off"

    @classmethod
    def sort_s_per_row(cls) -> float:
        return cls._sort

    @classmethod
    def eval_s_per_row(cls) -> float:
        return cls._eval

    @classmethod
    def observe_sort(cls, rows: int, secs: float) -> None:
        if rows >= cls.MIN_ROWS and secs > 0 and cls.enabled():
            cls._sort += cls.ALPHA * (secs / rows - cls._sort)

    @classmethod
    def observe_eval(cls, rows_terms: int, secs: float) -> None:
        if rows_terms >= cls.MIN_ROWS and secs > 0 and cls.enabled():
            cls._eval += cls.ALPHA * (secs / rows_terms - cls._eval)

    @classmethod
    def reset(cls) -> None:
        cls._sort = _HOST_SORT_S_PER_ROW
        cls._eval = _HOST_EVAL_S_PER_ROW
# Block size past which an ambient mesh upgrades the packed merge to the
# cross-chip sample-sort (parallel/merge.py). Below it the all-to-all's
# fixed cost (extra device sort + exchange + per-device dispatch) outweighs
# the parallelism. Read per call like HORAEDB_SCAN_PATH, so A/B harnesses
# and the virtual-mesh dryrun can flip it after import.
def _sharded_min_rows() -> int:
    return int(os.environ.get("HORAEDB_SHARDED_MIN_ROWS", 4_000_000))


def _pack_sort_keys(
    col, sort_keys: tuple[str, ...], n: int
) -> tuple[np.ndarray, int] | None:
    """Pack the (pk..., __seq__) sort keys into ONE u64 per row: pk columns
    offset to their min, __seq__ replaced by its dense rank (sequences are
    ns-clock file ids — ranking costs one np.unique and saves ~50 bits).
    Returns (packed, seq_width) or None when a key is non-integer or the
    widths exceed 63 bits (bit 63 stays free as the reject/padding
    sentinel). Shared by the host argsort merge and the packed device
    kernel, so both orderings are definitionally identical."""
    if n == 0:
        return None
    encs: list[tuple[np.ndarray, int]] = []
    for name in sort_keys:
        a = col(name)
        if not np.issubdtype(a.dtype, np.integer):
            return None
        if name == SEQ_COLUMN_NAME:
            uniq = np.unique(a)
            enc = np.searchsorted(uniq, a).astype(np.uint64)
            width = max(1, int(len(uniq) - 1).bit_length())
        else:
            lo, hi = int(a.min()), int(a.max())
            span = hi - lo  # python ints: no overflow on u64/i64 extremes
            if span >= (1 << 63):
                return None
            if a.dtype == np.uint64:
                enc = a - np.uint64(lo)
            else:
                enc = (a.astype(np.int64) - lo).astype(np.uint64)
            width = max(1, span.bit_length())
        encs.append((enc, width))
    if sum(w for _, w in encs) > 63:
        return None
    packed = np.zeros(n, np.uint64)
    for enc, width in encs:
        packed = (packed << np.uint64(width)) | enc
    return packed, encs[-1][1]


_PACK_SENTINEL = PACK_SENTINEL  # shared masked-row contract (ops/blocks.py)

# once-per-process flag for the forced-sharded-without-mesh downgrade
# warning (the scanstats note still records every occurrence)
_warned_sharded_no_mesh = False


@lru_cache(maxsize=64)
def _build_packed_index_kernel(seq_width: int, do_dedup: bool):
    """Single-lane merge kernel: the whole (pk..., seq-rank) ordering rides
    one u64 (rejected rows pre-sunk to the all-ones sentinel on host), so
    the device sorts TWO operands (key + iota) instead of mask + every key
    lane + iota — and only 8 bytes/row ever cross the link inbound, 4
    bytes/survivor outbound. Dedup needs no pk gathers: the group id is
    packed >> seq_width."""

    @xjit(kernel="packed_merge")
    def kernel(packed, num_valid):
        n = packed.shape[0]
        iota = jnp.arange(n, dtype=jnp.int32)
        sp, perm = jax.lax.sort((packed, iota), num_keys=1, is_stable=True)
        # valid rows (63-bit keys) sort strictly before sentinel rows
        inb = jnp.arange(n) < num_valid
        if do_dedup:
            grp = sp >> np.uint64(seq_width)
            nxt = jnp.concatenate([grp[1:], grp[-1:]])
            keep = inb & ((jnp.arange(n) == num_valid - 1) | (nxt != grp))
        else:
            keep = inb
        kcnt = jnp.sum(keep)
        pos = jnp.where(keep, jnp.cumsum(keep) - 1,
                        kcnt + jnp.cumsum(~keep) - 1)
        out_idx = jnp.zeros(n, dtype=jnp.int32).at[pos].set(perm)
        return out_idx, kcnt

    return kernel


def _host_merge_indices(
    col_of,
    n_rows: int,
    sort_keys: tuple[str, ...],
    num_pk: int,
    mask: np.ndarray | None,
    do_dedup: bool,
    lanes=None,
) -> np.ndarray:
    """Vectorized host merge: filter -> stable sort by (pk..., __seq__) ->
    last-value dedup. Returns row indices (into the unfiltered input) in
    output order.

    `col_of(name)` returns the full numpy lane for a sort-key column. Rows
    are compacted through `mask` FIRST, so the O(n log n) sort runs on
    surviving rows only — the reason this path demolishes the device round
    trip on selective scans over slow links.

    With `lanes` (a colblock.ArrowLanes over the chunked scan table) the
    merge consumes lanes chunk-wise: the sortedness probe checks per-chunk
    order + chunk boundaries, and the mask compaction gathers survivors
    straight out of the per-chunk views — no full-column combine_chunks
    copy ever happens on this route (the r19 baseline's 4 host_prep
    copies).

    Sort strategy: pack all sort keys into one u64 (pk columns offset to
    their min, __seq__ replaced by its dense rank — sequences are ns-clock
    file ids, ranking costs one np.unique and saves ~50 bits) and run ONE
    stable argsort; fall back to np.lexsort when the packed widths exceed
    63 bits or a key is floating-point. Dedup = keep-last per pk group,
    matching the reference MergeExec's LastValueOperator (operator.rs:36-44).
    """
    if mask is not None:
        base = np.nonzero(mask)[0]
        n = len(base)
    else:
        base = None
        n = n_rows
    if n == 0:
        return np.empty(0, np.int64)

    def col(name: str) -> np.ndarray:
        if lanes is not None:
            if base is not None:
                return lanes.gather_sorted(name, base)
            return lanes.lane(name)
        a = np.asarray(col_of(name))
        return a[base] if base is not None else a

    if lanes is not None:
        presorted = _lanes_presorted(lanes, sort_keys)
    else:
        presorted = _rows_presorted(
            {k: np.asarray(col_of(k)) for k in sort_keys}, sort_keys
        )
    # presorted shortcut: a compacted segment (or one flush's disjoint
    # shards, pre-ordered by _order_tables_by_first_key) is already in
    # (pk..., seq) order — survivors keep input order and dedup is one
    # adjacent compare: O(n) total, no sort
    if presorted:
        if do_dedup:
            keep = np.zeros(n, dtype=bool)
            keep[-1] = True
            if lanes is not None and base is None:
                for name in sort_keys[:num_pk]:
                    keep[:-1] |= _adjacent_neq_chunked(lanes, name)
            else:
                for name in sort_keys[:num_pk]:
                    a = col(name)
                    keep[:-1] |= a[:-1] != a[1:]
            final = base[keep] if base is not None else np.nonzero(keep)[0]
        else:
            final = base if base is not None else np.arange(n)
        return final

    packres = _pack_sort_keys(col, sort_keys, n)
    if packres is not None:
        packed, seq_width = packres
        order = np.argsort(packed, kind="stable")
        if do_dedup:
            group = packed[order] >> np.uint64(seq_width)
            keep = np.empty(n, dtype=bool)
            keep[:-1] = group[:-1] != group[1:]
            keep[-1] = True
        else:
            keep = None
    else:
        order = np.lexsort(tuple(col(k) for k in reversed(sort_keys)))
        if do_dedup:
            keep = np.zeros(n, dtype=bool)
            keep[-1] = True
            for name in sort_keys[:num_pk]:
                a = col(name)[order]
                keep[:-1] |= a[:-1] != a[1:]
        else:
            keep = None

    final = base[order] if base is not None else order
    return final[keep] if keep is not None else final


@lru_cache(maxsize=256)
def _build_index_kernel(
    key_names: tuple[str, ...],
    sort_keys: tuple[str, ...],
    pk_names: tuple[str, ...],
    template: Predicate | None,
    use_mask: bool,
    do_dedup: bool,
    presorted: bool,
):
    """Index-only scan kernel: mask -> sort -> dedup -> COMPACTED surviving
    row indices. The device sees only the sort-key (+ predicate) lanes and
    returns kept_count + int32 indices — 4 bytes per surviving row across
    the link instead of every column in both directions. The host then
    materializes any column type (incl. binary) with one arrow take.

    `use_mask=True` takes a precomputed host mask as a lane (predicates
    referencing binary columns, or masks the planner already paid for);
    otherwise the predicate template evaluates on device.
    """

    def core(cols: dict, mask, num_valid):
        n = cols[sort_keys[0]].shape[0]
        valid = jnp.arange(n) < num_valid
        mask = mask & valid
        kept = jnp.sum(mask)
        if presorted:
            pos = jnp.where(mask, jnp.cumsum(mask) - 1,
                            kept + jnp.cumsum(~mask) - 1)
            perm = jnp.zeros(n, dtype=jnp.int32).at[pos].set(
                jnp.arange(n, dtype=jnp.int32)
            )
        else:
            keys = [cols[k] for k in sort_keys]
            perm = jax.lax.sort(
                ((~mask).astype(jnp.int32), *keys,
                 jnp.arange(n, dtype=jnp.int32)),
                num_keys=1 + len(keys), is_stable=True,
            )[-1]
        if do_dedup:
            sorted_pk = {k: jnp.take(cols[k], perm, axis=0) for k in pk_names}
            keep = dedup_ops.dedup_last_value(sorted_pk, list(pk_names), kept)
        else:
            keep = jnp.arange(n) < kept
        kcnt = jnp.sum(keep)
        pos2 = jnp.where(keep, jnp.cumsum(keep) - 1,
                         kcnt + jnp.cumsum(~keep) - 1)
        out_idx = jnp.zeros(n, dtype=jnp.int32).at[pos2].set(perm.astype(jnp.int32))
        return out_idx, kcnt

    if use_mask:

        @xjit(kernel="index_merge_mask")
        def kernel(cols: dict, ext_mask, num_valid):
            return core(cols, ext_mask != 0, num_valid)

    else:

        @xjit(kernel="index_merge_filter")
        def kernel(cols: dict, literals: tuple, num_valid):
            n = cols[sort_keys[0]].shape[0]
            mask = filter_ops.eval_predicate(template, cols, literals)
            del n
            return core(cols, mask, num_valid)

    del key_names  # cache key only
    return kernel


def _plan_and_merge(
    schema: StorageSchema,
    n: int,
    col_of,
    predicate: Predicate | None,
    host_mask_fn,
    binary_pred: bool,
    itemsize_of,
    defer_device: bool = False,
    lanes=None,
) -> "np.ndarray | object":
    """Decide host-SIMD vs index-only-device for one materializing merge and
    run it; returns surviving row indices in output order.

    `defer_device=True` splits the device route into issue/collect: the
    kernel is DISPATCHED (async, device queue) and a zero-arg `collect()`
    closure comes back instead of indices — the chunked scan uses this to
    double-buffer: chunk i's kernel runs while chunk i+1 decodes and packs
    on host (VERDICT r03 #2). Host routes always return indices directly.

    Cost model (all terms measured, see _LinkProfile): the device pays
    key-lane H2D + 4 B/survivor D2H + dispatch latency; the host pays a
    vectorized predicate eval over all rows plus sort/dedup/take over
    SURVIVING rows only. The host mask is evaluated lazily — when the device
    wins even at worst-case selectivity, the predicate ships as a template
    and evaluates on device (no host pass at all).

    `HORAEDB_SCAN_PATH` in {auto, host, device, sharded} overrides (A/B
    harnesses, tests). Binary-column predicates always evaluate on host (the
    device has no byte lanes) but may still merge on device via the mask lane.

    When an ambient mesh is installed (parallel/mesh.py) the packed route
    upgrades to the cross-chip sample-sort merge (parallel/merge.py) for
    blocks past `HORAEDB_SHARDED_MIN_ROWS` — the sharded analog of the
    reference's single-node SortPreservingMergeExec (read.rs:479-492);
    `sharded` mode forces it regardless of size (tests, dryrun).
    """
    pk_names = tuple(schema.primary_key_names)
    sort_keys = pk_names + (SEQ_COLUMN_NAME,)
    do_dedup = schema.update_mode == UpdateMode.OVERWRITE
    if n == 0:
        return np.empty(0, np.int64)

    pred_cols = filter_ops.pred_columns(predicate)
    mode = os.environ.get("HORAEDB_SCAN_PATH", "auto")
    if mode not in ("auto", "host", "device", "sharded"):
        # a typo'd override must fail LOUDLY: an unknown mode falling
        # through to auto would silently measure the wrong path — the
        # exact A/B-honesty failure the explicit modes exist to prevent
        raise HoraeError(
            f"HORAEDB_SCAN_PATH={mode!r} is not one of "
            "auto/host/device/sharded"
        )
    link = _LinkProfile.get()
    dispatch = link["dispatch_s"]

    def host_merge(mask: np.ndarray | None) -> np.ndarray:
        scanstats.note("path_host_merge")
        SCAN_PATH.labels("host").inc()
        sel_rows = int(np.count_nonzero(mask)) if mask is not None else n
        t0 = time.perf_counter()
        with scanstats.stage("host_merge"):
            res = _host_merge_indices(
                col_of, n, sort_keys, len(pk_names), mask, do_dedup,
                lanes=lanes,
            )
        # feed the planner's rolling host-sort estimate — but only when the
        # merge actually sorted (the presorted O(n) shortcut is routed
        # unconditionally and would poison the per-row figure)
        if _presorted and not _presorted[0]:
            _HostCalib.observe_sort(sel_rows, time.perf_counter() - t0)
        return res

    key_bytes = sum(itemsize_of(name) for name in sort_keys)

    def device_merge_packed(mask):
        """Single-u64-lane device merge -> np.ndarray indices, a zero-arg
        collect closure (defer_device), or None when keys don't pack. Worth
        the ~30 ns/row host pack only when it saves more link time than it
        costs — i.e. slow links, exactly where the device path's H2D hurts.
        Routes to the cross-chip sample-sort merge when a mesh is ambient."""
        from horaedb_tpu.parallel.mesh import active_mesh

        mesh = active_mesh()
        if mode == "sharded" and mesh is None:
            # forced sharded with no ambient mesh: the likeliest harness
            # mistake (mesh install failed/skipped) — same honesty bar as
            # the unpackable fallback below, the downgrade must be visible.
            # The scanstats note records every occurrence; the log line is
            # once-per-process (this fires on EVERY chunk of every scan —
            # repeating it would bury the rest of the log)
            scanstats.note("path_sharded_fallback_no_mesh")
            global _warned_sharded_no_mesh
            if not _warned_sharded_no_mesh:
                _warned_sharded_no_mesh = True
                logger.warning(
                    "HORAEDB_SCAN_PATH=sharded but no mesh is active; "
                    "falling back to the single-device kernel (n=%d)", n,
                )
        # size-based upgrade only in auto mode: an explicit mode=device
        # must PIN the single-device kernel even on a mesh-active process,
        # or A/B harnesses silently measure the sharded path (the same
        # honesty bar the unpackable-fallback warning below holds)
        want_sharded = mesh is not None and (
            mode == "sharded"
            or (mode == "auto" and n >= _sharded_min_rows())
        )
        if not want_sharded and (key_bytes - 8) / link["h2d_bw"] < 30e-9:
            return None
        with scanstats.stage("host_prep"):
            packres = _pack_sort_keys(col_of, sort_keys, n)
            if packres is None:
                if mode == "sharded" or want_sharded:
                    # forced/auto-upgraded sharded mode downgrading is worth
                    # a trace: an A/B harness must not silently measure the
                    # single-device path (float or >63-bit keys don't pack)
                    scanstats.note("path_sharded_fallback_unpackable")
                    logger.warning(
                        "sharded merge requested but sort keys do not pack "
                        "into u64; falling back to the single-device lane "
                        "kernel (n=%d)", n,
                    )
                return None
            packed, seq_width = packres
            if mask is not None:
                packed = np.where(mask, packed, _PACK_SENTINEL)
                nv = int(np.count_nonzero(mask))
            else:
                nv = n
        if want_sharded:
            from horaedb_tpu.parallel.merge import sharded_packed_merge

            scanstats.note("path_device_merge_sharded")
            SCAN_PATH.labels("sharded").inc()
            with scanstats.stage("device_merge"):
                res = sharded_packed_merge(
                    packed, seq_width, do_dedup, mesh, defer=defer_device
                )
            return res
        scanstats.note("path_device_merge_packed")
        SCAN_PATH.labels("device").inc()
        with scanstats.stage("h2d"):
            block = Block.from_numpy({"__packed__": packed},
                                     pad_keys=("__packed__",))
            if scanstats.active():  # fence only for attribution
                # jaxlint: disable=J001 h2d attribution fence; profiling runs only
                jax.block_until_ready(list(block.columns.values()))
        with scanstats.stage("device_merge"):
            kernel = _build_packed_index_kernel(seq_width, do_dedup)
            out_idx, kcnt = kernel(block.columns["__packed__"], nv)
        if defer_device:
            return lambda: _collect_device(out_idx, kcnt)
        return _collect_device(out_idx, kcnt)

    def _collect_device(out_idx, kcnt) -> np.ndarray:
        """Sync point of a dispatched device merge (split out so deferred
        callers can overlap the kernel with the next chunk's host work)."""
        with scanstats.stage("device_merge"):
            k = int(kcnt)
        if k == 0:
            return np.empty(0, np.int64)
        with scanstats.stage("d2h"):
            return np.asarray(out_idx[:k]).astype(np.int64)

    def device_merge(mask):
        # -> np.ndarray indices, or a collect closure under defer_device
        if mask is not None or predicate is None:
            packed_res = device_merge_packed(mask)
            if packed_res is not None:
                return packed_res
        scanstats.note("path_device_merge")
        SCAN_PATH.labels("device").inc()
        need = list(sort_keys)
        if mask is None:
            need += [c for c in sorted(pred_cols) if c not in need]
        arrays = {name: col_of(name) for name in need}
        with scanstats.stage("host_prep"):
            presorted = _rows_presorted(arrays, sort_keys)
            if mask is not None:
                arrays = dict(arrays)
                arrays["__mask__"] = mask.astype(np.uint8)
        with scanstats.stage("h2d"):
            block = Block.from_numpy(arrays, pad_keys=sort_keys)
            if scanstats.active():  # fence only for attribution
                # jaxlint: disable=J001 h2d attribution fence; profiling runs only
                jax.block_until_ready(list(block.columns.values()))
        with scanstats.stage("device_merge"):
            if mask is not None:
                kernel = _build_index_kernel(
                    tuple(block.names), sort_keys, pk_names, None, True,
                    do_dedup, presorted,
                )
                cols = {k: v for k, v in block.columns.items() if k != "__mask__"}
                out_idx, kcnt = kernel(cols, block.columns["__mask__"], block.num_valid)
            else:
                template, raw = filter_ops.split_literals(predicate)
                literals = filter_ops.literal_arrays(
                    template, raw, {k: v.dtype for k, v in block.columns.items()}
                )
                kernel = _build_index_kernel(
                    tuple(block.names), sort_keys, pk_names, template, False,
                    do_dedup, presorted,
                )
                out_idx, kcnt = kernel(block.columns, literals, block.num_valid)
        if defer_device:
            return lambda: _collect_device(out_idx, kcnt)
        return _collect_device(out_idx, kcnt)

    tmpl_bytes = key_bytes + sum(
        itemsize_of(c) for c in pred_cols if c not in sort_keys
    )

    def dev_cost(lane_bytes: int, sel: int) -> float:
        return (
            n * lane_bytes / link["h2d_bw"]
            + n * link["sort_s_per_row"]
            + sel * 4 / link["d2h_bw"]
            + 8 * dispatch
        )

    def host_cost(sel: int) -> float:
        # the arrow take that materializes survivors is paid identically by
        # both paths (the caller runs it on the returned indices), so it
        # appears in neither cost
        return sel * _HostCalib.sort_s_per_row()

    _presorted: list[bool] = []

    def keys_presorted() -> bool:
        """Lazily-computed-once: already in (pk..., seq) order? A compacted
        segment is; the host path then skips its sort entirely (O(n)
        adjacent compares, zero transfer), which no device route can beat."""
        if not _presorted:
            with scanstats.stage("host_prep"):
                if lanes is not None:
                    _presorted.append(_lanes_presorted(lanes, sort_keys))
                else:
                    _presorted.append(_rows_presorted(
                        {k: np.asarray(col_of(k)) for k in sort_keys},
                        sort_keys,
                    ))
        return _presorted[0]

    n_terms = (
        max(1, len(list(filter_ops.iter_nodes(predicate))))
        if predicate is not None else 1
    )

    def timed_eval() -> np.ndarray:
        t0 = time.perf_counter()
        mask = host_mask_fn()
        _HostCalib.observe_eval(n * n_terms, time.perf_counter() - t0)
        return mask

    def eval_mask() -> np.ndarray | None:
        if predicate is None:
            return None
        with scanstats.stage("host_filter"):
            return timed_eval()

    if mode == "device":
        if binary_pred:
            return device_merge(eval_mask())
        return device_merge(None)
    if mode == "sharded":
        # force the cross-chip route: host-eval any predicate into a mask so
        # the packed path (the only sharded one) is always eligible
        return device_merge(eval_mask())
    if mode == "host":
        return host_merge(eval_mask())
    # ambient-mesh auto upgrade (docs/operations.md): past the sharded
    # threshold the cross-chip merge supersedes the single-device cost
    # compare — dev_cost models ONE device and would undersell an N-chip
    # merge. Presorted blocks keep their O(n) host shortcut (no sort left
    # to shard).
    if n >= _sharded_min_rows() and not keys_presorted():
        from horaedb_tpu.parallel.mesh import active_mesh

        if active_mesh() is not None:
            return device_merge(eval_mask())
    if predicate is None:
        if not keys_presorted() and dev_cost(key_bytes, n) < host_cost(n):
            return device_merge(None)
        return host_merge(None)

    # auto with a predicate: if the device wins even at worst-case
    # selectivity, skip the host eval entirely
    eval_cost = n * _HostCalib.eval_s_per_row() * n_terms
    if not binary_pred and dev_cost(tmpl_bytes, n) < eval_cost \
            and not keys_presorted():
        return device_merge(None)
    with scanstats.stage("host_filter"):
        mask = timed_eval()
        sel = int(np.count_nonzero(mask))
    if sel == 0:
        return np.empty(0, np.int64)
    if keys_presorted() or host_cost(sel) <= dev_cost(key_bytes + 1, sel):
        return host_merge(mask)
    return device_merge(mask)


# ---------------------------------------------------------------------------
# fused per-segment scan kernel
# ---------------------------------------------------------------------------


@lru_cache(maxsize=256)
def _build_scan_kernel(
    col_names: tuple[str, ...],
    sort_keys: tuple[str, ...],
    pk_names: tuple[str, ...],
    template: Predicate | None,
    do_dedup: bool,
    presorted: bool = False,
):
    """jit-compiled: mask -> sort(rejected to tail) -> dedup mask.

    Cache key is (schema columns, sort keys, predicate *template*, mode); the
    predicate's literal values are traced operands (ops/filter.py Slot), so a
    new constant reuses the compiled executable.

    `presorted`: the caller verified (host-side, O(n)) that rows are already
    (pk..., __seq__)-sorted — the common case: a compacted segment is one
    sorted SST, and one flush's shards are disjoint sorted ranges. The
    O(n log n) multi-key lexsort collapses to an O(n) STABLE partition
    (rejected rows sink, relative order preserved on both sides), built from
    two cumsums + one scatter of arange.
    """

    @xjit(kernel="scan_kernel")
    def kernel(cols: dict, literals: tuple, num_valid):
        n = cols[sort_keys[0]].shape[0]
        valid = jnp.arange(n) < num_valid
        mask = filter_ops.eval_predicate(template, cols, literals) & valid
        kept = jnp.sum(mask)
        if presorted:
            # stable partition: valid rows keep their (sorted) order as a
            # prefix, rejected/padding rows sink in order
            pos = jnp.where(mask, jnp.cumsum(mask) - 1,
                            kept + jnp.cumsum(~mask) - 1)
            perm = jnp.zeros(n, dtype=pos.dtype).at[pos].set(jnp.arange(n))
        else:
            # Rejected/padding rows sink: ~mask is the most significant key.
            # ONE variadic lax.sort with an iota payload replaces the
            # one-pass-per-key lexsort (measured 5.3x at the merge shape).
            keys = [cols[k] for k in sort_keys]
            perm = jax.lax.sort(
                ((~mask).astype(jnp.int32), *keys,
                 jnp.arange(n, dtype=jnp.int32)),
                num_keys=1 + len(keys), is_stable=True,
            )[-1]
        sorted_cols = {k: jnp.take(v, perm, axis=0) for k, v in cols.items()}
        if do_dedup:
            keep = dedup_ops.dedup_last_value(sorted_cols, list(pk_names), kept)
        else:
            keep = jnp.arange(n) < kept
        starts = dedup_ops.run_starts(
            [sorted_cols[k] for k in pk_names], jnp.arange(n) < kept
        )
        return sorted_cols, perm, keep, starts, kept

    del col_names  # part of the cache key only
    return kernel


def _order_tables_by_first_key(tables: list, sort_keys) -> list:
    """Order per-SST tables by their first row's sort key (each SST is
    internally sorted, so the first row is its minimum). Non-overlapping
    SSTs — compaction's pk-partitioned outputs, one flush's shards — then
    concatenate into a fully sorted run and the scan kernel's presorted
    fast path replaces its lexsort with an O(n) partition. Overlapping
    SSTs are unaffected (the O(n) sortedness check still decides)."""
    if len(tables) <= 1:
        return tables

    def first_key(t):
        return tuple(t.column(k)[0].as_py() for k in sort_keys)

    return sorted(tables, key=first_key)


def _lanes_presorted(lanes, sort_keys: tuple) -> bool:
    """Chunk-aware `_rows_presorted` over a colblock.ArrowLanes: each
    chunk checks independently (zero-copy per-chunk views) and the chunk
    BOUNDARIES compare as scalar key tuples — no full-column
    materialization. Memoized per sort-key tuple across planner probes."""
    key = tuple(sort_keys)
    cached = lanes.presorted_cache.get(key)
    if cached is not None:
        return cached
    chks = {k: lanes.chunks(k) for k in sort_keys}
    nch = len(chks[sort_keys[0]]) if chks[sort_keys[0]] else 0
    ok = True
    prev_last = None
    for i in range(nch):
        sub = {k: chks[k][i] for k in sort_keys}
        if len(sub[sort_keys[0]]) == 0:
            continue
        if not _rows_presorted(sub, key):
            ok = False
            break
        first = tuple(int(sub[k][0]) for k in sort_keys)
        if prev_last is not None and first < prev_last:
            ok = False
            break
        prev_last = tuple(int(sub[k][-1]) for k in sort_keys)
    lanes.presorted_cache[key] = ok
    return ok


def _adjacent_neq_chunked(lanes, name: str) -> np.ndarray:
    """`a[:-1] != a[1:]` for one lane, computed per chunk (+ boundary
    compares) — the presorted-dedup compare without a combine copy."""
    views = lanes.chunks(name)
    bounds = lanes.bounds
    n = int(bounds[-1])
    neq = np.zeros(max(n - 1, 0), dtype=bool)
    for i, v in enumerate(views):
        lo = int(bounds[i])
        if len(v) > 1:
            neq[lo:lo + len(v) - 1] = v[:-1] != v[1:]
        nxt = views[i + 1] if i + 1 < len(views) else None
        if nxt is not None and len(v) and len(nxt):
            neq[lo + len(v) - 1] = v[-1] != nxt[0]
    return neq


def _rows_presorted(arrays: dict, sort_keys: tuple) -> bool:
    """O(n) host check: nondecreasing lexicographic (pk..., __seq__) order.
    Vectorized compares; ~10 ms per 2M rows vs ~1.5 s for the device
    lexsort it lets the kernel skip."""
    n = len(arrays[sort_keys[0]])
    if n <= 1:
        return True
    tie = np.ones(n - 1, dtype=bool)
    for k in sort_keys:
        a = np.asarray(arrays[k])
        hd, tl = a[:-1], a[1:]
        lt = hd < tl
        if not np.all(~tie | lt | (hd == tl)):
            return False
        tie = tie & (hd == tl)
        if not tie.any():
            return True
    return True


# ---------------------------------------------------------------------------
# parquet IO with row-group pruning
# ---------------------------------------------------------------------------


class ParquetReader:
    """Per-SST parquet access + the per-segment device pipeline
    (reference: read.rs ParquetReader/build_df_plan)."""

    def __init__(
        self,
        store: ObjectStore,
        sst_path_gen: SstPathGenerator,
        schema: StorageSchema,
        scan_block_rows: int = 32 * 1024 * 1024,
        scan_cache_bytes: int = 0,
        enc_cache_bytes: int = 32 * 1024 * 1024,
    ):
        self._store = store
        self._path_gen = sst_path_gen
        self._schema = schema
        self._scan_block_rows = scan_block_rows
        # SSTs are immutable: cache open parquet handles (footer + schema
        # already parsed) keyed by path — the analog of the reference's
        # footer-size hint on its ParquetFileReaderFactory (read.rs:78-93).
        # Entries are (handle, per-handle lock): reads run in worker threads
        # and a pyarrow handle must not serve two reads at once. Protocol:
        # readers hold the handle lock for the whole read (the inserting
        # reader publishes the lock ALREADY ACQUIRED); closers (LRU eviction,
        # evict_cached) pop under the cache lock then acquire the handle lock
        # before close, so a handle is never closed mid-read. A busy handle
        # falls back to a transient open.
        self._pf_cache: "OrderedDict[str, tuple[pq.ParquetFile, threading.Lock]]" = OrderedDict()
        self._pf_cache_cap = 128
        self._pf_cache_lock = threading.Lock()
        # file_id -> decoded bloom sidecar (None = probed, no sidecar).
        # SSTs are immutable so entries never go stale; deletes evict.
        self._bloom_cache: dict[int, "dict | None"] = {}
        self._bloom_lock = threading.Lock()
        # Block cache at ROW-GROUP granularity, keyed (sst_id, row_group,
        # columns): pruning still decides which groups a query touches (the
        # selective-query win stays intact), and repeat reads of the hot
        # groups skip object-store IO + parquet decode. Immutable SSTs keep
        # entries fresh; deletes evict; LRU by decoded bytes.
        self._blk_cache: "OrderedDict[tuple[int, int, tuple], pa.Table]" = OrderedDict()
        self._blk_cache_bytes = 0
        self._blk_cache_cap = scan_cache_bytes
        self._blk_lock = threading.Lock()
        # sst_id -> (parquet FileMetaData, arrow schema): lets a read whose
        # pruned row groups are ALL cached skip the store entirely (footers
        # are tiny; evicted with the sst)
        self._meta_cache: dict[int, tuple] = {}
        # sst_id -> (decoded `.enc` sidecar, resident bytes). Value None =
        # probed, absent/unreadable. Encoded sidecars are immutable like
        # their SSTs; LRU by RESIDENT BYTES like the block cache above
        # (a 1M-row sidecar is ~MBs decoded — an entry-count bound would
        # leave the footprint unbounded across big SSTs), deletes evict,
        # cap 0 disables. Cold fetches single-flight per sst id so N
        # concurrent scans over a fresh tree pay one GET+decode, not N.
        self._enc_cache: "OrderedDict[int, tuple[object, int]]" = OrderedDict()
        self._enc_cache_bytes = 0
        self._enc_cache_cap = enc_cache_bytes
        self._enc_lock = threading.Lock()
        # sst_id -> (owning loop, future) for the in-flight sidecar fetch;
        # futures are loop-bound, so a caller on a DIFFERENT loop (engines
        # are occasionally driven from more than one) duplicates the fetch
        # rather than awaiting across loops
        self._enc_inflight: "dict[int, tuple[object, object]]" = {}
        # Zero-arg callable returning the table's current Visibility (or
        # None) — retention + tombstone masking applied to EVERY read_sst
        # result via the shared helper (storage/visibility.py, jaxlint
        # J010). Installed by ObjectBasedStorage; None = no masking.
        self.visibility_provider = None
        # Tombstones for evicted sst ids: an in-flight read racing a delete
        # must not repopulate the caches after eviction (the entry would
        # leak forever). Bounded FIFO — old ids' reads are long finished.
        self._evicted_ids: "OrderedDict[int, None]" = OrderedDict()
        # unified pool registry (common/bytebudget.py): the reader's two
        # byte-budgeted caches report occupancy via weakref providers
        # (readers are per-table and come and go with engines — a pushed
        # gauge would drift; the provider sums only live readers)
        GLOBAL_POOLS.register_provider(
            "scan", self,
            lambda r: (r._blk_cache_bytes, len(r._blk_cache)),
        )
        GLOBAL_POOLS.register_provider(
            "sidecar", self,
            lambda r: (r._enc_cache_bytes, len(r._enc_cache)),
        )
        if scan_cache_bytes:
            GLOBAL_POOLS.set_capacity("scan", scan_cache_bytes)
        if enc_cache_bytes:
            GLOBAL_POOLS.set_capacity("sidecar", enc_cache_bytes)

    def _tombstoned(self, sst_id: int) -> bool:
        return sst_id in self._evicted_ids

    def _assemble_cached(self, sst_id: int, get, predicate):
        """Serve a read purely from cache when the footer is known and every
        pruned row group is resident; None = fall through to IO."""
        with self._blk_lock:
            entry = self._meta_cache.get(sst_id)
        if entry is None:
            return None
        meta, arrow_schema = entry
        keep = _select_row_groups(meta, arrow_schema, predicate)
        if not keep:
            return arrow_schema.empty_table()
        parts = []
        for rg in keep:
            t = get(rg)
            if t is None:
                return None
            parts.append(t)
        return memtrace.tracked_concat_tables(parts, "host_prep")

    def _rg_cache_hooks(self, sst_id: int, cols_key: tuple):
        """(get, put) closures for _read_pruned, or None when disabled.

        The serving tier's device residency cache (serving/residency.py)
        rides these hooks as a tier ABOVE the host block cache: a
        heat-admitted block serves from its pinned entry (noted
        `blocks_resident` — no store IO, no parquet decode, and on
        accelerator backends the lanes are already HBM handles), while
        every non-resident touch feeds the heat gate. Blocks served here
        are pre-visibility, exactly like the host cache (read_sst masks
        after assembly), so a later tombstone can never be skipped."""
        if self._blk_cache_cap <= 0:
            return None
        from horaedb_tpu.serving import RESIDENCY, serving_env_off
        from horaedb_tpu.serving.residency import RESIDENCY_CACHE

        # the honesty switch disables this layer too: HORAEDB_SERVING=off
        # must force genuinely cold reads (store GET + parquet decode) —
        # serving answers are A/B'd against that oracle, and a pinned
        # block silently riding the "forced cold" run would exonerate a
        # residency-layer defect
        residency = (
            RESIDENCY_CACHE
            if RESIDENCY_CACHE.enabled and not serving_env_off() else None
        )
        # per-read probe dedup: _assemble_cached probes every kept row
        # group and falls through to _read_pruned on a partial hit, which
        # probes them again — without this one query double-counts
        # blocks_resident/blocks_fetched and ticks the heat gate twice
        # per block (admission after fewer distinct scans than
        # residency_admit_after documents)
        seen: set[int] = set()

        def get(rg: int):
            first = rg not in seen
            seen.add(rg)
            if residency is not None:
                t = residency.resident_block(sst_id, rg, cols_key)
                if t is not None:
                    if first:
                        scanstats.note("blocks_resident")
                        RESIDENCY.labels("resident").inc()
                    return t
            with self._blk_lock:
                t = self._blk_cache.get((sst_id, rg, cols_key))
                if t is not None:
                    self._blk_cache.move_to_end((sst_id, rg, cols_key))
            if t is not None and residency is not None and first:
                scanstats.note("blocks_fetched")
                RESIDENCY.labels("fetched").inc()
                if self._tombstoned(sst_id):
                    return t
                # host-cache hits feed the heat gate too: the second touch
                # of a hot block promotes it to the pinned tier. On
                # promotion the HOST entry is dropped: both tiers retain
                # the same pa.Table object, and charging table.nbytes to
                # both budgets double-counted every hot block's resident
                # bytes (the doppelganger audit, tests/test_memtrace.py)
                if residency.note_fetch(sst_id, rg, cols_key, t):
                    with self._blk_lock:
                        old = self._blk_cache.pop(
                            (sst_id, rg, cols_key), None
                        )
                        if old is not None:
                            self._blk_cache_bytes -= old.nbytes
            return t

        def put(rg: int, table: pa.Table) -> None:
            size = table.nbytes
            if residency is not None:
                scanstats.note("blocks_fetched")
                RESIDENCY.labels("fetched").inc()
                if not self._tombstoned(sst_id):
                    # residency admission runs BEFORE the host-cache size
                    # gate: its budget (and cap//4 dominate-check) is its
                    # own — a block too big for the host cache can still
                    # earn a device pin. An admitted block skips the host
                    # insert entirely: the pinned tier serves it first on
                    # every later get(), so a host copy would be pure
                    # double-charged residency (the doppelganger audit)
                    if residency.note_fetch(sst_id, rg, cols_key, table):
                        return
            if size > self._blk_cache_cap // 4:
                return  # one entry must not dominate the cache
            with self._blk_lock:
                if self._tombstoned(sst_id) or (sst_id, rg, cols_key) in self._blk_cache:
                    return
                self._blk_cache[(sst_id, rg, cols_key)] = table
                self._blk_cache_bytes += size
                while self._blk_cache_bytes > self._blk_cache_cap and self._blk_cache:
                    _k, old = self._blk_cache.popitem(last=False)
                    self._blk_cache_bytes -= old.nbytes
                    GLOBAL_POOLS.note_eviction("scan")

        return get, put

    async def _bloom_skip(self, sst: SstFile, predicate) -> bool:
        """True when the SST's bloom sidecar proves no row can satisfy the
        predicate's conjunctive equality constraints (storage/bloom.py).
        Sound under the engine's filter-BEFORE-dedup plan order."""
        from horaedb_tpu.storage import bloom as bloom_mod

        constraints = bloom_mod.eq_constraints(predicate)
        if not constraints:
            return False
        with self._bloom_lock:
            probed = sst.id in self._bloom_cache
            blooms = self._bloom_cache.get(sst.id)
        if not probed:
            from horaedb_tpu.objstore import NotFound

            try:
                data = await self._store.get(self._path_gen.generate_bloom(sst.id))
                blooms = bloom_mod.decode_blooms(data)
            except NotFound:
                blooms = None
            except Exception:  # noqa: BLE001 — corrupt sidecar: never prune
                logger.warning("unreadable bloom sidecar for sst %d", sst.id)
                blooms = None
            with self._bloom_lock:
                self._bloom_cache[sst.id] = blooms
        if blooms is None:
            return False
        return bloom_mod.can_skip(blooms, constraints)

    async def read_sst(
        self,
        sst: SstFile,
        columns: list[str] | None,
        predicate: Predicate | None,
        use_block_cache: bool = True,
    ) -> pa.Table:
        """Read one SST's projected columns, skipping row groups whose
        min/max statistics can't satisfy the predicate (and whole SSTs whose
        bloom sidecar rules the predicate out). Format-v2 SSTs serve
        qualifying reads from the encoded-lane sidecar instead (predicates
        evaluate on the encoded form, pages prune on zone maps, lanes
        decode through the sanctioned funnel) — per SST, so mixed v1/v2
        trees scan exactly with each file on its own path."""
        # cooperative deadline per SST read: an expired query stops
        # paying IO + decode here, SST by SST (common/deadline.py)
        deadline_ctx.check("sst_read")
        path = self._path_gen.generate(sst.id)
        if predicate is not None and await self._bloom_skip(sst, predicate):
            # EXPLAIN provenance: this SST never cost any IO
            scanstats.note("ssts_bloom_pruned")
            fields = [
                f for f in self._schema.arrow_schema
                if columns is None or f.name in columns
            ]
            return pa.schema(fields).empty_table()
        if sst.meta.format_version >= 2:
            from horaedb_tpu.ops import decode as decode_ops

            if decode_ops.scan_mode() != "raw":
                enc = await self._enc_sidecar(sst)
                if enc is not None:
                    # off-loop like the parquet decode below: a full-SST
                    # numpy expansion (and, on first use, the decode
                    # calibration micro-A/B incl. kernel compiles) must
                    # not freeze the event loop's admission/deadline/
                    # cancellation machinery
                    try:
                        table = await asyncio.to_thread(
                            self._read_encoded, enc, columns, predicate
                        )
                    except Exception:  # noqa: BLE001 — the parquet
                        # object is authoritative: ANY malformed-sidecar
                        # decode error (truncated payload a header-level
                        # check missed, lying page metadata) degrades
                        # this read, never 500s the query
                        logger.warning(
                            "encoded read failed for sst %d; falling "
                            "back to parquet", sst.id, exc_info=True,
                        )
                        table = None
                    if table is not None:
                        scanstats.note("ssts_read")
                        scanstats.note("ssts_encoded")
                        # per-tenant usage provenance (telemetry/metering):
                        # bytes this query MATERIALIZED from storage (the
                        # decoded size — the work done for this tenant;
                        # wire-size compression provenance is the separate
                        # encoded_bytes/decoded_bytes pair)
                        scanstats.note("bytes_scanned", int(table.nbytes))
                        return self._mask_visibility(sst, table)
        scanstats.note("ssts_read")
        cols_key = tuple(sorted(columns)) if columns is not None else ("*",)
        rg_cache = self._rg_cache_hooks(sst.id, cols_key) if use_block_cache else None
        if rg_cache is not None:
            cached = self._assemble_cached(sst.id, rg_cache[0], predicate)
            if cached is not None:
                # block-cache-served reads charge the same materialized
                # bytes as cold reads: usage metering must not depend on
                # which cache layer answered an identical query
                scanstats.note("bytes_scanned", int(cached.nbytes))
                return self._mask_visibility(sst, cached)

        def meta_sink(meta, arrow_schema) -> None:
            with self._blk_lock:
                if not self._tombstoned(sst.id):
                    self._meta_cache.setdefault(sst.id, (meta, arrow_schema))

        def _close_evicted(evicted) -> None:
            if evicted is not None:
                old, old_lock = evicted
                with old_lock:  # wait out any in-flight read
                    old.close()

        def _read() -> pa.Table:
            with self._pf_cache_lock:
                entry = self._pf_cache.get(path)
                if entry is not None:
                    self._pf_cache.move_to_end(path)
            if entry is not None:
                pf, handle_lock = entry
                if handle_lock.acquire(blocking=False):
                    try:
                        return _read_pruned(pf, columns, predicate, rg_cache,
                                            meta_sink if rg_cache else None)
                    finally:
                        handle_lock.release()
                # handle busy with a concurrent read: open transient
            local = self._store.local_path(path)
            if local is None:
                raise _NeedBytes()
            pf = pq.ParquetFile(local)
            my_lock = threading.Lock()
            my_lock.acquire()  # published pre-acquired: we read it first
            inserted = False
            evicted = None
            if entry is None:
                with self._pf_cache_lock:
                    if path not in self._pf_cache:
                        self._pf_cache[path] = (pf, my_lock)
                        inserted = True
                        if len(self._pf_cache) > self._pf_cache_cap:
                            _, evicted = self._pf_cache.popitem(last=False)
            try:
                return _read_pruned(pf, columns, predicate, rg_cache,
                                            meta_sink if rg_cache else None)
            finally:
                my_lock.release()
                if not inserted:
                    pf.close()  # transient handle (cache busy or lost race)
                _close_evicted(evicted)

        def _read_bytes(data: bytes) -> pa.Table:
            pf = pq.ParquetFile(io.BytesIO(data))
            return _read_pruned(pf, columns, predicate, rg_cache,
                                            meta_sink if rg_cache else None)

        from horaedb_tpu.objstore import NotFound

        try:
            table = await asyncio.to_thread(_read)
        except _NeedBytes:
            data = await self._store.get(path)
            table = await asyncio.to_thread(_read_bytes, data)
        except FileNotFoundError as e:
            # compaction deleted the file after the caller's manifest
            # snapshot; normalized so scan layers can refresh + retry
            raise NotFound(f"sst object vanished: {path}") from e
        scanstats.note("bytes_scanned", int(table.nbytes))
        return self._mask_visibility(sst, table)

    async def _enc_sidecar(self, sst: SstFile):
        """Cached decoded `.enc` sidecar of a format-v2 SST, or None
        (absent/corrupt — the parquet path covers it; a manifest-registered
        v2 SST always has one, so a miss is a degraded store, not a bug)."""
        loop = asyncio.get_running_loop()
        fut = None
        while True:
            with self._enc_lock:
                hit = self._enc_cache.get(sst.id)
                if hit is not None:
                    self._enc_cache.move_to_end(sst.id)
                    return hit[0]
                flight = self._enc_inflight.get(sst.id)
                if flight is None:
                    fut = loop.create_future()
                    self._enc_inflight[sst.id] = (loop, fut)
                    break
            f_loop, f_fut = flight
            if f_loop is not loop:
                break  # cross-loop caller: duplicate the fetch for this read
            # single-flight: the leader resolves the future with its verdict
            # (None on a transient failure — this read falls back to parquet)
            return await f_fut
        enc, cacheable = None, False
        try:
            enc, cacheable = await self._fetch_enc_sidecar(sst)
        finally:
            if fut is not None:
                if cacheable:
                    self._enc_cache_put(sst.id, enc)
                with self._enc_lock:
                    entry = self._enc_inflight.get(sst.id)
                    if entry is not None and entry[1] is fut:
                        del self._enc_inflight[sst.id]
                if not fut.done():
                    fut.set_result(enc)
        if fut is None and cacheable:
            self._enc_cache_put(sst.id, enc)
        return enc

    def _enc_cache_put(self, sst_id: int, enc) -> None:
        if self._enc_cache_cap <= 0:
            return
        nbytes = 64 if enc is None else enc.footprint_bytes() + 64
        with self._blk_lock:
            tomb = self._tombstoned(sst_id)
        with self._enc_lock:
            if not tomb and sst_id not in self._enc_cache:
                self._enc_cache[sst_id] = (enc, nbytes)
                self._enc_cache_bytes += nbytes
                while self._enc_cache_bytes > self._enc_cache_cap and self._enc_cache:
                    _, (_, nb) = self._enc_cache.popitem(last=False)
                    self._enc_cache_bytes -= nb
                    GLOBAL_POOLS.note_eviction("sidecar")

    async def _fetch_enc_sidecar(self, sst: SstFile):
        """One store fetch + decode of an SST's `.enc` object. Returns
        (enc-or-None, cacheable): transient store failures are NOT
        cacheable (the SST is immutable; a cached None would downgrade it
        to parquet for the entry's lifetime), NotFound and corrupt bytes
        are deterministic verdicts and are."""
        from horaedb_tpu.objstore import NotFound
        from horaedb_tpu.storage import encoding as enc_mod

        t0 = time.perf_counter()
        try:
            # deducted record, not a nested stage(): the callers wrap
            # read_sst in their own io_decode block, and a nested stage
            # would double-attribute this fetch to the io lane
            data = await self._store.get(self._path_gen.generate_enc(sst.id))
        except NotFound:
            enc = None  # definitively absent: cacheable
        except Exception:  # noqa: BLE001 — a TRANSIENT store failure
            # (breaker open, retries exhausted, deadline spent) must not
            # poison the cache. Fall back for THIS read only.
            logger.warning(
                "enc sidecar fetch failed for sst %d (transient; "
                "falling back to parquet for this read)", sst.id,
            )
            scanstats.record(
                "io_decode", time.perf_counter() - t0, deduct=True
            )
            return None, False
        else:
            try:
                enc = enc_mod.decode_blob(data)
                if enc.num_rows != sst.meta.num_rows:
                    raise HoraeError(
                        f"enc sidecar rows {enc.num_rows} != "
                        f"sst {sst.meta.num_rows}"
                    )
            except Exception:  # noqa: BLE001 — corrupt sidecar bytes are
                # deterministic (the object is immutable): cache the miss;
                # the parquet object remains authoritative
                logger.warning("unreadable enc sidecar for sst %d", sst.id)
                enc = None
        scanstats.record("io_decode", time.perf_counter() - t0, deduct=True)
        return enc, True

    def _read_encoded(self, enc, columns, predicate) -> "pa.Table | None":
        """Serve one SST read from its encoded sidecar: per-page zone
        pruning, predicate evaluation on the ENCODED form (rle run
        skipping, dict-id rewrite — storage/encoding.py), then decode of
        the surviving pages only, through the dispatcher-chosen funnel
        (ops/decode.py device kernels or the host numpy funnel). None =
        the sidecar does not cover the requested lanes; caller falls back
        to parquet. Row-exact: the predicate filter here runs BEFORE the
        merge exactly like the reference plan's FilterExec, so dropping
        rejected rows early is semantically identical to the parquet
        path's later row-wise mask."""
        from horaedb_tpu.ops import decode as decode_ops
        from horaedb_tpu.storage import encoding as enc_mod

        schema = self._schema.arrow_schema
        names = [
            f.name for f in schema if columns is None or f.name in columns
        ]
        if any(n not in enc.lanes for n in names):
            return None
        fields = [schema.field(schema.names.index(n)) for n in names]
        keep_pages, pruned = enc_mod.prune_pages(enc, predicate)
        if pruned:
            scanstats.note("pages_pruned", pruned)
        # per-lane encoding provenance (EXPLAIN `encoding.lanes`)
        for n in names:
            scanstats.note(f"enclane_{n}={enc.lanes[n].codec}", 0)
        if not keep_pages:
            return pa.schema(fields).empty_table()

        def lane_decode(n: str) -> np.ndarray:
            """Full-lane decode through the CALIBRATED dispatcher — the
            single decode entry for predicate eval and materialization,
            so the env pin and the decode_impl provenance cover both."""
            lane = enc.lanes[n]
            rows = sum(lane.pages[p].rows for p in keep_pages)
            impl = decode_ops.choose(lane.codec, rows)
            scanstats.note(f"decode_impl_{impl}", 0)
            return enc_mod.decode_lane(lane, keep_pages, impl=impl)

        # deducted stage, not a nested stage(): read_sst runs inside the
        # callers' io_decode stage blocks, and attribution must count the
        # expansion ONCE — in the decode lane, with any first-use kernel
        # compile inside the block deducted into ITS lane, not both
        with scanstats.deducted_stage("decode"):
            decoded: dict[str, np.ndarray] = {}
            mask = None
            if predicate is not None:
                stats = enc_mod.EncodedEvalStats()
                mask = enc_mod.encoded_mask(
                    enc, predicate, keep_pages, stats, decoded,
                    decode=lane_decode,
                )
                if stats.runs_skipped:
                    scanstats.note("runs_skipped", stats.runs_skipped)
                if mask is not None and bool(mask.all()):
                    mask = None  # nothing rejected: skip the take
            sel = np.nonzero(mask)[0] if mask is not None else None
            if sel is not None and len(sel) == 0:
                return pa.schema(fields).empty_table()
            arrays = []
            enc_bytes = dec_bytes = 0
            for n in names:
                lane = enc.lanes[n]
                if lane.codec == "null":
                    count = len(sel) if sel is not None else sum(
                        lane.pages[p].rows for p in keep_pages
                    )
                    arrays.append(pa.nulls(count, fields[names.index(n)].type))
                    continue
                arr = decoded.get(n)
                if arr is None:
                    arr = lane_decode(n)
                enc_bytes += sum(lane.pages[p].length for p in keep_pages)
                dec_bytes += arr.nbytes
                if sel is not None:
                    arr = arr[sel]
                arrays.append(_np_to_arrow(arr, fields[names.index(n)].type))
            scanstats.note("encoded_bytes", enc_bytes)
            scanstats.note("decoded_bytes", dec_bytes)
            # lineage: every decoded lane is a fresh host buffer
            memtrace.track_bytes(dec_bytes, "decode", "alloc")
        return pa.Table.from_arrays(arrays, schema=pa.schema(fields))

    def _mask_visibility(self, sst: SstFile, table: pa.Table) -> pa.Table:
        """Retention + tombstone masking via the SHARED helper
        (storage/visibility.py) — the single funnel every scan route,
        the downsample pushdown, and compaction read through. Applied
        AFTER the block cache (cache entries stay raw/immutable; a
        tombstone created later still masks cached hits) and BEFORE the
        merge (exact for last-writer-wins, see the helper's contract)."""
        if self.visibility_provider is None or table.num_rows == 0:
            return table
        vis = self.visibility_provider()
        if vis is None:
            return table
        from horaedb_tpu.storage.visibility import apply_visibility

        return apply_visibility(table, vis, sst_range=sst.meta.time_range)

    def evict_cached(self, file_id: int) -> None:
        """Drop the cached handle of a deleted SST (compaction calls this
        before physical deletes so file descriptors don't linger)."""
        with self._pf_cache_lock:
            entry = self._pf_cache.pop(self._path_gen.generate(file_id), None)
        with self._bloom_lock:
            self._bloom_cache.pop(file_id, None)
        with self._enc_lock:
            ent = self._enc_cache.pop(file_id, None)
            if ent is not None:
                self._enc_cache_bytes -= ent[1]
        with self._blk_lock:
            self._meta_cache.pop(file_id, None)
            for key in [k for k in self._blk_cache if k[0] == file_id]:
                self._blk_cache_bytes -= self._blk_cache.pop(key).nbytes
            self._evicted_ids[file_id] = None
            while len(self._evicted_ids) > 65536:
                self._evicted_ids.popitem(last=False)
        # device residency rides the same eviction funnel: a compaction-
        # deleted SST's pinned blocks die with it (serving/residency.py)
        from horaedb_tpu.serving.residency import RESIDENCY_CACHE

        RESIDENCY_CACHE.evict_sst(file_id)
        if entry is not None:
            pf, handle_lock = entry
            with handle_lock:  # wait out any in-flight read
                pf.close()

    async def scan_segment(
        self,
        ssts: list[SstFile],
        predicate: Predicate | None,
        projections: list[int] | None,
        keep_builtin: bool,
        batch_size: int = DEFAULT_SCAN_BATCH_SIZE,
        use_block_cache: bool = True,
    ) -> list[pa.RecordBatch]:
        """Traced entry point of the per-segment pipeline: the span anchors
        the per-stage lane timings (scanstats bridges every stage() into the
        active span's `stages` attr) for /debug/traces."""
        with tracing.span(
            "scan_segment", ssts=len(ssts),
            rows=sum(s.meta.num_rows for s in ssts),
        ):
            return await self._scan_segment(
                ssts, predicate, projections, keep_builtin, batch_size,
                use_block_cache,
            )

    async def _scan_segment(
        self,
        ssts: list[SstFile],
        predicate: Predicate | None,
        projections: list[int] | None,
        keep_builtin: bool,
        batch_size: int = DEFAULT_SCAN_BATCH_SIZE,
        use_block_cache: bool = True,
    ) -> list[pa.RecordBatch]:
        """The fused device pipeline for one time segment.

        Segments whose SSTs exceed `scan_block_rows` in total take the
        hierarchical path: per-chunk device passes (filter+merge+dedup) whose
        sorted outputs merge in a device tree — the blockwise/carry-state
        streaming shape of SURVEY §5.7 (LastValue dedup is idempotent across
        levels, so intermediate dedup is safe; Append mode never dedups).
        """
        # shared prologue/epilogue with the chunked path lives in
        # _resolve_read_names/_output_names/_slice_batches
        pk_types = [
            self._schema.arrow_schema.field(n).type
            for n in self._schema.primary_key_names
        ]
        if any(_is_binary_like(t) for t in pk_types):
            # binary primary keys: sort/dedup on host via arrow compute (the
            # reference compares binary pks too, macros.rs compare dispatch)
            return await self._scan_segment_host(
                ssts, predicate, projections, keep_builtin, batch_size,
                use_block_cache=use_block_cache,
            )
        total_rows = sum(s.meta.num_rows for s in ssts)
        if total_rows > self._scan_block_rows and len(ssts) > 1:
            fetched = self._resolve_read_names(projections, keep_builtin)
            has_binary = any(
                _is_binary_like(f.type)
                for f in self._schema.arrow_schema
                if f.name in fetched
            )
            if not has_binary:
                return await self._scan_segment_chunked(
                    ssts, predicate, projections, keep_builtin, batch_size,
                    use_block_cache=use_block_cache,
                )
            # binary columns keep the single-block hybrid path
        schema = self._schema
        read_names = self._resolve_read_names(projections, keep_builtin)

        with scanstats.stage("io_decode"):
            tables = await asyncio.gather(
                *(self.read_sst(s, read_names, predicate,
                   use_block_cache=use_block_cache) for s in ssts)
            )
        tables = [t for t in tables if t.num_rows > 0]
        if not tables:
            return []
        with scanstats.stage("host_prep"):
            tables = _order_tables_by_first_key(
                tables, tuple(schema.primary_key_names) + (SEQ_COLUMN_NAME,)
            )
            # NO combine_chunks here: it would copy EVERY column; the merge
            # touches only key/predicate lanes, which _merge_table combines
            # per-column on demand, and arrow take handles chunked input —
            # measured 35% of config-2 wall clock saved
            table = memtrace.tracked_concat_tables(tables, "host_prep")
        out_names = self._output_names(read_names, keep_builtin)

        # append mode with binary VALUE columns concatenates group bytes on
        # host and keeps the fused-kernel path (group starts come from the
        # device run-boundary mask)
        value_names = {schema.arrow_schema.names[i] for i in schema.value_idxes}
        has_binary_value = any(
            _is_binary_like(table.schema.field(v).type)
            for v in value_names if v in table.schema.names
        )
        if schema.update_mode == UpdateMode.APPEND and has_binary_value:
            (
                sorted_cols, perm, _keep, starts, kept, numeric_names, binary_names,
            ) = self._fused_pass(table, predicate)
            # group-byte concatenation + arrow rebuild is CPU-bound
            # host work: off the event loop (J018)
            result = await asyncio.to_thread(
                self._materialize_append_mode,
                table, sorted_cols, np.asarray(perm), np.asarray(starts),
                int(kept), numeric_names, binary_names, out_names,
            )
            return self._slice_batches(result, batch_size)

        # unified materializing merge: the planner picks host SIMD or the
        # index-only device kernel; either way the output is a row-index
        # vector and ONE arrow take materializes every column type
        idx = self._merge_table(table, predicate)
        if len(idx) == 0:
            return []
        with scanstats.stage("materialize"):
            # arrow take materializes fresh column buffers (the ONE copy
            # this plan shape pays); combine then flattens any chunking
            taken = memtrace.track(
                table.select(out_names).take(pa.array(idx)),
                "materialize", "copy",
            )
            result = memtrace.tracked_combine(taken, "materialize")
        batches = result.to_batches(max_chunksize=batch_size)
        return [b for b in batches if b.num_rows > 0]

    def _merge_table(self, table: pa.Table, predicate: Predicate | None) -> np.ndarray:
        """_plan_and_merge over a decoded arrow table, consumed through a
        chunk-aware ArrowLanes block: the host route (sortedness probe,
        predicate eval, mask compaction, key packing) reads per-chunk
        zero-copy views, so no per-column combine_chunks copy happens —
        only device routes fall back to `lanes.lane` (the ONE sanctioned
        contiguous materialization, cached across planner probes)."""
        lanes = colblock.ArrowLanes(table, stage="host_prep")

        pred_cols = filter_ops.pred_columns(predicate)
        binary_pred = any(
            _is_binary_like(table.schema.field(c).type)
            for c in pred_cols if c in table.schema.names
        )

        def host_mask_fn() -> np.ndarray:
            if binary_pred:
                return filter_ops.eval_predicate_host(predicate, table)
            return lanes.eval_chunked(
                lambda cols: filter_ops.eval_predicate_np(predicate, cols),
                sorted(pred_cols),
            )

        def itemsize_of(name: str) -> int:
            t = table.schema.field(name).type
            try:
                return max(1, t.bit_width // 8)
            except (ValueError, AttributeError):
                return 16  # variable-width: rough planning estimate

        return _plan_and_merge(
            self._schema, table.num_rows, lanes.lane, predicate,
            host_mask_fn, binary_pred, itemsize_of, lanes=lanes,
        )

    async def _scan_segment_host(
        self,
        ssts: list[SstFile],
        predicate: Predicate | None,
        projections: list[int] | None,
        keep_builtin: bool,
        batch_size: int,
        use_block_cache: bool = True,
    ) -> list[pa.RecordBatch]:
        """Host merge/dedup for schemas with binary primary keys: arrow
        compute sort + vectorized adjacent-row boundary detection. Numeric
        predicate columns still evaluate through the shared predicate
        engine."""
        import pyarrow.compute as pc

        schema = self._schema
        read_names = self._resolve_read_names(projections, keep_builtin)
        # Sequential chunked reads with immediate filtering bound peak memory
        # to (filtered rows so far + one raw chunk); filter BEFORE dedup
        # (reference plan order).
        filtered: list[pa.Table] = []
        chunk: list[SstFile] = []
        chunk_rows = 0

        async def flush() -> None:
            nonlocal chunk, chunk_rows
            if not chunk:
                return
            tables = await asyncio.gather(
                *(self.read_sst(s, read_names, predicate,
               use_block_cache=use_block_cache) for s in chunk)
            )
            tables = [t for t in tables if t.num_rows > 0]
            chunk, chunk_rows = [], 0
            if not tables:
                return
            t = memtrace.tracked_combine(
                memtrace.tracked_concat_tables(tables, "host_prep"),
                "host_prep",
            )
            if predicate is not None:
                mask = filter_ops.eval_predicate_host(predicate, t)
                t = t.filter(pa.array(mask))
            if t.num_rows:
                filtered.append(t)

        for s in ssts:
            if chunk and chunk_rows + s.meta.num_rows > self._scan_block_rows:
                await flush()
            chunk.append(s)
            chunk_rows += s.meta.num_rows
        await flush()
        if not filtered:
            return []
        table = memtrace.tracked_combine(
            memtrace.tracked_concat_tables(filtered, "host_prep"),
            "host_prep",
        )

        pk_names = schema.primary_key_names
        sort_keys = [(n, "ascending") for n in pk_names] + [(SEQ_COLUMN_NAME, "ascending")]
        table = memtrace.tracked_combine(
            memtrace.track(table.sort_by(sort_keys), "host_prep", "copy"),
            "host_prep",
        )

        if schema.update_mode == UpdateMode.OVERWRITE and table.num_rows > 1:
            n = table.num_rows
            next_differs = np.zeros(n, dtype=bool)
            next_differs[-1] = True
            for name in pk_names:
                col = memtrace.tracked_combine(table.column(name), "host_prep")
                neq = pc.fill_null(
                    pc.not_equal(col.slice(0, n - 1), col.slice(1, n)), True
                ).to_numpy(zero_copy_only=False)
                next_differs[: n - 1] |= neq
            table = table.filter(pa.array(next_differs))
        elif schema.update_mode == UpdateMode.APPEND:
            # binary value columns concat per group (BytesMergeOperator)
            value_names = {schema.arrow_schema.names[i] for i in schema.value_idxes}
            has_binary_value = any(
                _is_binary_like(schema.arrow_schema.field(v).type)
                for v in value_names
            )
            if has_binary_value and table.num_rows > 1:
                n = table.num_rows
                starts = np.zeros(n, dtype=bool)
                starts[0] = True
                for name in pk_names:
                    col = memtrace.tracked_combine(
                        table.column(name), "host_prep"
                    )
                    neq = pc.fill_null(
                        pc.not_equal(col.slice(1, n), col.slice(0, n - 1)), True
                    ).to_numpy(zero_copy_only=False)
                    starts[1:] |= neq
                start_idx = np.nonzero(starts)[0]
                ends = np.append(start_idx[1:], n)
                # resolve value columns BY NAME in the projected table (the
                # schema-level idxes shift under projection)
                all_names = schema.arrow_schema.names
                value_names_ordered = [all_names[i] for i in schema.value_idxes]
                op = BytesMergeOperator(
                    [
                        table.schema.names.index(v)
                        for v in value_names_ordered
                        if v in table.schema.names
                    ]
                )
                def _merge_groups() -> list[pa.RecordBatch]:
                    # per-group byte concatenation is CPU-bound host
                    # work: one thread hop for the whole batch (J018)
                    return [
                        op.merge(table.slice(s, e - s).to_batches()[0])
                        if e - s > 1
                        else table.slice(s, 1).to_batches()[0]
                        for s, e in zip(start_idx, ends)
                    ]

                groups = await asyncio.to_thread(_merge_groups)
                table = pa.Table.from_batches(groups)

        out_names = self._output_names(read_names, keep_builtin)
        result = memtrace.tracked_combine(
            table.select(out_names), "materialize"
        )
        batches = result.to_batches(max_chunksize=batch_size)
        return [b for b in batches if b.num_rows > 0]

    def _fused_pass(
        self,
        table: pa.Table,
        predicate: Predicate | None,
        extra_arrays: dict[str, np.ndarray] | None = None,
    ):
        """The shared fused device pass: numeric/binary split, SoA block,
        literal casting, and the jitted filter->sort->dedup kernel. Used by
        the single-block scan, the hierarchical merge levels, and aggregate
        pushdown (`extra_arrays` rides host-computed lanes, e.g. the dense
        series index, through the same permutation)."""
        schema = self._schema
        pk_names = tuple(schema.primary_key_names)
        sort_keys = pk_names + (SEQ_COLUMN_NAME,)

        numeric_names, binary_names = [], []
        for name in table.schema.names:
            t = table.schema.field(name).type
            if _is_binary_like(t):
                binary_names.append(name)
            else:
                numeric_names.append(name)
        ensure(
            all(k in numeric_names for k in sort_keys),
            "primary key and seq columns must be numeric for the device path",
        )

        arrays = {
            name: arrow_column_to_numpy(
                memtrace.tracked_combine(table.column(name), "host_prep")
            )
            for name in numeric_names
        }
        if extra_arrays:
            arrays.update(extra_arrays)
        with scanstats.stage("h2d"):
            block = Block.from_numpy(arrays, pad_keys=sort_keys)
            memtrace.device_staged(
                sum(int(a.nbytes) for a in arrays.values()), "h2d"
            )

        template, raw_literals = filter_ops.split_literals(predicate)
        literals = filter_ops.literal_arrays(
            template, raw_literals, {k: v.dtype for k, v in block.columns.items()}
        )
        do_dedup = schema.update_mode == UpdateMode.OVERWRITE and not binary_names
        kernel = _build_scan_kernel(
            tuple(block.names), sort_keys, pk_names, template, do_dedup,
            presorted=_rows_presorted(arrays, sort_keys),
        )
        with scanstats.stage("device_merge"):
            sorted_cols, perm, keep, starts, kept = kernel(
                block.columns, literals, block.num_valid
            )
        return sorted_cols, perm, keep, starts, kept, numeric_names, binary_names

    async def _scan_segment_chunked(
        self,
        ssts: list[SstFile],
        predicate: Predicate | None,
        projections: list[int] | None,
        keep_builtin: bool,
        batch_size: int,
        use_block_cache: bool = True,
    ) -> list[pa.RecordBatch]:
        """Hierarchical scan: chunked device passes + a device merge tree."""
        schema = self._schema
        all_names = schema.arrow_schema.names
        read_names = self._resolve_read_names(projections, keep_builtin)
        pk_names = tuple(schema.primary_key_names)
        sort_keys = pk_names + (SEQ_COLUMN_NAME,)
        cap = self._scan_block_rows

        def greedy_partition(items: list, rows_of) -> list[list]:
            out, cur, cur_rows = [], [], 0
            for it in items:
                r = rows_of(it)
                if cur and cur_rows + r > cap:
                    out.append(cur)
                    cur, cur_rows = [], 0
                cur.append(it)
                cur_rows += r
            if cur:
                out.append(cur)
            return out

        def run_block(
            arrays: dict[str, np.ndarray], pred, defer: bool = False
        ):
            """Merge one in-memory block: the planner routes host SIMD vs the
            index-only device kernel (only key/predicate lanes ever cross the
            link; survivors gather from the HOST arrays). With `defer`, a
            device-routed merge returns a zero-arg closure producing the
            gathered block later (kernel already dispatched)."""
            n = len(arrays[sort_keys[0]])
            p_cols = filter_ops.pred_columns(pred)

            def host_mask_fn() -> np.ndarray:
                return filter_ops.eval_predicate_np(
                    pred, {c: arrays[c] for c in p_cols}
                )

            res = _plan_and_merge(
                schema, n, arrays.__getitem__, pred, host_mask_fn, False,
                lambda name: arrays[name].dtype.itemsize,
                defer_device=defer,
            )
            if callable(res):
                def gather():
                    idx = res()  # ONE device sync + index D2H per block
                    return {k: a[idx] for k, a in arrays.items()}
                return gather
            return {k: a[res] for k, a in arrays.items()}

        # level 0: filter + merge + dedup per SST chunk, with the NEXT
        # chunk's parquet decode prefetching on worker threads while this
        # chunk merges (the decode/compute overlap of SURVEY §7 risk (c))
        level: list[dict[str, np.ndarray]] = []
        chunks = greedy_partition(ssts, lambda s: s.meta.num_rows)

        async def read_chunk(chunk: list[SstFile]) -> list[pa.Table]:
            with scanstats.stage("io_decode"):
                tables = await asyncio.gather(
                    *(self.read_sst(s, read_names, predicate,
                       use_block_cache=use_block_cache) for s in chunk)
                )
            return [t for t in tables if t.num_rows > 0]

        next_task = asyncio.ensure_future(read_chunk(chunks[0])) if chunks else None
        pending = None  # chunk i-1's deferred device merge (double buffer)

        def settle() -> None:
            nonlocal pending
            if pending is not None:
                out = pending()
                pending = None
                if len(out[sort_keys[0]]):
                    level.append(out)

        try:
            for i in range(len(chunks)):
                tables = await next_task
                next_task = None
                if i + 1 < len(chunks):
                    next_task = asyncio.ensure_future(read_chunk(chunks[i + 1]))
                    await asyncio.sleep(0)  # let the prefetch reach its threads
                if not tables:
                    continue
                with scanstats.stage("host_prep"):
                    tables = _order_tables_by_first_key(tables, sort_keys)
                    table = memtrace.tracked_combine(
                        memtrace.tracked_concat_tables(tables, "host_prep"),
                        "host_prep",
                    )
                    arrays = {
                        name: arrow_column_to_numpy(
                            memtrace.tracked_combine(
                                table.column(name), "host_prep"
                            )
                        )
                        for name in table.schema.names
                    }
                # double buffer: chunk i's kernel was dispatched last
                # iteration and ran WHILE this chunk decoded and packed;
                # collect it only now, right before dispatching chunk i+1
                # (at most two chunks of key lanes live on device)
                out = run_block(arrays, predicate, defer=True)
                settle()
                if callable(out):
                    pending = out
                elif len(out[sort_keys[0]]):
                    level.append(out)
            settle()
        except BaseException:
            # a failed merge must not abandon the in-flight prefetch (its
            # reads would race a subsequent evict/close and its exception
            # would be logged as never-retrieved); a dispatched device merge
            # is harmless to drop — device arrays free with their refs
            if next_task is not None:
                next_task.cancel()
                try:
                    await next_task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
            raise
        # merge tree: combine sorted deduped runs until one remains
        while len(level) > 1:
            next_level = []
            for group in greedy_partition(level, lambda r: len(r[sort_keys[0]])):
                if len(group) == 1:
                    next_level.append(group[0])
                    continue
                cat = {
                    k: memtrace.tracked_concat(
                        [g[k] for g in group], "host_prep"
                    )
                    for k in group[0]
                }
                next_level.append(run_block(cat, None))
            if len(next_level) == len(level):
                # every pair exceeds the cap: merge only the two smallest
                # runs (guaranteed progress with minimal cap overshoot —
                # merging everything would defeat the memory bound)
                next_level.sort(key=lambda r: len(r[sort_keys[0]]))
                a, b = next_level[0], next_level[1]
                cat = {
                    k: memtrace.tracked_concat([a[k], b[k]], "host_prep")
                    for k in a
                }
                next_level = [run_block(cat, None)] + next_level[2:]
            level = next_level
        if not level:
            return []
        final = level[0]
        out_names = self._output_names(read_names, keep_builtin)
        cols = [
            _np_to_arrow(final[n], schema.arrow_schema.field(all_names.index(n)).type)
            for n in out_names
        ]
        out_schema = pa.schema(
            [schema.arrow_schema.field(all_names.index(n)) for n in out_names]
        )
        result = pa.RecordBatch.from_arrays(cols, schema=out_schema)
        return self._slice_batches(result, batch_size)

    async def scan_segment_downsample(
        self,
        ssts: list[SstFile],
        predicate: Predicate | None,
        ts_column: str,
        value_column: str,
        series_column: str,
        series_ids: np.ndarray,
        t0: int,
        bucket_ms: int,
        num_buckets: int,
        with_minmax: bool = True,
        use_block_cache: bool = True,
        packed_ok: bool = False,
    ) -> dict:
        """Aggregate pushdown: scan one segment and reduce it to dense
        [num_series, num_buckets] grids ON DEVICE — raw rows never cross back
        to host (SURVEY's #1 offload target: scan->filter->aggregate fused).

        `series_ids` is a SORTED array of series keys; dense output row i
        corresponds to series_ids[i], rows with other keys are dropped.
        Dedup semantics are preserved: the fused kernel sorts and
        last-value-dedups before the reduction, exactly like the
        materializing path. Correct whenever duplicates cannot span segments
        (true for any schema whose primary key includes the timestamp, e.g.
        the metric-engine data table).

        Segments above `scan_block_rows` route through the hierarchical scan
        and aggregate its sorted output run — device memory stays bounded.

        Returns host numpy grids: sum and count, plus min/max when
        `with_minmax` (no mean — callers derive it after combining partials).
        """
        import jax.numpy as jnp

        from horaedb_tpu.ops import aggregate as agg_ops

        num_series = len(series_ids)
        grids = {
            "sum": np.zeros((num_series, num_buckets)),
            "count": np.zeros((num_series, num_buckets)),
        }
        if with_minmax:
            grids["min"] = np.full((num_series, num_buckets), np.inf)
            grids["max"] = np.full((num_series, num_buckets), -np.inf)

        def dense_sid(col: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
            """(dense position, hit mask). Misses keep their MONOTONE
            searchsorted position (not -1): the sorted-segment compaction
            needs monotone keys, and misses are excluded via the reduction's
            weight column instead of a key sentinel."""
            pos = np.searchsorted(series_ids, col)
            pos_c = np.clip(pos, 0, max(0, len(series_ids) - 1))
            hit = series_ids[pos_c] == col
            return pos_c.astype(np.int32), hit

        from horaedb_tpu.parallel.mesh import active_mesh

        mesh = active_mesh()

        def accumulate_sorted(ts_np, sid_np, val_np, valid_np=None):
            """Fold one sorted run into the grids (sorted-segment fast path).
            With an ambient multi-device mesh installed, rows shard over
            "rows" and the output grid over "series" (SURVEY §2.5's
            shard_map-over-SST-partitions); partials combine via psum/pmin/
            pmax over ICI. Single device: the local sorted kernel.
            `valid_np` excludes rows via the reduction's weight column
            (sid_np must stay monotone for excluded rows too)."""
            # cooperative deadline between device-lane launches: each fold
            # is one kernel dispatch — an expired query stops dispatching
            # (host-side check; never traced into the kernel body)
            deadline_ctx.check("device_lane")
            if mesh is not None:
                # path counter rides sharded_downsample (one inc per fold)
                with scanstats.stage("device_agg"):
                    out = self._sharded_accumulate(
                        mesh, ts_np, sid_np, val_np, t0, bucket_ms,
                        num_series, num_buckets, with_minmax, valid_np=valid_np,
                    )
            else:
                SCAN_PATH.labels("device").inc()
                with scanstats.stage("device_agg"):
                    out = agg_ops.downsample_sorted(
                        ts_np, sid_np, val_np, t0, bucket_ms,
                        num_series=num_series, num_buckets=num_buckets,
                        with_minmax=with_minmax, valid=valid_np,
                    )
                # lane attribution: which registry impl the calibrated
                # dispatcher ran this fold on (host reduceat vs a device
                # kernel decides whether device_agg even touched a device)
                from horaedb_tpu.ops import agg_registry

                scanstats.note("agg_impl_" + agg_registry.last_choice())
            grids["sum"] += np.asarray(out["sum"])
            grids["count"] += np.asarray(out["count"])
            if with_minmax:
                grids["min"] = np.minimum(grids["min"], np.asarray(out["min"]))
                grids["max"] = np.maximum(grids["max"], np.asarray(out["max"]))

        total_rows = sum(s.meta.num_rows for s in ssts)
        if total_rows > self._scan_block_rows and len(ssts) > 1:
            # bounded-memory path: hierarchical scan yields merged, deduped,
            # pk-sorted batches; fold each into the grids
            batches = await self._scan_segment_chunked(
                ssts, predicate, None, False, batch_size=self._scan_block_rows
            )
            for b in batches:
                sp, hit = dense_sid(arrow_column_to_numpy(b.column(series_column)))
                accumulate_sorted(
                    arrow_column_to_numpy(b.column(ts_column)),
                    sp,
                    arrow_column_to_numpy(b.column(value_column)),
                    valid_np=hit if not hit.all() else None,
                )
            return grids

        read_names = self._resolve_read_names(None, False)
        with scanstats.stage("io_decode"):
            tables = await asyncio.gather(
                *(self.read_sst(s, read_names, predicate,
                   use_block_cache=use_block_cache) for s in ssts)
            )
        tables = [t for t in tables if t.num_rows > 0]
        if not tables:
            return grids
        with scanstats.stage("host_prep"):
            tables = _order_tables_by_first_key(
                tables,
                tuple(self._schema.primary_key_names) + (SEQ_COLUMN_NAME,),
            )
            table = memtrace.tracked_combine(
                memtrace.tracked_concat_tables(tables, "host_prep"),
                "host_prep",
            )
            sid, sid_hit = dense_sid(
                arrow_column_to_numpy(
                    memtrace.tracked_combine(
                        table.column(series_column), "host_prep"
                    )
                )
            )

        fast = (
            self._packed_downsample_pass(table, predicate, sid, sid_hit,
                                         ts_column, value_column, num_series)
            if packed_ok else None
        )
        if fast is not None:
            ts_s, sid_s, val_s = fast
            if len(ts_s):
                accumulate_sorted(ts_s, sid_s, val_s)
            return grids

        # the hit mask rides the fused pass's permutation as an int lane so
        # set-membership misses stay excludable after the device sort; the
        # lane is skipped on the common all-hit query (no series subset)
        extra = {"__sid__": sid}
        all_hit = bool(sid_hit.all())
        if not all_hit:
            extra["__sidok__"] = sid_hit.astype(np.int32)
        sorted_cols, _perm, keep, _starts, _kept, _num, _bin = self._fused_pass(
            table, predicate, extra_arrays=extra
        )
        row_ok = keep if all_hit else keep & (sorted_cols["__sidok__"] != 0)
        if mesh is not None:
            # mesh path: the merged/deduped rows leave the fused pass and
            # shard over the mesh for the reduction; misses keep their
            # monotone position and are zeroed via the weight column
            accumulate_sorted(
                np.asarray(sorted_cols[ts_column]).astype(np.int64),
                np.asarray(sorted_cols["__sid__"]).astype(np.int32),
                np.asarray(sorted_cols[value_column]),
                valid_np=np.asarray(row_ok),
            )
            return grids
        # device-side reduction of the surviving rows (row_ok is a mask)
        out = agg_ops.downsample(
            sorted_cols[ts_column].astype(jnp.int64),
            sorted_cols["__sid__"],
            sorted_cols[value_column],
            row_ok,
            t0,
            bucket_ms,
            num_series=num_series,
            num_buckets=num_buckets,
        )
        for k in list(grids):
            grids[k] = np.asarray(out[k])
        return grids

    # packed-key sort budget: sid | ts-offset | seq-rank must fit below the
    # sink bit (63). Exceeding any budget falls back to the fused lexsort.
    _PACK_SID_BITS = 17
    _PACK_TS_BITS = 34   # ~198 days of ms offsets within one scan
    _PACK_SEQ_BITS = 12  # distinct write sequences per segment

    def _packed_downsample_pass(
        self, table, predicate, sid, sid_valid, ts_column, value_column, num_series
    ):
        """Single-key replacement for the fused kernel's 6-lane lexsort on
        the downsample pushdown path: (dense sid, ts, seq-rank) pack into
        one u64, the predicate evaluates on host, rejected rows sink above
        bit 63, and one stable integer argsort (radix on host) yields the
        merge permutation — ~10x cheaper than the multi-key device lexsort
        at this path's fixed shape. Dedup stays filter-first/last-value:
        among surviving rows of one (sid, ts) cell the max seq-rank (the
        sort's last) wins, matching the fused kernel's semantics.

        Returns (ts, sid, values) as pk-sorted, deduped, fully-valid host
        lanes for accumulate_sorted, or None when the shape exceeds the
        pack budgets (huge spans, >2^12 distinct seqs, append mode) — the
        caller then runs the general fused pass.

        CONTRACT (why scan_segment_downsample gates this on `packed_ok`):
        dedup here is by (sid, ts), NOT the full schema pk. The caller must
        guarantee every non-(series, ts) pk column is pinned — e.g. the
        metric engine pins metric_id via an eq predicate and field_id is
        constant — otherwise distinct-pk rows sharing (tsid, ts) would
        wrongly collapse."""
        from horaedb_tpu.storage.config import UpdateMode

        if self._schema.update_mode != UpdateMode.OVERWRITE:
            return None
        if num_series >= (1 << self._PACK_SID_BITS):
            return None
        ts_np = arrow_column_to_numpy(
            memtrace.tracked_combine(table.column(ts_column), "host_prep")
        )
        n = len(ts_np)
        if n == 0:
            return (np.empty(0, np.int64),) * 3
        seq_np = arrow_column_to_numpy(
            memtrace.tracked_combine(
                table.column(SEQ_COLUMN_NAME), "host_prep"
            )
        )
        uniq_seq = np.unique(seq_np)
        if len(uniq_seq) > (1 << self._PACK_SEQ_BITS):
            return None
        ts_min = int(ts_np.min())
        span = int(ts_np.max()) - ts_min
        if span >= (1 << self._PACK_TS_BITS):
            return None
        mask = memtrace.tracked_copy(sid_valid, "host_prep")
        if predicate is not None:
            mask = mask & filter_ops.eval_predicate_host(predicate, table)
        srank = (
            np.searchsorted(uniq_seq, seq_np).astype(np.uint64)
            if len(uniq_seq) > 1 else np.zeros(n, np.uint64)
        )
        shift_ts = np.uint64(self._PACK_SEQ_BITS)
        shift_sid = np.uint64(self._PACK_SEQ_BITS + self._PACK_TS_BITS)
        packed = (
            (sid.astype(np.int64).astype(np.uint64) << shift_sid)
            | ((ts_np - ts_min).astype(np.uint64) << shift_ts)
            | srank
        )
        sink = np.uint64(1 << 63)
        packed = np.where(mask, packed, sink)
        perm = np.argsort(packed, kind="stable")
        packed_s = packed[perm]
        # keep-last within each (sid, ts) group among surviving rows
        group = packed_s >> shift_ts
        keep = np.empty(n, dtype=bool)
        if n > 1:
            keep[:-1] = group[:-1] != group[1:]
        keep[-1] = True
        keep &= packed_s < sink
        idx = perm[keep]
        val_np = arrow_column_to_numpy(
            memtrace.tracked_combine(table.column(value_column), "host_prep")
        )
        return (
            ts_np[idx],
            sid[idx].astype(np.int32),
            val_np[idx],
        )

    @staticmethod
    def _sharded_accumulate(
        mesh, ts_np, sid_np, val_np, t0, bucket_ms,
        num_series: int, num_buckets: int, with_minmax: bool,
        valid_np=None,
    ) -> dict:
        """One sorted run reduced over the ambient mesh — delegates to
        the first-class mesh layer (parallel/mesh.py::mesh_downsample),
        which owns the series padding, per-lane row pads, and the
        accelerator dtype rule the sharded lane grew up with here."""
        from horaedb_tpu.parallel.mesh import mesh_downsample

        return mesh_downsample(
            mesh, ts_np, sid_np, val_np, t0, bucket_ms,
            num_series, num_buckets, with_minmax=with_minmax,
            valid_np=valid_np, sorted_input=True,
        )

    # -- shared prologue/epilogue ---------------------------------------------
    def _resolve_read_names(self, projections: list[int] | None, keep_builtin: bool) -> list[str]:
        """Columns to fetch: projection + forced pk/__seq__ (types.rs:203-216),
        plus __reserved__ when builtins are kept."""
        proj = self._schema.fill_required_projections(projections)
        all_names = self._schema.arrow_schema.names
        read_names = list(all_names) if proj is None else [all_names[i] for i in sorted(proj)]
        if keep_builtin and RESERVED_COLUMN_NAME not in read_names:
            read_names.append(RESERVED_COLUMN_NAME)
        return read_names

    @staticmethod
    def _output_names(read_names: list[str], keep_builtin: bool) -> list[str]:
        """Output = everything fetched minus builtins unless keep_builtin —
        matching the reference plan's output schema after MergeExec."""
        return [n for n in read_names if keep_builtin or not StorageSchema.is_builtin_name(n)]

    @staticmethod
    def _slice_batches(result: pa.RecordBatch, batch_size: int) -> list[pa.RecordBatch]:
        if result.num_rows == 0:
            return []
        return [result.slice(i, batch_size) for i in range(0, result.num_rows, batch_size)]

    # -- host materialization ------------------------------------------------
    def _materialize_append_mode(
        self,
        table: pa.Table,
        sorted_cols: dict[str, jax.Array],
        perm: np.ndarray,
        starts: np.ndarray,
        kept: int,
        numeric_names: list[str],
        binary_names: list[str],
        out_names: list[str],
    ) -> pa.RecordBatch:
        """Append mode with binary values: groups collapse by concatenating
        value bytes (BytesMergeOperator) on host; group extents come from the
        device run-boundary mask."""
        value_names = {
            self._schema.arrow_schema.names[i] for i in self._schema.value_idxes
        }
        start_idx = np.nonzero(starts[:kept])[0]
        ends = np.append(start_idx[1:], kept)
        cols = []
        for name in out_names:
            f = table.schema.field(name)
            if name in binary_names:
                src = memtrace.track(
                    memtrace.tracked_combine(
                        table.column(name), "materialize"
                    ).take(pa.array(perm[:kept])),
                    "materialize", "copy",
                )
                if name in value_names:
                    vals = src.to_pylist()
                    joined = [
                        b"".join(v for v in vals[s:e] if v is not None)
                        for s, e in zip(start_idx, ends)
                    ]
                    cols.append(pa.array(joined, type=f.type))
                else:
                    cols.append(src.take(pa.array(start_idx)))
            else:
                np_col = np.asarray(sorted_cols[name])[:kept]
                # non-value numeric columns take the group's first row; numeric
                # value columns in append mode also take first (reference only
                # concatenates binary value columns, operator.rs:59-111)
                cols.append(_np_to_arrow(np_col[start_idx], f.type))
        return pa.RecordBatch.from_arrays(
            cols, schema=pa.schema([table.schema.field(n) for n in out_names])
        )


class _NeedBytes(Exception):
    pass


def _select_row_groups(meta, arrow_schema, predicate) -> list[int]:
    """Row groups whose min/max statistics can satisfy the predicate."""
    keep_groups = []
    unsigned = {
        name
        for name in arrow_schema.names
        if pa.types.is_unsigned_integer(arrow_schema.field(name).type)
    }
    for rg in range(meta.num_row_groups):
        stats: dict[str, tuple] = {}
        g = meta.row_group(rg)
        for ci in range(g.num_columns):
            col = g.column(ci)
            st = col.statistics
            if st is not None and st.has_min_max:
                name = col.path_in_schema
                lo = _stat_value(st.min, name in unsigned)
                hi = _stat_value(st.max, name in unsigned)
                if lo > hi:  # u64 range straddling 2**63 wrapped; stats unusable
                    continue
                stats[name] = (lo, hi)
        if filter_ops.prune_range(predicate, stats):
            keep_groups.append(rg)
    return keep_groups


def _read_pruned(
    pf: pq.ParquetFile,
    columns: list[str] | None,
    predicate: Predicate | None,
    rg_cache=None,   # optional (get(rg), put(rg, table)) hooks
    meta_sink=None,  # optional callback stashing (metadata, schema_arrow)
) -> pa.Table:
    keep_groups = _select_row_groups(pf.metadata, pf.schema_arrow, predicate)
    if meta_sink is not None:
        meta_sink(pf.metadata, pf.schema_arrow)
    if not keep_groups:
        return pf.schema_arrow.empty_table()
    if rg_cache is not None:
        # per-row-group block cache: pruning still applies (keys are
        # individual row groups), repeat reads of the hot groups skip decode
        get, put = rg_cache
        parts = []
        for rg in keep_groups:
            t = get(rg)
            if t is None:
                t = pf.read_row_group(rg, columns=columns, use_threads=True)
                memtrace.track(t, "materialize", "alloc")
                put(rg, t)
            parts.append(t)
        return memtrace.tracked_concat_tables(parts, "materialize")
    return pf.read_row_groups(keep_groups, columns=columns, use_threads=True)


def _stat_value(v, is_unsigned: bool = False):
    """Normalize parquet statistics to the numeric domain predicates use:
    - timestamp columns report datetime.datetime; literals are epoch ms;
    - uint64 columns are stored as signed int64 physically, so ids >= 2**63
      (seahash ids routinely are) come back negative and must re-wrap."""
    import calendar
    import datetime

    if isinstance(v, datetime.datetime):
        # exact integer epoch ms — float .timestamp()*1000 truncates ~1% of
        # millisecond values down by 1, which would mis-prune row groups
        return calendar.timegm(v.utctimetuple()) * 1000 + v.microsecond // 1000
    if is_unsigned and isinstance(v, int) and v < 0:
        return v + (1 << 64)
    return v


def _np_to_arrow(arr: np.ndarray, t: pa.DataType) -> pa.Array:
    if t == pa.timestamp("ms"):
        return pa.array(arr.astype("datetime64[ms]"))
    return pa.array(arr, type=t)
