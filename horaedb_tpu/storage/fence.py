"""Cross-process single-writer-per-region enforcement: epoch fencing.

The reference gets single-writer-per-region *by construction* — one process
owns one `ObjectBasedStorage` and the RFC's meta plane routes each region to
exactly one node (/root/reference/docs/rfcs/20240827-metric-engine.md:28-76;
/root/reference/src/columnar_storage/src/types.rs:135 notes the object store
is the only shared medium). A shared S3 data plane gives no such guarantee:
nothing stops two processes from both mounting one region root and racing
its manifest. This module turns the assumption into an enforced contract.

Design — monotonic epoch claims via conditional put (the fencing-token
pattern, adapted to object stores):

- Ownership of `{root}` is an epoch number. To acquire, list
  `{root}/fence/`, take max+1, and `put_if_absent` the zero-padded epoch
  key. The conditional put is the arbiter: exactly one contender can create
  a given epoch (S3 `If-None-Match: *`; local FS atomic link; memory dict).
- Highest epoch wins, forever. A writer holding epoch E validates before
  every manifest mutation that no epoch > E exists; if one does it raises
  FencedError and the engine refuses the write — the deposed writer can
  never again move the manifest.
- Validation is one LIST, cached for `validate_interval` seconds (0 =
  validate every time, used by tests for deterministic interleavings). The
  residual window is the in-flight mutation a deposed writer issued between
  its last validation and the usurper's claim — the same window any
  lease/fencing design has without server-side CAS on every object; closing
  it entirely would need conditional puts on each delta/snapshot write.
  With default settings that window is seconds; correctness of committed
  history is unaffected because delta files are append-only and
  id-monotonic (a stale delta adds a stale SST record; it never corrupts
  the snapshot codec or clobbers another file).

Epoch claims are never deleted: the dir stays tiny (one object per
failover) and doubles as an ownership audit log.
"""

from __future__ import annotations

import json
import logging
import time

from horaedb_tpu.common.error import FatalError, HoraeError
from horaedb_tpu.objstore import ObjectStore, PreconditionFailed

logger = logging.getLogger(__name__)

FENCE_DIR = "fence"


class FencedError(FatalError):
    """This writer's epoch has been superseded — it no longer owns the
    region and must stop mutating its manifest. A FatalError in the
    taxonomy (common/error.py): the resilience layer must never retry
    past it, and the flush pipeline surfaces it instead of parking."""


def _fence_dir(root: str) -> str:
    return f"{root}/{FENCE_DIR}"


def _epoch_path(root: str, epoch: int) -> str:
    return f"{_fence_dir(root)}/{epoch:020d}"


def _epoch_of(path: str) -> int:
    try:
        return int(path.rsplit("/", 1)[-1])
    except ValueError:
        return -1


def _top_epoch(metas) -> int:
    """Highest epoch among fence-dir entries; non-epoch objects (the
    conditional-put capability-probe sentinel) are ignored, not -1 —
    they must never shift epoch numbering."""
    return max((e for m in metas if (e := _epoch_of(m.path)) >= 0), default=0)


class EpochFence:
    """A claimed writer epoch on one region root (see module docstring)."""

    def __init__(
        self,
        store: ObjectStore,
        root: str,
        epoch: int,
        node_id: str,
        validate_interval_s: float = 5.0,
    ):
        self._store = store
        self._root = root
        self.epoch = epoch
        self.node_id = node_id
        self._validate_interval = validate_interval_s
        self._last_validated = time.monotonic()

    @classmethod
    async def acquire(
        cls,
        store: ObjectStore,
        root: str,
        node_id: str,
        validate_interval_s: float = 5.0,
        max_attempts: int = 16,
    ) -> "EpochFence":
        """Claim the next epoch on `root`. Loses of the conditional-put race
        retry with the next number; every successful return is the unique
        owner of a strictly higher epoch than all prior owners."""
        # Part of the ObjectStore contract (base-class no-op for stores
        # that enforce natively; S3-likes really probe the endpoint):
        # run it before trusting put_if_absent with region ownership.
        await store.verify_conditional_puts(_fence_dir(root))
        payload = json.dumps(
            {"node": node_id, "acquired_unix_ms": int(time.time() * 1000)}
        ).encode()
        for _ in range(max_attempts):
            metas = await store.list(_fence_dir(root))
            top = _top_epoch(metas)
            epoch = top + 1
            try:
                await store.put_if_absent(_epoch_path(root, epoch), payload)
            except PreconditionFailed:
                continue  # another contender took this epoch; re-list
            logger.info(
                "fence acquired: root=%s epoch=%d node=%s", root, epoch, node_id
            )
            return cls(store, root, epoch, node_id, validate_interval_s)
        raise HoraeError(
            f"could not acquire fence on {root} after {max_attempts} attempts "
            "(heavy ownership contention)"
        )

    async def ensure_valid(self, force: bool = False) -> None:
        """Raise FencedError if a higher epoch exists. Cached for
        `validate_interval` seconds unless `force`."""
        if (
            not force
            and self._validate_interval > 0
            and time.monotonic() - self._last_validated < self._validate_interval
        ):
            return
        metas = await self._store.list(_fence_dir(self._root))
        top = _top_epoch(metas)
        if top > self.epoch:
            raise FencedError(
                f"writer epoch {self.epoch} on {self._root} superseded by "
                f"{top}: this process no longer owns the region"
            )
        self._last_validated = time.monotonic()

    async def current_owner(self) -> dict:
        """The newest claim's payload (diagnostics / admin surface)."""
        metas = [
            m for m in await self._store.list(_fence_dir(self._root))
            if _epoch_of(m.path) >= 0  # skip the capability-probe sentinel
        ]
        if not metas:
            return {}
        newest = max(metas, key=lambda m: _epoch_of(m.path))
        info = json.loads(await self._store.get(newest.path))
        info["epoch"] = _epoch_of(newest.path)
        return info
