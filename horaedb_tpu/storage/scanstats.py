"""Per-stage scan timing attribution.

The reference accepts DataFusion's ExecutionPlanMetricsSet but never reads it
(read.rs:84); here stage timing is first-class because the engine's perf
story spans three very different lanes — object-store IO + parquet decode
(host), host<->device transfer (PCIe or, in dev environments, a network
tunnel), and the XLA kernel itself — and optimizing the wrong lane is the
classic failure mode (VERDICT r02: configs 1-2 were assumed kernel-bound,
measured 95% transfer-bound).

Usage:
    with scan_stats() as st:
        ... run scans ...
    st.as_dict()  # {"io_decode_s": ..., "host_prep_s": ..., ...}

The collector is a contextvar, so concurrent asyncio tasks spawned inside the
block attribute into the same collector without threading it through every
call. Overhead when no collector is active: one contextvar get per stage.
Stage sums can exceed wall clock (stages from concurrent SST reads overlap).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass, field


@dataclass
class ScanStats:
    seconds: dict[str, float] = field(default_factory=dict)
    counts: dict[str, int] = field(default_factory=dict)

    def add(self, stage: str, secs: float) -> None:
        self.seconds[stage] = self.seconds.get(stage, 0.0) + secs
        self.counts[stage] = self.counts.get(stage, 0) + 1

    def count(self, stage: str, n: int = 1) -> None:
        self.counts[stage] = self.counts.get(stage, 0) + n

    def as_dict(self) -> dict:
        out = {f"{k}_s": round(v, 4) for k, v in self.seconds.items()}
        out.update({k: v for k, v in self.counts.items() if k not in self.seconds})
        return out


_ACTIVE: ContextVar[ScanStats | None] = ContextVar("horaedb_scan_stats", default=None)


@contextmanager
def scan_stats():
    """Collect stage timings for every scan inside the block."""
    st = ScanStats()
    token = _ACTIVE.set(st)
    try:
        yield st
    finally:
        _ACTIVE.reset(token)


@contextmanager
def stage(name: str):
    """Time one stage into the active collector (no-op when none)."""
    st = _ACTIVE.get()
    if st is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        st.add(name, time.perf_counter() - t0)


def active() -> bool:
    """True when a collector is attached. Device paths use this to decide
    whether to fence async transfers for attribution: with no collector,
    skipping the fence lets H2D overlap kernel dispatch in the device
    queue (the un-fenced form is the production fast path)."""
    return _ACTIVE.get() is not None


def note(name: str, n: int = 1) -> None:
    """Bump a counter (e.g. rows decoded, path taken) on the active collector."""
    st = _ACTIVE.get()
    if st is not None:
        st.count(name, n)
