"""Per-stage scan timing attribution.

The reference accepts DataFusion's ExecutionPlanMetricsSet but never reads it
(read.rs:84); here stage timing is first-class because the engine's perf
story spans three very different lanes — object-store IO + parquet decode
(host), host<->device transfer (PCIe or, in dev environments, a network
tunnel), and the XLA kernel itself — and optimizing the wrong lane is the
classic failure mode (VERDICT r02: configs 1-2 were assumed kernel-bound,
measured 95% transfer-bound).

Usage:
    with scan_stats() as st:
        ... run scans ...
    st.as_dict()  # {"io_decode_s": ..., "host_prep_s": ..., ...}

The collector is a contextvar, so concurrent asyncio tasks spawned inside the
block attribute into the same collector without threading it through every
call. Every stage ALSO feeds the process-wide
`horaedb_scan_stage_seconds{stage=...}` histogram (server/metrics.py) and the
active trace span (common/tracing.py), so lane attribution is continuous on
/metrics — not just inside ad-hoc scan_stats() blocks. Overhead per stage:
two perf_counter calls + one histogram observe, against stage bodies that
decode whole segments or dispatch device kernels.
Stage sums can exceed wall clock (stages from concurrent SST reads overlap).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass, field

from horaedb_tpu.common import tracing
from horaedb_tpu.server.metrics import GLOBAL_METRICS

# Canonical lane names for the /metrics histogram: the raw stage names are
# scan-internal (h2d/d2h/device_merge), but operators reason in the three
# lanes VERDICT r02 established — IO+decode, host<->device transfer, XLA
# kernel — plus the compile lane xprof feeds (a retrace storm looks like a
# kernel stall unless it has its own label). Stages outside the map keep
# their own label (host_merge, host_filter, materialize, encode, ...).
_STAGE_LANE = {
    "h2d": "transfer",
    "d2h": "transfer",
    "device_merge": "kernel",
    "device_agg": "kernel",
}

STAGE_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_scan_stage_seconds",
    help="Per-stage scan time by lane (io_decode, host_prep, transfer, "
         "kernel, compile, ...): the request-attribution view of scanstats.",
    labelnames=("stage",),
    # OpenMetrics exemplars: each bucket remembers the trace id of its
    # latest observation, so a stage-latency spike on a dashboard links
    # straight to a /debug/traces/{id} span tree
    exemplars=True,
)
# Pre-register the canonical lanes so /metrics always exposes the full
# attribution surface (zero-count histograms), even before the first scan
# routes through a given lane on this process. `decode` is the
# encoded-lane expansion stage (storage/encoding.py + ops/decode.py) —
# first-class because the compressed-domain scan's whole bet is moving
# wall time from io_decode/transfer into this (much smaller) lane.
for _lane in ("io_decode", "host_prep", "transfer", "kernel", "compile",
              "decode"):
    STAGE_SECONDS.labels(_lane)
del _lane

# Roofline-attribution lane of each stage (attribution(), query EXPLAIN):
# anything not listed is host-side work.
_BOUND_LANE = {
    "io_decode": "io",
    "h2d": "transfer",
    "d2h": "transfer",
    "transfer": "transfer",
    "device_merge": "kernel",
    "device_agg": "kernel",
    "kernel": "kernel",
    "compile": "compile",
    "decode": "decode",
}


@dataclass
class ScanStats:
    seconds: dict[str, float] = field(default_factory=dict)
    counts: dict[str, int] = field(default_factory=dict)
    # instrumented-kernel invocations (common/xprof.py feeds this): which
    # device kernels this query actually ran, and how often
    kernels: dict[str, int] = field(default_factory=dict)
    # buffer-lineage ledger (common/memtrace.py): scan_stats() opens one
    # alongside the timing collector, so every query route carries the
    # pinned `memory` EXPLAIN verdict without per-handler wiring. None
    # under HORAEDB_MEMTRACE=off.
    mem: object = None

    def add(self, stage: str, secs: float) -> None:
        self.seconds[stage] = self.seconds.get(stage, 0.0) + secs
        self.counts[stage] = self.counts.get(stage, 0) + 1

    def count(self, stage: str, n: int = 1) -> None:
        self.counts[stage] = self.counts.get(stage, 0) + n

    def as_dict(self) -> dict:
        out = {f"{k}_s": round(v, 4) for k, v in self.seconds.items()}
        out.update({k: v for k, v in self.counts.items() if k not in self.seconds})
        return out

    def attribution(self) -> dict:
        """Fold the raw stage seconds into the roofline lanes and name the
        binding one: `bound` in io | transfer | kernel | compile | host
        (None when nothing was timed). This is the live half of the
        roofline story — xprof's kernel catalog supplies the predicted
        FLOPs/bytes envelope, this supplies the measured split."""
        lanes = {"io": 0.0, "host": 0.0, "transfer": 0.0, "kernel": 0.0,
                 "compile": 0.0, "decode": 0.0}
        for stage_name, secs in self.seconds.items():
            lanes[_BOUND_LANE.get(stage_name, "host")] += secs
        bound = max(lanes, key=lanes.get) if any(lanes.values()) else None
        return {
            "lanes_s": {k: round(v, 6) for k, v in lanes.items()},
            "bound": bound,
        }


_ACTIVE: ContextVar[ScanStats | None] = ContextVar("horaedb_scan_stats", default=None)

# Compile-time deduction cell of the innermost open stage() block (None
# outside any stage). Compiles fire INSIDE stage bodies — xprof's wrapper
# detects them mid-`device_agg`/`device_merge` — so without this the
# compile wall time would land in BOTH the enclosing stage's lane and the
# compile lane, the kernel lane would always dominate, and `bound` could
# never actually say "compile". record("compile", ...) credits the cell;
# stage() subtracts it from its own elapsed time on close and propagates
# it to the enclosing stage's cell (nested stages must deduct too).
_COMPILE_DEDUCT: ContextVar["_DeductCell | None"] = ContextVar(
    "horaedb_scan_compile_deduct", default=None
)


class _DeductCell:
    """Deduction accumulator for one open stage. Credits arrive from
    WORKER THREADS too — asyncio.to_thread copies the context, so the
    concurrent per-SST decodes under one io_decode stage all share the
    enclosing stage's cell — hence the lock (a bare `+=` is a lost-update
    race) and the cap: cumulative credit never exceeds the stage's
    elapsed wall, so overlapping thread-seconds deduct at most the time
    that could physically have overlapped and the stage's own lane never
    silently absorbs a negative."""

    __slots__ = ("_t0", "_total", "_lock")

    def __init__(self) -> None:
        self._t0 = time.perf_counter()
        self._total = 0.0
        self._lock = threading.Lock()

    def add(self, secs: float) -> None:
        with self._lock:
            self._total = min(
                self._total + secs, time.perf_counter() - self._t0
            )

    def total(self) -> float:
        with self._lock:
            return self._total


@contextmanager
def scan_stats():
    """Collect stage timings — and buffer lineage — for every scan
    inside the block: the memtrace ledger opens with the collector, so
    the per-query memory verdict needs no per-route plumbing."""
    from horaedb_tpu.common import memtrace

    st = ScanStats()
    token = _ACTIVE.set(st)
    try:
        with memtrace.mem_trace() as ledger:
            st.mem = ledger
            yield st
    finally:
        _ACTIVE.reset(token)


@contextmanager
def stage(name: str):
    """Time one stage into (a) the active per-query collector when one is
    attached, (b) the process-wide `horaedb_scan_stage_seconds{stage=...}`
    histogram — ALWAYS, so lane attribution shows on /metrics without any
    collector — and (c) the active trace span's `stages` attr. Stages wrap
    chunky work (a segment's decode, one device merge), so the two
    perf_counter calls + one histogram observe are noise next to the work
    itself."""
    st = _ACTIVE.get()
    cell = _DeductCell()
    token = _COMPILE_DEDUCT.set(cell)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = max(0.0, time.perf_counter() - t0 - cell.total())
        _COMPILE_DEDUCT.reset(token)
        outer = _COMPILE_DEDUCT.get()
        if outer is not None:
            outer.add(cell.total())
        if st is not None:
            st.add(name, dt)
        STAGE_SECONDS.labels(_STAGE_LANE.get(name, name)).observe(dt)
        tracing.add_stage(name, dt)


@contextmanager
def deducted_stage(name: str):
    """stage() for expansion work that runs INSIDE another stage's block
    (the encoded read path's `decode` lane runs inside the callers'
    `io_decode` stages): times the body, subtracts any nested deduction
    credits (a first-use kernel compile fires mid-decode and records the
    compile lane via xprof) so the compile seconds are not counted in
    BOTH the compile and this lane, then records the net with
    record(..., deduct=True) so the enclosing stage deducts the whole
    wall — every second lands in exactly one lane."""
    cell = _DeductCell()
    token = _COMPILE_DEDUCT.set(cell)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = max(0.0, time.perf_counter() - t0 - cell.total())
        _COMPILE_DEDUCT.reset(token)
        outer = _COMPILE_DEDUCT.get()
        if outer is not None:
            # nested credits (compile) must also deduct from the
            # enclosing stage; record() below adds `dt` itself
            outer.add(cell.total())
        record(name, dt, deduct=True)


def record(name: str, secs: float, *, deduct: "bool | None" = None) -> None:
    """Fold an externally-timed duration in as if a stage() block measured
    it: collector + process histogram + active trace span. xprof reports
    compile time through this (the compile happens inside jax's dispatch,
    where no `with stage(...):` block can wrap it); a compile recorded
    inside an open stage is deducted from that stage so the time is
    attributed ONCE — to the compile lane. `deduct=True` extends the
    same once-only attribution to any lane recorded inside an enclosing
    stage (the encoded read path records its `decode` expansion and
    sidecar-fetch time this way from inside the callers' `io_decode`
    blocks — without the deduction, io would double-count every decode
    second and `bound` could never say "decode")."""
    if deduct is None:
        deduct = name == "compile"
    if deduct:
        cell = _COMPILE_DEDUCT.get()
        if cell is not None:
            cell.add(secs)
    st = _ACTIVE.get()
    if st is not None:
        st.add(name, secs)
    STAGE_SECONDS.labels(_STAGE_LANE.get(name, name)).observe(secs)
    tracing.add_stage(name, secs)


def kernel_use(name: str) -> None:
    """Note one invocation of an instrumented kernel on the active
    collector (no-op without one — one contextvar get, the same
    steady-state budget as span())."""
    st = _ACTIVE.get()
    if st is not None:
        st.kernels[name] = st.kernels.get(name, 0) + 1


def active() -> bool:
    """True when a collector is attached. Device paths use this to decide
    whether to fence async transfers for attribution: with no collector,
    skipping the fence lets H2D overlap kernel dispatch in the device
    queue (the un-fenced form is the production fast path)."""
    return _ACTIVE.get() is not None


def note(name: str, n: int = 1) -> None:
    """Bump a counter (e.g. rows decoded, path taken) on the active collector."""
    st = _ACTIVE.get()
    if st is not None:
        st.count(name, n)


def current() -> "ScanStats | None":
    """The active collector object (or None). The query batcher keys its
    concurrency signal on collector IDENTITY: a regioned query's N
    fan-out sub-queries share one collector, so they count as ONE client
    and a lone regioned query keeps the no-window fast path."""
    return _ACTIVE.get()


def get_note(name: str) -> "int | None":
    """Read a counter off the active collector (None without one or when
    the note was never set). The admission slot uses this to learn how
    wide a stacked launch its query rode (batched_with) without threading
    the batcher through the slot protocol."""
    st = _ACTIVE.get()
    return None if st is None else st.counts.get(name)


def note_max(name: str, n: int) -> None:
    """Record the MAXIMUM of `n` across the collector's lifetime instead
    of a running sum — for width-style facts (e.g. regions fanned out)
    that repeat per sub-query and would over-report if accumulated."""
    st = _ACTIVE.get()
    if st is not None:
        st.counts[name] = max(st.counts.get(name, 0), n)
