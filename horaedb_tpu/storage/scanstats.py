"""Per-stage scan timing attribution.

The reference accepts DataFusion's ExecutionPlanMetricsSet but never reads it
(read.rs:84); here stage timing is first-class because the engine's perf
story spans three very different lanes — object-store IO + parquet decode
(host), host<->device transfer (PCIe or, in dev environments, a network
tunnel), and the XLA kernel itself — and optimizing the wrong lane is the
classic failure mode (VERDICT r02: configs 1-2 were assumed kernel-bound,
measured 95% transfer-bound).

Usage:
    with scan_stats() as st:
        ... run scans ...
    st.as_dict()  # {"io_decode_s": ..., "host_prep_s": ..., ...}

The collector is a contextvar, so concurrent asyncio tasks spawned inside the
block attribute into the same collector without threading it through every
call. Every stage ALSO feeds the process-wide
`horaedb_scan_stage_seconds{stage=...}` histogram (server/metrics.py) and the
active trace span (common/tracing.py), so lane attribution is continuous on
/metrics — not just inside ad-hoc scan_stats() blocks. Overhead per stage:
two perf_counter calls + one histogram observe, against stage bodies that
decode whole segments or dispatch device kernels.
Stage sums can exceed wall clock (stages from concurrent SST reads overlap).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass, field

from horaedb_tpu.common import tracing
from horaedb_tpu.server.metrics import GLOBAL_METRICS

# Canonical lane names for the /metrics histogram: the raw stage names are
# scan-internal (h2d/d2h/device_merge), but operators reason in the three
# lanes VERDICT r02 established — IO+decode, host<->device transfer, XLA
# kernel. Stages outside the map keep their own label (host_merge,
# host_filter, materialize, encode, ...).
_STAGE_LANE = {
    "h2d": "transfer",
    "d2h": "transfer",
    "device_merge": "kernel",
    "device_agg": "kernel",
}

STAGE_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_scan_stage_seconds",
    help="Per-stage scan time by lane (io_decode, host_prep, transfer, "
         "kernel, ...): the request-attribution view of scanstats.",
    labelnames=("stage",),
)
# Pre-register the canonical lanes so /metrics always exposes the full
# attribution surface (zero-count histograms), even before the first scan
# routes through a given lane on this process.
for _lane in ("io_decode", "host_prep", "transfer", "kernel"):
    STAGE_SECONDS.labels(_lane)
del _lane


@dataclass
class ScanStats:
    seconds: dict[str, float] = field(default_factory=dict)
    counts: dict[str, int] = field(default_factory=dict)

    def add(self, stage: str, secs: float) -> None:
        self.seconds[stage] = self.seconds.get(stage, 0.0) + secs
        self.counts[stage] = self.counts.get(stage, 0) + 1

    def count(self, stage: str, n: int = 1) -> None:
        self.counts[stage] = self.counts.get(stage, 0) + n

    def as_dict(self) -> dict:
        out = {f"{k}_s": round(v, 4) for k, v in self.seconds.items()}
        out.update({k: v for k, v in self.counts.items() if k not in self.seconds})
        return out


_ACTIVE: ContextVar[ScanStats | None] = ContextVar("horaedb_scan_stats", default=None)


@contextmanager
def scan_stats():
    """Collect stage timings for every scan inside the block."""
    st = ScanStats()
    token = _ACTIVE.set(st)
    try:
        yield st
    finally:
        _ACTIVE.reset(token)


@contextmanager
def stage(name: str):
    """Time one stage into (a) the active per-query collector when one is
    attached, (b) the process-wide `horaedb_scan_stage_seconds{stage=...}`
    histogram — ALWAYS, so lane attribution shows on /metrics without any
    collector — and (c) the active trace span's `stages` attr. Stages wrap
    chunky work (a segment's decode, one device merge), so the two
    perf_counter calls + one histogram observe are noise next to the work
    itself."""
    st = _ACTIVE.get()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if st is not None:
            st.add(name, dt)
        STAGE_SECONDS.labels(_STAGE_LANE.get(name, name)).observe(dt)
        tracing.add_stage(name, dt)


def active() -> bool:
    """True when a collector is attached. Device paths use this to decide
    whether to fence async transfers for attribution: with no collector,
    skipping the fence lets H2D overlap kernel dispatch in the device
    queue (the un-fenced form is the production fast path)."""
    return _ACTIVE.get() is not None


def note(name: str, n: int = 1) -> None:
    """Bump a counter (e.g. rows decoded, path taken) on the active collector."""
    st = _ACTIVE.get()
    if st is not None:
        st.count(name, n)
