"""Columnar storage engine (reference: src/columnar_storage).

Public surface mirrors the reference trait boundary
(`trait ColumnarStorage { schema; write; scan; compact }`, storage.rs:58-89):

    from horaedb_tpu.storage import (
        ColumnarStorage, ObjectBasedStorage,
        WriteRequest, ScanRequest, CompactRequest,
        StorageConfig, UpdateMode, StorageSchema, TimeRange,
    )
"""

from horaedb_tpu.storage.config import (
    ManifestConfig,
    SchedulerConfig,
    StorageConfig,
    UpdateMode,
    WriteConfig,
)
from horaedb_tpu.storage.sst import FileMeta, SstFile, SstPathGenerator, allocate_id
from horaedb_tpu.storage.storage import (
    ColumnarStorage,
    CompactRequest,
    ObjectBasedStorage,
    ScanRequest,
    WriteRequest,
)
from horaedb_tpu.storage.types import StorageSchema, TimeRange, Timestamp, WriteResult

__all__ = [
    "ColumnarStorage",
    "ObjectBasedStorage",
    "WriteRequest",
    "ScanRequest",
    "CompactRequest",
    "StorageConfig",
    "WriteConfig",
    "ManifestConfig",
    "SchedulerConfig",
    "UpdateMode",
    "StorageSchema",
    "TimeRange",
    "Timestamp",
    "WriteResult",
    "SstFile",
    "FileMeta",
    "SstPathGenerator",
    "allocate_id",
]
