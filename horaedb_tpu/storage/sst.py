"""SST file handles: id allocation, metadata, TTL, compaction marking, paths.

Reference: src/columnar_storage/src/sst.rs. Invariants preserved:
- file ids come from a process-wide monotonic counter seeded with the
  nanosecond wall clock, so ids never go backwards across restarts
  (sst.rs:36-46) — the id doubles as the write sequence used for dedup;
- `in_compaction` is a flag ensuring an SST is picked at most once
  (mark/unmark, sst.rs:97-107);
- `is_expired` compares the range end against a TTL horizon (sst.rs:109-114);
- data path layout is `{prefix}/data/{id}.sst` (sst.rs:202-204).
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field

from horaedb_tpu.common.error import HoraeError
from horaedb_tpu.pb import sst_pb2
from horaedb_tpu.storage.types import TimeRange

PREFIX_PATH = "data"

_U64_MASK = (1 << 64) - 1


class _IdAllocator:
    """Monotonic id allocator seeded from the ns clock (sst.rs:36-46).

    Don't move the server clock backwards between restarts — same caveat as
    the reference.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counter = itertools.count(time.time_ns() & _U64_MASK)

    def allocate(self) -> int:
        with self._lock:
            return next(self._counter) & _U64_MASK

    def ensure_above(self, floor: int) -> None:
        """Advance past `floor` if the clock seed fell at or below it — the
        startup guard against a clock moved backwards (or another process's
        ids already in the manifest): allocating an id <= an existing SST id
        would silently overwrite data, since the id doubles as the dedup
        sequence."""
        with self._lock:
            current = next(self._counter)
            self._counter = itertools.count(max(current, floor + 1) & _U64_MASK)


_ALLOCATOR = _IdAllocator()


def allocate_id() -> int:
    return _ALLOCATOR.allocate()


def ensure_id_above(floor: int) -> None:
    _ALLOCATOR.ensure_above(floor)


@dataclass(frozen=True)
class FileMeta:
    """SST metadata carried in the manifest (sst.rs FileMeta).

    TPU-build extension: `format_version` 2 marks an SST with an
    encoded-lane sidecar (`{id}.enc`, storage/encoding.py) and
    `encodings` names each lane's codec — the descriptor readers gate on
    (v1 SSTs take the full parquet decode path) and EXPLAIN surfaces.
    Mixed-version trees scan exactly; compaction rewrites v1 inputs into
    v2 outputs when encoding is enabled, upgrading the tree naturally."""

    max_sequence: int
    num_rows: int
    size: int
    time_range: TimeRange
    format_version: int = 1
    encodings: tuple[tuple[str, str], ...] = ()


@dataclass
class SstFile:
    """Handle to one immutable sorted parquet SST."""

    id: int
    meta: FileMeta
    _in_compaction: bool = field(default=False, compare=False)

    # -- compaction marking (sst.rs:97-107) --------------------------------
    def mark_compaction(self) -> None:
        self._in_compaction = True

    def unmark_compaction(self) -> None:
        self._in_compaction = False

    def is_compaction(self) -> bool:
        return self._in_compaction

    # -- TTL (sst.rs:109-114) ----------------------------------------------
    def is_expired(self, expire_before_ms: int | None) -> bool:
        if expire_before_ms is None:
            return False
        return self.meta.time_range.end < expire_before_ms

    # -- protobuf bridge (sst.rs:125-190) ----------------------------------
    def to_pb(self) -> sst_pb2.SstFile:
        pb = sst_pb2.SstFile()
        pb.id = self.id
        pb.meta.max_sequence = self.meta.max_sequence
        pb.meta.num_rows = self.meta.num_rows
        pb.meta.size = self.meta.size
        pb.meta.time_range.start = self.meta.time_range.start
        pb.meta.time_range.end = self.meta.time_range.end
        if self.meta.format_version > 1:
            pb.meta.format_version = self.meta.format_version
            for column, codec in self.meta.encodings:
                e = pb.meta.encodings.add()
                e.column = column
                e.codec = codec
        return pb

    @classmethod
    def from_pb(cls, pb: sst_pb2.SstFile) -> "SstFile":
        if not pb.HasField("meta"):
            raise HoraeError(f"sst pb missing meta: id={pb.id}")
        return cls(
            id=pb.id,
            meta=FileMeta(
                max_sequence=pb.meta.max_sequence,
                num_rows=pb.meta.num_rows,
                size=pb.meta.size,
                time_range=TimeRange(pb.meta.time_range.start, pb.meta.time_range.end),
                # proto3 absent scalar decodes 0: a delta written before
                # the format existed is a v1 (plain parquet) SST
                format_version=max(1, pb.meta.format_version),
                encodings=tuple(
                    (e.column, e.codec) for e in pb.meta.encodings
                ),
            ),
        )


@dataclass(frozen=True)
class SstPathGenerator:
    """`{prefix}/data/{id}.sst` (sst.rs:202-204)."""

    prefix: str

    def generate(self, file_id: int) -> str:
        return f"{self.prefix}/{PREFIX_PATH}/{file_id}.sst"

    def generate_bloom(self, file_id: int) -> str:
        """Sidecar bloom-filter object (pyarrow cannot write parquet blooms;
        see storage/bloom.py)."""
        return f"{self.prefix}/{PREFIX_PATH}/{file_id}.bloom"

    def generate_enc(self, file_id: int) -> str:
        """Encoded-lane sidecar of a format-v2 SST (storage/encoding.py):
        per-lane columnar encodings + zone maps the compressed-domain scan
        reads instead of the parquet columns."""
        return f"{self.prefix}/{PREFIX_PATH}/{file_id}.enc"

    def generate_rollup(self, file_id: int) -> str:
        """Pre-aggregated rollup SST (storage/rollup.py) — a DISTINCT
        artifact kind under its own prefix: never listed among the data
        SSTs, so raw scans and the data-orphan GC are oblivious to it;
        manifest/rollup/{id} records are its registry."""
        return f"{self.prefix}/rollup/{file_id}.sst"
