"""Host-side merge operators for duplicate primary-key groups.

Reference: src/columnar_storage/src/operator.rs. Overwrite mode (LastValue)
runs on device as a mask kernel (ops/dedup.py); these host operators are
(a) the Append-mode bytes-concat path, which is inherently variable-length
and stays on host (SURVEY §7 risk (b)), and (b) the oracle implementation the
device path is differentially tested against.
"""

from __future__ import annotations

from abc import ABC, abstractmethod

import pyarrow as pa

from horaedb_tpu.common.error import HoraeError, ensure


class MergeOperator(ABC):
    """Collapse one group of rows sharing a primary key into a single row
    (operator.rs:30-34)."""

    @abstractmethod
    def merge(self, group: pa.RecordBatch) -> pa.RecordBatch: ...


class LastValueOperator(MergeOperator):
    """Overwrite mode: the row with max sequence wins. Input groups arrive
    sorted by (pk, seq) so that is the final row (operator.rs:36-44)."""

    def merge(self, group: pa.RecordBatch) -> pa.RecordBatch:
        ensure(group.num_rows > 0, "empty merge group")
        return group.slice(group.num_rows - 1, 1)


class BytesMergeOperator(MergeOperator):
    """Append mode: binary value columns concatenate across the group; other
    columns come from the first row (operator.rs:59-111)."""

    def __init__(self, value_idxes: list[int]):
        self._value_idxes = value_idxes

    def merge(self, group: pa.RecordBatch) -> pa.RecordBatch:
        ensure(group.num_rows > 0, "empty merge group")
        if group.num_rows == 1:
            return group
        cols = []
        for i, col in enumerate(group.columns):
            if i in self._value_idxes:
                t = col.type
                if not (pa.types.is_binary(t) or pa.types.is_large_binary(t)):
                    raise HoraeError(f"append-mode value column must be binary, got {t}")
                joined = b"".join(v for v in col.to_pylist() if v is not None)
                cols.append(pa.array([joined], type=t))
            else:
                cols.append(col.slice(0, 1))
        return pa.RecordBatch.from_arrays(cols, schema=group.schema)
