"""Row visibility: tombstone deletes + retention, ONE shared mask helper.

Production metric stores need two subtractive operations an LSM never
gets for free: **retention** (rows older than a horizon stop existing)
and **tombstone deletes** (DELETE by series-matcher + time-range, the
GDPR/tenant-offboarding path). Both are *logical first, physical later*:

- scan time: every SST read funnels through :func:`apply_visibility`
  (ParquetReader.read_sst), so deleted/expired rows are MASKED before
  the merge — reads are exact whether or not compaction has run;
- compaction time: the compaction executor reads its inputs through the
  same funnel (under :func:`mask_context` ``"compact"``), so rewritten
  SSTs physically lack the rows — the delete eventually reclaims bytes
  and the GDPR story holds.

Masking runs BEFORE merge-dedup, which is exact for last-writer-wins:
a tombstone only ever matches rows with ``__seq__ < tombstone.seq``, so
a newer surviving version of the same primary key still wins, and
re-applying a tombstone to already-compacted data is a no-op.

This module is the ONLY place tombstone/retention row filtering may be
implemented (jaxlint J010 enforces it): per-reader ad-hoc filters would
silently diverge between the materializing scan, the chunked scan, the
downsample pushdown, and compaction — the exact class of bug that makes
deletes "mostly work".

Tombstone records are manifest-level objects (storage/manifest) encoded
as JSON — low-volume control-plane state, debuggable with `cat`.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
from dataclasses import dataclass

import numpy as np
import pyarrow as pa

from horaedb_tpu.common.error import HoraeError, ensure
from horaedb_tpu.server.metrics import GLOBAL_METRICS
from horaedb_tpu.storage import scanstats
from horaedb_tpu.storage.types import SEQ_COLUMN_NAME, TimeRange

logger = logging.getLogger(__name__)

TOMBSTONES_APPLIED = GLOBAL_METRICS.counter(
    "horaedb_tombstones_applied_total",
    help="Rows masked (context=scan) or physically removed at rewrite "
         "(context=compact) by tombstone delete records, by table root.",
    labelnames=("table", "context"),
)

# Which pipeline is consuming the masked rows right now: "scan" (query
# reads — rows are masked in the returned batches) or "compact" (the
# compaction executor — masked rows are physically absent from the
# rewritten output). Contextvar so the compaction executor flips it for
# its whole read without threading a flag through every scan layer.
_MASK_CONTEXT: contextvars.ContextVar[str] = contextvars.ContextVar(
    "horaedb_mask_context", default="scan"
)


@contextlib.contextmanager
def mask_context(context: str):
    """Run a block with visibility masking attributed to `context`."""
    token = _MASK_CONTEXT.set(context)
    try:
        yield
    finally:
        _MASK_CONTEXT.reset(token)


@dataclass(frozen=True)
class Tombstone:
    """One delete record: rows matching every matcher, inside
    ``time_range``, written BEFORE the delete (``__seq__ < seq``) are
    invisible. Rows written after the delete (seq >= this record's seq)
    survive — re-ingest into a deleted range works.

    ``matchers`` is a conjunction of (column, values) terms over integer
    columns; ``values=None`` is a wildcard (any value matches). The
    metric engine's series-matcher delete compiles to
    ``[("metric_id", (mid,)), ("tsid", <resolved tsids> | None)]``.
    """

    id: int
    seq: int
    time_range: TimeRange
    matchers: tuple[tuple[str, tuple[int, ...] | None], ...]

    def to_json(self) -> bytes:
        return json.dumps({
            "id": self.id,
            "seq": self.seq,
            "start": self.time_range.start,
            "end": self.time_range.end,
            "matchers": [
                [col, None if vals is None else list(vals)]
                for col, vals in self.matchers
            ],
        }).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "Tombstone":
        try:
            d = json.loads(data)
            return cls(
                id=int(d["id"]),
                seq=int(d["seq"]),
                time_range=TimeRange(int(d["start"]), int(d["end"])),
                matchers=tuple(
                    (str(col), None if vals is None else tuple(int(v) for v in vals))
                    for col, vals in d["matchers"]
                ),
            )
        except (KeyError, TypeError, ValueError) as e:
            raise HoraeError("corrupt tombstone record") from e


@dataclass(frozen=True)
class Visibility:
    """The subtractive state one table's scans must honor right now."""

    table: str
    # the schema's designated time column for row-exact time filtering;
    # None = no time column (retention then prunes whole SSTs only, and
    # time-range tombstones cannot be created)
    time_column: str | None
    tombstones: tuple[Tombstone, ...] = ()
    retention_floor_ms: int | None = None

    @property
    def empty(self) -> bool:
        return not self.tombstones and self.retention_floor_ms is None


def _column_lane(table: pa.Table, name: str) -> np.ndarray | None:
    if name not in table.schema.names:
        return None
    from horaedb_tpu.ops.blocks import arrow_column_to_numpy

    return np.asarray(arrow_column_to_numpy(table.column(name).combine_chunks()))


def apply_visibility(
    table: pa.Table,
    vis: "Visibility | None",
    sst_range: "TimeRange | None" = None,
) -> pa.Table:
    """Filter one SST's decoded rows through retention + tombstones.

    Exactness contract: runs BEFORE merge-dedup on per-SST tables, which
    preserves last-writer-wins (see module docstring). Idempotent — safe
    on already-compacted data.

    `sst_range` (the file's manifest time range) lets non-overlapping
    tombstones skip without touching any column. A tombstone whose
    matcher names a column absent from `table` is skipped for this read
    (scan projections always include the primary key + ``__seq__``, so
    this only arises for exotic projections) — skipping errs on the
    visible side, never deletes the wrong rows.
    """
    if vis is None or vis.empty or table.num_rows == 0:
        return table
    floor = vis.retention_floor_ms
    # sst_range short-circuits BOTH subtractive passes: a file whose
    # manifest range starts past the floor cannot hold an expired row,
    # and a tombstone that doesn't overlap the file cannot match — the
    # common in-retention/undeleted read then returns without touching
    # (or materializing) any column
    need_retention = (
        floor is not None and vis.time_column is not None
        and (sst_range is None or sst_range.start < floor)
    )
    tombs = [
        t for t in vis.tombstones
        if sst_range is None or t.time_range.overlaps(sst_range)
    ]
    if not need_retention and not tombs:
        return table
    n = table.num_rows
    ts = _column_lane(table, vis.time_column) if vis.time_column else None
    drop = None
    retained_out = 0
    if need_retention and ts is not None:
        expired = ts < floor
        retained_out = int(np.count_nonzero(expired))
        if retained_out:
            drop = expired
    tomb_rows = 0
    tombs_applied = 0
    seq = None
    for t in tombs:
        if ts is None:
            continue  # no time column: tombstones cannot be evaluated
        if seq is None:
            seq = _column_lane(table, SEQ_COLUMN_NAME)
            if seq is None:
                # no __seq__ in this projection: cannot prove rows predate
                # the delete — err visible (scan paths always fetch it)
                logger.warning(
                    "tombstone skipped: projection lacks %s (table=%s)",
                    SEQ_COLUMN_NAME, vis.table,
                )
                break
        m = (ts >= t.time_range.start) & (ts < t.time_range.end)
        if not m.any():
            continue
        m &= seq < np.uint64(t.seq)
        bad = False
        for col, vals in t.matchers:
            if vals is None:
                continue
            lane = _column_lane(table, col)
            if lane is None:
                bad = True
                break
            if len(vals) == 1:
                m &= lane == lane.dtype.type(vals[0])
            else:
                m &= np.isin(lane, np.asarray(vals, dtype=lane.dtype))
        if bad:
            continue
        hit = int(np.count_nonzero(m))
        if hit:
            tombs_applied += 1
            tomb_rows += hit
            drop = m if drop is None else (drop | m)
    if drop is None:
        return table
    total = int(np.count_nonzero(drop))
    if total == 0:
        return table
    context = _MASK_CONTEXT.get()
    if tomb_rows:
        TOMBSTONES_APPLIED.labels(vis.table, context).inc(tomb_rows)
        scanstats.note("tombstones_applied", tombs_applied)
        scanstats.note("tombstone_rows_masked", tomb_rows)
    if retained_out:
        scanstats.note("retention_rows_masked", retained_out)
    return table.filter(pa.array(~drop))


def build_series_matchers(
    metric_id: int, tsids: "list[int] | None"
) -> tuple[tuple[str, tuple[int, ...] | None], ...]:
    """The metric-engine delete shape: one metric, optionally a resolved
    TSID set (None = every series of the metric)."""
    ensure(metric_id >= 0, "metric_id must be non-negative")
    return (
        ("metric_id", (int(metric_id),)),
        ("tsid", None if tsids is None else tuple(int(t) for t in sorted(tsids))),
    )
