"""Per-SST column bloom filters.

Reference: `build_write_props` applies per-column bloom-filter options to the
parquet writer (src/columnar_storage/src/storage.rs:258-298). pyarrow (25.x)
cannot WRITE parquet bloom filters, so the same capability ships as a
sidecar object `{prefix}/data/{id}.bloom` holding one bloom per enabled
column. The reader consults it for conjunctive equality / set-membership
predicates and skips SSTs that definitely lack every probed value — an
object-store GET saved per pruned SST.

Format (little-endian):
    magic u32 = 0xB100F11E | version u8 | n_cols u8
    per column: name_len u16 | name | type_tag u8 | k u8 | m_bits u64
                | ceil(m/8) bytes

The per-column type tag (int / float / bytes) drives value canonicalization
on BOTH sides: a probe literal is coerced to the column's domain before
hashing, so `Compare("v", "eq", 5)` against a float column hashes the same
bytes the build side hashed for 5.0 (and an unrepresentable literal like
5.5 against an int column soundly proves absence). Probing hashes the
canonical bytes with seahash under two seeds and derives k indexes by
double hashing (Kirsch-Mitzenmacher).
"""

from __future__ import annotations

import math
import struct

import numpy as np
import pyarrow as pa

from horaedb_tpu.common.error import HoraeError
from horaedb_tpu.common.hash import seahash

MAGIC = 0xB100F11E
VERSION = 2
DEFAULT_FPP = 0.01

TAG_INT = 0     # canonical: 8-byte LE of the u64 bit pattern
TAG_FLOAT = 1   # canonical: 8-byte LE IEEE f64
TAG_BYTES = 2   # canonical: raw bytes (str encodes UTF-8)

_UNREPRESENTABLE = object()  # probe literal outside the column's domain


def tag_of_arrow_type(t: pa.DataType) -> int:
    if pa.types.is_integer(t) or pa.types.is_boolean(t):
        return TAG_INT
    if pa.types.is_floating(t):
        return TAG_FLOAT
    if (pa.types.is_binary(t) or pa.types.is_large_binary(t)
            or pa.types.is_string(t) or pa.types.is_large_string(t)):
        return TAG_BYTES
    raise HoraeError(f"unsupported bloom column type: {t}")


def _canonical(v, tag: int):
    """Coerce a value into the column's domain; returns the hashable bytes
    or _UNREPRESENTABLE when the value cannot equal any column value."""
    if tag == TAG_INT:
        if isinstance(v, float):
            if not v.is_integer():
                return _UNREPRESENTABLE
            v = int(v)
        if isinstance(v, (bool, int, np.integer)):
            return struct.pack("<Q", int(v) & (1 << 64) - 1)
        return _UNREPRESENTABLE
    if tag == TAG_FLOAT:
        if isinstance(v, (bool, int, float, np.integer, np.floating)):
            return struct.pack("<d", float(v))
        return _UNREPRESENTABLE
    if tag == TAG_BYTES:
        if isinstance(v, str):
            return v.encode()
        if isinstance(v, (bytes, bytearray)):
            return bytes(v)
        return _UNREPRESENTABLE
    raise HoraeError(f"unknown bloom type tag: {tag}")


def _h2(data: bytes) -> tuple[int, int]:
    h1 = seahash(data)
    h2 = seahash(b"\x9e" + data)
    return h1, h2 | 1  # odd second hash: full-period double hashing


class BloomFilter:
    """One column's bloom: m bits, k hash probes, a domain type tag."""

    def __init__(self, bits: np.ndarray, k: int, tag: int):
        self.bits = bits  # uint8 array, len ceil(m/8)
        self.k = k
        self.m = len(bits) * 8
        self.tag = tag

    @classmethod
    def build(cls, values, tag: int, fpp: float = DEFAULT_FPP) -> "BloomFilter":
        uniq = {v for v in values if v is not None}  # nulls never probe-match
        n = max(1, len(uniq))
        m = max(64, int(-n * math.log(fpp) / (math.log(2) ** 2)))
        m = (m + 7) // 8 * 8
        k = max(1, round(m / n * math.log(2)))
        bits = np.zeros(m // 8, dtype=np.uint8)
        bf = cls(bits, k, tag)
        for v in uniq:
            data = _canonical(v, tag)
            if data is _UNREPRESENTABLE:
                raise HoraeError(
                    f"bloom build: value {v!r} outside column domain (tag {tag})"
                )
            bf._add(data)
        return bf

    def _add(self, data: bytes) -> None:
        h1, h2 = _h2(data)
        for i in range(self.k):
            idx = (h1 + i * h2) % self.m
            self.bits[idx >> 3] |= 1 << (idx & 7)

    def may_contain(self, v) -> bool:
        data = _canonical(v, self.tag)
        if data is _UNREPRESENTABLE:
            return False  # cannot equal any stored value
        h1, h2 = _h2(data)
        for i in range(self.k):
            idx = (h1 + i * h2) % self.m
            if not (self.bits[idx >> 3] >> (idx & 7)) & 1:
                return False
        return True


def build_blooms(
    table: pa.Table, columns: list[str], fpp: float = DEFAULT_FPP
) -> dict[str, BloomFilter]:
    out = {}
    for name in columns:
        col = table.column(name)
        tag = tag_of_arrow_type(col.type)
        out[name] = BloomFilter.build(col.to_pylist(), tag, fpp)
    return out


def encode_blooms(blooms: dict[str, BloomFilter]) -> bytes:
    parts = [struct.pack("<IBB", MAGIC, VERSION, len(blooms))]
    for name, bf in sorted(blooms.items()):
        nb = name.encode()
        parts.append(struct.pack("<H", len(nb)))
        parts.append(nb)
        parts.append(struct.pack("<BBQ", bf.tag, bf.k, bf.m))
        parts.append(bf.bits.tobytes())
    return b"".join(parts)


def decode_blooms(data: bytes) -> dict[str, BloomFilter]:
    if len(data) < 6:
        raise HoraeError("bloom sidecar truncated")
    magic, version, n_cols = struct.unpack_from("<IBB", data, 0)
    if magic != MAGIC:
        raise HoraeError(f"bad bloom magic {magic:#x}")
    if version != VERSION:
        raise HoraeError(f"unsupported bloom version {version}")
    off = 6
    out = {}
    for _ in range(n_cols):
        (name_len,) = struct.unpack_from("<H", data, off)
        off += 2
        name = data[off : off + name_len].decode()
        off += name_len
        tag, k, m = struct.unpack_from("<BBQ", data, off)
        off += 10
        nbytes = m // 8
        bits = np.frombuffer(data[off : off + nbytes], dtype=np.uint8)
        if len(bits) != nbytes:
            raise HoraeError("bloom sidecar truncated")
        off += nbytes
        out[name] = BloomFilter(bits.copy(), k, tag)
    return out


def eq_constraints(predicate) -> dict[str, set]:
    """Extract conjunctive equality constraints: {column: candidate values}.
    A row can only match the predicate if, for each returned column, its
    value is one of the candidates — the sound condition for bloom pruning.
    Or/Not subtrees contribute nothing (conservative)."""
    from horaedb_tpu.ops import filter as F

    out: dict[str, set] = {}

    def walk(p) -> None:
        if isinstance(p, F.And):
            for c in p.children:
                walk(c)
        elif isinstance(p, F.Compare) and p.op == "eq":
            s = out.setdefault(p.column, set())
            s.add(p.literal)
        elif isinstance(p, F.InSet):
            out.setdefault(p.column, set()).update(p.values)

    if predicate is not None:
        walk(predicate)
    # A column constrained twice keeps all candidates (superset = sound).
    return out


def can_skip(blooms: dict[str, BloomFilter], constraints: dict[str, set]) -> bool:
    """True when some constrained+bloomed column contains NONE of its
    candidate values — the SST cannot produce a matching row."""
    for col, values in constraints.items():
        bf = blooms.get(col)
        if bf is None or len(values) > 256:  # cap probe work per SST
            continue
        if not any(bf.may_contain(v) for v in values):
            return True
    return False
