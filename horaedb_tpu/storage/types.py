"""Core storage types: timestamps, time ranges, the storage schema contract.

Reference: src/columnar_storage/src/types.rs. The schema contract is identical:

    pk_1, ..., pk_N, value_1, ..., value_M, __seq__, __reserved__

- the first `num_primary_keys` user columns are the primary key (sort key);
- at least one value column must follow;
- `__seq__` (uint64) is the write sequence (== SST file id) used for dedup;
- `__reserved__` (uint64, all-null today) holds future tombstone/expiry flags.

Host-side batches are pyarrow RecordBatches; `ops/blocks.py` defines the
device-side struct-of-arrays layout the kernels consume.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import pyarrow as pa

from horaedb_tpu.common.error import ensure

BUILTIN_COLUMN_NUM = 2
SEQ_COLUMN_NAME = "__seq__"
RESERVED_COLUMN_NAME = "__reserved__"


@dataclass(frozen=True, order=True)
class Timestamp:
    """Millisecond timestamp (reference: types.rs:45-133)."""

    value: int

    MIN = -(2**63)
    MAX = 2**63 - 1

    def truncate_by(self, duration_ms: int) -> "Timestamp":
        """Floor to a segment boundary (python floordiv floors toward -inf,
        matching the bucketing the picker needs for negative timestamps)."""
        return Timestamp(self.value - self.value % duration_ms)

    def __add__(self, other: "Timestamp | int") -> "Timestamp":
        o = other.value if isinstance(other, Timestamp) else other
        return Timestamp(self.value + o)

    def __sub__(self, other: "Timestamp | int") -> "Timestamp":
        o = other.value if isinstance(other, Timestamp) else other
        return Timestamp(self.value - o)


@dataclass(frozen=True)
class TimeRange:
    """Half-open [start, end) in ms (reference: types.rs passim)."""

    start: int  # inclusive
    end: int    # exclusive

    def __post_init__(self) -> None:
        ensure(self.start <= self.end, f"invalid time range [{self.start}, {self.end})")

    def overlaps(self, other: "TimeRange") -> bool:
        return self.start < other.end and other.start < self.end

    def contains(self, ts: int) -> bool:
        return self.start <= ts < self.end

    def merge(self, other: "TimeRange") -> "TimeRange":
        return TimeRange(min(self.start, other.start), max(self.end, other.end))

    @classmethod
    def union_of(cls, ranges: list["TimeRange"]) -> "TimeRange":
        ensure(len(ranges) > 0, "cannot union zero time ranges")
        out = ranges[0]
        for r in ranges[1:]:
            out = out.merge(r)
        return out


@dataclass
class WriteResult:
    """Outcome of one SST write (reference: types.rs WriteResult)."""

    id: int
    seq: int
    size: int


@dataclass
class StorageSchema:
    """User schema + builtin columns (reference: types.rs:143-240)."""

    arrow_schema: pa.Schema
    num_primary_keys: int
    seq_idx: int
    reserved_idx: int
    value_idxes: list[int]
    update_mode: "object" = None  # UpdateMode; typed loosely to avoid import cycle

    @classmethod
    def try_new(
        cls,
        arrow_schema: pa.Schema,
        num_primary_keys: int,
        update_mode,
    ) -> "StorageSchema":
        ensure(num_primary_keys > 0, "num_primary_keys should large than 0")
        names = arrow_schema.names
        ensure(
            SEQ_COLUMN_NAME not in names and RESERVED_COLUMN_NAME not in names,
            "schema should not use builtin columns name",
        )
        value_idxes = list(range(num_primary_keys, len(names)))
        ensure(len(value_idxes) > 0, "no value column found")

        fields = list(arrow_schema) + [
            pa.field(SEQ_COLUMN_NAME, pa.uint64(), nullable=True),
            pa.field(RESERVED_COLUMN_NAME, pa.uint64(), nullable=True),
        ]
        full = pa.schema(fields, metadata=arrow_schema.metadata)
        return cls(
            arrow_schema=full,
            num_primary_keys=num_primary_keys,
            seq_idx=len(fields) - 2,
            reserved_idx=len(fields) - 1,
            value_idxes=value_idxes,
            update_mode=update_mode,
        )

    @staticmethod
    def is_builtin_name(name: str) -> bool:
        return name in (SEQ_COLUMN_NAME, RESERVED_COLUMN_NAME)

    @property
    def primary_key_names(self) -> list[str]:
        return self.arrow_schema.names[: self.num_primary_keys]

    @property
    def user_schema(self) -> pa.Schema:
        """Schema without builtin columns (what scan returns by default)."""
        return pa.schema(
            [self.arrow_schema.field(i)
             for i in range(len(self.arrow_schema.names) - BUILTIN_COLUMN_NUM)],
            metadata=self.arrow_schema.metadata,
        )

    def fill_required_projections(self, projections: list[int] | None) -> list[int] | None:
        """Primary keys + __seq__ are always fetched (reference: types.rs:203-216)."""
        if projections is None:
            return None
        proj = list(projections)
        for i in range(self.num_primary_keys):
            if i not in proj:
                proj.append(i)
        if self.seq_idx not in proj:
            proj.append(self.seq_idx)
        return proj

    def fill_builtin_columns(self, batch: pa.RecordBatch, sequence: int) -> pa.RecordBatch:
        """Append __seq__=sequence and all-null __reserved__ (types.rs:219-239)."""
        n = batch.num_rows
        cols = list(batch.columns)
        cols.append(pa.array(np.full(n, sequence, dtype=np.uint64)))
        cols.append(pa.nulls(n, type=pa.uint64()))
        return pa.RecordBatch.from_arrays(cols, schema=self.arrow_schema)
