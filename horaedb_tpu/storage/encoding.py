"""Lightweight columnar encodings for SST lanes + the compressed-domain
scan helpers (ROADMAP open item 1; LSM-OPD arXiv:2508.11862).

An encoded SST is a `.enc` sidecar object next to the parquet file (the
same pattern as the bloom sidecar): per-lane encoded PAGES with min/max
zone maps, self-described by a JSON header. The parquet object remains the
durable, universally-readable representation — the sidecar is the scan
accelerator, and a reader that cannot use it (v1 SST, missing lane,
unsupported dtype) falls back to the parquet path with identical results.

Codecs (chosen per lane by measured encoded size, never guessed):

  rle    sorted/run-heavy integer lanes (tsid): (run values, run lengths);
         predicates evaluate PER RUN and expand — run skipping instead of
         row-wise masks.
  dict   low-cardinality integer lanes (tag/id): lane-level dictionary +
         bit-packed ids; predicates rewrite to dict-id comparisons (the
         predicate runs over the dictionary, not the rows).
  dod    timestamps: per-page (first, first_delta) + zigzag bit-packed
         second-order deltas (Gorilla-style). Regular scrape intervals
         pack to ~0 bits/row.
  xor    float values: per-page first raw bits + bit-packed XOR stream of
         consecutive bit patterns (Gorilla's float trick, fixed-width per
         page instead of per-value varint — vectorizable on both ends).
  null   all-null lanes (__reserved__): zero payload.
  raw    passthrough bytes (still pages + zone maps, so pruning works).

Page boundaries are SHARED across lanes (page i covers the same rows in
every lane), so a page pruned by one lane's zone map drops that row range
from every lane before any decode.

Bit-exactness contract: decode(encode(x)) == x for every codec, verified
bit-for-bit by tests/test_encoding.py (floats compare on their u64 bit
patterns, so NaN payloads and -0.0 survive).

Decoding an encoded lane ANYWHERE else is a jaxlint J012 error: this
module and ops/decode.py (the device kernels) are the only sanctioned
decode funnels, reached through ParquetReader's encoded read path.
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field

import numpy as np
import pyarrow as pa

from horaedb_tpu.common.error import HoraeError, ensure

# sidecar wire format: magic | version u8 | header_len u32 | header JSON
# | payload bytes
ENC_MAGIC = 0xE27C_0DEC
ENC_VERSION = 1
_HEADER = struct.Struct("<IBI")

# FileMeta.format_version values: v1 = plain parquet SST (no sidecar),
# v2 = parquet + encoded-lane sidecar
SST_FORMAT_V1 = 1
SST_FORMAT_V2 = 2

DEFAULT_PAGE_ROWS = 4096

_U64_ONE = np.uint64(1)
_U64_63 = np.uint64(63)

_DTYPES = {"<i8", "<i4", "<u8", "<u4", "<f8", "<f4"}


# ---------------------------------------------------------------------------
# bit packing (LSB-first within the stream; payload padded to u32 words so
# the device kernel can view it as a word lane)
# ---------------------------------------------------------------------------


def pack_bits(vals: np.ndarray, width: int) -> bytes:
    """Pack u64 `vals` (each < 2**width) into an LSB-first bitstream,
    padded to a multiple of 4 bytes (u32 word alignment for the device
    unpack kernel)."""
    if width == 0 or len(vals) == 0:
        return b""
    shifts = np.arange(width, dtype=np.uint64)
    bits = ((vals[:, None] >> shifts) & _U64_ONE).astype(np.uint8)
    packed = np.packbits(bits.reshape(-1), bitorder="little").tobytes()
    pad = (-len(packed)) % 4
    return packed + b"\x00" * pad


def unpack_bits(buf: bytes, n: int, width: int) -> np.ndarray:
    """Inverse of pack_bits -> u64 array of length n.

    width <= 32 takes the vectorized word-gather (the host mirror of the
    device kernel's two-word bit-window read: O(n), no per-bit matrix);
    wider values can span three u32 words, so they fall back to the
    unpackbits matrix — rare in practice (only near-incompressible lanes
    pack wider than 32, and those lose to raw at codec choice)."""
    if width == 0 or n == 0:
        return np.zeros(n, np.uint64)
    if width <= 32:
        words = np.frombuffer(buf, "<u4").astype(np.uint64)
        w = np.empty(len(words) + 1, np.uint64)  # +1 guard word: the
        w[:-1] = words                           # last straddle read
        w[-1] = 0
        bit = np.arange(n, dtype=np.uint64) * np.uint64(width)
        wi = (bit >> np.uint64(5)).astype(np.int64)
        off = bit & np.uint64(31)
        comb = w[wi] | (w[wi + 1] << np.uint64(32))
        return (comb >> off) & np.uint64((1 << width) - 1)
    bits = np.unpackbits(
        np.frombuffer(buf, np.uint8), count=n * width, bitorder="little"
    ).reshape(n, width).astype(np.uint64)
    shifts = np.arange(width, dtype=np.uint64)
    return (bits << shifts).sum(axis=1, dtype=np.uint64)


def zigzag(v: np.ndarray) -> np.ndarray:
    """i64 -> u64 zigzag (small magnitudes -> small codes), mod-2^64 safe."""
    uv = np.ascontiguousarray(v, dtype=np.int64).view(np.uint64)
    return (uv << _U64_ONE) ^ (np.uint64(0) - (uv >> _U64_63))


def unzigzag(z: np.ndarray) -> np.ndarray:
    uv = (z >> _U64_ONE) ^ (np.uint64(0) - (z & _U64_ONE))
    return uv.view(np.int64)


def _bit_width(vals: np.ndarray) -> int:
    if len(vals) == 0:
        return 0
    m = int(vals.max())
    return m.bit_length()


# ---------------------------------------------------------------------------
# encoded representation
# ---------------------------------------------------------------------------


@dataclass
class EncPage:
    """One page of one lane. `p0`/`p1` are codec parameters:
    dod: (first, first_delta); xor: (first value's u64 bits, 0);
    rle: (number of runs, 0); dict/raw/null: unused."""

    rows: int
    off: int
    length: int
    lo: "int | float | None"
    hi: "int | float | None"
    width: int = 0
    p0: int = 0
    p1: int = 0


@dataclass
class EncLane:
    name: str
    codec: str  # rle | dict | dod | xor | null | raw
    dtype: str  # numpy dtype str of the decoded lane
    rows: int
    pages: list[EncPage]
    dict_values: "list[int] | None" = None
    payload: bytes = b""

    def encoded_bytes(self) -> int:
        n = sum(p.length for p in self.pages)
        if self.dict_values is not None:
            # the dictionary ships as decimal text inside the sidecar's
            # JSON header (encode_blob), not as fixed-width words — charge
            # the wire what it actually pays so the >=_MIN_WIN codec race
            # and the bytes/row bench stay honest for large-id dicts
            n += len(json.dumps(self.dict_values, separators=(",", ":")))
        return n

    def decoded_bytes(self) -> int:
        return self.rows * np.dtype(self.dtype).itemsize


@dataclass
class EncodedSst:
    """Decoded sidecar: lanes share page boundaries (`page_rows`)."""

    num_rows: int
    page_rows: int
    lanes: dict[str, EncLane] = field(default_factory=dict)

    @property
    def num_pages(self) -> int:
        if self.num_rows == 0:
            return 0
        return -(-self.num_rows // self.page_rows)

    def descriptor(self) -> tuple[tuple[str, str], ...]:
        """(lane, codec) pairs — the FileMeta/manifest-pb encoding
        descriptor and the EXPLAIN provenance payload."""
        return tuple((n, l.codec) for n, l in self.lanes.items())

    def footprint_bytes(self) -> int:
        """Resident size of this decoded sidecar — what the reader's
        byte-bounded cache charges per entry: lane payloads (the dominant
        term; held as bytes) + dictionaries + per-page header objects."""
        n = 0
        for lane in self.lanes.values():
            n += len(lane.payload)
            if lane.dict_values is not None:
                n += len(lane.dict_values) * 8
            n += len(lane.pages) * 96  # EncPage object overhead
        return n


# ---------------------------------------------------------------------------
# per-codec encode (host, vectorized numpy)
# ---------------------------------------------------------------------------


def _page_slices(n: int, page_rows: int) -> list[tuple[int, int]]:
    return [(i, min(i + page_rows, n)) for i in range(0, n, page_rows)]


def _zone(arr: np.ndarray) -> tuple:
    """(lo, hi) page statistics; None when unusable (NaN present)."""
    if len(arr) == 0:
        return None, None
    if np.issubdtype(arr.dtype, np.floating):
        if np.isnan(arr).any():
            return None, None
        return float(arr.min()), float(arr.max())
    return int(arr.min()), int(arr.max())


def _encode_rle(arr: np.ndarray, page_rows: int) -> EncLane | None:
    pages, parts, off = [], [], 0
    for s, e in _page_slices(len(arr), page_rows):
        page = arr[s:e]
        change = np.flatnonzero(page[1:] != page[:-1])
        starts = np.concatenate(([0], change + 1))
        values = page[starts]
        lengths = np.diff(np.concatenate((starts, [len(page)]))).astype("<u4")
        blob = values.astype(arr.dtype.newbyteorder("<")).tobytes() + lengths.tobytes()
        lo, hi = _zone(values)
        pages.append(EncPage(rows=len(page), off=off, length=len(blob),
                             lo=lo, hi=hi, p0=len(values)))
        parts.append(blob)
        off += len(blob)
    return EncLane("", "rle", arr.dtype.str, len(arr), pages, payload=b"".join(parts))


def _encode_dict(arr: np.ndarray, page_rows: int, max_dict: int) -> EncLane | None:
    uniq, inv = np.unique(arr, return_inverse=True)
    if len(uniq) > max_dict:
        return None
    width = _bit_width(np.asarray([max(0, len(uniq) - 1)], np.uint64))
    inv = inv.astype(np.uint64)
    pages, parts, off = [], [], 0
    for s, e in _page_slices(len(arr), page_rows):
        blob = pack_bits(inv[s:e], width)
        lo, hi = _zone(arr[s:e])
        pages.append(EncPage(rows=e - s, off=off, length=len(blob),
                             lo=lo, hi=hi, width=width))
        parts.append(blob)
        off += len(blob)
    return EncLane("", "dict", arr.dtype.str, len(arr), pages,
                   dict_values=[int(v) for v in uniq], payload=b"".join(parts))


def _encode_dod(arr: np.ndarray, page_rows: int) -> EncLane | None:
    if not np.issubdtype(arr.dtype, np.signedinteger):
        return None
    a = arr.astype(np.int64, copy=False)
    pages, parts, off = [], [], 0
    for s, e in _page_slices(len(a), page_rows):
        page = a[s:e]
        lo, hi = _zone(page)
        first = int(page[0])
        if len(page) >= 2:
            # deltas mod 2^64 (u64 wrap), exact on decode by the same wrap
            d = (page.view(np.uint64)[1:] - page.view(np.uint64)[:-1]).view(np.int64)
            first_delta = int(d[0])
            dd = zigzag((d.view(np.uint64)[1:] - d.view(np.uint64)[:-1]).view(np.int64))
            width = _bit_width(dd)
            blob = pack_bits(dd, width)
        else:
            first_delta, width, blob = 0, 0, b""
        pages.append(EncPage(rows=len(page), off=off, length=len(blob),
                             lo=lo, hi=hi, width=width, p0=first, p1=first_delta))
        parts.append(blob)
        off += len(blob)
    return EncLane("", "dod", arr.dtype.str, len(a), pages, payload=b"".join(parts))


def _encode_xor(arr: np.ndarray, page_rows: int) -> EncLane | None:
    if arr.dtype not in (np.float64, np.float32):
        return None
    wide = arr.dtype == np.float64
    bits = arr.view(np.uint64 if wide else np.uint32).astype(np.uint64)
    pages, parts, off = [], [], 0
    for s, e in _page_slices(len(arr), page_rows):
        page = bits[s:e]
        lo, hi = _zone(arr[s:e])
        first = int(page[0])
        if len(page) >= 2:
            x = page[1:] ^ page[:-1]
            width = _bit_width(x)
            blob = pack_bits(x, width)
        else:
            width, blob = 0, b""
        pages.append(EncPage(rows=len(page), off=off, length=len(blob),
                             lo=lo, hi=hi, width=width, p0=first))
        parts.append(blob)
        off += len(blob)
    return EncLane("", "xor", arr.dtype.str, len(arr), pages, payload=b"".join(parts))


def _encode_raw(arr: np.ndarray, page_rows: int) -> EncLane:
    pages, parts, off = [], [], 0
    little = arr.astype(arr.dtype.newbyteorder("<"), copy=False)
    for s, e in _page_slices(len(arr), page_rows):
        blob = little[s:e].tobytes()
        lo, hi = _zone(arr[s:e])
        pages.append(EncPage(rows=e - s, off=off, length=len(blob), lo=lo, hi=hi))
        parts.append(blob)
        off += len(blob)
    return EncLane("", "raw", arr.dtype.str, len(arr), pages, payload=b"".join(parts))


# A non-raw codec must beat raw by this factor to be chosen: decoding
# costs real scan-time work, so a near-tie (xor over incompressible
# values packs to ~1.0x) must lose to raw's free frombuffer decode.
_MIN_WIN = 0.8


def encode_lane(name: str, arr: np.ndarray, page_rows: int = DEFAULT_PAGE_ROWS,
                max_dict: int = 4096, prefer_ts: bool = False) -> EncLane:
    """Encode one lane, choosing the codec by MEASURED encoded size: the
    smallest wins, but a non-raw codec must be at least 1/_MIN_WIN
    smaller than raw (decode is paid per scan; a size near-tie decodes
    strictly slower than raw's frombuffer). Raw is always a candidate,
    so encoding never inflates beyond the page/zone-map overhead.
    `prefer_ts` (the time column) drops dict from the candidate list —
    range predicates probe time lanes per page, and a dict-encoded ts
    page answers them only after a full dictionary gather, so even a
    size win there would lose the scan; among the remaining candidates
    size still decides."""
    ensure(arr.ndim == 1, f"lane {name!r} must be 1-D")
    if arr.dtype.str not in _DTYPES:
        raise HoraeError(f"lane {name!r} dtype {arr.dtype} not encodable")
    candidates: list[EncLane] = []
    if np.issubdtype(arr.dtype, np.integer) and len(arr):
        n_runs = 1 + int(np.count_nonzero(arr[1:] != arr[:-1]))
        if n_runs * 2 <= len(arr):
            c = _encode_rle(arr, page_rows)
            if c is not None:
                candidates.append(c)
        if not prefer_ts:
            c = _encode_dict(arr, page_rows, max_dict)
            if c is not None:
                candidates.append(c)
        if np.issubdtype(arr.dtype, np.signedinteger):
            c = _encode_dod(arr, page_rows)
            if c is not None:
                candidates.append(c)
    elif len(arr):
        c = _encode_xor(arr, page_rows)
        if c is not None:
            candidates.append(c)
    raw = _encode_raw(arr, page_rows)
    budget = raw.encoded_bytes() * _MIN_WIN
    winners = [c for c in candidates if c.encoded_bytes() <= budget]
    best = min(winners, key=lambda c: c.encoded_bytes()) if winners else raw
    best.name = name
    return best


# ---------------------------------------------------------------------------
# per-codec decode (host, vectorized numpy — the sanctioned host funnel)
# ---------------------------------------------------------------------------


def _page_payload(lane: EncLane, p: EncPage) -> bytes:
    return lane.payload[p.off:p.off + p.length]


def dict_array(dict_values, dt: np.dtype) -> np.ndarray:
    """Lane dictionary as a typed array (u64 values survive the JSON
    round trip as Python ints above 2^63). Shared with the device
    kernels in ops/decode.py — ONE materialization of the JSON-int
    convention, so host and device can never drift."""
    if np.issubdtype(dt, np.unsignedinteger):
        vals = np.asarray([np.uint64(v) for v in dict_values], np.uint64)
    else:
        vals = np.asarray(dict_values, np.int64)
    return vals.astype(dt, copy=False)


def _decode_page_host(lane: EncLane, p: EncPage) -> np.ndarray:
    dt = np.dtype(lane.dtype)
    if lane.codec == "raw":
        return np.frombuffer(_page_payload(lane, p), dtype=dt.newbyteorder("<"),
                             count=p.rows).astype(dt, copy=False)
    if lane.codec == "rle":
        blob = _page_payload(lane, p)
        vals = np.frombuffer(blob, dtype=dt.newbyteorder("<"), count=p.p0)
        lengths = np.frombuffer(blob, dtype="<u4", count=p.p0,
                                offset=p.p0 * dt.itemsize)
        return np.repeat(vals.astype(dt, copy=False), lengths.astype(np.int64))
    if lane.codec == "dict":
        ids = unpack_bits(_page_payload(lane, p), p.rows, p.width).astype(np.int64)
        return dict_array(lane.dict_values, dt)[ids]
    if lane.codec == "dod":
        if p.rows == 1:
            return np.asarray([p.p0], dtype=np.int64).astype(dt, copy=False)
        first = np.uint64(p.p0 & 0xFFFF_FFFF_FFFF_FFFF)
        first_delta = np.uint64(p.p1 & 0xFFFF_FFFF_FFFF_FFFF)
        dd = unzigzag(unpack_bits(_page_payload(lane, p), p.rows - 2, p.width))
        d = np.empty(p.rows - 1, np.uint64)
        d[0] = first_delta
        np.cumsum(dd.view(np.uint64), out=d[1:])  # mod-2^64 prefix sum
        d[1:] += first_delta
        out = np.empty(p.rows, np.uint64)
        out[0] = first
        np.cumsum(d, out=out[1:])
        out[1:] += first
        return out.view(np.int64).astype(dt, copy=False)
    if lane.codec == "xor":
        wide = dt == np.float64
        if p.rows == 1:
            bits = np.asarray([p.p0], np.uint64)
        else:
            x = unpack_bits(_page_payload(lane, p), p.rows - 1, p.width)
            bits = np.empty(p.rows, np.uint64)
            bits[0] = np.uint64(p.p0)
            np.bitwise_xor.accumulate(
                np.concatenate((bits[:1], x)), out=bits
            )
        if wide:
            return bits.view(np.float64)
        return bits.astype(np.uint32).view(np.float32)
    raise HoraeError(f"unknown codec {lane.codec!r}")


def decode_lane(lane: EncLane, page_idxs: "list[int] | None" = None,
                impl: str = "host") -> np.ndarray:
    """Decode a lane (or a subset of its pages, in page order) to the exact
    original array. `impl="device"` routes qualifying pages through the
    JAX kernels in ops/decode.py (expanding in device memory, then
    materializing) and falls back to host per page when a page's shape
    is outside the device envelope (width > 32)."""
    pages = lane.pages if page_idxs is None else [lane.pages[i] for i in page_idxs]
    if not pages:
        return np.empty(0, np.dtype(lane.dtype))
    if impl == "device" and lane.codec in ("dod", "xor", "dict", "rle"):
        from horaedb_tpu.ops import decode as decode_ops

        parts = []
        for p in pages:
            out = decode_ops.decode_page_device(
                lane.codec, lane.dtype, _page_payload(lane, p), p.rows,
                p.width, p.p0, p.p1, lane.dict_values,
            )
            parts.append(out if out is not None else _decode_page_host(lane, p))
    else:
        parts = [_decode_page_host(lane, p) for p in pages]
    return parts[0] if len(parts) == 1 else np.concatenate(parts)


# ---------------------------------------------------------------------------
# table <-> sidecar blob
# ---------------------------------------------------------------------------


def encode_table(table: pa.Table, page_rows: int = DEFAULT_PAGE_ROWS,
                 max_dict: int = 4096, time_column: "str | None" = None,
                 lanes: "list[str] | None" = None) -> "EncodedSst | None":
    """Encode every eligible column of `table` into an EncodedSst; None
    when no lane qualifies (all-binary schema). A lane with partial nulls
    is skipped (readers needing it fall back to parquet); an ALL-null lane
    encodes as codec `null` (zero payload)."""
    from horaedb_tpu.ops.blocks import arrow_column_to_numpy

    enc = EncodedSst(num_rows=table.num_rows, page_rows=page_rows)
    for field_ in table.schema:
        name = field_.name
        if lanes is not None and name not in lanes:
            continue
        col = table.column(name)
        if col.null_count == table.num_rows and table.num_rows > 0:
            pages = [EncPage(rows=e - s, off=0, length=0, lo=None, hi=None)
                     for s, e in _page_slices(table.num_rows, page_rows)]
            enc.lanes[name] = EncLane(name, "null", "<u8", table.num_rows, pages)
            continue
        if col.null_count > 0:
            continue
        try:
            arr = arrow_column_to_numpy(col.combine_chunks())
        except (HoraeError, KeyError, pa.ArrowInvalid):
            continue  # binary/unsupported lane: parquet remains its home
        if arr.dtype.str not in _DTYPES:
            continue
        is_ts = time_column is not None and name == time_column
        enc.lanes[name] = encode_lane(name, arr, page_rows=page_rows,
                                      max_dict=max_dict, prefer_ts=is_ts)
    return enc if enc.lanes else None


def encode_blob(enc: EncodedSst) -> bytes:
    header = {
        "num_rows": enc.num_rows,
        "page_rows": enc.page_rows,
        "lanes": [
            {
                "name": l.name, "codec": l.codec, "dtype": l.dtype,
                "rows": l.rows, "dict": l.dict_values, "payload_off": 0,
                "pages": [
                    [p.rows, p.off, p.length, p.lo, p.hi, p.width, p.p0, p.p1]
                    for p in l.pages
                ],
            }
            for l in enc.lanes.values()
        ],
    }
    # assign payload offsets lane by lane
    off = 0
    payloads = []
    for lane_hdr, lane in zip(header["lanes"], enc.lanes.values()):
        lane_hdr["payload_off"] = off
        payloads.append(lane.payload)
        off += len(lane.payload)
    hj = json.dumps(header, separators=(",", ":")).encode("utf-8")
    return _HEADER.pack(ENC_MAGIC, ENC_VERSION, len(hj)) + hj + b"".join(payloads)


def decode_blob(data: bytes) -> EncodedSst:
    ensure(len(data) >= _HEADER.size, "enc sidecar shorter than header")
    magic, version, hlen = _HEADER.unpack_from(data, 0)
    ensure(magic == ENC_MAGIC, "invalid enc sidecar magic")
    ensure(version == ENC_VERSION, f"unsupported enc sidecar version {version}")
    ensure(len(data) >= _HEADER.size + hlen, "enc sidecar header truncated")
    header = json.loads(data[_HEADER.size:_HEADER.size + hlen])
    body = data[_HEADER.size + hlen:]
    enc = EncodedSst(num_rows=header["num_rows"], page_rows=header["page_rows"])
    for lh in header["lanes"]:
        pages = [EncPage(rows=r, off=o, length=ln, lo=lo, hi=hi, width=w,
                         p0=a, p1=b)
                 for r, o, ln, lo, hi, w, a, b in lh["pages"]]
        size = sum(p.length for p in pages)
        poff = lh["payload_off"]
        # extent + row-count validation: a TRUNCATED payload behind an
        # intact header must fail HERE (one deterministic, cacheable
        # verdict at load) — never as a short-buffer ValueError inside a
        # per-page np.frombuffer mid-query
        ensure(poff + size <= len(body),
               f"enc sidecar payload truncated: lane {lh['name']!r} needs "
               f"[{poff}, {poff + size}) of {len(body)} payload bytes")
        ensure(sum(p.rows for p in pages) == lh["rows"],
               f"enc sidecar page rows disagree for lane {lh['name']!r}")
        enc.lanes[lh["name"]] = EncLane(
            lh["name"], lh["codec"], lh["dtype"], lh["rows"], pages,
            dict_values=lh["dict"], payload=body[poff:poff + size],
        )
    return enc


# ---------------------------------------------------------------------------
# compressed-domain predicate evaluation
# ---------------------------------------------------------------------------


def page_stats(enc: EncodedSst, page: int) -> dict[str, tuple]:
    """Zone map of one page across lanes, in filter_ops.prune_range form."""
    out = {}
    for name, lane in enc.lanes.items():
        p = lane.pages[page]
        if p.lo is not None and p.hi is not None:
            out[name] = (p.lo, p.hi)
    return out


def prune_pages(enc: EncodedSst, predicate) -> tuple[list[int], int]:
    """(kept page indices, pruned count) by per-page min/max zone maps —
    the page analog of parquet row-group pruning, conservative for any
    predicate shape."""
    from horaedb_tpu.ops import filter as filter_ops

    if predicate is None:
        return list(range(enc.num_pages)), 0
    keep = [
        p for p in range(enc.num_pages)
        if filter_ops.prune_range(predicate, page_stats(enc, p))
    ]
    return keep, enc.num_pages - len(keep)


class EncodedEvalStats:
    """Provenance of one compressed-domain predicate evaluation."""

    def __init__(self) -> None:
        self.runs_skipped = 0
        self.dict_rewrites = 0


def _run_expand(run_mask: np.ndarray, lengths: np.ndarray) -> np.ndarray:
    return np.repeat(run_mask, lengths.astype(np.int64))


def encoded_mask(enc: EncodedSst, predicate, keep_pages: list[int],
                 stats: "EncodedEvalStats | None" = None,
                 decoded: "dict[str, np.ndarray] | None" = None,
                 decode=None) -> "np.ndarray | None":
    """Row mask of `predicate` over the concatenated kept pages, computed
    in the compressed domain where the codec allows:

    - rle lanes: the compare runs PER RUN (one compare per run, not per
      row) and expands; runs rejected whole are `runs_skipped`;
    - dict lanes: the compare runs over the DICTIONARY, then the packed
      ids probe a boolean LUT — the tsid-predicate-to-dict-id rewrite;
    - everything else decodes the lane (into `decoded`, shared with the
      caller so materialization never decodes twice; via the caller's
      `decode(name)` hook when given — the reader threads the calibrated
      dispatcher through it — else the host funnel) and evaluates with
      the exact same numpy semantics as filter_ops.eval_predicate_np.

    Returns None when the predicate references a lane the sidecar does
    not carry (caller falls back to the parquet path)."""
    from horaedb_tpu.ops import filter as filter_ops

    if predicate is None:
        return None
    if decoded is None:
        decoded = {}

    def lane_values(name: str) -> np.ndarray:
        a = decoded.get(name)
        if a is None:
            a = (decode(name) if decode is not None
                 else decode_lane(enc.lanes[name], keep_pages))
            decoded[name] = a
        return a

    def ev(p) -> np.ndarray:
        if isinstance(p, filter_ops.And):
            m = ev(p.children[0])
            for c in p.children[1:]:
                m = m & ev(c)
            return m
        if isinstance(p, filter_ops.Or):
            m = ev(p.children[0])
            for c in p.children[1:]:
                m = m | ev(c)
            return m
        if isinstance(p, filter_ops.Not):
            return ~ev(p.child)
        if isinstance(p, (filter_ops.Compare, filter_ops.InSet)):
            name = p.column
            lane = enc.lanes[name]
            if lane.codec == "rle":
                return _rle_node_mask(lane, p, keep_pages, stats)
            if lane.codec == "dict":
                return _dict_node_mask(lane, p, keep_pages, stats)
            cols = {name: lane_values(name)}
            return filter_ops.eval_predicate_np(p, cols)
        raise HoraeError(f"unsupported predicate node {type(p).__name__}")

    for col in filter_ops.pred_columns(predicate):
        if col not in enc.lanes or enc.lanes[col].codec == "null":
            return None
    try:
        return ev(predicate)
    except HoraeError:
        return None


def _node_mask_on_values(node, values: np.ndarray) -> np.ndarray:
    from horaedb_tpu.ops import filter as filter_ops

    return filter_ops.eval_predicate_np(node, {node.column: values})


def _rle_node_mask(lane: EncLane, node, keep_pages: list[int],
                   stats: "EncodedEvalStats | None") -> np.ndarray:
    dt = np.dtype(lane.dtype)
    parts = []
    for pi in keep_pages:
        p = lane.pages[pi]
        blob = _page_payload(lane, p)
        vals = np.frombuffer(blob, dtype=dt.newbyteorder("<"), count=p.p0).astype(dt, copy=False)
        lengths = np.frombuffer(blob, dtype="<u4", count=p.p0, offset=p.p0 * dt.itemsize)
        run_mask = _node_mask_on_values(node, vals)
        if stats is not None:
            stats.runs_skipped += int(len(run_mask) - np.count_nonzero(run_mask))
        parts.append(_run_expand(run_mask, lengths))
    return np.concatenate(parts) if parts else np.empty(0, bool)


def _dict_node_mask(lane: EncLane, node, keep_pages: list[int],
                    stats: "EncodedEvalStats | None") -> np.ndarray:
    dt = np.dtype(lane.dtype)
    lut = _node_mask_on_values(node, dict_array(lane.dict_values, dt))
    if stats is not None:
        stats.dict_rewrites += 1
    parts = []
    for pi in keep_pages:
        p = lane.pages[pi]
        ids = unpack_bits(_page_payload(lane, p), p.rows, p.width).astype(np.int64)
        parts.append(lut[ids])
    return np.concatenate(parts) if parts else np.empty(0, bool)
