"""Compaction-time downsample rollups (serving tier layer a).

Compaction is the ONE place that sees a segment's full, LWW-resolved,
tombstone-applied content (the merged table it is about to rewrite), so
it is the one place a pre-aggregated artifact can be emitted that is
EXACT by construction: late data has been merge-deduped, deletes have
been physically applied, and duplicate sequences resolved — nothing to
reconcile at query time.

Artifacts:
- one rollup SST per (segment, resolution) under ``{root}/rollup/{id}.sst``
  (a distinct artifact kind: its own prefix, never listed among the data
  SSTs — raw scans and the data orphan GC are oblivious);
- one JSON record per artifact under ``{root}/manifest/rollup/{id}``
  (storage/manifest) carrying the FRESHNESS CONTRACT: the exact source
  data-SST ids the rollup was derived from, and the tombstone ids whose
  masking it already includes.

Substitution (``plan_rollups``, consumed only by the planner choke point
in engine/data.py — jaxlint J013): a segment's raw scan may be replaced
by its rollup iff

1. the segment's CURRENT live SST set == the record's source set (any
   flush/backfill/compaction since the build changes the set — ids are
   never reused — so staleness is structurally impossible);
2. every live tombstone overlapping the segment is in the record's
   applied set (a delete issued after the build forces raw until the
   next compaction re-emits);
3. the retention floor does not cut into the segment (row-exact raw
   masking vs whole-bucket rollup rows would otherwise disagree);
4. the query grid is resolution-aligned: ``bucket_ms``, the grid anchor,
   and the range end are all multiples of the rollup resolution (every
   grid bucket is then an exact union of rollup buckets).

Rollup schema: the table's non-time primary keys (e.g. metric_id, tsid,
field_id) + ``ts`` (bucket start) + sum/count/min/max over the
configured value column. A 30-day range at step=1h reads ~720 rows per
series instead of every raw sample — the billion-point-query fix.
"""

from __future__ import annotations

import asyncio
import io
import json
import logging
import threading
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from horaedb_tpu.common import memtrace
from horaedb_tpu.common.bytebudget import GLOBAL_POOLS
from horaedb_tpu.common.error import HoraeError, ensure
from horaedb_tpu.storage.types import TimeRange

logger = logging.getLogger(__name__)

# decoded-rollup read cache: artifacts are immutable and bucket-count
# sized, so a small byte-bounded LRU makes repeat panel queries pure
# memory reads. Superseded artifacts evict via evict_rollup; the budget
# is configured at engine open ([metric_engine.serving] rollup_cache,
# 0 disables) like the tier's other two caches.
_CACHE: "OrderedDict[int, tuple[dict, int]]" = OrderedDict()
_CACHE_BYTES = 0
_CACHE_CAP = 16 * 1024 * 1024
_CACHE_LOCK = threading.Lock()


class _PoolView:
    """Module-level anchor for the unified pool registry's weakref
    provider (the cache itself is module globals, not an instance)."""


_POOL_VIEW = _PoolView()
GLOBAL_POOLS.register_provider(
    "rollup", _POOL_VIEW,
    lambda _v: (_CACHE_BYTES, len(_CACHE)),
)
GLOBAL_POOLS.set_capacity("rollup", _CACHE_CAP)


def configure_cache(capacity_bytes: int) -> None:
    """Size the decoded-artifact LRU (ServingTier does this at engine
    open); shrinking evicts oldest-first immediately."""
    global _CACHE_BYTES, _CACHE_CAP
    with _CACHE_LOCK:
        _CACHE_CAP = capacity_bytes
        while _CACHE_BYTES > _CACHE_CAP and _CACHE:
            _, (_l, nb) = _CACHE.popitem(last=False)
            _CACHE_BYTES -= nb
            GLOBAL_POOLS.note_eviction("rollup")
    GLOBAL_POOLS.set_capacity("rollup", capacity_bytes)

STAT_COLUMNS = ("sum", "count", "min", "max")


@dataclass(frozen=True)
class RollupRecord:
    """One rollup artifact's registry entry (JSON, manifest-level)."""

    id: int                 # record id (allocation-unique)
    resolution_ms: int
    segment_start: int
    sst_id: int             # the rollup/{id}.sst object
    num_rows: int
    size: int
    time_range: TimeRange
    source_sst_ids: tuple   # the data SSTs the rollup was derived from
    tombstone_ids: tuple    # tombstones already applied at build time

    def to_json(self) -> bytes:
        return json.dumps({
            "id": self.id,
            "resolution_ms": self.resolution_ms,
            "segment_start": self.segment_start,
            "sst_id": self.sst_id,
            "num_rows": self.num_rows,
            "size": self.size,
            "time_range": [self.time_range.start, self.time_range.end],
            "source_sst_ids": list(self.source_sst_ids),
            "tombstone_ids": list(self.tombstone_ids),
        }).encode()

    @classmethod
    def from_json(cls, data: bytes) -> "RollupRecord":
        try:
            d = json.loads(data.decode())
            return cls(
                id=int(d["id"]),
                resolution_ms=int(d["resolution_ms"]),
                segment_start=int(d["segment_start"]),
                sst_id=int(d["sst_id"]),
                num_rows=int(d["num_rows"]),
                size=int(d["size"]),
                time_range=TimeRange(*d["time_range"]),
                source_sst_ids=tuple(int(x) for x in d["source_sst_ids"]),
                tombstone_ids=tuple(int(x) for x in d["tombstone_ids"]),
            )
        except (ValueError, KeyError, TypeError) as e:
            raise HoraeError(f"corrupt rollup record: {e}") from e


def compute_rollup(
    table: pa.Table,
    group_columns: list[str],
    ts_column: str,
    value_column: str,
    resolution_ms: int,
) -> pa.Table:
    """Aggregate a pk-sorted merged table into per-(group, bucket)
    sum/count/min/max rows. The input MUST be the compaction merge
    output: sorted by (group_columns..., ts), already deduped and
    visibility-masked — every guarantee the freshness contract leans on.
    Output rows keep the group order, so rollup SSTs are pk-sorted under
    the same (group..., ts) key as the data table."""
    n = table.num_rows
    ensure(n > 0, "cannot roll up an empty table")
    ts = np.asarray(memtrace.tracked_combine(
        table.column(ts_column), "flush_encode"
    ).to_numpy(zero_copy_only=False), dtype=np.int64)
    bucket = ts - ts % resolution_ms
    vals = np.asarray(memtrace.tracked_combine(
        table.column(value_column), "flush_encode"
    ).to_numpy(zero_copy_only=False), dtype=np.float64)
    groups = [
        np.asarray(memtrace.tracked_combine(
            table.column(c), "flush_encode"
        ).to_numpy(zero_copy_only=False))
        for c in group_columns
    ]
    # boundaries where any group key or the bucket changes (input sorted)
    change = np.zeros(n, dtype=bool)
    change[0] = True
    if n > 1:
        acc = bucket[1:] != bucket[:-1]
        for g in groups:
            acc = acc | (g[1:] != g[:-1])
        change[1:] = acc
    starts = np.flatnonzero(change)
    counts = np.diff(np.append(starts, n))
    sums = np.add.reduceat(vals, starts)
    mins = np.minimum.reduceat(vals, starts)
    maxs = np.maximum.reduceat(vals, starts)
    cols = {c: g[starts] for c, g in zip(group_columns, groups)}
    cols[ts_column] = bucket[starts]
    cols["sum"] = sums
    cols["count"] = counts.astype(np.int64)
    cols["min"] = mins
    cols["max"] = maxs
    return pa.table(cols)


def encode_rollup(table: pa.Table) -> bytes:
    """One small parquet object per artifact (bucket-count scale — the
    streaming writer machinery would be overhead here)."""
    sink = io.BytesIO()
    pq.write_table(table, sink, compression="zstd")
    return sink.getvalue()


def decode_rollup(data: bytes) -> dict:
    """Rollup object -> numpy lane dict (what the planner folds)."""
    t = pq.read_table(io.BytesIO(data))
    return {
        name: memtrace.tracked_combine(t.column(name), "decode").to_numpy(
            zero_copy_only=False
        )
        for name in t.schema.names
    }


def aligned_resolutions(
    resolutions, t0: int, end: int, bucket_ms: int,
) -> list[int]:
    """Resolutions (coarsest first) an exact substitution can use for a
    grid anchored at `t0` with `bucket_ms` buckets clipped at `end`."""
    return sorted(
        (
            r for r in resolutions
            if r > 0 and bucket_ms % r == 0 and t0 % r == 0 and end % r == 0
        ),
        reverse=True,
    )


def plan_rollups(
    storage,
    segments: list,
    rng: TimeRange,
    t0: int,
    bucket_ms: int,
) -> dict:
    """segment_start -> usable RollupRecord (coarsest aligned resolution
    that passes the freshness contract); segments absent from the map
    scan raw. Pure in-memory planning — manifest state only, no IO.
    Consumed ONLY by the planner choke point (jaxlint J013)."""
    from horaedb_tpu.storage.types import Timestamp

    cfg = storage.rollup_config
    records = storage.manifest.rollup_records()
    if not records or not cfg.enabled:
        return {}
    usable_res = aligned_resolutions(
        cfg.resolutions, t0, rng.end, bucket_ms
    )
    if not usable_res:
        return {}
    seg_ms = storage.segment_duration_ms
    floor = storage.retention_floor()
    tombs = storage.manifest.all_tombstones()
    out = {}
    for seg in segments:
        seg_start = Timestamp(
            seg[0].meta.time_range.start
        ).truncate_by(seg_ms).value
        if floor is not None and floor > seg_start:
            continue  # retention cuts into the segment: raw is row-exact
        seg_range = TimeRange(seg_start, seg_start + seg_ms)
        live_ids = {
            s.id for s in storage.manifest.find_ssts(seg_range)
            if Timestamp(s.meta.time_range.start).truncate_by(seg_ms).value
            == seg_start
        }
        overlapping = {
            t.id for t in tombs if t.time_range.overlaps(seg_range)
        }
        for res in usable_res:
            rec = records.get((seg_start, res))
            if rec is None:
                continue
            if set(rec.source_sst_ids) != live_ids:
                continue  # data changed since the build: structurally stale
            if not overlapping <= set(rec.tombstone_ids):
                continue  # a newer delete is not reflected: raw until rebuilt
            out[seg_start] = rec
            break
    return out


async def read_rollup(storage, record: RollupRecord) -> dict:
    """Fetch + decode one rollup artifact (cached). Raises on a store
    failure — the planner degrades that segment to a raw scan."""
    global _CACHE_BYTES
    with _CACHE_LOCK:
        hit = _CACHE.get(record.sst_id)
        if hit is not None:
            _CACHE.move_to_end(record.sst_id)
            return hit[0]
    path = storage.sst_path_gen.generate_rollup(record.sst_id)
    data = await storage.store.get(path)
    # parquet decode is CPU-bound host work: off the event loop (J018)
    lanes = await asyncio.to_thread(decode_rollup, data)
    nbytes = sum(a.nbytes for a in lanes.values())
    with _CACHE_LOCK:
        if record.sst_id not in _CACHE and nbytes <= _CACHE_CAP // 4:
            _CACHE[record.sst_id] = (lanes, nbytes)
            _CACHE_BYTES += nbytes
            memtrace.track_bytes(nbytes, "rollup_fill", "view")
            while _CACHE_BYTES > _CACHE_CAP and _CACHE:
                _, (_l, nb) = _CACHE.popitem(last=False)
                _CACHE_BYTES -= nb
                GLOBAL_POOLS.note_eviction("rollup")
    return lanes


def evict_rollup(sst_id: int) -> None:
    """Eviction funnel for superseded/deleted artifacts."""
    global _CACHE_BYTES
    with _CACHE_LOCK:
        ent = _CACHE.pop(sst_id, None)
        if ent is not None:
            _CACHE_BYTES -= ent[1]
