"""The columnar storage engine.

Reference: src/columnar_storage/src/storage.rs. The trait boundary is
preserved (`ColumnarStorage { schema; write; scan; compact }`,
storage.rs:58-89) and the object layout is identical:

    {root}/manifest/snapshot          binary snapshot (manifest/encoding.py)
    {root}/manifest/delta/{id}        protobuf deltas
    {root}/data/{id}.sst              sorted parquet SSTs

Execution is TPU-shaped instead of DataFusion-shaped:
- write: per-batch primary-key sort runs as one XLA lexsort on device
  (replacing MemoryExec->SortExec, storage.rs:244-256), then parquet encode
  on host with sorting-columns metadata;
- scan: per-segment fused device pipeline (storage/read.py), segments
  unioned old->new (storage.rs:343-369);
- every write is one new sorted SST — no WAL, no memtable; the SST write is
  the durability event, then the manifest delta commits it (SURVEY §3.2).
"""

from __future__ import annotations

import asyncio
import io
import logging
from abc import ABC, abstractmethod
from typing import AsyncIterator

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from horaedb_tpu.common.error import HoraeError, context, ensure
from horaedb_tpu.objstore import ObjectStore
from horaedb_tpu.ops import sort as sort_ops
from horaedb_tpu.ops.blocks import arrow_column_to_numpy
from horaedb_tpu.storage.config import StorageConfig, UpdateMode, WriteConfig
from horaedb_tpu.storage.manifest import Manifest
from horaedb_tpu.storage.read import (
    CompactRequest,
    ParquetReader,
    ScanRequest,
    WriteRequest,
)
from horaedb_tpu.storage.sst import FileMeta, SstFile, SstPathGenerator, allocate_id
from horaedb_tpu.storage.types import (
    StorageSchema,
    TimeRange,
    Timestamp,
    WriteResult,
)

logger = logging.getLogger(__name__)


def jax_backend_is_cpu() -> bool:
    import jax

    try:
        return jax.default_backend() == "cpu"
    except Exception:  # noqa: BLE001 — no backend at all: treat as host
        return True


def _is_pk_sorted(keys: list[np.ndarray]) -> bool:
    """O(n) vectorized check that rows are lexicographically nondecreasing
    over `keys` (most-significant first)."""
    n = len(keys[0])
    if n <= 1:
        return True
    decided_lt = np.zeros(n - 1, dtype=bool)
    for k in keys:
        a, b = k[:-1], k[1:]
        gt = (a > b) & ~decided_lt
        if gt.any():
            return False
        decided_lt |= a < b
    return True


class ColumnarStorage(ABC):
    """The storage-engine interface (storage.rs:77-87). The output stream of
    `scan` is sorted by primary keys, old segments before new ones."""

    @property
    @abstractmethod
    def schema(self) -> StorageSchema: ...

    @abstractmethod
    async def write(self, req: WriteRequest) -> None: ...

    @abstractmethod
    def scan(self, req: ScanRequest) -> AsyncIterator[pa.RecordBatch]: ...

    @abstractmethod
    async def compact(self, req: CompactRequest) -> None: ...


class ObjectBasedStorage(ColumnarStorage):
    """Object-store-backed engine (storage.rs ObjectBasedStorage)."""

    def __init__(self) -> None:
        raise HoraeError("use ObjectBasedStorage.try_new")

    @classmethod
    async def try_new(
        cls,
        root: str,
        store: ObjectStore,
        arrow_schema: pa.Schema,
        num_primary_keys: int,
        segment_duration_ms: int,
        config: StorageConfig | None = None,
        enable_compaction_scheduler: bool = True,
        start_background_merger: bool = True,
    ) -> "ObjectBasedStorage":
        self = object.__new__(cls)
        config = config or StorageConfig()
        self._root = root.strip("/")
        self._store = store
        self._config = config
        self._segment_duration = segment_duration_ms
        self._schema = StorageSchema.try_new(
            arrow_schema, num_primary_keys, config.update_mode
        )
        self._manifest = await Manifest.try_new(
            self._root,
            store,
            config.manifest,
            start_background_merger=start_background_merger,
        )
        self._path_gen = SstPathGenerator(self._root)
        self._reader = ParquetReader(
            store, self._path_gen, self._schema,
            scan_block_rows=config.scan_block_rows,
        )
        self._scheduler = None
        if enable_compaction_scheduler:
            # imported lazily: compaction depends on this module's writer
            from horaedb_tpu.storage.compaction.scheduler import CompactionScheduler

            self._scheduler = CompactionScheduler(
                storage=self,
                manifest=self._manifest,
                config=config.scheduler,
                segment_duration_ms=segment_duration_ms,
            )
            self._scheduler.start()
        return self

    async def close(self) -> None:
        if self._scheduler is not None:
            await self._scheduler.close()
        await self._manifest.close()

    # -- accessors ----------------------------------------------------------
    @property
    def schema(self) -> StorageSchema:
        return self._schema

    @property
    def manifest(self) -> Manifest:
        return self._manifest

    @property
    def parquet_reader(self) -> ParquetReader:
        return self._reader

    @property
    def segment_duration_ms(self) -> int:
        return self._segment_duration

    # -- write path (storage.rs:189-333) ------------------------------------
    async def write(self, req: WriteRequest) -> None:
        if req.enable_check:
            start_seg = Timestamp(req.time_range.start).truncate_by(self._segment_duration)
            end_seg = Timestamp(req.time_range.end - 1).truncate_by(self._segment_duration)
            ensure(
                start_seg == end_seg,
                f"time range of one write must fall in one segment, "
                f"range: [{req.time_range.start}, {req.time_range.end})",
            )
        result = await self.write_batch(req.batch)
        meta = FileMeta(
            max_sequence=result.seq,
            num_rows=req.batch.num_rows,
            size=result.size,
            time_range=req.time_range,
        )
        await self._manifest.add_file(result.id, meta)

    async def write_batch(self, batch: pa.RecordBatch) -> WriteResult:
        file_id = allocate_id()
        sorted_batch = await asyncio.to_thread(self._sort_batch, batch)
        # file ids are increasing, so the id doubles as the sequence
        with_builtin = self._schema.fill_builtin_columns(sorted_batch, file_id)
        table = pa.Table.from_batches([with_builtin])
        size = await self.write_sst(file_id, table)
        return WriteResult(id=file_id, seq=file_id, size=size)

    def _sort_batch(self, batch: pa.RecordBatch) -> pa.RecordBatch:
        """Primary-key sort on device (replaces SortExec, storage.rs:244-256).

        The permutation is computed over the numeric pk lanes with one XLA
        lexsort; the gather applies to all columns via pyarrow take so binary
        payloads never touch the device. Schemas with binary primary keys
        sort on host via arrow compute (the device path needs numeric lanes).
        """
        if batch.num_rows <= 1:
            return batch
        pk_names = self._schema.primary_key_names
        pk_types = [batch.schema.field(n).type for n in pk_names]
        if any(
            pa.types.is_binary(t) or pa.types.is_large_binary(t) or pa.types.is_string(t)
            for t in pk_types
        ):
            import pyarrow.compute as pc

            perm = pc.sort_indices(
                pa.Table.from_batches([batch]),
                sort_keys=[(n, "ascending") for n in pk_names],
            )
            return batch.take(perm)
        keys = [
            np.asarray(arrow_column_to_numpy(batch.column(batch.schema.names.index(name))))
            for name in pk_names
        ]
        if _is_pk_sorted(keys):
            # presorted batches (e.g. the metric engine's series-ordered
            # ingest flush) skip the sort entirely; the O(n) check costs a
            # few vector compares
            return batch
        if jax_backend_is_cpu():
            # np.lexsort beats XLA's CPU sort ~2x; the device path only pays
            # off on real accelerators
            perm = np.lexsort(tuple(reversed(keys)))
        else:
            perm = np.asarray(sort_ops.sort_permutation(keys))
        return batch.take(pa.array(perm))

    async def write_sst(self, file_id: int, table: pa.Table) -> int:
        """Encode a (sorted, builtin-filled) table as one parquet SST and put
        it to the object store; returns the object size."""
        path = self._path_gen.generate(file_id)
        cfg = self._config.write

        def _encode() -> bytes:
            sink = io.BytesIO()
            sorting = [
                pq.SortingColumn(i)
                for i in range(self._schema.num_primary_keys)
            ] + [pq.SortingColumn(self._schema.seq_idx)]
            writer = pq.ParquetWriter(
                sink,
                table.schema,
                compression=cfg.compression.value if cfg.compression.value != "none" else "NONE",
                use_dictionary=cfg.enable_dict,
                write_statistics=True,
                sorting_columns=sorting if cfg.enable_sorting_columns else None,
            )
            for start in range(0, table.num_rows, cfg.max_row_group_size):
                writer.write_table(
                    table.slice(start, cfg.max_row_group_size),
                    row_group_size=cfg.max_row_group_size,
                )
            writer.close()
            return sink.getvalue()

        data = await asyncio.to_thread(_encode)
        # The manifest wire format carries size/num_rows as u32 (sst.proto,
        # encoding.py); reject before paying the upload so an unregistrable
        # SST is never orphaned in the store.
        ensure(len(data) < 2**32, f"sst too large for manifest format: {len(data)}")
        ensure(table.num_rows < 2**32, f"sst row count too large: {table.num_rows}")
        with context(f"write sst {path}"):
            await self._store.put(path, data)
        return len(data)

    # -- scan path (storage.rs:335-370) --------------------------------------
    async def scan(self, req: ScanRequest) -> AsyncIterator[pa.RecordBatch]:
        ssts = self._manifest.find_ssts(req.range)
        if not ssts:
            return
        for segment_ssts in self.group_by_segment(ssts):
            batches = await self._reader.scan_segment(
                segment_ssts,
                predicate=req.predicate,
                projections=req.projections,
                keep_builtin=False,
            )
            for b in batches:
                yield b

    def group_by_segment(self, ssts: list[SstFile]) -> list[list[SstFile]]:
        """Bucket SSTs by segment start, ordered old->new (storage.rs:343-345)."""
        buckets: dict[int, list[SstFile]] = {}
        for s in ssts:
            seg = Timestamp(s.meta.time_range.start).truncate_by(self._segment_duration)
            buckets.setdefault(seg.value, []).append(s)
        return [buckets[k] for k in sorted(buckets)]

    # -- compaction (storage.rs:372-374) --------------------------------------
    async def compact(self, req: CompactRequest) -> None:
        ensure(self._scheduler is not None, "compaction scheduler disabled")
        self._scheduler.trigger_compaction()

    @property
    def compaction_scheduler(self):
        return self._scheduler
