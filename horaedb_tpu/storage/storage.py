"""The columnar storage engine.

Reference: src/columnar_storage/src/storage.rs. The trait boundary is
preserved (`ColumnarStorage { schema; write; scan; compact }`,
storage.rs:58-89) and the object layout is identical:

    {root}/manifest/snapshot          binary snapshot (manifest/encoding.py)
    {root}/manifest/delta/{id}        protobuf deltas
    {root}/data/{id}.sst              sorted parquet SSTs

Execution is TPU-shaped instead of DataFusion-shaped:
- write: per-batch primary-key sort runs as one XLA lexsort on device
  (replacing MemoryExec->SortExec, storage.rs:244-256), then parquet encode
  on host with sorting-columns metadata;
- scan: per-segment fused device pipeline (storage/read.py), segments
  unioned old->new (storage.rs:343-369);
- every write is one new sorted SST — no WAL, no memtable; the SST write is
  the durability event, then the manifest delta commits it (SURVEY §3.2).
"""

from __future__ import annotations

import asyncio
import io
import logging
import time
from abc import ABC, abstractmethod
from typing import AsyncIterator

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from horaedb_tpu.common import memtrace, tracing
from horaedb_tpu.common.error import HoraeError, context, ensure
from horaedb_tpu.objstore import ObjectStore
from horaedb_tpu.ops import sort as sort_ops
from horaedb_tpu.ops.blocks import arrow_column_to_numpy
from horaedb_tpu.server.metrics import BYTES_BUCKETS, GLOBAL_METRICS
from horaedb_tpu.storage import scanstats
from horaedb_tpu.storage.config import StorageConfig
from horaedb_tpu.storage.manifest import Manifest
from horaedb_tpu.storage.read import (
    CompactRequest,
    ParquetReader,
    ScanRequest,
    WriteRequest,
)
from horaedb_tpu.storage.sst import FileMeta, SstFile, SstPathGenerator, allocate_id
from horaedb_tpu.storage.types import StorageSchema, Timestamp, WriteResult

logger = logging.getLogger(__name__)

WRITE_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_storage_write_seconds",
    help="One storage write (sort + parquet encode + upload + manifest "
         "commit), by table root.",
    labelnames=("table",),
)
WRITE_ROWS = GLOBAL_METRICS.counter(
    "horaedb_storage_write_rows_total",
    help="Rows written to durable SSTs, by table root.",
    labelnames=("table",),
)
SST_BYTES = GLOBAL_METRICS.histogram(
    "horaedb_sst_bytes",
    help="Encoded size of SST objects written (flush shards, compaction "
         "outputs, direct writes).",
    buckets=BYTES_BUCKETS,
)
SCAN_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_storage_scan_seconds",
    help="One storage scan, first SST lookup to last batch yielded (early "
         "consumer breaks count as completed scans), by table root.",
    labelnames=("table",),
)
# Shared with engine/flush_executor.py (registry is idempotent by name):
# flush-profile SST writes attribute their encode vs upload cost here; the
# drain stage is observed at the memtable seal/sort.
FLUSH_STAGE_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_flush_stage_seconds",
    help="Per-stage flush cost: drain (memtable -> pk-sorted column "
         "lanes), encode (parquet), upload (object-store PUT).",
    labelnames=("table", "stage"),
)
ORPHAN_SSTS_GC = GLOBAL_METRICS.counter(
    "horaedb_orphan_ssts_gc_total",
    help="Orphan SST objects (uploaded but never manifest-committed — a "
         "crash between upload and commit) reclaimed at storage open.",
    labelnames=("table",),
)


def jax_backend_is_cpu() -> bool:
    import jax

    try:
        return jax.default_backend() == "cpu"
    except Exception:  # noqa: BLE001 — no backend at all: treat as host
        return True


def _is_pk_sorted(keys: list[np.ndarray]) -> bool:
    """O(n) vectorized check that rows are lexicographically nondecreasing
    over `keys` (most-significant first)."""
    n = len(keys[0])
    if n <= 1:
        return True
    decided_lt = np.zeros(n - 1, dtype=bool)
    for k in keys:
        a, b = k[:-1], k[1:]
        gt = (a > b) & ~decided_lt
        if gt.any():
            return False
        decided_lt |= a < b
    return True


class ColumnarStorage(ABC):
    """The storage-engine interface (storage.rs:77-87). The output stream of
    `scan` is sorted by primary keys, old segments before new ones."""

    @property
    @abstractmethod
    def schema(self) -> StorageSchema: ...

    @abstractmethod
    async def write(self, req: WriteRequest) -> None: ...

    @abstractmethod
    def scan(self, req: ScanRequest) -> AsyncIterator[pa.RecordBatch]: ...

    @abstractmethod
    async def compact(self, req: CompactRequest) -> None: ...


class ObjectBasedStorage(ColumnarStorage):
    """Object-store-backed engine (storage.rs ObjectBasedStorage)."""

    def __init__(self) -> None:
        raise HoraeError("use ObjectBasedStorage.try_new")

    @classmethod
    async def try_new(
        cls,
        root: str,
        store: ObjectStore,
        arrow_schema: pa.Schema,
        num_primary_keys: int,
        segment_duration_ms: int,
        config: StorageConfig | None = None,
        enable_compaction_scheduler: bool = True,
        start_background_merger: bool = True,
        sst_executor=None,
        manifest_executor=None,
        fence_node_id: str | None = None,
        fence_validate_interval_s: float = 5.0,
        fence=None,
        gc_orphans: bool = True,
        time_column: str | None = None,
        read_only: bool = False,
    ) -> "ObjectBasedStorage":
        """`sst_executor` / `manifest_executor`: optional
        concurrent.futures.Executors for CPU-heavy SST work (sort, parquet
        encode, bloom build) and manifest snapshot folds. Sized from the
        server's ThreadConfig (the analog of the reference's dedicated
        runtimes, main.rs:102-119); None = default pool / inline.

        `fence_node_id`: when set, acquire an EpochFence on `root` before
        opening — this process claims exclusive write ownership of the
        region (storage/fence.py); a later claimant deposes it and its
        writes fail with FencedError. The reference gets single-writer by
        construction (types.rs:135); a shared store needs it enforced.
        `fence`: share an already-acquired EpochFence instead (one claim
        covering several tables under one ownership root — the metric
        engine's six tables fence as one region).

        `time_column`: the schema column holding the row's timestamp
        (epoch ms), enabling ROW-exact retention masking and time-range
        tombstone deletes (storage/visibility.py). None = retention only
        prunes/expires whole SSTs (manifest time ranges) and
        `delete_rows` is unavailable.

        `read_only`: cluster replica mode (horaedb_tpu/cluster) — a VIEW
        over a root another writer process owns on the shared store. No
        fence, no compaction scheduler, no orphan GC, no background
        merger; the manifest loads via the in-memory delta fold and every
        write/delete raises. Scans work unchanged."""
        self = object.__new__(cls)
        if read_only:
            # a replica must never mutate the owner's root: every write
            # path below is gated, and the store-touching open-time
            # maintenance (GC, snapshot folds, compaction) is disabled
            enable_compaction_scheduler = False
            start_background_merger = False
            gc_orphans = False
            fence_node_id = None
            fence = None
        config = config or StorageConfig()
        self._root = root.strip("/")
        self._store = store
        self._config = config
        self._read_only = read_only
        self._time_column = time_column
        if time_column is not None:
            ensure(
                time_column in arrow_schema.names,
                f"time_column {time_column!r} not in schema",
            )
            # pre-register the tombstone family children so /metrics shows
            # the zero state from boot (the PR2 convention)
            from horaedb_tpu.storage.visibility import TOMBSTONES_APPLIED

            for ctx in ("scan", "compact"):
                TOMBSTONES_APPLIED.labels(self._root, ctx)
        self._sst_executor = sst_executor
        self._segment_duration = segment_duration_ms
        # file_id -> (format_version, encodings) of a just-written SST,
        # consumed by the FileMeta construction site (write / compaction)
        self._pending_enc: dict[int, tuple] = {}
        self._schema = StorageSchema.try_new(
            arrow_schema, num_primary_keys, config.update_mode
        )
        self._fence = fence
        if fence is None and fence_node_id is not None:
            from horaedb_tpu.storage.fence import EpochFence

            self._fence = await EpochFence.acquire(
                store, self._root, fence_node_id,
                validate_interval_s=fence_validate_interval_s,
            )
        self._manifest = await Manifest.try_new(
            self._root,
            store,
            config.manifest,
            start_background_merger=start_background_merger,
            executor=manifest_executor,
            fence=self._fence,
            read_only=read_only,
        )
        # Startup id-collision guard: never allocate at or below an id the
        # manifest already holds (clock moved backwards across restarts, or
        # ids minted by another process against this store root).
        existing = self._manifest.all_ssts()
        if existing:
            from horaedb_tpu.storage.sst import ensure_id_above

            ensure_id_above(max(s.id for s in existing))
        self._path_gen = SstPathGenerator(self._root)
        if gc_orphans:
            # crash recovery: a writer that died between SST upload and
            # manifest commit left data objects nothing references — safe
            # to reclaim here because the manifest bootstrap above already
            # folded every committed delta, and single-writer ownership
            # (by construction or epoch fence) means no concurrent
            # uploader exists at open
            await self._gc_orphan_ssts()
            # rollup artifacts live under their own prefix with their own
            # registry (manifest/rollup records) — reclaim objects a crash
            # stranded between the artifact PUT and the record PUT
            await self._gc_orphan_rollups()
        self._reader = ParquetReader(
            store, self._path_gen, self._schema,
            scan_block_rows=config.scan_block_rows,
            scan_cache_bytes=config.scan_cache.as_bytes(),
            enc_cache_bytes=config.encoding.sidecar_cache.as_bytes(),
        )
        # EVERY SST read (materializing scan, chunked scan, downsample
        # pushdown, compaction) funnels through the shared visibility mask
        # (storage/visibility.py) via this provider — the single place
        # tombstone/retention filtering happens (jaxlint J010)
        self._reader.visibility_provider = self.visibility
        self._scheduler = None
        if enable_compaction_scheduler:
            # imported lazily: compaction depends on this module's writer
            from horaedb_tpu.storage.compaction.scheduler import CompactionScheduler

            self._scheduler = CompactionScheduler(
                storage=self,
                manifest=self._manifest,
                config=config.scheduler,
                segment_duration_ms=segment_duration_ms,
            )
            self._scheduler.start()
        return self

    async def close(self) -> None:
        if self._scheduler is not None:
            await self._scheduler.close()
        await self._manifest.close()

    async def _gc_orphan_ssts(self) -> None:
        """Reclaim data objects the manifest does not reference (crash
        between upload and commit, or a bloom-failure cleanup that itself
        failed). Best-effort: a faulty store at open degrades to a log
        line, never a failed boot — the orphans cost capacity, not
        correctness, and the next open retries. Orphan ids also raise the
        id-allocation floor so a fresh write can never mint an id whose
        `.sst` path is already occupied by a dead object."""
        from horaedb_tpu.storage.sst import ensure_id_above

        try:
            metas = await self._store.list(f"{self._root}/data")
        except Exception as e:  # noqa: BLE001 — GC is best-effort at open
            logger.warning("orphan sst gc skipped (list failed): %s", e)
            return
        live = {s.id for s in self._manifest.all_ssts()}
        by_id: dict[int, list[str]] = {}
        for m in metas:
            name = m.path.rsplit("/", 1)[-1]
            stem, _, ext = name.partition(".")
            if ext not in ("sst", "bloom", "enc") or not stem.isdigit():
                continue
            fid = int(stem)
            if fid in live:
                continue
            by_id.setdefault(fid, []).append(m.path)
        if not by_id:
            return
        ensure_id_above(max(by_id))
        paths = [p for ps in by_id.values() for p in ps]
        results = await asyncio.gather(
            *(self._store.delete(p) for p in paths), return_exceptions=True
        )
        failed = [
            p for p, r in zip(paths, results) if isinstance(r, BaseException)
        ]
        for p in failed:
            logger.warning("orphan sst gc: failed to delete %s", p)
        # count only FULLY reclaimed orphans: a failed delete stays behind
        # for the next open to retry, and counting it now would double-count
        # it then (and lie to the runbook watching this family)
        failed_ids = {
            int(p.rsplit("/", 1)[-1].partition(".")[0]) for p in failed
        }
        ORPHAN_SSTS_GC.labels(self._root).inc(len(by_id) - len(
            failed_ids & set(by_id)
        ))
        logger.info(
            "orphan sst gc: root=%s orphans=%d objects=%d (failed=%d)",
            self._root, len(by_id), len(paths), len(failed),
        )

    async def _gc_orphan_rollups(self) -> None:
        """Reclaim rollup objects no record references (crash between the
        artifact PUT and its record PUT, or a failed supersede-delete).
        Best-effort like the data orphan GC; ids raise the allocation
        floor for the same reason."""
        from horaedb_tpu.objstore import NotFound
        from horaedb_tpu.storage.sst import ensure_id_above

        try:
            metas = await self._store.list(f"{self._root}/rollup")
        except NotFound:
            return
        except Exception as e:  # noqa: BLE001 — GC is best-effort at open
            logger.warning("rollup orphan gc skipped (list failed): %s", e)
            return
        live = self._manifest.referenced_rollup_sst_ids()
        orphans = []
        for m in metas:
            name = m.path.rsplit("/", 1)[-1]
            stem, _, ext = name.partition(".")
            if ext != "sst" or not stem.isdigit():
                continue
            if int(stem) not in live:
                orphans.append((int(stem), m.path))
        if not orphans:
            return
        ensure_id_above(max(i for i, _ in orphans))
        results = await asyncio.gather(
            *(self._store.delete(p) for _i, p in orphans),
            return_exceptions=True,
        )
        failed = sum(1 for r in results if isinstance(r, BaseException))
        logger.info(
            "rollup orphan gc: root=%s orphans=%d (failed=%d)",
            self._root, len(orphans), failed,
        )

    def _ensure_writable(self, what: str) -> None:
        if self._read_only:
            from horaedb_tpu.common.error import ReplicaReadOnlyError

            raise ReplicaReadOnlyError(
                f"storage {self._root} is a read-only replica view; "
                f"refusing {what} (route the mutation to the owning writer)"
            )

    # -- accessors ----------------------------------------------------------
    @property
    def read_only(self) -> bool:
        return self._read_only

    def manifest_epoch(self) -> int:
        """The manifest's monotonic epoch (Manifest.epoch) — the number
        the cluster staleness token and /api/v1/cluster/status compare
        between writer and replicas."""
        return self._manifest.epoch()

    @property
    def schema(self) -> StorageSchema:
        return self._schema

    @property
    def manifest(self) -> Manifest:
        return self._manifest

    @property
    def parquet_reader(self) -> ParquetReader:
        return self._reader

    @property
    def segment_duration_ms(self) -> int:
        return self._segment_duration

    @property
    def time_column(self) -> str | None:
        return self._time_column

    @property
    def store(self) -> ObjectStore:
        return self._store

    @property
    def sst_path_gen(self) -> SstPathGenerator:
        return self._path_gen

    @property
    def rollup_config(self):
        """Rollup emission/substitution knobs (storage/rollup.py)."""
        return self._config.rollup

    # -- visibility: retention + tombstone deletes (storage/visibility.py) --
    def retention_floor(self) -> int | None:
        """Rows/SSTs older than this are out of retention. Single source of
        truth is the compaction scheduler's TTL, so scan-time masking and
        compaction-time expiry can never disagree."""
        ttl = self._config.scheduler.ttl
        if ttl is None:
            return None
        from horaedb_tpu.common.time_ext import now_ms

        return now_ms() - ttl.as_millis()

    def visibility(self):
        """Current Visibility for this table's reads, or None (the common
        fast path: nothing subtractive is configured)."""
        tombs = self._manifest.all_tombstones()
        floor = self.retention_floor() if self._time_column else None
        if not tombs and floor is None:
            return None
        from horaedb_tpu.storage.visibility import Visibility

        return Visibility(
            table=self._root,
            time_column=self._time_column,
            tombstones=tuple(tombs),
            retention_floor_ms=floor,
        )

    def select_ssts(self, time_range: TimeRange) -> list[SstFile]:
        """Manifest overlap selection + retention pruning: SSTs wholly
        older than the retention floor never cost IO even before the
        compaction picker expires them. EXPLAIN provenance:
        `ssts_retention_pruned` counts what the horizon removed here."""
        ssts = self._manifest.find_ssts(time_range)
        floor = self.retention_floor()
        if floor is not None:
            kept = [s for s in ssts if s.meta.time_range.end >= floor]
            pruned = len(ssts) - len(kept)
            if pruned:
                scanstats.note("ssts_retention_pruned", pruned)
            ssts = kept
        return ssts

    async def delete_rows(
        self,
        time_range: TimeRange,
        matchers: "tuple[tuple[str, tuple[int, ...] | None], ...]",
    ):
        """Create + persist one tombstone delete record: rows matching
        every matcher inside `time_range` whose `__seq__` predates this
        call become invisible to scans NOW and are physically removed when
        compaction rewrites their SSTs. Returns the Tombstone.

        The sequence is allocated HERE, from the same monotonic allocator
        as write sequences — every row acked (sealed/written) before this
        call has a smaller seq and is therefore covered; rows written
        after it survive (re-ingest into a deleted range works)."""
        self._ensure_writable("delete_rows")
        ensure(
            self._time_column is not None,
            "delete_rows requires a table with a time_column",
        )
        from horaedb_tpu.storage.visibility import Tombstone

        for col, _vals in matchers:
            ensure(
                col in self._schema.arrow_schema.names,
                f"tombstone matcher column {col!r} not in schema",
            )
        rid = allocate_id()
        tomb = Tombstone(
            id=rid, seq=rid, time_range=time_range, matchers=tuple(matchers)
        )
        await self._manifest.add_tombstone(tomb)
        # serving-tier invalidation funnel (jaxlint J013): the new
        # tombstone id changes the visibility epoch in every cache key
        # covering this range; purge the table's entries eagerly too
        from horaedb_tpu.serving.cache import RESULT_CACHE

        RESULT_CACHE.serving_invalidate(self._root, "delete", time_range)
        logger.info(
            "tombstone created: root=%s id=%d range=[%d,%d) matchers=%s",
            self._root, rid, time_range.start, time_range.end, matchers,
        )
        return tomb

    # -- write path (storage.rs:189-333) ------------------------------------
    async def write(self, req: WriteRequest) -> None:
        self._ensure_writable("write")
        if self._fence is not None:
            # reject BEFORE the encode+upload: the manifest update would
            # fence anyway, but by then a deposed writer has already PUT a
            # full SST object nobody will ever reference (no orphan GC)
            await self._fence.ensure_valid()
        if req.enable_check:
            start_seg = Timestamp(req.time_range.start).truncate_by(self._segment_duration)
            end_seg = Timestamp(req.time_range.end - 1).truncate_by(self._segment_duration)
            ensure(
                start_seg == end_seg,
                f"time range of one write must fall in one segment, "
                f"range: [{req.time_range.start}, {req.time_range.end})",
            )
        with tracing.span("storage_write", table=self._root,
                          rows=req.batch.num_rows), \
                WRITE_SECONDS.labels(self._root).time():
            result = await self.write_batch(
                req.batch, presorted=req.presorted, seq=req.seq,
                fast_encode=req.fast_encode,
            )
            fmt, encodings = self.pop_enc_meta(result.id)
            meta = FileMeta(
                max_sequence=result.seq,
                num_rows=req.batch.num_rows,
                size=result.size,
                time_range=req.time_range,
                format_version=fmt,
                encodings=encodings,
            )
            await self._manifest.add_file(result.id, meta)
        # serving-tier invalidation funnel (jaxlint J013): a committed SST
        # changes the table's sealed set — cached results for it are dead
        from horaedb_tpu.serving.cache import RESULT_CACHE

        RESULT_CACHE.serving_invalidate(self._root, "flush", req.time_range)
        WRITE_ROWS.labels(self._root).inc(req.batch.num_rows)

    async def _run_sst(self, fn, *args):
        """Run CPU-heavy SST work on the configured executor (ThreadConfig
        sizing) or the default thread pool."""
        if self._sst_executor is None:
            return await asyncio.to_thread(fn, *args)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._sst_executor, lambda: fn(*args)
        )

    async def write_batch(
        self,
        batch: pa.RecordBatch,
        presorted: bool = False,
        seq: int | None = None,
        fast_encode: bool = False,
    ) -> WriteResult:
        file_id = allocate_id()
        if presorted:
            sorted_batch = batch
        else:
            sorted_batch = await self._run_sst(self._sort_batch, batch)
        # file ids are increasing, so the id doubles as the sequence unless
        # the caller pinned one at snapshot time (same allocator, so the
        # combined seq stream stays monotonic with unbuffered writes)
        if seq is None:
            seq = file_id
        with_builtin = self._schema.fill_builtin_columns(sorted_batch, seq)
        table = pa.Table.from_batches([with_builtin])
        size = await self.write_sst(file_id, table, fast_encode=fast_encode)
        return WriteResult(id=file_id, seq=seq, size=size)

    def _sort_batch(self, batch: pa.RecordBatch) -> pa.RecordBatch:
        """Primary-key sort on device (replaces SortExec, storage.rs:244-256).

        The permutation is computed over the numeric pk lanes with one XLA
        lexsort; the gather applies to all columns via pyarrow take so binary
        payloads never touch the device. Schemas with binary primary keys
        sort on host via arrow compute (the device path needs numeric lanes).
        """
        if batch.num_rows <= 1:
            return batch
        pk_names = self._schema.primary_key_names
        pk_types = [batch.schema.field(n).type for n in pk_names]
        if any(
            pa.types.is_binary(t) or pa.types.is_large_binary(t) or pa.types.is_string(t)
            for t in pk_types
        ):
            import pyarrow.compute as pc

            perm = pc.sort_indices(
                pa.Table.from_batches([batch]),
                sort_keys=[(n, "ascending") for n in pk_names],
            )
            return batch.take(perm)
        keys = [
            np.asarray(arrow_column_to_numpy(batch.column(batch.schema.names.index(name))))
            for name in pk_names
        ]
        if _is_pk_sorted(keys):
            # presorted batches (e.g. the metric engine's series-ordered
            # ingest flush) skip the sort entirely; the O(n) check costs a
            # few vector compares
            return batch
        if jax_backend_is_cpu():
            # np.lexsort beats XLA's CPU sort ~2x; the device path only pays
            # off on real accelerators
            perm = np.lexsort(tuple(reversed(keys)))
        else:
            perm = np.asarray(sort_ops.sort_permutation(keys))
        return batch.take(pa.array(perm))

    def _writer_kwargs(self, fast: bool = False) -> dict:
        """ParquetWriter options from WriteConfig, per-column overrides
        applied (the analog of build_write_props, storage.rs:258-298).

        `fast=True` = the ingest-flush L0 profile: snappy + plain encodings
        (measured ~2x the encode rate of zstd+BYTE_STREAM_SPLIT at ~1.7x
        output bytes). Statistics and sorting columns are preserved — the
        read path's row-group pruning and presorted fast path see no
        difference; compaction re-encodes outputs with the tuned profile."""
        cfg = self._config.write
        if fast and cfg.flush_fast_encode:
            sorting = [
                pq.SortingColumn(i) for i in range(self._schema.num_primary_keys)
            ] + [pq.SortingColumn(self._schema.seq_idx)]
            return dict(
                compression="SNAPPY",
                use_dictionary=False,
                write_statistics=True,
                write_batch_size=cfg.write_batch_size,
                sorting_columns=sorting if cfg.enable_sorting_columns else None,
            )
        names = self._schema.arrow_schema.names
        col_opts = cfg.column_options or {}

        def opt(n: str, attr: str):
            per = col_opts.get(n)
            return getattr(per, attr, None) if per is not None else None

        # dictionary: global bool, upgraded to a column list when any
        # per-column override exists
        if any(opt(n, "enable_dict") is not None for n in names):
            use_dictionary: bool | list = [
                n for n in names
                if (opt(n, "enable_dict")
                    if opt(n, "enable_dict") is not None else cfg.enable_dict)
            ]
        else:
            use_dictionary = cfg.enable_dict
        global_comp = cfg.compression.value if cfg.compression.value != "none" else "NONE"
        if any(opt(n, "compression") for n in names):
            compression: str | dict = {
                n: (opt(n, "compression") or global_comp) for n in names
            }
        else:
            compression = global_comp
        column_encoding = {
            n: opt(n, "encoding") for n in names if opt(n, "encoding")
        }
        # type-driven defaults for columns with NO explicit override and no
        # dictionary page: DELTA_BINARY_PACKED on integer/timestamp lanes,
        # BYTE_STREAM_SPLIT on float lanes (measured 8.1 B/row vs 13.1
        # plain on the bench write shape — the ingest copy-tax pin in
        # tools/mem_smoke.py gates the ratio). Skipped entirely when
        # dictionary encoding is globally ON (parquet forbids mixing
        # column_encoding with a dictionary-encoded column).
        if use_dictionary is not True:
            dict_cols = set(use_dictionary) if isinstance(
                use_dictionary, list) else set()
            for n in names:
                if n in column_encoding or n in dict_cols:
                    continue
                t = self._schema.arrow_schema.field(n).type
                if pa.types.is_integer(t) or pa.types.is_timestamp(t):
                    column_encoding[n] = "DELTA_BINARY_PACKED"
                elif pa.types.is_floating(t):
                    column_encoding[n] = "BYTE_STREAM_SPLIT"
        column_encoding = column_encoding or None
        sorting = [
            pq.SortingColumn(i) for i in range(self._schema.num_primary_keys)
        ] + [pq.SortingColumn(self._schema.seq_idx)]
        return dict(
            compression=compression,
            use_dictionary=use_dictionary,
            write_statistics=True,
            write_batch_size=cfg.write_batch_size,
            column_encoding=column_encoding,
            sorting_columns=sorting if cfg.enable_sorting_columns else None,
        )

    def _bloom_columns(self) -> list[str]:
        """Columns with bloom filters enabled (global flag or per-column).
        Builtin columns never get blooms — equality probes on them make no
        sense and `__reserved__` is null-filled."""
        from horaedb_tpu.storage.types import RESERVED_COLUMN_NAME, SEQ_COLUMN_NAME

        cfg = self._config.write
        col_opts = cfg.column_options or {}
        out = []
        for n in self._schema.arrow_schema.names:
            if n in (SEQ_COLUMN_NAME, RESERVED_COLUMN_NAME):
                continue
            per = getattr(col_opts.get(n), "enable_bloom_filter", None) if n in col_opts else None
            if per is True or (per is None and cfg.enable_bloom_filter):
                out.append(n)
        return out

    async def write_sst(
        self, file_id: int, table: pa.Table, fast_encode: bool = False
    ) -> int:
        """Encode a (sorted, builtin-filled) table as one parquet SST,
        STREAMED to the object store at chunk granularity — host memory
        stays O(row group + chunk), not O(table), matching the reference's
        AsyncArrowWriter streaming (storage.rs:192-224). Returns object size.

        When bloom filters are enabled, a sidecar `{id}.bloom` lands after
        the SST but before the file is registrable in the manifest, so
        readers never observe a registered SST without its sidecar."""
        import queue as _queue
        import threading as _threading

        path = self._path_gen.generate(file_id)
        cfg = self._config.write
        # The manifest wire format carries num_rows as u32 (sst.proto,
        # encoding.py); reject before paying any upload.
        ensure(table.num_rows < 2**32, f"sst row count too large: {table.num_rows}")

        CHUNK = 4 << 20
        kwargs = self._writer_kwargs(fast=fast_encode)

        # Small tables (registration batches, flush shards) skip the
        # producer-thread/queue streaming machinery: one worker-thread
        # encode into memory + one put. The streaming path exists to bound
        # host memory for LARGE tables; the threshold admits a whole flush
        # shard (~5-10 MB input -> ~1-3 MB object), whose streaming
        # loop<->thread ping-pong measured ~18 ms per shard — more than the
        # encode itself. Peak extra memory = one encoded object (< input).
        if table.nbytes <= 4 * CHUNK:
            def _encode_small() -> bytes:
                sink = io.BytesIO()
                writer = pq.ParquetWriter(sink, table.schema, **kwargs)
                writer.write_table(table, row_group_size=cfg.max_row_group_size)
                writer.close()
                return sink.getvalue()

            t_enc = time.perf_counter()
            blob = await self._run_sst(_encode_small)
            # lineage: the encoded object is a fresh buffer distinct from
            # the table's lanes (the copy-tax of the flush encode)
            memtrace.track_bytes(len(blob), "flush_encode", "alloc")
            if fast_encode:
                # flush-path stage attribution: encode (thread pool; pyarrow
                # cannot thread one file's columns, so flush parallelism is
                # shard-level across the pool) vs the upload PUT below
                FLUSH_STAGE_SECONDS.labels(self._root, "encode").observe(
                    time.perf_counter() - t_enc
                )
            ensure(len(blob) < 2**32, f"sst too large for manifest format: {len(blob)}")
            t_up = time.perf_counter()
            with context(f"write sst {path}"):
                await self._store.put(path, blob)
            if fast_encode:
                FLUSH_STAGE_SECONDS.labels(self._root, "upload").observe(
                    time.perf_counter() - t_up
                )
            # bloom first, enc LAST: _write_enc_sidecar registers the
            # pending (format, encodings) entry only once nothing after
            # it can fail, so a failed write never strands it
            await self._write_bloom_sidecar(file_id, path, table)
            await self._write_enc_sidecar(file_id, path, table)
            SST_BYTES.observe(len(blob))
            return len(blob)

        q: _queue.Queue = _queue.Queue(maxsize=4)
        cancel = _threading.Event()
        done = _threading.Event()

        class _Sink(io.RawIOBase):
            def __init__(self):
                self.parts: list[bytes] = []
                self.pending = 0

            def writable(self):
                return True

            def write(self, b):
                if cancel.is_set():
                    raise IOError("sst stream cancelled")
                # accumulate whole chunks in a list (O(1) append) instead of
                # a bytearray whose head-slicing memmoves the tail each emit
                self.parts.append(bytes(b))
                self.pending += len(b)
                while self.pending >= CHUNK:
                    blob = b"".join(self.parts)
                    q.put(blob[:CHUNK])
                    rest = blob[CHUNK:]
                    self.parts = [rest] if rest else []
                    self.pending = len(rest)
                return len(b)

            def flush_tail(self):
                if self.pending:
                    q.put(b"".join(self.parts))
                    self.parts = []
                    self.pending = 0

        def _produce() -> None:
            try:
                sink = _Sink()
                writer = pq.ParquetWriter(sink, table.schema, **kwargs)
                # one call: pyarrow splits into max_row_group_size row
                # groups in C++ (same file layout as a Python slice loop,
                # without per-group Python/GIL overhead)
                writer.write_table(table, row_group_size=cfg.max_row_group_size)
                writer.close()
                sink.flush_tail()
                q.put(None)  # EOF
            except BaseException as e:  # noqa: BLE001 — relayed to consumer
                q.put(e)
            finally:
                done.set()

        # The CPU-heavy encode runs on the sized SST executor when one is
        # configured (ThreadConfig) — ad-hoc threads would bypass exactly
        # the contention bound the executor exists for.
        if self._sst_executor is not None:
            self._sst_executor.submit(_produce)
        else:
            _threading.Thread(target=_produce, daemon=True).start()

        async def chunks():
            total = 0
            while True:
                item = await asyncio.to_thread(q.get)
                if item is None:
                    return
                if isinstance(item, BaseException):
                    raise item
                total += len(item)
                memtrace.track_bytes(len(item), "flush_encode", "alloc")
                # size is u32 in the manifest format: abort mid-stream
                # (put_stream discards the partial object)
                ensure(total < 2**32, f"sst too large for manifest format: {total}")
                yield item

        try:
            t_up = time.perf_counter()
            with context(f"write sst {path}"):
                size = await self._store.put_stream(path, chunks())
            if fast_encode:
                # streaming path overlaps encode with the PUT; the combined
                # wall time attributes to upload (encode rides the stream)
                FLUSH_STAGE_SECONDS.labels(self._root, "upload").observe(
                    time.perf_counter() - t_up
                )
        finally:
            cancel.set()
            while not done.is_set():
                try:  # unblock a producer stuck on a full queue
                    q.get_nowait()
                except _queue.Empty:
                    pass
                done.wait(timeout=0.05)

        # bloom first, enc last (see write_sst fast path): the pending
        # enc entry must be the final fallible step
        await self._write_bloom_sidecar(file_id, path, table)
        await self._write_enc_sidecar(file_id, path, table)
        SST_BYTES.observe(size)
        return size

    def pop_enc_meta(self, file_id: int) -> tuple[int, tuple]:
        """(format_version, encodings) of a just-written SST — consumed
        exactly once by the FileMeta construction site."""
        return self._pending_enc.pop(file_id, (1, ()))

    async def _write_enc_sidecar(self, file_id: int, path: str, table) -> None:
        """Encoded-lane sidecar AFTER the SST object lands and BEFORE the
        manifest can reference it — a registered v2 SST always has its
        sidecar. Encode cost is attributed per table
        (horaedb_flush_stage_seconds{stage=enc_encode}); a failed PUT
        reclaims the SST object best-effort and raises, exactly like the
        bloom sidecar path."""
        cfg = self._config.encoding
        if not cfg.enabled or table.num_rows < cfg.min_rows:
            return
        from horaedb_tpu.storage import encoding as enc_mod

        def _encode_and_pack():
            # blob serialization rides the same offload as the encode:
            # b"".join over multi-MB lane payloads on the event loop would
            # stall admission/deadline servicing during flush bursts
            e = enc_mod.encode_table(
                table, cfg.page_rows, cfg.max_dict,
                self._time_column, cfg.lanes,
            )
            return (e, enc_mod.encode_blob(e)) if e is not None else (None, None)

        try:
            t0 = time.perf_counter()
            enc, blob = await self._run_sst(_encode_and_pack)
            if enc is None:
                return
            FLUSH_STAGE_SECONDS.labels(self._root, "enc_encode").observe(
                time.perf_counter() - t0
            )
            await self._store.put(self._path_gen.generate_enc(file_id), blob)
        except BaseException:
            try:
                await self._store.delete(path)
            except Exception:  # noqa: BLE001 — orphan cleanup best-effort
                logger.warning(
                    "orphaned sst object %s after enc sidecar failure", path
                )
            raise
        self._pending_enc[file_id] = (
            enc_mod.SST_FORMAT_V2, enc.descriptor(),
        )

    async def _write_bloom_sidecar(self, file_id: int, path: str, table) -> None:
        """Bloom sidecar AFTER the SST lands: readers only learn ids via the
        manifest (updated after this returns), so ordering is safe, and a
        failed stream can't orphan a sidecar. If the sidecar put itself
        fails, the SST object is reclaimed best-effort before raising."""
        bloom_cols = self._bloom_columns()
        if not bloom_cols:
            return
        from horaedb_tpu.storage import bloom as bloom_mod

        try:
            blooms = await self._run_sst(
                bloom_mod.build_blooms, table, bloom_cols
            )
            await self._store.put(
                self._path_gen.generate_bloom(file_id),
                bloom_mod.encode_blooms(blooms),
            )
        except BaseException:
            try:
                await self._store.delete(path)
            except Exception:  # noqa: BLE001 — orphan cleanup best-effort
                logger.warning("orphaned sst object %s after bloom failure", path)
            raise

    # -- scan path (storage.rs:335-370) --------------------------------------
    async def scan(self, req: ScanRequest) -> AsyncIterator[pa.RecordBatch]:
        """Per-segment scans, old segments first. The NEXT segment's
        read+kernel overlaps with the consumer draining the current one
        (bounded one-segment prefetch — the async analog of the reference's
        UnionExec driving per-segment plans concurrently); an early consumer
        break (limit pushdown) cancels the prefetch."""
        t0 = time.perf_counter()
        ssts = self.select_ssts(req.range)
        if req.min_sst_id is not None:
            ssts = [s for s in ssts if s.id > req.min_sst_id]
        # EXPLAIN provenance: time-range SST selection (reads and bloom
        # prunes are noted per SST in read.py)
        scanstats.note("ssts_selected", len(ssts))
        if not ssts:
            return
        segments = self.group_by_segment(ssts)

        def start(seg):
            return asyncio.ensure_future(self.scan_segment_retrying(
                seg, req.range,
                lambda fresh: self._reader.scan_segment(
                    fresh,
                    predicate=req.predicate,
                    projections=req.projections,
                    keep_builtin=False,
                ),
                empty_result=[],
            ))

        from horaedb_tpu.common import deadline as deadline_ctx

        pending = start(segments[0])
        try:
            for i in range(len(segments)):
                batches = await pending
                # cooperative deadline between segments: an expired query
                # stops here instead of prefetching + decoding the rest
                deadline_ctx.check("segment_scan")
                pending = start(segments[i + 1]) if i + 1 < len(segments) else None
                for b in batches:
                    yield b
        finally:
            if pending is not None:
                pending.cancel()
                try:
                    await pending
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
            # NOT a tracing span: an async generator's frame suspends across
            # consumer turns, and a contextvar set inside it would leak into
            # the consumer's context — the per-stage spans attach from
            # scan_segment (a plain coroutine) instead
            SCAN_SECONDS.labels(self._root).observe(time.perf_counter() - t0)

    async def scan_segment_retrying(self, seg_ssts, time_range, op, empty_result=None):
        """Run a per-segment scan `op`, refreshing the segment's SST list
        from the manifest on NotFound: a compaction may physically delete
        input files between the caller's manifest snapshot and the read.
        Sound because compaction is segment-local (picker groups by
        segment), so the replacement SST lives in the same segment; an
        empty refresh means the data was TTL-expired.

        A store-unavailable failure (breaker open / retries exhausted in
        the resilience layer) is NOT retried here — the store layer
        already spent its budget. It is noted as `ssts_unavailable` scan
        provenance (EXPLAIN / the 503 body carries it) and re-raised
        typed, so the HTTP layer sheds instead of 500ing."""
        from horaedb_tpu.common.error import UnavailableError
        from horaedb_tpu.objstore import NotFound

        seg_key = Timestamp(seg_ssts[0].meta.time_range.start).truncate_by(
            self._segment_duration
        ).value
        for _attempt in range(3):
            try:
                return await op(seg_ssts)
            except UnavailableError:
                scanstats.note("ssts_unavailable", len(seg_ssts))
                raise
            except NotFound:
                fresh = [
                    s for s in self._manifest.find_ssts(time_range)
                    if Timestamp(s.meta.time_range.start).truncate_by(
                        self._segment_duration
                    ).value == seg_key
                ]
                if not fresh:
                    return empty_result
                logger.info(
                    "segment scan raced a compaction; retrying with %d fresh ssts",
                    len(fresh),
                )
                seg_ssts = fresh
        return await op(seg_ssts)  # last attempt: let NotFound propagate

    def group_by_segment(self, ssts: list[SstFile]) -> list[list[SstFile]]:
        """Bucket SSTs by segment start, ordered old->new (storage.rs:343-345)."""
        buckets: dict[int, list[SstFile]] = {}
        for s in ssts:
            seg = Timestamp(s.meta.time_range.start).truncate_by(self._segment_duration)
            buckets.setdefault(seg.value, []).append(s)
        return [buckets[k] for k in sorted(buckets)]

    # -- compaction (storage.rs:372-374) --------------------------------------
    async def compact(self, req: CompactRequest) -> None:
        ensure(self._scheduler is not None, "compaction scheduler disabled")
        self._scheduler.trigger_compaction(time_range=req.time_range)

    @property
    def compaction_scheduler(self):
        return self._scheduler
