"""Manifest snapshot binary codec — byte-compatible with the reference.

Format (reference: src/columnar_storage/src/manifest/encoding.rs:78-250):

  header  = magic(u32 LE = 0xCAFE1234) | version(u8 = 1) | flag(u8 = 0)
          | length(u64 LE)                                   -> 14 bytes
  record  = id(u64) | start(i64) | end(i64) | size(u32) | num_rows(u32)
          (all little-endian)                                -> 32 bytes
  length  = record_count * 32, integrity-checked on decode.

The snapshot plus the protobuf delta log IS the engine's checkpoint/resume
subsystem (SURVEY §5.4). Byte-exactness gives free conformance tests.

The hot encode/decode is vectorized with numpy (a snapshot with a million SSTs
is a 32 MB buffer — per-record Python loops would be the bottleneck the
reference's C codec avoids).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

from horaedb_tpu.common.error import HoraeError, ensure
from horaedb_tpu.pb import sst_pb2
from horaedb_tpu.storage.sst import FileMeta, SstFile
from horaedb_tpu.storage.types import TimeRange

MAGIC = 0xCAFE_1234
VERSION = 1
HEADER_LEN = 14
RECORD_LEN = 32
_HEADER = struct.Struct("<IBBQ")
# One record: id u64 | start i64 | end i64 | size u32 | num_rows u32.
_RECORD_DTYPE = np.dtype(
    [("id", "<u8"), ("start", "<i8"), ("end", "<i8"), ("size", "<u4"), ("num_rows", "<u4")]
)


@dataclass
class Snapshot:
    """Decoded snapshot state: the full list of live SSTs at merge time."""

    ssts: dict[int, SstFile]  # keyed by file id; insertion order preserved

    @classmethod
    def empty(cls) -> "Snapshot":
        return cls(ssts={})

    @classmethod
    def from_bytes(cls, data: bytes) -> "Snapshot":
        if len(data) == 0:
            return cls.empty()
        ensure(len(data) >= HEADER_LEN, "snapshot shorter than header")
        magic, version, _flag, length = _HEADER.unpack_from(data, 0)
        ensure(magic == MAGIC, "invalid bytes to convert to header.")
        ensure(version == VERSION, f"unsupported snapshot version: {version}")
        body = data[HEADER_LEN:]
        ensure(len(body) == length, "snapshot length mismatch")
        ensure(length % RECORD_LEN == 0, "snapshot body not a multiple of record size")
        recs = np.frombuffer(body, dtype=_RECORD_DTYPE)
        ssts: dict[int, SstFile] = {}
        for rid, start, end, size, num_rows in recs.tolist():
            # Known reference quirk: a snapshot may contain duplicate file ids
            # (encoding.rs:304-305 cites horaedb#1608); last record wins here,
            # which also dedups on re-encode.
            ssts[rid] = SstFile(
                id=rid,
                meta=FileMeta(
                    max_sequence=rid,
                    num_rows=int(num_rows),
                    size=int(size),
                    time_range=TimeRange(int(start), int(end)),
                ),
            )
        return cls(ssts=ssts)

    def to_bytes(self) -> bytes:
        files = list(self.ssts.values())
        recs = np.empty(len(files), dtype=_RECORD_DTYPE)
        # column-wise fills vectorize the encode (one tuple-assignment per
        # record was the hot spot in benchmarks/encoding_bench.py)
        recs["id"] = [f.id for f in files]
        recs["start"] = [f.meta.time_range.start for f in files]
        recs["end"] = [f.meta.time_range.end for f in files]
        recs["size"] = [f.meta.size for f in files]
        recs["num_rows"] = [f.meta.num_rows for f in files]
        body = recs.tobytes()
        return _HEADER.pack(MAGIC, VERSION, 0, len(body)) + body

    # -- delta application (order matters: adds then deletes, because delta
    # -- files are read unsorted; reference manifest/mod.rs:289-299) ---------
    def add_records(self, files: list[SstFile]) -> None:
        for f in files:
            self.ssts[f.id] = f

    def delete_records(self, ids: list[int]) -> None:
        for i in ids:
            self.ssts.pop(i, None)

    def into_ssts(self) -> list[SstFile]:
        return list(self.ssts.values())


# -- protobuf delta bridge (reference: encoding.rs:31-76) --------------------

def encode_update(to_adds: list[SstFile], to_deletes: list[int]) -> bytes:
    pb = sst_pb2.ManifestUpdate()
    for f in to_adds:
        pb.to_adds.append(f.to_pb())
    pb.to_deletes.extend(to_deletes)
    return pb.SerializeToString()


def decode_update(data: bytes) -> tuple[list[SstFile], list[int]]:
    pb = sst_pb2.ManifestUpdate()
    try:
        pb.ParseFromString(data)
    except Exception as e:  # noqa: BLE001
        raise HoraeError("corrupt manifest delta") from e
    return [SstFile.from_pb(f) for f in pb.to_adds], list(pb.to_deletes)
