"""Manifest snapshot binary codec — byte-compatible with the reference.

Format (reference: src/columnar_storage/src/manifest/encoding.rs:78-250):

  header  = magic(u32 LE = 0xCAFE1234) | version(u8 = 1) | flag(u8 = 0)
          | length(u64 LE)                                   -> 14 bytes
  record  = id(u64) | start(i64) | end(i64) | size(u32) | num_rows(u32)
          (all little-endian)                                -> 32 bytes
  length  = record_count * 32, integrity-checked on decode.

The snapshot plus the protobuf delta log IS the engine's checkpoint/resume
subsystem (SURVEY §5.4). Byte-exactness gives free conformance tests.

The hot encode/decode is vectorized with numpy (a snapshot with a million SSTs
is a 32 MB buffer — per-record Python loops would be the bottleneck the
reference's C codec avoids).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

from horaedb_tpu.common.error import HoraeError, ensure
from horaedb_tpu.pb import sst_pb2
from horaedb_tpu.storage.sst import FileMeta, SstFile
from horaedb_tpu.storage.types import TimeRange

MAGIC = 0xCAFE_1234
VERSION = 1
# Snapshot format v2 (TPU-build extension): appends the SST format_version
# (storage/encoding.py: 2 = encoded-lane sidecar present) to each record.
# The encoder emits v1 BYTES whenever every SST is format v1 — the
# reference-conformance surface stays byte-exact for reference-shaped
# trees, and only trees that actually hold encoded SSTs pay the new field.
# Per-lane codec maps are NOT folded into the snapshot: the `.enc` sidecar
# self-describes them (ground truth at read time) and pb deltas carry them
# for provenance until the next merge.
VERSION_ENC = 2
HEADER_LEN = 14
RECORD_LEN = 32
RECORD_LEN_V2 = 36
_HEADER = struct.Struct("<IBBQ")
# One record: id u64 | start i64 | end i64 | size u32 | num_rows u32.
_RECORD_DTYPE = np.dtype(
    [("id", "<u8"), ("start", "<i8"), ("end", "<i8"), ("size", "<u4"), ("num_rows", "<u4")]
)
# v2 record: v1 + format_version u32.
_RECORD_DTYPE_V2 = np.dtype(
    [("id", "<u8"), ("start", "<i8"), ("end", "<i8"), ("size", "<u4"),
     ("num_rows", "<u4"), ("fmt", "<u4")]
)


@dataclass
class Snapshot:
    """Decoded snapshot state: the full list of live SSTs at merge time."""

    ssts: dict[int, SstFile]  # keyed by file id; insertion order preserved

    @classmethod
    def empty(cls) -> "Snapshot":
        return cls(ssts={})

    @classmethod
    def from_bytes(cls, data: bytes) -> "Snapshot":
        if len(data) == 0:
            return cls.empty()
        ensure(len(data) >= HEADER_LEN, "snapshot shorter than header")
        magic, version, _flag, length = _HEADER.unpack_from(data, 0)
        ensure(magic == MAGIC, "invalid bytes to convert to header.")
        ensure(version in (VERSION, VERSION_ENC),
               f"unsupported snapshot version: {version}")
        rec_len = RECORD_LEN if version == VERSION else RECORD_LEN_V2
        rec_dtype = _RECORD_DTYPE if version == VERSION else _RECORD_DTYPE_V2
        body = data[HEADER_LEN:]
        ensure(len(body) == length, "snapshot length mismatch")
        ensure(length % rec_len == 0, "snapshot body not a multiple of record size")
        recs = np.frombuffer(body, dtype=rec_dtype)
        ssts: dict[int, SstFile] = {}
        for rec in recs.tolist():
            rid, start, end, size, num_rows = rec[:5]
            fmt = int(rec[5]) if version == VERSION_ENC else 1
            # Known reference quirk: a snapshot may contain duplicate file ids
            # (encoding.rs:304-305 cites horaedb#1608); last record wins here,
            # which also dedups on re-encode.
            ssts[rid] = SstFile(
                id=rid,
                meta=FileMeta(
                    max_sequence=rid,
                    num_rows=int(num_rows),
                    size=int(size),
                    time_range=TimeRange(int(start), int(end)),
                    format_version=max(1, fmt),
                ),
            )
        return cls(ssts=ssts)

    def to_bytes(self) -> bytes:
        files = list(self.ssts.values())
        # v1 bytes unless an encoded SST forces the v2 record (see
        # VERSION_ENC above) — all-v1 trees stay reference-byte-exact
        v2 = any(f.meta.format_version > 1 for f in files)
        recs = np.empty(len(files), dtype=_RECORD_DTYPE_V2 if v2 else _RECORD_DTYPE)
        # column-wise fills vectorize the encode (one tuple-assignment per
        # record was the hot spot in benchmarks/encoding_bench.py)
        recs["id"] = [f.id for f in files]
        recs["start"] = [f.meta.time_range.start for f in files]
        recs["end"] = [f.meta.time_range.end for f in files]
        recs["size"] = [f.meta.size for f in files]
        recs["num_rows"] = [f.meta.num_rows for f in files]
        if v2:
            recs["fmt"] = [f.meta.format_version for f in files]
        body = recs.tobytes()
        return _HEADER.pack(MAGIC, VERSION_ENC if v2 else VERSION, 0, len(body)) + body

    # -- delta application (order matters: adds then deletes, because delta
    # -- files are read unsorted; reference manifest/mod.rs:289-299) ---------
    def add_records(self, files: list[SstFile]) -> None:
        for f in files:
            self.ssts[f.id] = f

    def delete_records(self, ids: list[int]) -> None:
        for i in ids:
            self.ssts.pop(i, None)

    def into_ssts(self) -> list[SstFile]:
        return list(self.ssts.values())


# -- protobuf delta bridge (reference: encoding.rs:31-76) --------------------

def encode_update(to_adds: list[SstFile], to_deletes: list[int]) -> bytes:
    pb = sst_pb2.ManifestUpdate()
    for f in to_adds:
        pb.to_adds.append(f.to_pb())
    pb.to_deletes.extend(to_deletes)
    return pb.SerializeToString()


def decode_update(data: bytes) -> tuple[list[SstFile], list[int]]:
    pb = sst_pb2.ManifestUpdate()
    try:
        pb.ParseFromString(data)
    except Exception as e:  # noqa: BLE001
        raise HoraeError("corrupt manifest delta") from e
    return [SstFile.from_pb(f) for f in pb.to_adds], list(pb.to_deletes)
