"""Manifest: snapshot + delta log over the object store.

Reference: src/columnar_storage/src/manifest/mod.rs. Semantics preserved:

- In-memory list of live SSTs; every update writes one protobuf delta file to
  `{root}/manifest/delta/{id}` (the durability point) then applies in memory.
- A background merger folds deltas into the binary `snapshot` file
  (`encoding.py`) on a timer OR when signalled; startup runs a first merge so
  recovery = read snapshot after folding leftover deltas (mod.rs:195-215).
- Delta-count backpressure: above the soft threshold a merge is scheduled;
  above the hard threshold the write is REJECTED with an error
  (mod.rs:248-262) — the engine's overload-protection contract.
- Merge applies all adds BEFORE all deletes because delta files are read in
  unspecified order (mod.rs:289-299).
- Post-commit delta deletions never fail the merge — log-only
  (mod.rs:310-330).
"""

from __future__ import annotations

import asyncio
import logging

from horaedb_tpu.common.error import HoraeError, context, ensure
from horaedb_tpu.objstore import NotFound, ObjectStore
from horaedb_tpu.storage.config import ManifestConfig
from horaedb_tpu.storage.manifest import encoding
from horaedb_tpu.storage.manifest.encoding import Snapshot, decode_update, encode_update
from horaedb_tpu.storage.sst import FileMeta, SstFile, allocate_id
from horaedb_tpu.storage.types import TimeRange

logger = logging.getLogger(__name__)

PREFIX_PATH = "manifest"
SNAPSHOT_FILENAME = "snapshot"
DELTA_PREFIX = "delta"
TOMBSTONE_PREFIX = "tombstone"


def snapshot_path(root: str) -> str:
    return f"{root}/{PREFIX_PATH}/{SNAPSHOT_FILENAME}"


def delta_dir(root: str) -> str:
    return f"{root}/{PREFIX_PATH}/{DELTA_PREFIX}"


def delta_path(root: str, file_id: int) -> str:
    return f"{delta_dir(root)}/{file_id}"


def tombstone_dir(root: str) -> str:
    return f"{root}/{PREFIX_PATH}/{TOMBSTONE_PREFIX}"


def tombstone_path(root: str, record_id: int) -> str:
    return f"{tombstone_dir(root)}/{record_id}"


ROLLUP_PREFIX = "rollup"


def rollup_record_dir(root: str) -> str:
    return f"{root}/{PREFIX_PATH}/{ROLLUP_PREFIX}"


def rollup_record_path(root: str, record_id: int) -> str:
    return f"{rollup_record_dir(root)}/{record_id}"


class ManifestMerger:
    """Background delta→snapshot folder (mod.rs:178-333)."""

    def __init__(
        self, root: str, store: ObjectStore, config: ManifestConfig, executor=None,
        fence=None,
    ):
        self._root = root
        self._store = store
        self._config = config
        # Optional dedicated executor for the CPU-bound fold (decode deltas +
        # rebuild snapshot bytes), sized by the server's ThreadConfig — the
        # manifest-compact runtime analog (main.rs:102-119). None = fold
        # inline on the event loop (fine at test scale).
        self._executor = executor
        # Optional EpochFence (storage/fence.py): a deposed process must not
        # fold a stale view over the new owner's snapshot
        self._fence = fence
        self._deltas_num = 0
        self._merge_signal: asyncio.Queue[None] = asyncio.Queue(maxsize=config.channel_size)
        self._task: asyncio.Task | None = None
        self._merge_lock = asyncio.Lock()

    async def bootstrap(self) -> None:
        """First-run merge: fold any leftover deltas from a previous life into
        the snapshot so `read_snapshot` returns complete state (mod.rs:212-215)."""
        await self.do_merge()

    def start(self) -> None:
        self._task = asyncio.create_task(self._run(), name="manifest-merger")

    async def close(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    # -- write-path hooks ---------------------------------------------------
    def maybe_schedule_merge(self) -> None:
        """Count one new delta; soft→signal merge, hard→reject (mod.rs:248-262)."""
        # jaxlint: disable=J004 event-loop-confined; _merge_lock serializes the fold, not this
        self._deltas_num += 1
        if self._deltas_num > self._config.hard_merge_threshold:
            # jaxlint: disable=J004 event-loop-confined; _merge_lock serializes the fold, not this
            self._deltas_num -= 1
            raise HoraeError(
                f"Too many manifest delta files: {self._deltas_num + 1}, "
                f"hard limit: {self._config.hard_merge_threshold}"
            )
        if self._deltas_num > self._config.soft_merge_threshold:
            try:
                self._merge_signal.put_nowait(None)
            except asyncio.QueueFull:
                pass  # a merge is already queued; dropping the signal is fine

    def on_delta_write_failed(self) -> None:
        # jaxlint: disable=J004 event-loop-confined; _merge_lock serializes the fold, not this
        self._deltas_num -= 1

    @property
    def deltas_num(self) -> int:
        return self._deltas_num

    # -- merge loop ---------------------------------------------------------
    async def _run(self) -> None:
        """select!(interval tick, merge signal) loop (mod.rs:218-240)."""
        interval = self._config.merge_interval.seconds
        while True:
            sleep = asyncio.create_task(asyncio.sleep(interval))
            recv = asyncio.create_task(self._merge_signal.get())
            done, pending = await asyncio.wait(
                {sleep, recv}, return_when=asyncio.FIRST_COMPLETED
            )
            for t in pending:
                t.cancel()
            await asyncio.gather(*pending, return_exceptions=True)
            for t in done:
                with_exc = t.exception()
                if with_exc is not None and not isinstance(with_exc, asyncio.CancelledError):
                    raise with_exc
            if self._deltas_num > self._config.min_merge_threshold:
                try:
                    await self.do_merge()
                except Exception as e:  # noqa: BLE001 - keep the loop alive
                    from horaedb_tpu.storage.fence import FencedError

                    if isinstance(e, FencedError):
                        # terminal: this process lost region ownership — a
                        # retry loop would hammer the shared store (full
                        # delta LIST+GET+fold per interval) forever
                        logger.error(
                            "manifest merger stopping: %s", e
                        )
                        return
                    logger.exception("manifest merge failed; will retry")

    async def do_merge(self) -> None:
        """Fold all delta files into the snapshot (mod.rs:274-333)."""
        async with self._merge_lock:
            metas = await self._store.list(delta_dir(self._root))
            if not metas:
                return
            paths = [m.path for m in metas]
            # Parallel delta reads (TokioScope analog, mod.rs:283-287).
            blobs = await asyncio.gather(*(self._store.get(p) for p in paths))

            snapshot = await read_snapshot(self._store, snapshot_path(self._root))

            def fold() -> bytes:
                all_adds: list[SstFile] = []
                all_deletes: list[int] = []
                for blob in blobs:
                    adds, deletes = decode_update(blob)
                    all_adds.extend(adds)
                    all_deletes.extend(deletes)
                # Adds before deletes: deltas arrive unsorted (mod.rs:289-299).
                snapshot.add_records(all_adds)
                snapshot.delete_records(all_deletes)
                return snapshot.to_bytes()

            if self._executor is None:
                data = fold()
            else:
                data = await asyncio.get_running_loop().run_in_executor(
                    self._executor, fold
                )
            if self._fence is not None:
                # fresh check RIGHT before the snapshot write: a deposed
                # merger folding a stale delta list would regress the new
                # owner's snapshot and lose its folded adds forever
                await self._fence.ensure_valid(force=True)
            with context("write manifest snapshot"):
                await self._store.put(snapshot_path(self._root), data)
            # Commit point passed: delta deletions are best-effort (mod.rs:310-330).
            results = await asyncio.gather(
                *(self._store.delete(p) for p in paths), return_exceptions=True
            )
            for p, r in zip(paths, results):
                if isinstance(r, BaseException):
                    logger.error("failed to delete merged delta %s: %s", p, r)
            self._deltas_num = max(0, self._deltas_num - len(paths))


async def read_snapshot(store: ObjectStore, path: str) -> Snapshot:
    """Missing snapshot is an empty one (mod.rs:336-354)."""
    try:
        data = await store.get(path)
    except NotFound:
        return Snapshot.empty()
    with context(f"decode manifest snapshot {path}"):
        return Snapshot.from_bytes(data)


async def read_folded_view(store: ObjectStore, root: str) -> Snapshot:
    """Read-only manifest view for cluster replicas: snapshot + any
    unfolded deltas, folded IN MEMORY — nothing is ever written back.
    Safe to race the owning writer's merger: deltas are deleted only
    AFTER the snapshot containing them lands, so a NotFound mid-read
    means a fresher snapshot exists (retry re-reads it), and re-applying
    a delta already folded is idempotent (Snapshot.ssts keys by id).
    jaxlint J017 pins the consumers of this view to the cluster replica
    funnel (cluster/replica.py drives it via read-only storage opens)."""
    for _attempt in range(5):
        metas = await store.list(delta_dir(root))
        snapshot = await read_snapshot(store, snapshot_path(root))
        if not metas:
            return snapshot
        try:
            blobs = await asyncio.gather(*(store.get(m.path) for m in metas))
        except NotFound:
            continue  # the owner's merger folded under us; re-read
        all_adds: list[SstFile] = []
        all_deletes: list[int] = []
        for blob in blobs:
            adds, deletes = decode_update(blob)
            all_adds.extend(adds)
            all_deletes.extend(deletes)
        snapshot.add_records(all_adds)
        snapshot.delete_records(all_deletes)
        return snapshot
    # five straight races: the bare snapshot alone is still a consistent
    # (slightly staler) view — bounded staleness is the replica contract
    return await read_snapshot(store, snapshot_path(root))


class Manifest:
    """Live-SST registry (mod.rs:66-176)."""

    def __init__(
        self, root: str, store: ObjectStore, config: ManifestConfig, executor=None,
        fence=None, read_only: bool = False,
    ):
        self._root = root
        self._store = store
        self._config = config
        # Cluster replica mode (horaedb_tpu/cluster): this process holds a
        # VIEW of another writer's manifest — every mutation raises, the
        # merger never runs (its fold WRITES the snapshot), and loads use
        # the in-memory delta fold (read_folded_view).
        self._read_only = read_only
        self._ssts: list[SstFile] = []
        # Tombstone delete records (storage/visibility.py): manifest-level
        # control-plane state, one JSON object per record under
        # manifest/tombstone/{id}. Low volume by construction (deletes are
        # operator/GDPR events, not a data path).
        self._tombstone_records: "list" = []
        # Rollup artifact records (storage/rollup.py): the registry of
        # the DISTINCT pre-aggregated artifact kind, one JSON object per
        # record under manifest/rollup/{id}, keyed in memory by
        # (segment_start, resolution_ms). Low volume: one per live
        # (segment, resolution) at steady state.
        self._rollup_records: "dict[tuple[int, int], object]" = {}
        self._fence = fence
        self._merger = ManifestMerger(
            root, store, config, executor=executor, fence=fence
        )

    @classmethod
    async def try_new(
        cls,
        root: str,
        store: ObjectStore,
        config: ManifestConfig | None = None,
        start_background_merger: bool = True,
        executor=None,
        fence=None,
        read_only: bool = False,
    ) -> "Manifest":
        """`fence`: optional EpochFence enforcing cross-process single-writer
        ownership of this manifest root (storage/fence.py) — every update
        and snapshot fold validates the epoch first.

        `read_only`: open a VIEW of a manifest another process owns
        (cluster replica mode): the bootstrap fold stays in memory
        (read_folded_view), the background merger never starts, and every
        mutation raises — a replica must not move a writer's manifest."""
        m = cls(root, store, config or ManifestConfig(), executor=executor,
                fence=fence, read_only=read_only)
        if read_only:
            snapshot = await read_folded_view(store, root)
        else:
            await m._merger.bootstrap()
            snapshot = await read_snapshot(store, snapshot_path(root))
        m._ssts = snapshot.into_ssts()
        await m._load_tombstones()
        await m._load_rollups()
        logger.info(
            "manifest loaded: root=%s ssts=%d tombstones=%d%s",
            root, len(m._ssts), len(m._tombstone_records),
            " (read-only view)" if read_only else "",
        )
        if start_background_merger and not read_only:
            m._merger.start()
        return m

    def _ensure_writable(self, what: str) -> None:
        if self._read_only:
            raise HoraeError(
                f"manifest {self._root} is a read-only replica view; "
                f"refusing {what} (writes belong to the owning writer)"
            )

    def epoch(self) -> int:
        """Monotonic manifest epoch: the highest id any live record
        carries (SSTs, tombstones, rollups — all minted by the shared
        monotonic allocator). Every commit raises it (flush adds a fresh
        SST id, compaction outputs carry higher ids than their inputs,
        deletes mint tombstone ids), so writer-vs-replica comparison of
        this number IS the catch-up check the cluster status surfaces.
        GC can retire the max id holder; callers needing strict
        monotonicity floor it (cluster/replica.py does)."""
        top = max((s.id for s in self._ssts), default=0)
        top = max(top, max((int(t.id) for t in self._tombstone_records),
                           default=0))
        top = max(top, max((int(r.id) for r in self._rollup_records.values()),
                           default=0))
        return top

    async def close(self) -> None:
        await self._merger.close()

    # -- updates ------------------------------------------------------------
    async def add_file(self, file_id: int, meta: FileMeta) -> None:
        await self.update([SstFile(id=file_id, meta=meta)], [])

    async def update(self, to_adds: list[SstFile], to_deletes: list[int]) -> None:
        """Durability point: write one delta file, then apply in memory
        (mod.rs:120-157). Hard backpressure may reject the update."""
        # Encode BEFORE counting the delta: an encode failure (e.g. a meta
        # field overflowing the u32 wire format) must not leak a phantom
        # increment that the merger can never drain.
        self._ensure_writable("manifest update")
        if self._fence is not None:
            # single-writer fence: a superseded epoch must not commit
            await self._fence.ensure_valid()
        payload = encode_update(to_adds, to_deletes)
        self._merger.maybe_schedule_merge()
        path = delta_path(self._root, allocate_id())
        try:
            with context("write manifest delta"):
                await self._store.put(path, payload)
        except Exception:
            self._merger.on_delta_write_failed()
            raise
        delete_set = set(to_deletes)
        self._ssts = [s for s in self._ssts if s.id not in delete_set]
        self._ssts.extend(to_adds)

    # -- tombstone delete records (storage/visibility.py) --------------------
    async def _load_tombstones(self) -> None:
        """Recovery: fold every persisted tombstone record back in. A
        corrupt record fails the open loudly — silently skipping one would
        resurrect deleted data."""
        from horaedb_tpu.storage.visibility import Tombstone

        try:
            metas = await self._store.list(tombstone_dir(self._root))
        except NotFound:
            metas = []
        records = []
        for meta in metas:
            blob = await self._store.get(meta.path)
            with context(f"decode tombstone {meta.path}"):
                records.append(Tombstone.from_json(blob))
        records.sort(key=lambda t: t.seq)
        self._tombstone_records = records

    async def add_tombstone(self, tomb) -> None:
        """Durability point of a delete: the tombstone object's PUT. Applied
        in memory only after it lands — an acked delete survives a crash."""
        self._ensure_writable("tombstone add")
        if self._fence is not None:
            await self._fence.ensure_valid()
        with context("write tombstone record"):
            await self._store.put(
                tombstone_path(self._root, tomb.id), tomb.to_json()
            )
        self._tombstone_records.append(tomb)

    def all_tombstones(self) -> list:
        return list(self._tombstone_records)

    async def gc_tombstones(self) -> int:
        """Drop tombstones no live SST overlaps: no remaining row can match,
        so the record is dead weight (retention expiry and whole-range
        deletes converge here; a tombstone inside a still-live range stays —
        compaction keeps re-applying it, which is idempotent). Object
        deletions are best-effort: a failed delete keeps the record
        in memory AND on disk for the next pass. Returns records dropped."""
        if self._read_only or not self._tombstone_records:
            return 0
        live = self._ssts
        dead = [
            t for t in self._tombstone_records
            if not any(s.meta.time_range.overlaps(t.time_range) for s in live)
        ]
        if not dead:
            return 0
        results = await asyncio.gather(
            *(self._store.delete(tombstone_path(self._root, t.id)) for t in dead),
            return_exceptions=True,
        )
        dropped = []
        for t, r in zip(dead, results):
            if isinstance(r, BaseException) and not isinstance(r, NotFound):
                logger.warning(
                    "tombstone gc: failed to delete record %d: %s", t.id, r
                )
                continue
            dropped.append(t)
        if dropped:
            gone = {t.id for t in dropped}
            self._tombstone_records = [
                t for t in self._tombstone_records if t.id not in gone
            ]
            logger.info(
                "tombstone gc: root=%s dropped=%d remaining=%d",
                self._root, len(dropped), len(self._tombstone_records),
            )
        return len(dropped)

    # -- rollup artifact records (storage/rollup.py) -------------------------
    async def _load_rollups(self) -> None:
        """Recovery: fold persisted rollup records back in. Unlike
        tombstones, a corrupt/unreadable record is SAFE to drop — a
        rollup is a performance artifact, never a correctness one (the
        planner just scans raw) — so a bad record logs + skips instead of
        failing the open. Newer record wins a (segment, resolution) slot
        (ids are monotonic); losers are stale leftovers of a crash
        between the fresh record's PUT and the supersede-delete."""
        from horaedb_tpu.storage.rollup import RollupRecord

        try:
            metas = await self._store.list(rollup_record_dir(self._root))
        except NotFound:
            metas = []
        except Exception as e:  # noqa: BLE001 — registry load best-effort
            logger.warning("rollup record load skipped (list failed): %s", e)
            metas = []
        records: dict[tuple[int, int], RollupRecord] = {}
        losers: list[RollupRecord] = []
        for meta in metas:
            try:
                rec = RollupRecord.from_json(await self._store.get(meta.path))
            except Exception as e:  # noqa: BLE001 — perf artifact only
                logger.warning("skipping unreadable rollup record %s: %s",
                               meta.path, e)
                continue
            key = (rec.segment_start, rec.resolution_ms)
            prev = records.get(key)
            if prev is None or rec.id > prev.id:
                if prev is not None:
                    losers.append(prev)
                records[key] = rec
            else:
                losers.append(rec)
        self._rollup_records = records
        if losers and self._read_only:
            # a replica view never mutates the store: the owner's next
            # open/GC reclaims its own superseded records
            losers = []
        if losers:
            # delete the superseded record objects now, best-effort: no
            # later GC pass ever sees them (gc_rollups walks the in-memory
            # winners only), so each crashed supersede-delete would
            # otherwise leak one object every open re-lists forever.
            # Their .sst artifacts become unreferenced here and are
            # reclaimed by the rollup orphan GC at storage open.
            results = await asyncio.gather(
                *(self._store.delete(rollup_record_path(self._root, r.id))
                  for r in losers),
                return_exceptions=True,
            )
            failed = sum(
                1 for r in results
                if isinstance(r, BaseException) and not isinstance(r, NotFound)
            )
            logger.info(
                "rollup load: dropped %d superseded record(s) (failed=%d)",
                len(losers), failed,
            )

    async def add_rollup(self, record) -> None:
        """Register one rollup artifact (durability point: the record
        object's PUT). Replaces any older record for the same
        (segment, resolution); the CALLER deletes the replaced record's
        objects (supersede is part of the compaction commit path)."""
        self._ensure_writable("rollup record add")
        if self._fence is not None:
            await self._fence.ensure_valid()
        with context("write rollup record"):
            await self._store.put(
                rollup_record_path(self._root, record.id), record.to_json()
            )
        self._rollup_records[
            (record.segment_start, record.resolution_ms)
        ] = record

    async def remove_rollups(self, records: list) -> None:
        """Drop records + their SST objects, best-effort (superseded by
        a fresh build, or their sources died). A failed delete leaves
        the record for the next pass; the planner's source-set equality
        check keeps a stale survivor unusable either way."""
        from horaedb_tpu.storage.rollup import evict_rollup
        from horaedb_tpu.storage.sst import SstPathGenerator

        self._ensure_writable("rollup removal")
        if not records:
            return
        path_gen = SstPathGenerator(self._root)
        paths = []
        for r in records:
            paths.append(rollup_record_path(self._root, r.id))
            paths.append(path_gen.generate_rollup(r.sst_id))
            evict_rollup(r.sst_id)
        results = await asyncio.gather(
            *(self._store.delete(p) for p in paths), return_exceptions=True
        )
        for p, res in zip(paths, results):
            if isinstance(res, BaseException) and not isinstance(res, NotFound):
                logger.warning("rollup gc: failed to delete %s: %s", p, res)
        for r in records:
            key = (r.segment_start, r.resolution_ms)
            if self._rollup_records.get(key) is r:
                del self._rollup_records[key]

    async def gc_rollups(self) -> int:
        """Drop records whose source SSTs are no longer all live — their
        freshness contract can never pass again (ids are never reused).
        Called post-commit by the compaction executor; best-effort."""
        if self._read_only or not self._rollup_records:
            return 0
        live = {s.id for s in self._ssts}
        dead = [
            r for r in self._rollup_records.values()
            if not set(r.source_sst_ids) <= live
        ]
        await self.remove_rollups(dead)
        return len(dead)

    def rollup_records(self) -> dict:
        """(segment_start, resolution_ms) -> RollupRecord, live view."""
        return self._rollup_records

    def referenced_rollup_sst_ids(self) -> set:
        return {r.sst_id for r in self._rollup_records.values()}

    # -- queries ------------------------------------------------------------
    def all_ssts(self) -> list[SstFile]:
        return list(self._ssts)

    def find_ssts(self, time_range: TimeRange) -> list[SstFile]:
        """Overlap filter (mod.rs:165-172)."""
        return [s for s in self._ssts if s.meta.time_range.overlaps(time_range)]

    async def force_merge(self) -> None:
        """Deterministic merge hook for tests and shutdown."""
        if self._read_only:
            return  # the fold writes the snapshot; a view never does
        await self._merger.do_merge()

    @property
    def deltas_num(self) -> int:
        return self._merger.deltas_num
