"""K-way sorted merge on device: the standalone block-level primitive.

The reference heap-merges k sorted SST streams row-by-row on CPU
(SortPreservingMergeExec, read.rs:479-480). A comparison heap is the wrong
shape for a vector machine; the XLA-idiomatic k-way merge is concatenate +
one fused sort over the combined block — O(n log n) work but fully
data-parallel, and the inputs being pre-sorted makes the sort's comparator
networks cheap in practice.

The PRODUCTION merge paths live elsewhere: the scan/compaction pipeline
routes through storage/read.py (`_build_packed_index_kernel` single-chip,
`_build_scan_kernel` fused filter+sort+dedup, and the hierarchical chunked
scan's merge tree) and parallel/merge.py (the cross-chip sample-sort).
This module is the simple whole-block form those paths specialize — used
directly by small in-memory merges and as the oracle-sized building block
in tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from horaedb_tpu.common.error import ensure
from horaedb_tpu.ops.sort import sort_columns


def concat_blocks(blocks: list[dict[str, jax.Array]]) -> dict[str, jax.Array]:
    """Concatenate same-schema column dicts (padding rows and all)."""
    ensure(len(blocks) > 0, "cannot merge zero blocks")
    names = list(blocks[0].keys())
    return {k: jnp.concatenate([b[k] for b in blocks]) for k in names}


def merge_sorted(
    blocks: list[dict[str, jax.Array]],
    key_names: list[str],
) -> dict[str, jax.Array]:
    """Merge k sorted blocks into one block sorted by `key_names`.

    Padding rows must carry sentinel keys (blocks.py) — they sink to the tail
    of the merged ordering, so the result's valid region is the sum of input
    valid counts.
    """
    return sort_columns(concat_blocks(blocks), key_names)
