"""K-way sorted merge on device.

The reference heap-merges k sorted SST streams row-by-row on CPU
(SortPreservingMergeExec, read.rs:479-480). A comparison heap is the wrong
shape for a vector machine; the XLA-idiomatic k-way merge is concatenate +
one fused sort over the combined block — O(n log n) work but fully
data-parallel, and the inputs being pre-sorted makes the sort's comparator
networks cheap in practice. This is the core of both the scan path and the
compaction executor (SURVEY C12, BASELINE config 5).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from horaedb_tpu.common.error import ensure
from horaedb_tpu.ops.sort import sort_columns


def concat_blocks(blocks: list[dict[str, jax.Array]]) -> dict[str, jax.Array]:
    """Concatenate same-schema column dicts (padding rows and all)."""
    ensure(len(blocks) > 0, "cannot merge zero blocks")
    names = list(blocks[0].keys())
    return {k: jnp.concatenate([b[k] for b in blocks]) for k in names}


def merge_sorted(
    blocks: list[dict[str, jax.Array]],
    key_names: list[str],
) -> dict[str, jax.Array]:
    """Merge k sorted blocks into one block sorted by `key_names`.

    Padding rows must carry sentinel keys (blocks.py) — they sink to the tail
    of the merged ordering, so the result's valid region is the sum of input
    valid counts.
    """
    return sort_columns(concat_blocks(blocks), key_names)
