"""On-device decode kernels for encoded SST lanes + the calibrated
encoded-vs-host-vs-raw dispatcher (ROADMAP open item 1's device half).

The compressed-domain scan ships QUALIFYING lanes to the device in their
encoded form — bit-packed words instead of full-width rows — and expands
them in device memory, shrinking H2D bytes/row at the source (the wall
ROOFLINE §3 blames for config-5). Kernels are plain XLA (the tree's xjit
idiom, same as ops/blockagg.py), built once per (codec, width, padded
rows) and cached:

  bit-unpack         shift/mask gather over a u32 word lane — each output
                     element reads the two words its bit window can span
                     (widths <= 32, the device envelope; wider pages fall
                     back to the host funnel per page);
  delta prefix-sum   dod timestamps: two `lax.associative_scan(add)`
                     passes (log-depth vector prologue — the PR 3
                     block_scan machinery's scan, reused on the decode
                     path) over the unzigzagged second-order deltas;
  xor prefix-scan    float values: `lax.associative_scan(bitwise_xor)`
                     over the unpacked XOR stream, then a bitcast;
  rle expand         run values gathered through a searchsorted over the
                     cumulative run lengths.

Dispatch is measured, not guessed (the ops/agg_registry.py envelope): a
micro-A/B per (platform, codec) times host-numpy decode vs device decode
once and persists the winner; `HORAEDB_DECODE_IMPL` pins (host | device |
raw | auto), where `raw` disables the encoded read path entirely (the
A/B-honesty control bench.py measures against). The choice is exported as
`horaedb_decode_impl_total{impl=...}` and rides EXPLAIN provenance.

Decoding an encoded lane anywhere outside this module or
storage/encoding.py is a jaxlint J012 error (docs/static-analysis.md).
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import time
from functools import lru_cache

import numpy as np

from horaedb_tpu.common.calib_cache import CalibCache
from horaedb_tpu.common.error import ensure
from horaedb_tpu.common.xprof import xjit
from horaedb_tpu.server.metrics import GLOBAL_METRICS

logger = logging.getLogger(__name__)

DECODE_IMPL_TOTAL = GLOBAL_METRICS.counter(
    "horaedb_decode_impl_total",
    help="Decode lane the calibrated dispatcher selected per encoded-lane "
         "decode (host numpy funnel vs on-device kernels).",
    labelnames=("impl",),
)
for _i in ("host", "device"):
    DECODE_IMPL_TOTAL.labels(_i)
del _i

DECODE_IMPLS = ("host", "device")
# device bit-unpack envelope: one value spans at most two u32 words
DEVICE_MAX_WIDTH = 32
# pad rows to this granule so page-size jitter (last page of an SST) maps
# to a handful of compiled shapes per (codec, width), not one per size
_PAD_ROWS = 1024

CALIB_VERSION = 1

_U64_1 = np.uint64(1)


# ---------------------------------------------------------------------------
# kernels (xjit-instrumented; shapes static per cache key)
# ---------------------------------------------------------------------------


def _pad_rows(n: int) -> int:
    return max(_PAD_ROWS, ((n + _PAD_ROWS - 1) // _PAD_ROWS) * _PAD_ROWS)


def _words_for(n_pad: int, width: int) -> int:
    # +1 guard word: the straddle read of the last element may touch it
    return (n_pad * width + 31) // 32 + 1


@lru_cache(maxsize=256)
def _unpack_kernel(width: int, n_pad: int):
    """words u32 -> u64 values at fixed bit `width` (LSB-first stream)."""
    import jax.numpy as jnp

    @xjit(kernel="decode_unpack")
    def kernel(words):
        bit = jnp.arange(n_pad, dtype=jnp.int64) * width
        return _unpack_expr(jnp, words, bit, width)

    return kernel


def _unpack_expr(jnp, words, bit, width: int):
    """Traced shift/mask bit-window read: each element gathers the two u32
    words its `width`-bit window can span and shifts it out (the bitwidth-
    unpack primitive of the decode path)."""
    mask = np.uint64((1 << width) - 1)
    wi = bit // 32
    off = (bit % 32).astype(jnp.uint64)
    w0 = jnp.take(words, wi).astype(jnp.uint64)
    w1 = jnp.take(words, wi + 1).astype(jnp.uint64)
    return ((w0 | (w1 << jnp.uint64(32))) >> off) & mask


@lru_cache(maxsize=256)
def _dod_kernel(width: int, n_pad: int):
    """packed zigzag(d2) words + (first, first_delta) -> i64 values.

    The stream holds dd of rows [2, rows); the kernel gathers it into a
    row-aligned lane (rows 0/1 read zero), then two log-depth
    `lax.associative_scan(add)` passes reconstruct deltas and values.
    Mod-2^64 u64 arithmetic matches the host funnel bit for bit."""
    import jax
    import jax.numpy as jnp

    @xjit(kernel="decode_dod")
    def kernel(words, first, first_delta):
        i = jnp.arange(n_pad, dtype=jnp.int64)
        if width:
            z = _unpack_expr(jnp, words, jnp.maximum(i - 2, 0) * width, width)
        else:
            z = jnp.zeros(n_pad, jnp.uint64)
        dd = (z >> _U64_1) ^ (jnp.uint64(0) - (z & _U64_1))  # unzigzag
        dd = jnp.where(i >= 2, dd, jnp.uint64(0))
        # d[i] = first_delta + sum_{k<=i} dd[k] for i>=1; d[0] = 0
        d = jnp.where(i >= 1, first_delta, jnp.uint64(0)) \
            + jax.lax.associative_scan(jnp.add, dd)
        # v[i] = first + sum_{k<=i} d[k]
        v = first + jax.lax.associative_scan(jnp.add, d)
        return v.view(jnp.int64)

    return kernel


@lru_cache(maxsize=256)
def _xor_kernel(width: int, n_pad: int):
    """packed xor stream + first bits -> u64 bit patterns via an
    associative XOR scan (xor is associative: log-depth, fully vector).
    Stream position j holds row j+1's xor delta; row 0 is the raw bits."""
    import jax
    import jax.numpy as jnp

    @xjit(kernel="decode_xor")
    def kernel(words, first_bits):
        i = jnp.arange(n_pad, dtype=jnp.int64)
        if width:
            x = _unpack_expr(jnp, words, jnp.maximum(i - 1, 0) * width, width)
        else:
            x = jnp.zeros(n_pad, jnp.uint64)
        x = jnp.where(i >= 1, x, first_bits)
        return jax.lax.associative_scan(jnp.bitwise_xor, x)

    return kernel


@lru_cache(maxsize=64)
def _rle_kernel(n_pad: int, runs_pad: int):
    """run values + cumulative lengths -> expanded rows: one searchsorted
    over the cumulative-run boundary lane + one gather."""
    import jax.numpy as jnp

    @xjit(kernel="decode_rle")
    def kernel(values, cum):
        idx = jnp.searchsorted(cum, jnp.arange(n_pad, dtype=cum.dtype),
                               side="right")
        return jnp.take(values, jnp.clip(idx, 0, runs_pad - 1))

    return kernel


def decode_page_device(codec: str, dtype: str, payload: bytes, rows: int,
                       width: int, p0: int, p1: int,
                       dict_values) -> "np.ndarray | None":
    """Decode ONE encoded page on device and materialize the exact host
    array; None when the page is outside the device envelope (the caller
    falls back to the host funnel). The encoded payload — not the rows —
    is what crosses the link inbound."""
    import jax.numpy as jnp

    dt = np.dtype(dtype)
    if rows == 0:
        return np.empty(0, dt)
    if width > DEVICE_MAX_WIDTH:
        return None
    n_pad = _pad_rows(rows)

    def words_lane(count: int) -> np.ndarray:
        need = _words_for(n_pad, width)
        w = np.zeros(need, np.uint32)
        if width and count:
            have = np.frombuffer(payload, "<u4",
                                 count=(count * width + 31) // 32)
            w[:len(have)] = have
        return w

    if codec == "dod":
        if not np.issubdtype(dt, np.signedinteger):
            return None
        k = _dod_kernel(width, n_pad)
        out = np.asarray(k(
            words_lane(max(0, rows - 2)),
            np.uint64(p0 & 0xFFFF_FFFF_FFFF_FFFF),
            np.uint64(p1 & 0xFFFF_FFFF_FFFF_FFFF),
        ))
        return out[:rows].astype(dt, copy=False)
    if codec == "xor":
        if dt not in (np.float64, np.float32):
            return None
        k = _xor_kernel(width, n_pad)
        bits = np.asarray(k(
            words_lane(max(0, rows - 1)),
            np.uint64(p0 & 0xFFFF_FFFF_FFFF_FFFF),
        ))
        if dt == np.float64:
            return bits[:rows].view(np.float64)
        return bits[:rows].astype(np.uint32).view(np.float32)
    if codec == "dict":
        if dict_values is None:
            return None
        k = _unpack_kernel(width, n_pad) if width else None
        if width:
            ids = np.asarray(k(words_lane(rows)))[:rows].astype(np.int64)
        else:
            ids = np.zeros(rows, np.int64)
        from horaedb_tpu.storage.encoding import dict_array

        return dict_array(dict_values, dt)[ids]
    if codec == "rle":
        n_runs = p0
        if n_runs == 0:
            return np.empty(0, dt)
        vals = np.frombuffer(payload, dtype=dt.newbyteorder("<"),
                             count=n_runs).astype(dt, copy=False)
        lengths = np.frombuffer(payload, dtype="<u4", count=n_runs,
                                offset=n_runs * dt.itemsize)
        runs_pad = max(64, 1 << (n_runs - 1).bit_length())
        vp = np.zeros(runs_pad, dt)
        vp[:n_runs] = vals
        cum = np.full(runs_pad, np.int64(rows), np.int64)
        np.cumsum(lengths.astype(np.int64), out=cum[:n_runs])
        k = _rle_kernel(_pad_rows(rows), runs_pad)
        return np.asarray(k(vp, cum))[:rows]
    return None


# ---------------------------------------------------------------------------
# calibration + dispatch (the agg_registry envelope, decode-shaped)
# ---------------------------------------------------------------------------

_last_choice_ctx: "contextvars.ContextVar[str | None]" = \
    contextvars.ContextVar("horaedb_decode_last_choice", default=None)
_last_choice_global: str = "host"

# persistence shared with ops/agg_registry.py (common/calib_cache.py)
_calib_cache = CalibCache(
    env_var="HORAEDB_DECODE_CACHE",
    filename="decode_calib.json",
    version=CALIB_VERSION,
    tmp_prefix=".decode_calib.",
)


def configure_cache_dir(path: str) -> None:
    """Point the calibration cache under the engine's data root (called
    by storage bring-up); HORAEDB_DECODE_CACHE overrides with a full
    file path."""
    _calib_cache.configure_dir(path)


def cache_path() -> str:
    return _calib_cache.path()


def reset_cache(memory_only: bool = False) -> None:
    """Drop the in-memory view (tests); optionally leave the file."""
    _calib_cache.reset(memory_only)


_load_cache = _calib_cache.load
_store_entry = _calib_cache.store_entry


def _synth_lane(codec: str, n: int):
    """Synthetic encoded lane of one codec class for the micro-A/B."""
    from horaedb_tpu.storage import encoding as enc_mod

    rng = np.random.default_rng(0xDEC)
    if codec == "dod":
        arr = (np.arange(n, dtype=np.int64) * 15_000
               + rng.integers(-4, 5, n))
        lane = enc_mod._encode_dod(arr, enc_mod.DEFAULT_PAGE_ROWS)
    elif codec == "xor":
        arr = rng.normal(size=n).astype(np.float64)
        lane = enc_mod._encode_xor(arr, enc_mod.DEFAULT_PAGE_ROWS)
    elif codec == "dict":
        arr = rng.integers(0, 256, n, dtype=np.int64)
        lane = enc_mod._encode_dict(arr, enc_mod.DEFAULT_PAGE_ROWS, 4096)
    else:  # rle
        arr = np.repeat(
            rng.integers(0, 1 << 40, max(1, n // 64), dtype=np.int64), 64
        )[:n]
        lane = enc_mod._encode_rle(arr, enc_mod.DEFAULT_PAGE_ROWS)
    lane.name = codec
    return lane, arr


def _calibrate(codec: str, platform: str) -> dict:
    from horaedb_tpu.storage import encoding as enc_mod

    try:
        n = int(os.environ.get("HORAEDB_DECODE_CALIB_N", str(1 << 17)))
    except ValueError:
        n = 1 << 17
    lane, arr = _synth_lane(codec, n)
    ab: dict[str, float] = {}
    rejected: dict[str, str] = {}
    for impl in DECODE_IMPLS:
        try:
            out = enc_mod.decode_lane(lane, impl=impl)
            ensure(np.array_equal(
                out.view(np.uint64) if out.dtype == np.float64 else out,
                arr.view(np.uint64) if arr.dtype == np.float64 else arr,
            ), f"decode impl {impl} not bit-exact on {codec}")
            t0 = time.perf_counter()
            for _ in range(2):
                enc_mod.decode_lane(lane, impl=impl)
            ab[impl] = round(n / max((time.perf_counter() - t0) / 2, 1e-9))
        except Exception as e:  # noqa: BLE001 — an impl that cannot run
            # on this backend loses by forfeit, never kills dispatch
            rejected[impl] = f"{type(e).__name__}: {e}"[:200]
    if not ab:
        ab = {"host": 0.0}
    best = max(ab, key=ab.get)
    return {
        "impl": best, "ab": ab, "rejected": rejected, "n": n,
        "calibrated_unix": int(time.time()),
    }


def calibration_entry(codec: str, platform: str | None = None) -> tuple[dict, str]:
    if platform is None:
        import jax

        platform = jax.devices()[0].platform
    key = f"{platform}/{codec}"
    data = _load_cache()
    entry = (data.get("entries") or {}).get(key)
    if entry is not None:
        return entry, "cache"
    entry = _calibrate(codec, platform)
    _store_entry(key, entry)
    return entry, "calibrated"


def _record(name: str) -> str:
    global _last_choice_global
    _last_choice_ctx.set(name)
    _last_choice_global = name
    DECODE_IMPL_TOTAL.labels(name).inc()
    return name


def scan_mode() -> str:
    """The encoded-scan override: HORAEDB_DECODE_IMPL in {auto, host,
    device, raw}. `raw` disables the encoded read path entirely (every
    scan pays the full parquet decode — the A/B honesty control).
    An unrecognized value degrades to `auto` with a once-per-value
    warning: this runs on every v2-SST read, and a typo'd pin must not
    turn every scan over an encoded tree into an error."""
    mode = os.environ.get("HORAEDB_DECODE_IMPL", "auto")
    if mode not in ("auto", "host", "device", "raw"):
        _warn_bad_mode(mode)
        return "auto"
    return mode


@lru_cache(maxsize=8)
def _warn_bad_mode(mode: str) -> None:
    logger.warning(
        "HORAEDB_DECODE_IMPL=%r is not one of auto/host/device/raw; "
        "treating as 'auto'", mode,
    )


def choose(codec: str, n: int, platform: str | None = None) -> str:
    """Resolve the decode impl for one lane: env pin > calibration cache
    (micro-A/B on first use). Small lanes pin to host — the device
    dispatch overhead can never amortize under a page. raw/null lanes
    have no device decode at all (decode_lane routes only
    dod/xor/dict/rle through ops/decode.py), so they resolve to host
    unconditionally: calibrating them would A/B a synthetic stand-in
    lane, and a `device` verdict (pinned or calibrated) would put an
    impl in the provenance that the lane never actually runs."""
    if codec not in ("dod", "xor", "dict", "rle"):
        scan_mode()  # still validate the env pin
        return _record("host")
    mode = scan_mode()
    if mode in ("host", "device"):
        return _record(mode)
    if n < 2048:
        return _record("host")
    entry, _source = calibration_entry(codec, platform=platform)
    return _record(entry["impl"])


def last_choice() -> str:
    ctx = _last_choice_ctx.get()
    return ctx if ctx is not None else _last_choice_global


# ---------------------------------------------------------------------------
# sweep CLI (run_tpu_suite.sh: the decode half of the registry harvest)
# ---------------------------------------------------------------------------


def _sweep(n: int) -> dict:
    """Force a fresh micro-A/B of every codec at `n` rows on this
    platform and report rows/s per (codec, impl) plus the winner — the
    decode analog of agg_registry --sweep, run by run_tpu_suite.sh the
    moment hardware returns."""
    import jax

    platform = jax.devices()[0].platform
    os.environ["HORAEDB_DECODE_CALIB_N"] = str(n)
    reset_cache(memory_only=True)
    out: dict = {"metric": "decode_sweep", "platform": platform, "n": n}
    codecs = {}
    for codec in ("dod", "xor", "dict", "rle"):
        entry = _calibrate(codec, platform)
        codecs[codec] = {
            "impl": entry["impl"],
            "rows_per_sec": entry["ab"],
            "rejected": entry["rejected"],
        }
    out["codecs"] = codecs
    return out


def main(argv: "list[str] | None" = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sweep", type=int, nargs="?", const=1 << 20,
                    metavar="N_ROWS",
                    help="A/B host vs device decode for every codec at "
                         "N_ROWS and print one JSON line")
    args = ap.parse_args(argv)
    if args.sweep:
        print(json.dumps(_sweep(args.sweep)))
        return
    ap.print_help()


if __name__ == "__main__":
    main()
