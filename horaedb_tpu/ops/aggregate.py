"""Segment reductions: group-by-tag aggregation and time-bucket downsampling.

These are the kernels behind BASELINE configs 1-4 (range-aggregate,
group-by-tag avg/min/max, 5-minute downsample). The design maps each
(group, time-bucket) cell to a flat segment index and reduces with XLA
scatter-adds (`jax.ops.segment_*`) — one pass over the data, no sort needed,
entirely fusible with the predicate mask from filter.py.

Invalid/padding rows are routed to an out-of-range segment index, which XLA's
scatter drop-semantics discard for free — no host-side compaction on the
aggregate path (SURVEY §7 risk (e) resolved by reduction, not masking).

Dense i32 indices + f32 accumulation are deliberate: TPUs emulate 64-bit
integer lanes, so hot aggregation runs on native-width types. Host code maps
u64 TSIDs to dense series indices before dispatch (ops/__init__ docstring).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _masked_index(index: jax.Array, valid: jax.Array, num_segments: int) -> jax.Array:
    """Invalid rows -> index == num_segments (dropped by segment ops)."""
    return jnp.where(valid, index, num_segments).astype(jnp.int32)


def masked_segment_stats(
    values: jax.Array,
    idx: jax.Array,
    valid: jax.Array,
    num_segments: int,
    with_minmax: bool = True,
):
    """Shared masked segment-reduction core (also used by the sharded scan in
    parallel/scan.py): `idx` must already route invalid rows to num_segments.
    Returns (sum, count, min|None, max|None) flat arrays of len num_segments.

    Scatters are the expensive op on TPU — min/max are skipped when not
    requested, and values/ones stay flat 1-D (stacking features breaks the
    (8,128) tile layout and measures ~4x slower).
    """
    s = jax.ops.segment_sum(jnp.where(valid, values, 0), idx, num_segments + 1)[:-1]
    c = jax.ops.segment_sum(valid.astype(values.dtype), idx, num_segments + 1)[:-1]
    if not with_minmax:
        return s, c, None, None
    mn = jax.ops.segment_min(jnp.where(valid, values, jnp.inf), idx, num_segments + 1)[:-1]
    mx = jax.ops.segment_max(jnp.where(valid, values, -jnp.inf), idx, num_segments + 1)[:-1]
    return s, c, mn, mx


@partial(jax.jit, static_argnames=("num_segments",))
def grouped_stats(
    values: jax.Array,
    index: jax.Array,
    valid: jax.Array,
    num_segments: int,
) -> dict[str, jax.Array]:
    """sum / count / min / max / mean per segment, one fused pass.

    Empty segments report count 0, sum 0, min +inf, max -inf, mean NaN.
    """
    idx = _masked_index(index, valid, num_segments)
    s, c, mn, mx = masked_segment_stats(values, idx, valid, num_segments)
    return {"sum": s, "count": c, "min": mn, "max": mx, "mean": s / c}


def bucket_of(ts: jax.Array, t0, bucket_ms) -> jax.Array:
    """Time-bucket index relative to t0. i64-safe, result is i32-dense."""
    return ((ts - t0) // bucket_ms).astype(jnp.int32)


def downsample_sorted(
    ts,
    series_idx,
    values,
    t0,
    bucket_ms,
    num_series: int,
    num_buckets: int,
    with_minmax: bool = True,
) -> dict:
    """Downsample over rows SORTED by (series, ts) — the engine's natural
    scan-output order (pk = ids + timestamp), which makes the flat cell index
    monotone. sum/count dispatch to the Pallas sorted-segment kernel
    (ops/pallas_kernels.py; MXU one-hot matmuls instead of a scatter, with
    an automatic XLA fallback); min/max, when requested, still scatter.
    """
    from horaedb_tpu.ops.pallas_kernels import _F32_EXACT, sorted_segment_sum_count

    num_cells = num_series * num_buckets
    if num_cells >= _F32_EXACT:
        # grid too large for exact f32 cell-id recovery; use the scatter path
        valid = jnp.ones(jnp.asarray(values).shape[0], dtype=bool)
        out = downsample(ts, series_idx, values, valid, t0, bucket_ms,
                         num_series=num_series, num_buckets=num_buckets)
        if not with_minmax:
            out = {k: out[k] for k in ("sum", "count", "mean")}
        return out
    ts = jnp.asarray(ts)
    series_idx = jnp.asarray(series_idx)
    values = jnp.asarray(values)
    bucket = ((ts - t0) // bucket_ms).astype(jnp.int32)
    ok = (
        (bucket >= 0) & (bucket < num_buckets)
        & (series_idx >= 0) & (series_idx < num_series)
    )
    flat = jnp.where(ok, series_idx.astype(jnp.int32) * num_buckets + bucket, num_cells)
    s, c = sorted_segment_sum_count(flat, jnp.where(ok, values, 0.0), num_cells)
    shape = (num_series, num_buckets)
    out = {
        "sum": s.reshape(shape),
        "count": c.reshape(shape),
        "mean": (s / c).reshape(shape),
    }
    if with_minmax:
        mn = jax.ops.segment_min(
            jnp.where(ok, values, jnp.inf), flat, num_cells + 1
        )[:-1]
        mx = jax.ops.segment_max(
            jnp.where(ok, values, -jnp.inf), flat, num_cells + 1
        )[:-1]
        out["min"] = mn.reshape(shape)
        out["max"] = mx.reshape(shape)
    return out


@partial(jax.jit, static_argnames=("num_cells", "lanes"))
def lane_segment_sum_count(k, v, num_cells: int, lanes: int = 8):
    """Experimental lane-parallel scatter: rows reshape to [lanes, n/lanes]
    and each lane scatter-adds into its OWN partial grid (vmap batches the
    scatters), then the lanes tree-reduce. If XLA vectorizes the batched
    scatter across lanes, this trades lanes x grid memory for lanes-fold
    scatter parallelism — an A/B candidate against the block compaction on
    real hardware (queued from round-1 profiling). Works for unsorted input.
    """
    n = k.shape[0]
    m = n - n % lanes
    k2 = jnp.clip(k[:m], 0, num_cells).astype(jnp.int32).reshape(lanes, -1)
    v2 = v[:m].astype(jnp.float32).reshape(lanes, -1)

    def one(kl, vl):
        s = jax.ops.segment_sum(vl, kl, num_cells + 1)[:-1]
        c = jax.ops.segment_sum(jnp.ones_like(vl), kl, num_cells + 1)[:-1]
        return s, c

    s, c = jax.vmap(one)(k2, v2)
    s, c = s.sum(axis=0), c.sum(axis=0)
    if m < n:
        kt = jnp.clip(k[m:], 0, num_cells).astype(jnp.int32)
        vt = v[m:].astype(jnp.float32)
        s = s + jax.ops.segment_sum(vt, kt, num_cells + 1)[:-1]
        c = c + jax.ops.segment_sum(jnp.ones_like(vt), kt, num_cells + 1)[:-1]
    return s, c


@partial(jax.jit, static_argnames=("num_series", "num_buckets"))
def downsample(
    ts: jax.Array,
    series_idx: jax.Array,
    values: jax.Array,
    valid: jax.Array,
    t0,
    bucket_ms,
    num_series: int,
    num_buckets: int,
) -> dict[str, jax.Array]:
    """Per-(series, bucket) stats as dense [num_series, num_buckets] grids —
    the 5m-avg downsample of BASELINE config 4.
    """
    bucket = bucket_of(ts, t0, bucket_ms)
    in_grid = valid & (bucket >= 0) & (bucket < num_buckets) \
        & (series_idx >= 0) & (series_idx < num_series)
    flat = series_idx.astype(jnp.int32) * num_buckets + bucket
    stats = grouped_stats(values, flat, in_grid, num_series * num_buckets)
    return {k: v.reshape(num_series, num_buckets) for k, v in stats.items()}


@partial(jax.jit, static_argnames=("num_segments",))
def segment_last_value(
    values: jax.Array,
    seq: jax.Array,
    index: jax.Array,
    valid: jax.Array,
    num_segments: int,
) -> jax.Array:
    """Value of the max-seq row per segment — dedup-as-reduction for
    aggregation pipelines that don't need full row materialization.
    Implemented as an argmax over (seq) per segment via segment_max on a
    packed (seq, position) key."""
    n = values.shape[0]
    idx = _masked_index(index, valid, num_segments)
    # Two-stage argmax (no packed-key arithmetic: real sequences are ns-clock
    # file ids ~1.8e18, so seq*n would overflow int64): find each segment's
    # max seq, then take the latest row achieving it.
    seq_i = seq.astype(jnp.int64)
    max_seq = jax.ops.segment_max(jnp.where(valid, seq_i, jnp.iinfo(jnp.int64).min), idx, num_segments + 1)
    winner = valid & (seq_i == max_seq[idx])
    pos = jnp.arange(n, dtype=jnp.int64)
    best_pos = jax.ops.segment_max(jnp.where(winner, pos, -1), idx, num_segments + 1)[:-1]
    return jnp.where(best_pos >= 0, values[jnp.clip(best_pos, 0, n - 1)], jnp.nan)
