"""Segment reductions: group-by-tag aggregation and time-bucket downsampling.

These are the kernels behind BASELINE configs 1-4 (range-aggregate,
group-by-tag avg/min/max, 5-minute downsample). The design maps each
(group, time-bucket) cell to a flat segment index and reduces with XLA
scatter-adds (`jax.ops.segment_*`) — one pass over the data, no sort needed,
entirely fusible with the predicate mask from filter.py.

Invalid/padding rows are routed to an out-of-range segment index, which XLA's
scatter drop-semantics discard for free — no host-side compaction on the
aggregate path (SURVEY §7 risk (e) resolved by reduction, not masking).

Dense i32 indices + f32 accumulation are deliberate: TPUs emulate 64-bit
integer lanes, so hot aggregation runs on native-width types. Host code maps
u64 TSIDs to dense series indices before dispatch (ops/__init__ docstring).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from horaedb_tpu.common.xprof import xjit


def _masked_index(index: jax.Array, valid: jax.Array, num_segments: int) -> jax.Array:
    """Invalid rows -> index == num_segments (dropped by segment ops)."""
    return jnp.where(valid, index, num_segments).astype(jnp.int32)


def masked_cell_keys(series_idx, bucket, ok, num_series: int, num_buckets: int):
    """Cell-id construction shared by every downsample path: returns
    (safe, flat) where `safe` keeps masked rows at an IN-RANGE clipped id
    (their contribution rides the weight column) and `flat` routes them to
    the num_cells sentinel (scatter drop semantics, for min/max).

    Masked rows must NOT get sentinel keys on the sum/count path: sentinel
    interleaving breaks the sorted runs the block compaction exploits and
    trips its adaptive scatter fallback whenever a predicate is active.
    Both the sid and the bucket are clipped BEFORE forming the flat id —
    an out-of-window ts would otherwise spill into the neighbouring
    series' id range and destroy monotonicity. With the clip, keys stay
    monotone in (sid, ts) for any series-slice/time-window masking."""
    safe = jnp.clip(series_idx.astype(jnp.int32), 0, num_series - 1) \
        * num_buckets + jnp.clip(bucket, 0, num_buckets - 1)
    flat = jnp.where(ok, safe, num_series * num_buckets)
    return safe, flat


def masked_minmax(values, idx, valid, num_segments: int):
    """Scatter-based min/max per segment with sentinel-index drop semantics
    (`idx` must route invalid rows to num_segments; invalid values fill
    +/-inf). The SCATTER-path helper: compaction-eligible paths use
    blockagg.sorted_segment_min_max (masked-reduce block compaction)
    instead."""
    mn = jax.ops.segment_min(
        jnp.where(valid, values, jnp.inf), idx, num_segments + 1
    )[:-1]
    mx = jax.ops.segment_max(
        jnp.where(valid, values, -jnp.inf), idx, num_segments + 1
    )[:-1]
    return mn, mx


def masked_segment_stats(
    values: jax.Array,
    idx: jax.Array,
    valid: jax.Array,
    num_segments: int,
    with_minmax: bool = True,
):
    """Shared masked segment-reduction core (also used by the sharded scan in
    parallel/scan.py): `idx` must already route invalid rows to num_segments.
    Returns (sum, count, min|None, max|None) flat arrays of len num_segments.

    Scatters are the expensive op on TPU — min/max are skipped when not
    requested, and values/ones stay flat 1-D (stacking features breaks the
    (8,128) tile layout and measures ~4x slower).
    """
    # integers widen to 64-bit accumulation (exact, wrap-proof for narrow
    # int sums), matching blockagg._scatter_sum_count; floats keep
    # their own width (the engine's precision contract, data.py)
    vals = jnp.asarray(values)
    if jnp.issubdtype(vals.dtype, jnp.unsignedinteger):
        vals = vals.astype(jnp.uint64)
    elif not jnp.issubdtype(vals.dtype, jnp.floating):
        vals = vals.astype(jnp.int64)  # bool included
    s = jax.ops.segment_sum(jnp.where(valid, vals, 0), idx, num_segments + 1)[:-1]
    c = jax.ops.segment_sum(valid.astype(vals.dtype), idx, num_segments + 1)[:-1]
    if not with_minmax:
        return s, c, None, None
    mn, mx = masked_minmax(values, idx, valid, num_segments)
    return s, c, mn, mx


@xjit(kernel="grouped_stats", static_argnames=("num_segments",))
def grouped_stats(
    values: jax.Array,
    index: jax.Array,
    valid: jax.Array,
    num_segments: int,
) -> dict[str, jax.Array]:
    """sum / count / min / max / mean per segment, one fused pass.

    Empty segments report count 0, sum 0, min +inf, max -inf, mean NaN.
    Out-of-range indices are DROPPED regardless of `valid` (scatter
    out-of-bounds drop semantics, the pre-dispatch contract). On the
    accelerator sort path ONE device sort feeds all four stats: sum/count
    via the block-rank compaction, min/max via the masked-reduce
    compaction. Otherwise (CPU, sparse grids, non-f32) everything
    scatters, dtype-preserving.
    """
    from horaedb_tpu.ops.blockagg import (
        _F32_EXACT,
        segment_sum_count,
        sorted_segment_min_max,
        sorted_segment_sum_count,
        unsorted_strategy,
    )

    # the dispatcher's sort path clips indices into range, so out-of-range
    # rows must be folded into the mask here to keep the drop semantics;
    # integer values keep the exact dtype-preserving scatter (the block
    # compaction accumulates f32, which would round int sums above 2^24)
    valid = valid & (index >= 0) & (index < num_segments)
    idx = _masked_index(index, valid, num_segments)
    vals_j = jnp.asarray(values)
    if num_segments < _F32_EXACT and jnp.issubdtype(vals_j.dtype, jnp.floating):
        masked = jnp.where(valid, vals_j, 0)
        if unsorted_strategy(idx.shape[0], num_segments, masked.dtype) == "sort":
            # one device sort feeds all four stats (sentinels drop at the
            # tail bucket); min/max use the masked-reduce compaction
            k2, v2 = jax.lax.sort((idx, masked), num_keys=1)
            s, c = sorted_segment_sum_count(k2, v2, num_segments, impl="block")
            mn, mx = sorted_segment_min_max(k2, v2, num_segments, impl="block")
        else:
            s, c = segment_sum_count(idx, masked, num_segments, impl="scatter")
            mn, mx = masked_minmax(values, idx, valid, num_segments)
    else:
        s, c, mn, mx = masked_segment_stats(values, idx, valid, num_segments)
    return {"sum": s, "count": c, "min": mn, "max": mx, "mean": s / c}


def bucket_of(ts: jax.Array, t0, bucket_ms) -> jax.Array:
    """Time-bucket index relative to t0. i64-safe, result is i32-dense."""
    return ((ts - t0) // bucket_ms).astype(jnp.int32)


def downsample_sorted(
    ts,
    series_idx,
    values,
    t0,
    bucket_ms,
    num_series: int,
    num_buckets: int,
    with_minmax: bool = True,
    valid=None,
) -> dict:
    """Downsample over rows SORTED by (series, ts) — the engine's natural
    scan-output order (pk = ids + timestamp), which makes the flat cell index
    monotone. sum/count dispatch to the sorted-segment compaction
    (ops/blockagg.py; MXU one-hot matmuls instead of a scatter, with
    an automatic XLA fallback); min/max, when requested, use the
    masked-reduce compaction (sorted_segment_min_max, scatter fallback).

    `valid` (optional bool) excludes rows (predicate / set-membership miss)
    WITHOUT breaking the sorted runs: excluded rows must keep a monotone
    series_idx (e.g. the searchsorted position, not -1) and are zeroed via
    the compaction's weight column.

    Concrete (non-traced) inputs on the CPU backend consult the calibrated
    registry dispatcher first: when the measured winner is a host lane
    (np.add.reduceat over run boundaries), the WHOLE grid computes on host
    — no device dispatch at all, and no f32-exact grid-size ceiling (host
    keys are i64).
    """
    from horaedb_tpu.ops.blockagg import _F32_EXACT, sorted_segment_sum_count

    num_cells = num_series * num_buckets
    traced = any(
        isinstance(x, jax.core.Tracer)
        for x in (ts, series_idx, values, valid)
    )
    # resolve the dispatcher ONCE and thread the choice through both
    # reductions below — re-resolving per reduction would triple-count
    # horaedb_agg_impl_total and re-read env/cache on the scan hot path
    choice: str | None = None
    if not traced and jax.devices()[0].platform == "cpu":
        from horaedb_tpu.ops import agg_registry

        choice = agg_registry.choose_sorted(
            jnp.shape(values)[0], num_cells, concrete=True
        )
        if agg_registry.is_host_impl(choice):
            return agg_registry.host_downsample_sorted(
                ts, series_idx, values, t0, bucket_ms,
                num_series=num_series, num_buckets=num_buckets,
                with_minmax=with_minmax, valid=valid, impl=choice,
            )
    if num_cells >= _F32_EXACT:
        # grid too large for exact f32 cell-id recovery; use the scatter path
        v_mask = (
            jnp.ones(jnp.asarray(values).shape[0], dtype=bool)
            if valid is None else jnp.asarray(valid)
        )
        out = downsample(ts, series_idx, values, v_mask, t0, bucket_ms,
                         num_series=num_series, num_buckets=num_buckets)
        if not with_minmax:
            out = {k: out[k] for k in ("sum", "count", "mean")}
        return out
    ts = jnp.asarray(ts)
    series_idx = jnp.asarray(series_idx)
    values = jnp.asarray(values)
    bucket = ((ts - t0) // bucket_ms).astype(jnp.int32)
    ok = (
        (bucket >= 0) & (bucket < num_buckets)
        & (series_idx >= 0) & (series_idx < num_series)
    )
    if valid is not None:
        ok = ok & jnp.asarray(valid)
    safe, flat = masked_cell_keys(series_idx, bucket, ok, num_series, num_buckets)
    # typed zero fill: a weak 0.0 would promote integer values to float and
    # bypass the dtype-preserving integer scatter route
    s, c = sorted_segment_sum_count(
        safe, jnp.where(ok, values, jnp.zeros((), values.dtype)), num_cells,
        impl=choice, weights=ok.astype(values.dtype),
    )
    shape = (num_series, num_buckets)
    out = {
        "sum": s.reshape(shape),
        "count": c.reshape(shape),
        "mean": (s / c).reshape(shape),
    }
    if with_minmax:
        from horaedb_tpu.ops.blockagg import sorted_segment_min_max

        mn, mx = sorted_segment_min_max(
            safe, values, num_cells, impl=choice, valid=ok
        )
        out["min"] = mn.reshape(shape)
        out["max"] = mx.reshape(shape)
    return out


@xjit(kernel="lane_sum_count", static_argnames=("num_cells", "lanes"))
def lane_segment_sum_count(k, v, num_cells: int, lanes: int = 8, w=None):
    """Experimental lane-parallel scatter: rows reshape to [lanes, n/lanes]
    and each lane scatter-adds into its OWN partial grid (vmap batches the
    scatters), then the lanes tree-reduce. If XLA vectorizes the batched
    scatter across lanes, this trades lanes x grid memory for lanes-fold
    scatter parallelism — an A/B candidate against the block compaction on
    real hardware (queued from round-1 profiling). Works for unsorted input.
    `w` (optional) is each row's count contribution (predicate weights).
    """
    n = k.shape[0]
    m = n - n % lanes
    k2 = jnp.clip(k[:m], 0, num_cells).astype(jnp.int32).reshape(lanes, -1)
    v2 = v[:m].astype(jnp.float32).reshape(lanes, -1)
    w2 = (
        jnp.ones_like(v2) if w is None
        else w[:m].astype(jnp.float32).reshape(lanes, -1)
    )

    def one(kl, vl, wl):
        s = jax.ops.segment_sum(vl, kl, num_cells + 1)[:-1]
        c = jax.ops.segment_sum(wl, kl, num_cells + 1)[:-1]
        return s, c

    s, c = jax.vmap(one)(k2, v2, w2)
    s, c = s.sum(axis=0), c.sum(axis=0)
    if m < n:
        kt = jnp.clip(k[m:], 0, num_cells).astype(jnp.int32)
        vt = v[m:].astype(jnp.float32)
        wt = jnp.ones_like(vt) if w is None else w[m:].astype(jnp.float32)
        s = s + jax.ops.segment_sum(vt, kt, num_cells + 1)[:-1]
        c = c + jax.ops.segment_sum(wt, kt, num_cells + 1)[:-1]
    return s, c


@xjit(kernel="stacked_downsample",
      static_argnames=("num_series", "num_buckets"))
def stacked_downsample(
    ts: jax.Array,
    series_idx: jax.Array,
    values: jax.Array,
    valid: jax.Array,
    t0: jax.Array,
    bucket_ms,
    num_series: int,
    num_buckets: int,
) -> dict[str, jax.Array]:
    """Downsample grids for a STACK of coalesced queries in one launch —
    the query batcher's device lane (server/batching.py): inputs carry a
    leading query axis ([B, R] row lanes padded to shared power-of-two
    buckets, per-query `t0` as a [B] dynamic operand so start offsets
    never retrace), output is [B, num_series, num_buckets] per stat.

    Lane-offset flattening keeps bit-exact parity with solo execution
    while outrunning a vmapped scatter ~2x on CPU (measured): every row
    gets the flat cell id `lane * num_series * num_buckets + sid *
    num_buckets + bucket`, masked rows route to the one shared sentinel,
    and ONE segment reduction over the flattened [B*R] lanes fills every
    query's grid. Lanes own disjoint id ranges and each lane's rows stay
    contiguous and in scan order, so a cell accumulates exactly the rows
    — in exactly the order — its query's solo reduction would. Shapes
    are static in (B, R, num_series, num_buckets) — the batcher pads all
    three axes to power-of-two classes, so compiled executables are
    shared across launches and retraces stay caught by xprof.

    Accumulation dtype follows the inputs (f64 on the x64 CPU path, the
    engine's precision contract — see SampleManager.query_downsample)."""
    nb, cells = t0.shape[0], num_series * num_buckets
    bucket = ((ts - t0[:, None]) // bucket_ms).astype(jnp.int32)
    ok = (
        valid & (bucket >= 0) & (bucket < num_buckets)
        & (series_idx >= 0) & (series_idx < num_series)
    )
    lane = jnp.arange(nb, dtype=jnp.int32)[:, None]
    safe = jnp.clip(series_idx, 0, num_series - 1) * num_buckets \
        + jnp.clip(bucket, 0, num_buckets - 1)
    flat = jnp.where(ok, lane * cells + safe, nb * cells)
    s, c, mn, mx = masked_segment_stats(
        values.reshape(-1), flat.reshape(-1), ok.reshape(-1), nb * cells
    )
    shape = (nb, num_series, num_buckets)
    s, c = s.reshape(shape), c.reshape(shape)
    return {"sum": s, "count": c, "min": mn.reshape(shape),
            "max": mx.reshape(shape), "mean": s / c}


@xjit(kernel="downsample", static_argnames=("num_series", "num_buckets"))
def downsample(
    ts: jax.Array,
    series_idx: jax.Array,
    values: jax.Array,
    valid: jax.Array,
    t0,
    bucket_ms,
    num_series: int,
    num_buckets: int,
) -> dict[str, jax.Array]:
    """Per-(series, bucket) stats as dense [num_series, num_buckets] grids —
    the 5m-avg downsample of BASELINE config 4.
    """
    bucket = bucket_of(ts, t0, bucket_ms)
    in_grid = valid & (bucket >= 0) & (bucket < num_buckets) \
        & (series_idx >= 0) & (series_idx < num_series)
    flat = series_idx.astype(jnp.int32) * num_buckets + bucket
    stats = grouped_stats(values, flat, in_grid, num_series * num_buckets)
    return {k: v.reshape(num_series, num_buckets) for k, v in stats.items()}


@xjit(kernel="segment_last_value", static_argnames=("num_segments",))
def segment_last_value(
    values: jax.Array,
    seq: jax.Array,
    index: jax.Array,
    valid: jax.Array,
    num_segments: int,
) -> jax.Array:
    """Value of the max-seq row per segment — dedup-as-reduction for
    aggregation pipelines that don't need full row materialization.
    Implemented as an argmax over (seq) per segment via segment_max on a
    packed (seq, position) key."""
    n = values.shape[0]
    idx = _masked_index(index, valid, num_segments)
    # Two-stage argmax (no packed-key arithmetic: real sequences are ns-clock
    # file ids ~1.8e18, so seq*n would overflow int64): find each segment's
    # max seq, then take the latest row achieving it.
    seq_i = seq.astype(jnp.int64)
    max_seq = jax.ops.segment_max(
        jnp.where(valid, seq_i, jnp.iinfo(jnp.int64).min), idx, num_segments + 1
    )
    winner = valid & (seq_i == max_seq[idx])
    pos = jnp.arange(n, dtype=jnp.int64)
    best_pos = jax.ops.segment_max(jnp.where(winner, pos, -1), idx, num_segments + 1)[:-1]
    return jnp.where(best_pos >= 0, values[jnp.clip(best_pos, 0, n - 1)], jnp.nan)
