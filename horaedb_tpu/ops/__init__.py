"""Device kernels: the TPU data plane.

This package replaces the reference's DataFusion execution pipeline
(ParquetExec -> FilterExec -> SortPreservingMergeExec -> MergeExec,
src/columnar_storage/src/read.rs:429-494) with jit-compiled XLA:

  blocks.py     struct-of-arrays device block format (padded, static shapes)
  sort.py       multi-column lexicographic sort (XLA sort on composite keys)
  filter.py     vectorized predicate evaluation -> boolean mask
  dedup.py      run-boundary detection + last-value (max-seq) group masks
  merge.py      k-way sorted merge as concat+sort (the XLA-idiomatic shape)
  aggregate.py  segment reductions: group-by, time-bucket downsample
  blockagg.py   sorted-segment reduction strategies (block-rank compaction
                variants, fused sorted scatter, adaptive fallbacks)
  agg_registry.py  the impl registry + self-calibrating dispatcher behind
                every aggregate lane (micro-A/B once per platform/density,
                persisted; host reduceat/bincount lanes live here)

Everything operates on fixed-size padded blocks with validity masks — XLA
wants static shapes (SURVEY §7 risk (a)/(e)); dynamic row counts travel as
scalar `num_valid` operands and padding rows carry +inf sort keys so they sink
to the tail of every ordering.

Exact dedup/merge semantics need 64-bit keys (ids are u64 hashes, timestamps
i64), so importing this package enables jax x64. The perf-critical aggregate
kernels additionally offer dense-i32/f32 fast paths that avoid emulated
64-bit arithmetic on the MXU-adjacent vector units.
"""

import jax

jax.config.update("jax_enable_x64", True)

from horaedb_tpu.ops.blocks import Block  # noqa: E402
from horaedb_tpu.ops import sort, filter as filter_ops, dedup, merge, aggregate  # noqa: E402

__all__ = ["Block", "sort", "filter_ops", "dedup", "merge", "aggregate"]
