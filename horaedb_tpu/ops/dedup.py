"""Run-boundary detection and sequence-based dedup masks.

Replaces the reference's MergeExec/MergeStream row-group loop
(read.rs:99-385): input sorted by (pk..., __seq__), consecutive rows with
equal pks form a group, and the merge operator collapses each group. On TPU
the group scan becomes data-parallel mask algebra:

  starts[i]   = valid[i] and any(pk[i] != pk[i-1])          (run boundary)
  seg_ids     = cumsum(starts) - 1                          (group index)
  last-value  = keep rows that end their group               (max __seq__ wins,
                because the sort is stable with seq as the least key)

This is exactly the "run-length boundary detection + segment-reduce" design
named in SURVEY C8. The reference's `pending_batch` carry across stream
batches (read.rs:308-330) maps to the host-side carry loop in
storage/read.py for segments larger than one device block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def run_starts(key_cols: list[jax.Array], valid: jax.Array) -> jax.Array:
    """Boolean mask: row i begins a new primary-key group.

    Pure mask algebra (an iota compare, not `.at[0].set` — which lowers to a
    scatter and the scan kernel's plan-shape contract is scatter-free)."""
    n = key_cols[0].shape[0]
    diff = jnp.arange(n) == 0
    for col in key_cols:
        prev = jnp.concatenate([col[:1], col[:-1]])
        diff = diff | (col != prev)
    return diff & valid


def segment_ids(starts: jax.Array) -> jax.Array:
    """Group index per row: 0-based, monotone. Padding rows inherit the last
    group's id; mask with `valid` downstream."""
    return jnp.cumsum(starts.astype(jnp.int32)) - 1


def last_in_group_mask(starts: jax.Array, valid: jax.Array, num_valid) -> jax.Array:
    """Keep-mask selecting the final row of every group — the LastValueOperator
    (operator.rs:36-44): with rows sorted by (pk, seq), the last row of a group
    holds the max sequence, i.e. the newest write wins (Overwrite mode)."""
    n = starts.shape[0]
    next_is_new = jnp.concatenate([starts[1:], jnp.ones(1, dtype=bool)])
    is_final_valid_row = jnp.arange(n) == (num_valid - 1)
    return valid & (next_is_new | is_final_valid_row)


def dedup_last_value(
    columns: dict[str, jax.Array],
    key_names: list[str],
    num_valid,
) -> jax.Array:
    """One-shot: keep-mask for Overwrite-mode dedup over a sorted block."""
    n = columns[key_names[0]].shape[0]
    valid = jnp.arange(n) < num_valid
    starts = run_starts([columns[k] for k in key_names], valid)
    return last_in_group_mask(starts, valid, num_valid)
