"""Self-calibrating aggregation kernel registry (ROOFLINE §1's queued
experiments, made dispatchable).

The segment-reduction workhorse behind downsample/group-by used to be a
hard-coded impl per platform (`HORAEDB_SORTED_IMPL` defaulting to scatter
on CPU, the block compaction on accelerators). The measured record says
that is wrong twice over:

- the sort-vs-hash-vs-scatter winner flips with group density AND with the
  box (arXiv:2411.13245): on one CI container XLA's scatter runs the bench
  shape at 35 M rows/s and beats every host lane; on another the same
  scatter manages 4.7 M while a host `np.add.reduceat` over run boundaries
  does 24.5 M — a 5× swing in OPPOSITE directions for identical code;
- ROOFLINE §1 queues three never-built block-compaction variants
  (ranks=32, bf16 one-hot, associative_scan prologue) whose value can only
  be decided by measurement on the hardware at hand.

So: every interchangeable (sum, count) strategy registers here with its
capability envelope (traceable under jit? host-only? platform limits?),
and `choose_sorted`/`choose_unsorted` pick by a micro-A/B run once per
(platform, density class) and persisted under the data root — the
aggregate-path analog of storage/read.py's `_HostCalib`/`_LinkProfile`
measured-not-assumed planning. The choice is exported as
`horaedb_agg_impl_total{impl=...}` and pinnable via `HORAEDB_AGG_IMPL`.

Execution stays in ops/blockagg.py (device lanes) and this module (host
lanes); blockagg's `sorted_segment_sum_count(impl=...)` accepts every name
registered here, so the registry is metadata + measurement + choice, not a
parallel code path.

The host lanes are the one place in the engine allowed to call
`np.add.reduceat`/`np.minimum.reduceat` on the aggregate path — jaxlint
J006 rejects new ad-hoc host reductions and one-hot materializations
outside the registry modules (docs/static-analysis.md).
"""

from __future__ import annotations

import contextvars
import json
import os
import time
from dataclasses import dataclass

import numpy as np

from horaedb_tpu.common.calib_cache import CalibCache
from horaedb_tpu.common.error import ensure
from horaedb_tpu.server.metrics import GLOBAL_METRICS

AGG_IMPL_TOTAL = GLOBAL_METRICS.counter(
    "horaedb_agg_impl_total",
    help="Aggregation kernel the calibrated dispatcher selected, per "
         "dispatch (trace-time dispatches count once per compile).",
    labelnames=("impl",),
)
# pre-register the universal fallback so the family renders on /metrics
# from boot (same pattern as horaedb_scan_path_total)
AGG_IMPL_TOTAL.labels("scatter")

# bf16 one-hot value-lane error budget per grid cell, vs the f64 oracle:
# |err| <= BF16_L1_BUDGET * sum(|v|) + BF16_ATOL. Inputs round to bf16
# (rel ~2^-9 each), so the cell-sum error is bounded by the cell's L1 mass,
# not its (possibly cancelling) sum. Counts stay exact: 0/1 weights and
# one-hot entries are exactly representable in bf16 and partials accumulate
# f32. The calibrator verifies the budget against a live f64 oracle before
# ever letting the lane win (and records the rejection if it fails).
BF16_L1_BUDGET = 2.0 ** -7
BF16_ATOL = 1e-3

CALIB_VERSION = 2


@dataclass(frozen=True)
class AggImpl:
    """One registered (sum, count) strategy.

    `traceable`: usable on jax tracers (inside jit/shard_map). Host lanes
    are not — they need concrete arrays.
    `platforms`: backends the impl is worth measuring on (() = all).
    """

    name: str
    kind: str  # "device" | "host"
    traceable: bool
    platforms: tuple[str, ...]
    description: str


SORTED_IMPLS: dict[str, AggImpl] = {
    impl.name: impl
    for impl in (
        AggImpl("scatter", "device", True, (),
                "two plain segment-sum scatters (dtype-preserving)"),
        AggImpl("scatter_fused", "device", True, (),
                "ONE stacked (value, weight) scatter with "
                "indices_are_sorted — halves the scatter passes"),
        AggImpl("block", "device", True, (),
                "block-rank one-hot compaction, block=512 ranks=64"),
        AggImpl("block_wide", "device", True, (),
                "block-rank compaction, block=2048 ranks=256 (the r02 "
                "loser, kept measurable)"),
        AggImpl("block_r32", "device", True, (),
                "ROOFLINE §1 exp 1: ranks=32 halves one-hot traffic; "
                "density-triggered scatter fallback covers sparse blocks"),
        AggImpl("block_bf16", "device", True, (),
                "ROOFLINE §1 exp 2: bf16 one-hot for the value/count "
                "features (ids recovered exactly via boundary max-reduce); "
                "gated by the f64-oracle error budget"),
        AggImpl("block_scan", "device", True, (),
                "ROOFLINE §1 exp 3: boundary-segmented associative_scan "
                "rank prologue instead of cumsum"),
        AggImpl("lanes", "device", True, (),
                "lane-parallel vmap scatter over partial grids"),
        AggImpl("reduceat", "host", False, ("cpu",),
                "host run-boundary lane: np.add.reduceat over "
                "searchsorted/diff boundaries — near memory-bandwidth "
                "bound on sorted CPU input"),
    )
}

UNSORTED_IMPLS: dict[str, AggImpl] = {
    impl.name: impl
    for impl in (
        AggImpl("scatter", "device", True, (),
                "two plain segment-sum scatters"),
        AggImpl("sort", "device", True, (),
                "device sort + block compaction"),
        AggImpl("bincount", "host", False, ("cpu",),
                "host np.bincount pair (hash-style grouping)"),
    )
}


def sorted_impl_names(platform: str | None = None,
                      concrete: bool = True) -> list[str]:
    """Registered sorted-lane names eligible on `platform` (None = all)."""
    return [
        i.name for i in SORTED_IMPLS.values()
        if (not i.platforms or platform is None or platform in i.platforms)
        and (concrete or i.traceable)
    ]


def unsorted_impl_names(platform: str | None = None,
                        concrete: bool = True) -> list[str]:
    return [
        i.name for i in UNSORTED_IMPLS.values()
        if (not i.platforms or platform is None or platform in i.platforms)
        and (concrete or i.traceable)
    ]


def is_host_impl(name: str) -> bool:
    impl = SORTED_IMPLS.get(name) or UNSORTED_IMPLS.get(name)
    return impl is not None and impl.kind == "host"


# ---------------------------------------------------------------------------
# host lanes (the only sanctioned np.*.reduceat on the aggregate path)
# ---------------------------------------------------------------------------


def _acc_dtype(v: np.ndarray) -> np.dtype:
    """Accumulation dtype mirroring blockagg._scatter_sum_count: floats keep
    their width (the engine's precision contract), integers widen to 64-bit
    exact accumulation."""
    if np.issubdtype(v.dtype, np.floating):
        return v.dtype
    if np.issubdtype(v.dtype, np.unsignedinteger):
        return np.dtype(np.uint64)
    return np.dtype(np.int64)


def _run_starts(k: np.ndarray) -> np.ndarray:
    b = np.flatnonzero(k[1:] != k[:-1])
    starts = np.empty(len(b) + 1, np.int64)
    starts[0] = 0
    starts[1:] = b + 1
    return starts


def host_reduceat_sum_count(k_sorted, v, num_cells: int, weights=None):
    """(sum, count) per cell over SORTED host arrays via run-boundary
    `np.add.reduceat` — no per-row scatter at all; the only scatter left is
    one unique-index assignment over the runs. Contract matches
    blockagg.sorted_segment_sum_count: invalid rows either carry sentinel
    ids >= num_cells (contiguous runs, dropped here by the cell filter) or
    ride the `weights` column with values pre-masked to 0."""
    k = np.asarray(k_sorted)
    v = np.asarray(v)
    acc = _acc_dtype(v)
    gs = np.zeros(num_cells, acc)
    gc = np.zeros(num_cells, acc)
    n = k.shape[0]
    if n == 0:
        return gs, gc
    starts = _run_starts(k)
    sums = np.add.reduceat(v.astype(acc, copy=False), starts)
    if weights is None:
        ends = np.empty(len(starts), np.int64)
        ends[:-1] = starts[1:]
        ends[-1] = n
        cnts = (ends - starts).astype(acc)
    else:
        cnts = np.add.reduceat(
            np.asarray(weights).astype(acc, copy=False), starts
        )
    cells = k[starts]
    ok = (cells >= 0) & (cells < num_cells)
    cok, sok, nok = cells[ok], sums[ok], cnts[ok]
    if len(cok) and not np.all(cok[1:] >= cok[:-1]):
        # non-monotone key stream (e.g. sid clipping folded two series
        # onto one): a cell can span several runs, so ACCUMULATE — plain
        # assignment would keep only the last run (silent data loss).
        # ufunc.at is slower, but this is the off-contract slow path.
        np.add.at(gs, cok, sok)
        np.add.at(gc, cok, nok)
    else:
        # monotone + consecutive-distinct runs => unique cells: assign
        gs[cok] = sok
        gc[cok] = nok
    return gs, gc


def host_reduceat_min_max(k_sorted, v, num_cells: int, valid=None):
    """(min, max) per cell over SORTED host arrays via
    np.minimum/np.maximum.reduceat; +/-inf fills mark empty cells, matching
    blockagg.sorted_segment_min_max."""
    k = np.asarray(k_sorted)
    v = np.asarray(v)
    vd = v.dtype if np.issubdtype(v.dtype, np.floating) else np.dtype(np.float64)
    gmn = np.full(num_cells, np.inf, vd)
    gmx = np.full(num_cells, -np.inf, vd)
    n = k.shape[0]
    if n == 0:
        return gmn, gmx
    if valid is not None:
        valid = np.asarray(valid)
        v_lo = np.where(valid, v, vd.type(np.inf))
        v_hi = np.where(valid, v, vd.type(-np.inf))
    else:
        v_lo = v_hi = v.astype(vd, copy=False)
    starts = _run_starts(k)
    mns = np.minimum.reduceat(v_lo, starts)
    mxs = np.maximum.reduceat(v_hi, starts)
    cells = k[starts]
    ok = (cells >= 0) & (cells < num_cells)
    cok = cells[ok]
    if len(cok) and not np.all(cok[1:] >= cok[:-1]):
        # non-monotone stream: a cell spans several runs — reduce, don't
        # assign (mirrors host_reduceat_sum_count's accumulate fallback)
        np.minimum.at(gmn, cok, mns[ok])
        np.maximum.at(gmx, cok, mxs[ok])
    else:
        gmn[cok] = mns[ok]
        gmx[cok] = mxs[ok]
    return gmn, gmx


def host_bincount_sum_count(k, v, num_cells: int, weights=None):
    """(sum, count) per cell for UNSORTED host arrays via np.bincount —
    the hash-grouping analog (arXiv:2411.13245's other contender). Sentinel
    ids >= num_cells drop via the minlength+slice trick."""
    k = np.asarray(k)
    v = np.asarray(v)
    acc = _acc_dtype(v)
    if k.shape[0] == 0:
        return np.zeros(num_cells, acc), np.zeros(num_cells, acc)
    kc = np.clip(k, 0, num_cells).astype(np.int64, copy=False)
    gs = np.bincount(kc, weights=v, minlength=num_cells + 1)[:-1]
    if weights is None:
        gc = np.bincount(kc, minlength=num_cells + 1)[:-1].astype(acc)
    else:
        gc = np.bincount(
            kc, weights=np.asarray(weights), minlength=num_cells + 1
        )[:-1]
    # bincount with weights accumulates f64; fold back to the contract dtype
    return gs.astype(acc, copy=False), gc.astype(acc, copy=False)


# host sum/count lanes by registered impl name: the host_downsample_*
# pipelines (and bench A/B) dispatch through these, so a NEW host impl
# must register here too or every caller fails loudly with a KeyError
# instead of silently measuring the wrong lane
HOST_SORTED_FNS = {"reduceat": host_reduceat_sum_count}
HOST_UNSORTED_FNS = {"bincount": host_bincount_sum_count}


def host_downsample_sorted(
    ts,
    series_idx,
    values,
    t0,
    bucket_ms,
    num_series: int,
    num_buckets: int,
    with_minmax: bool = True,
    valid=None,
    impl: str = "reduceat",
) -> dict:
    """Full host-lane downsample over rows SORTED by (series, ts): the
    numpy mirror of aggregate.downsample_sorted for concrete CPU inputs
    when the dispatcher picks a host lane. Accumulates in the value
    dtype (f64 in the engine's CPU precision contract). `impl` names the
    registered host sum/count lane — an unregistered name KeyErrors
    loudly rather than silently timing/running a different lane."""
    ts = np.asarray(ts)
    sid = np.asarray(series_idx)
    v = np.asarray(values)
    # scalar coercion: jnp scalars mixed into numpy arithmetic would pull
    # the whole pipeline back onto the jax dispatch path
    t0 = int(np.asarray(t0))
    bucket_ms = int(np.asarray(bucket_ms))
    bucket = ((ts.astype(np.int64) - t0) // bucket_ms).astype(np.int64)
    ok = (
        (bucket >= 0) & (bucket < num_buckets)
        & (sid >= 0) & (sid < num_series)
    )
    if valid is not None:
        ok = ok & np.asarray(valid)
    safe = (
        np.clip(sid.astype(np.int64), 0, num_series - 1) * num_buckets
        + np.clip(bucket, 0, num_buckets - 1)
    )
    num_cells = num_series * num_buckets
    all_ok = bool(ok.all())
    acc = _acc_dtype(v)
    vm = v.astype(acc, copy=False) if all_ok else \
        np.where(ok, v, v.dtype.type(0)).astype(acc, copy=False)
    s, c = HOST_SORTED_FNS[impl](
        safe, vm, num_cells,
        weights=None if all_ok else ok.astype(acc),
    )
    shape = (num_series, num_buckets)
    with np.errstate(invalid="ignore", divide="ignore"):
        out = {
            "sum": s.reshape(shape),
            "count": c.reshape(shape),
            "mean": (s / c).reshape(shape),
        }
    if with_minmax:
        mn, mx = host_reduceat_min_max(
            safe, v, num_cells, valid=None if all_ok else ok
        )
        out["min"] = mn.reshape(shape)
        out["max"] = mx.reshape(shape)
    return out


def host_downsample_unsorted(
    ts,
    series_idx,
    values,
    t0,
    bucket_ms,
    num_series: int,
    num_buckets: int,
    with_minmax: bool = True,
    valid=None,
    impl: str = "bincount",
) -> dict:
    """Host-lane downsample for UNSORTED rows (the hash-grouping
    contender in bench A/B); `impl` names the registered host unsorted
    sum/count lane (KeyError on unregistered names). min/max, when
    requested, use np.minimum.at / np.maximum.at — correct but
    scatter-speed; the lane exists for the sum/count shapes where
    bincount wins."""
    ts = np.asarray(ts)
    sid = np.asarray(series_idx)
    v = np.asarray(values)
    t0 = int(np.asarray(t0))
    bucket_ms = int(np.asarray(bucket_ms))
    bucket = ((ts.astype(np.int64) - t0) // bucket_ms).astype(np.int64)
    ok = (
        (bucket >= 0) & (bucket < num_buckets)
        & (sid >= 0) & (sid < num_series)
    )
    if valid is not None:
        ok = ok & np.asarray(valid)
    safe = (
        np.clip(sid.astype(np.int64), 0, num_series - 1) * num_buckets
        + np.clip(bucket, 0, num_buckets - 1)
    )
    num_cells = num_series * num_buckets
    acc = _acc_dtype(v)
    all_ok = bool(ok.all())
    vm = v.astype(acc, copy=False) if all_ok else \
        np.where(ok, v, v.dtype.type(0)).astype(acc, copy=False)
    s, c = HOST_UNSORTED_FNS[impl](
        safe, vm, num_cells, weights=None if all_ok else ok.astype(acc)
    )
    shape = (num_series, num_buckets)
    with np.errstate(invalid="ignore", divide="ignore"):
        out = {
            "sum": s.reshape(shape),
            "count": c.reshape(shape),
            "mean": (s / c).reshape(shape),
        }
    if with_minmax:
        vd = v.dtype if np.issubdtype(v.dtype, np.floating) else np.dtype(np.float64)
        mn = np.full(num_cells, np.inf, vd)
        mx = np.full(num_cells, -np.inf, vd)
        kk = safe[ok]
        np.minimum.at(mn, kk, v[ok])
        np.maximum.at(mx, kk, v[ok])
        out["min"] = mn.reshape(shape)
        out["max"] = mx.reshape(shape)
    return out


# ---------------------------------------------------------------------------
# execution shims (one entry point per registry, used by the calibrator
# and by bench A/B — production callers go through blockagg's dispatcher)
# ---------------------------------------------------------------------------


def run_sorted(name: str, k_sorted, v, num_cells: int, weights=None):
    """Execute one registered sorted impl on concrete or traced inputs."""
    ensure(name in SORTED_IMPLS, f"unknown sorted agg impl {name!r}")
    if name == "reduceat":
        return host_reduceat_sum_count(k_sorted, v, num_cells, weights=weights)
    from horaedb_tpu.ops.blockagg import sorted_segment_sum_count

    return sorted_segment_sum_count(
        k_sorted, v, num_cells, impl=name, weights=weights
    )


def run_unsorted(name: str, k, v, num_cells: int, weights=None):
    ensure(name in UNSORTED_IMPLS, f"unknown unsorted agg impl {name!r}")
    if name == "bincount":
        return host_bincount_sum_count(k, v, num_cells, weights=weights)
    from horaedb_tpu.ops.blockagg import segment_sum_count

    return segment_sum_count(k, v, num_cells, impl=name, weights=weights)


# ---------------------------------------------------------------------------
# calibration cache
# ---------------------------------------------------------------------------

# density regimes calibrate separately: the block compactions need >=
# block/ranks rows per cell to engage at all, and reduceat's per-run cost
# amortizes with density — one winner does not serve both regimes
DENSE_ROWS_PER_CELL = 8

# last dispatcher decision, context-local first (accurate for code that
# dispatches and attributes in the same coroutine/thread — read.py's
# scanstats note), process-global fallback for observers in OTHER contexts
# (promql's span attr: best-effort, may mislabel under concurrent scans)
_last_choice_ctx: "contextvars.ContextVar[str | None]" = \
    contextvars.ContextVar("horaedb_agg_last_choice", default=None)
_last_choice_global: str = "scatter"

# persistence shared with ops/decode.py (common/calib_cache.py); the
# inventory fields self-invalidate the file when the impl set changes
_calib_cache = CalibCache(
    env_var="HORAEDB_AGG_CACHE",
    filename="agg_calib.json",
    version=CALIB_VERSION,
    tmp_prefix=".agg_calib.",
    inventory=lambda: {
        "sorted_impls": sorted(SORTED_IMPLS),
        "unsorted_impls": sorted(UNSORTED_IMPLS),
    },
)


def configure_cache_dir(path: str) -> None:
    """Point the calibration cache under the engine's data root (called by
    storage bring-up); HORAEDB_AGG_CACHE overrides with a full file path."""
    _calib_cache.configure_dir(path)


def cache_path() -> str:
    return _calib_cache.path()


def reset_cache(memory_only: bool = False) -> None:
    """Drop the in-memory view (tests); optionally leave the file."""
    _calib_cache.reset(memory_only)


_load_cache = _calib_cache.load
_store_entry = _calib_cache.store_entry


def density_class(n: int, num_cells: int) -> str:
    return "dense" if n >= DENSE_ROWS_PER_CELL * max(1, num_cells) else "sparse"


def _calib_shape(klass: str) -> tuple[int, int]:
    """Micro-A/B problem size: big enough that per-dispatch overhead does
    not decide the winner, small enough to stay well under a second per
    impl pass on any sane box. Env-tunable for tests."""
    try:
        n = int(os.environ.get("HORAEDB_AGG_CALIB_N", str(1 << 18)))
    except ValueError:
        n = 1 << 18
    cells = max(1, n // 16) if klass == "dense" else 2 * n
    return n, cells


def _time_impl(fn, iters: int = 2) -> float:
    """Seconds per pass, forcing completion via np.asarray (host arrays
    pass through free; device arrays sync)."""
    out = fn()
    np.asarray(out[0]), np.asarray(out[1])  # warm / compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    np.asarray(out[0]), np.asarray(out[1])
    return (time.perf_counter() - t0) / iters


def _bf16_within_budget(s, oracle_sum, l1) -> bool:
    err = np.abs(np.asarray(s, dtype=np.float64) - oracle_sum)
    return bool(np.all(err <= BF16_L1_BUDGET * l1 + BF16_ATOL))


def _calibrate(kind: str, platform: str, klass: str) -> dict:
    """Measure every eligible impl on a synthetic stream of the density
    class and return {impl, device_impl, ab, ...} — persisted by caller."""
    n, cells = _calib_shape(klass)
    rng = np.random.default_rng(0xA66)
    k = np.sort(rng.integers(0, cells, n)).astype(np.int32)
    v = rng.normal(size=n).astype(np.float32)
    if kind == "unsorted":
        k = rng.permutation(k).astype(np.int32)
        names = unsorted_impl_names(platform)
        runner, impls = run_unsorted, UNSORTED_IMPLS
    else:
        names = sorted_impl_names(platform)
        runner, impls = run_sorted, SORTED_IMPLS
    oracle_sum = np.bincount(k, weights=v.astype(np.float64), minlength=cells)
    l1 = np.bincount(k, weights=np.abs(v.astype(np.float64)), minlength=cells)
    ab: dict[str, float] = {}
    rejected: dict[str, str] = {}
    for name in names:
        try:
            s, _c = runner(name, k, v, cells)
            if not _bf16_within_budget(s, oracle_sum, l1):
                # every lane is held to the bf16 budget here (it is the
                # loosest bound we accept); in practice only block_bf16
                # comes near it
                rejected[name] = "exceeds f64-oracle error budget"
                continue
            secs = _time_impl(lambda name=name: runner(name, k, v, cells))
            ab[name] = round(n / max(secs, 1e-9))
        except Exception as e:  # noqa: BLE001 — an impl that cannot run
            # on this backend loses by forfeit, it must not kill dispatch
            rejected[name] = f"{type(e).__name__}: {e}"[:200]
    if not ab:
        ab = {"scatter": 0.0}
    best = max(ab, key=ab.get)
    device_ab = {x: r for x, r in ab.items() if impls[x].traceable}
    entry = {
        "impl": best,
        "device_impl": max(device_ab, key=device_ab.get) if device_ab else "scatter",
        "ab": ab,
        "rejected": rejected,
        "n": n,
        "num_cells": cells,
        "calibrated_unix": int(time.time()),
    }
    return entry


def calibration_entry(kind: str, n: int, num_cells: int,
                      platform: str | None = None) -> tuple[dict, str]:
    """(entry, source) for the (platform, kind, density) regime; source is
    'cache' (warm) or 'calibrated' (cold micro-A/B just ran)."""
    if platform is None:
        import jax

        platform = jax.devices()[0].platform
    klass = density_class(n, num_cells)
    key = f"{platform}/{kind}/{klass}"
    data = _load_cache()
    entry = (data.get("entries") or {}).get(key)
    if entry is not None:
        return entry, "cache"
    entry = _calibrate(kind, platform, klass)
    _store_entry(key, entry)
    return entry, "calibrated"


def _record(name: str) -> str:
    global _last_choice_global
    _last_choice_ctx.set(name)
    _last_choice_global = name
    AGG_IMPL_TOTAL.labels(name).inc()
    return name


def choose_sorted(n: int, num_cells: int, *, concrete: bool = True,
                  platform: str | None = None) -> str:
    """Resolve the sorted-lane impl: HORAEDB_AGG_IMPL pin > legacy
    HORAEDB_SORTED_IMPL pin > calibration cache (micro-A/B on first use).
    `concrete=False` (tracer inputs) restricts to traceable impls."""
    pinned = os.environ.get("HORAEDB_AGG_IMPL")
    if pinned:
        ensure(pinned in SORTED_IMPLS,
               f"HORAEDB_AGG_IMPL={pinned!r} is not one of "
               f"{sorted(SORTED_IMPLS)}")
        if concrete or SORTED_IMPLS[pinned].traceable:
            return _record(pinned)
    legacy = os.environ.get("HORAEDB_SORTED_IMPL", "auto")
    if legacy != "auto" and legacy in SORTED_IMPLS:
        if concrete or SORTED_IMPLS[legacy].traceable:
            return _record(legacy)
    entry, _source = calibration_entry("sorted", n, num_cells,
                                       platform=platform)
    name = entry["impl"]
    if not concrete and not SORTED_IMPLS.get(
        name, SORTED_IMPLS["scatter"]
    ).traceable:
        name = entry.get("device_impl", "scatter")
    return _record(name)


def choose_unsorted(n: int, num_cells: int, *, concrete: bool = True,
                    platform: str | None = None) -> str:
    pinned = os.environ.get("HORAEDB_UNSORTED_IMPL", "auto")
    if pinned != "auto" and pinned in UNSORTED_IMPLS:
        if concrete or UNSORTED_IMPLS[pinned].traceable:
            return _record(pinned)
    entry, _source = calibration_entry("unsorted", n, num_cells,
                                       platform=platform)
    name = entry["impl"]
    if not concrete and not UNSORTED_IMPLS.get(
        name, UNSORTED_IMPLS["scatter"]
    ).traceable:
        name = entry.get("device_impl", "scatter")
    return _record(name)


def last_choice() -> str:
    """Most recent dispatcher decision for attribution: exact when the
    dispatch happened in the current context (same coroutine/thread, e.g.
    the scanstats note right after a fold); otherwise the process-global
    last decision — best-effort under concurrency."""
    ctx = _last_choice_ctx.get()
    return ctx if ctx is not None else _last_choice_global


# ---------------------------------------------------------------------------
# CLI sweep — the queued ROOFLINE §1 experiments, one command
# ---------------------------------------------------------------------------


def _sweep(n: int) -> dict:
    """Measure every registered impl at a dense sorted shape of n rows on
    the default backend and return a JSON-able report (run_tpu_suite.sh
    runs this FIRST in a healthy-tunnel window)."""
    import jax

    platform = jax.devices()[0].platform
    cells = max(1, n // 22)  # ~TSBS density (the config-4 shape)
    rng = np.random.default_rng(7)
    k = np.sort(rng.integers(0, cells, n)).astype(np.int32)
    v = rng.normal(size=n).astype(np.float32)
    report: dict = {
        "metric": "agg_registry_sweep",
        "platform": platform,
        "n_rows": n,
        "num_cells": cells,
        "sorted_ab": {},
        "unsorted_ab": {},
    }
    for name in sorted_impl_names(platform):
        try:
            secs = _time_impl(lambda name=name: run_sorted(name, k, v, cells))
            report["sorted_ab"][name] = round(n / max(secs, 1e-9))
        except Exception as e:  # noqa: BLE001 — report, keep sweeping
            report["sorted_ab"][name] = f"error: {e}"[:120]
    ku = rng.permutation(k).astype(np.int32)
    for name in unsorted_impl_names(platform):
        try:
            secs = _time_impl(lambda name=name: run_unsorted(name, ku, v, cells))
            report["unsorted_ab"][name] = round(n / max(secs, 1e-9))
        except Exception as e:  # noqa: BLE001
            report["unsorted_ab"][name] = f"error: {e}"[:120]
    return report


def main(argv: list[str] | None = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sweep", type=int, nargs="?", const=1 << 22,
                    metavar="N_ROWS",
                    help="measure every registered impl at N_ROWS and "
                         "print one JSON line")
    args = ap.parse_args(argv)
    if args.sweep:
        print(json.dumps(_sweep(args.sweep)))
        return
    ap.print_help()


if __name__ == "__main__":
    main()
