"""Vectorized predicate evaluation.

Replaces DataFusion's FilterExec + pushed-down pruning predicate
(read.rs:459-470). A predicate is a small static expression tree; evaluation
compiles to a fused elementwise mask kernel. Literals are passed as traced
scalars so changing a constant does NOT trigger an XLA recompile — only the
tree *shape* is static.

The same tree drives host-side SST/row-group pruning via min-max statistics
(`prune_range`), mirroring parquet page pruning in the reference.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Union

import jax.numpy as jnp
import numpy as np

from horaedb_tpu.common.error import HoraeError

# -- predicate tree ----------------------------------------------------------

_OPS = ("eq", "ne", "lt", "le", "gt", "ge")


@dataclass(frozen=True)
class Compare:
    column: str
    op: str  # one of _OPS
    literal: float | int

    def __post_init__(self):
        if self.op not in _OPS:
            raise HoraeError(f"unknown compare op: {self.op}")


@dataclass(frozen=True)
class InSet:
    """column IN (v1, v2, ...) — e.g. TSID membership from the inverted index.
    On device this becomes a broadcast compare against a literal vector
    (the 'device-side set-membership' op of SURVEY §7.7)."""

    column: str
    values: tuple

    def __post_init__(self):
        object.__setattr__(self, "values", tuple(self.values))


@dataclass(frozen=True)
class And:
    children: tuple

    def __init__(self, *children: "Predicate"):
        object.__setattr__(self, "children", tuple(children))


@dataclass(frozen=True)
class Or:
    children: tuple

    def __init__(self, *children: "Predicate"):
        object.__setattr__(self, "children", tuple(children))


@dataclass(frozen=True)
class Not:
    child: "Predicate"


Predicate = Union[Compare, InSet, And, Or, Not]


@dataclass(frozen=True)
class InSetProbe:
    """Template form of InSet after `split_literals`: the membership values
    travel as a dynamic padded array operand (values_slot) plus an active
    mask (mask_slot), so a new TSID set of the same size bucket reuses the
    compiled kernel instead of triggering an XLA recompile per query."""

    column: str
    values_slot: int
    mask_slot: int
    padded_size: int


@dataclass(frozen=True)
class Slot:
    """Placeholder for a literal extracted by `split_literals`. A predicate
    whose Compare literals are Slots is a hashable *template*: jit-compiled
    kernels key their cache on the template, and the literal values flow in
    as traced scalars — new constants, same executable. Carries the column
    name so the cast site needs no second type-dispatched tree walk."""

    idx: int
    column: str = ""


def iter_nodes(pred: Predicate):
    """Generic pre-order walk — the single structural traversal shared by
    every predicate pass (split/cast/eval helpers)."""
    yield pred
    if isinstance(pred, (And, Or)):
        for c in pred.children:
            yield from iter_nodes(c)
    elif isinstance(pred, Not):
        yield from iter_nodes(pred.child)


def pred_columns(pred: Predicate | None) -> set[str]:
    """Column names a predicate references (scan planners use this to decide
    which columns must reach the evaluation site)."""
    if pred is None:
        return set()
    out: set[str] = set()
    for node in iter_nodes(pred):
        c = getattr(node, "column", None)
        if c:
            out.add(c)
    return out


def _pad_bucket(n: int) -> int:
    """Next power of two (min 1): membership arrays pad to size buckets so
    compiled-kernel reuse is per bucket, not per exact set size."""
    return 1 << max(0, n - 1).bit_length() if n > 0 else 1


def is_template(pred: Predicate | None) -> bool:
    """True if `pred` already went through split_literals (contains Slot or
    InSetProbe markers)."""
    if pred is None:
        return False
    for node in iter_nodes(pred):
        if isinstance(node, InSetProbe):
            return True
        if isinstance(node, Compare) and isinstance(node.literal, Slot):
            return True
    return False


def split_literals(pred: Predicate | None) -> tuple[Predicate | None, tuple]:
    """Extract literals into a tuple, leaving dynamic markers behind:
    Compare literals become Slots; InSet value tuples become InSetProbe
    (padded values array + active mask, two slots).

    Idempotent: an already-split template passes through unchanged (with no
    literals — the original split's literals remain authoritative); without
    this, re-splitting would renumber Compare slots into collision with
    InSetProbe value/mask slots."""
    if is_template(pred):
        return pred, ()
    literals: list = []

    def walk(p: Predicate) -> Predicate:
        if isinstance(p, Compare):
            literals.append(p.literal)
            return Compare(p.column, p.op, Slot(len(literals) - 1, p.column))
        if isinstance(p, InSet):
            literals.append(tuple(p.values))
            literals.append(None)  # mask slot, filled by literal_arrays
            return InSetProbe(
                p.column,
                len(literals) - 2,
                len(literals) - 1,
                _pad_bucket(len(p.values)),
            )
        if isinstance(p, And):
            return And(*[walk(c) for c in p.children])
        if isinstance(p, Or):
            return Or(*[walk(c) for c in p.children])
        if isinstance(p, Not):
            return Not(walk(p.child))
        return p

    if pred is None:
        return None, ()
    return walk(pred), tuple(literals)


def _representable_values(vals, dt: np.dtype) -> list:
    """Membership-set values representable in a column dtype. For integer
    columns, equality can never hold for out-of-range or fractional values,
    so they drop from the set — the SINGLE definition shared by the device
    (_eval), numpy (eval_predicate_np), and template (literal_arrays)
    evaluators, keeping set semantics identical across all three."""
    vals_list = list(vals)
    if np.issubdtype(dt, np.integer):
        info = np.iinfo(dt)
        vals_list = [
            int(v) for v in vals_list
            if (not isinstance(v, float) or v.is_integer())
            and info.min <= v <= info.max
        ]
    return vals_list


def _checked_cast(v, dt: np.dtype, column: str):
    """Cast a literal to a column dtype, rejecting values the dtype cannot
    represent (silent wrapping or float truncation would silently change
    lt/ge/eq semantics — and host-side pruning, which compares exactly,
    would then disagree with device evaluation)."""
    if np.issubdtype(dt, np.integer):
        if isinstance(v, float):
            if not v.is_integer():
                raise HoraeError(
                    f"fractional literal {v} on integer column {column!r}; "
                    "rewrite the predicate with an integer bound"
                )
            v = int(v)
        info = np.iinfo(dt)
        if not (info.min <= v <= info.max):
            raise HoraeError(
                f"literal {v} out of range for column {column!r} ({dt})"
            )
    return np.asarray(v, dtype=dt)


def literal_arrays(
    template: Predicate | None, literals: tuple, dtypes: dict
) -> tuple:
    """Cast extracted literals to their columns' dtypes (a u64 id >= 2**63
    overflows the default int64 conversion at the jit boundary)."""
    if template is None:
        return ()
    slot_col: dict[int, str] = {}
    inset_nodes: dict[int, InSetProbe] = {}
    for node in iter_nodes(template):
        if isinstance(node, Compare) and isinstance(node.literal, Slot):
            slot_col[node.literal.idx] = node.literal.column or node.column
        elif isinstance(node, InSetProbe):
            inset_nodes[node.values_slot] = node
    out: list = [None] * len(literals)
    for i, v in enumerate(literals):
        if i in inset_nodes:
            node = inset_nodes[i]
            dt = np.dtype(dtypes.get(node.column, np.int64))
            vals_list = _representable_values(v, dt)
            k = len(vals_list)
            pad_val = vals_list[0] if k else 0
            padded = vals_list + [pad_val] * (node.padded_size - k)
            out[node.values_slot] = np.asarray(padded, dtype=dt)
            mask = np.zeros(node.padded_size, dtype=bool)
            mask[:k] = True
            out[node.mask_slot] = mask
        elif out[i] is None and i in slot_col:
            col = slot_col[i]
            dt = dtypes.get(col)
            out[i] = (
                _checked_cast(v, np.dtype(dt), col) if dt is not None else np.asarray(v)
            )
        elif out[i] is None:
            out[i] = np.asarray(v) if v is not None else np.zeros(0, dtype=bool)
    return tuple(out)


def time_range_pred(ts_column: str, start: int, end: int) -> Predicate:
    """[start, end) range scan predicate."""
    return And(Compare(ts_column, "ge", start), Compare(ts_column, "lt", end))


# -- device evaluation -------------------------------------------------------

def eval_predicate(
    pred: Predicate | None,
    columns: dict[str, jnp.ndarray],
    literals: tuple = (),
) -> jnp.ndarray:
    """Boolean keep-mask over a block. Traceable under jit; `literals` feeds
    Slot placeholders produced by `split_literals`."""
    n = next(iter(columns.values())).shape[0]
    if pred is None:
        return jnp.ones(n, dtype=bool)
    return _eval(pred, columns, literals)


def _eval(pred: Predicate, cols: dict[str, jnp.ndarray], literals: tuple = ()) -> jnp.ndarray:
    if isinstance(pred, Compare):
        c = cols[pred.column]
        if isinstance(pred.literal, Slot):
            lit = jnp.asarray(literals[pred.literal.idx], dtype=c.dtype)
        else:
            lit = jnp.asarray(_checked_cast(pred.literal, np.dtype(c.dtype), pred.column))
        if pred.op == "eq":
            return c == lit
        if pred.op == "ne":
            return c != lit
        if pred.op == "lt":
            return c < lit
        if pred.op == "le":
            return c <= lit
        if pred.op == "gt":
            return c > lit
        return c >= lit
    if isinstance(pred, InSetProbe):
        c = cols[pred.column]
        vals = jnp.asarray(literals[pred.values_slot]).astype(c.dtype)
        active = jnp.asarray(literals[pred.mask_slot])
        if pred.padded_size <= 128:
            # small sets: one broadcast compare, O(n*s) but fully vectorized
            hit = (c[:, None] == vals[None, :]) & active[None, :]
            return jnp.any(hit, axis=1)
        # large sets (engine TSID filters go up to 64K): O(n log s) binary
        # search over the sorted membership array. Padding duplicates a real
        # value so sortedness and equality stay exact; an all-padding (empty)
        # set is rejected by the active.any() guard.
        vals_sorted = jnp.sort(vals)
        pos = jnp.clip(
            jnp.searchsorted(vals_sorted, c), 0, pred.padded_size - 1
        )
        hit = vals_sorted[pos] == c
        return hit & jnp.any(active)
    if isinstance(pred, InSet):
        c = cols[pred.column]
        dt = np.dtype(c.dtype)
        vals_list = _representable_values(pred.values, dt)
        if not vals_list:
            return jnp.zeros(c.shape[0], dtype=bool)
        # Build with the column dtype directly: np.asarray on a mixed-magnitude
        # u64 tuple silently promotes to float64 and corrupts ids > 2**53.
        vals = jnp.asarray(np.asarray(vals_list, dtype=dt))
        return jnp.any(c[:, None] == vals[None, :], axis=1)
    if isinstance(pred, And):
        out = _eval(pred.children[0], cols, literals)
        for ch in pred.children[1:]:
            out = out & _eval(ch, cols, literals)
        return out
    if isinstance(pred, Or):
        out = _eval(pred.children[0], cols, literals)
        for ch in pred.children[1:]:
            out = out | _eval(ch, cols, literals)
        return out
    if isinstance(pred, Not):
        return ~_eval(pred.child, cols, literals)
    raise HoraeError(f"unknown predicate node: {pred!r}")


# -- host-side evaluation (binary-capable) -----------------------------------

def eval_predicate_host(pred: Predicate | None, table) -> np.ndarray:
    """Vectorized predicate evaluation over a pyarrow Table on host —
    supports binary/string columns (bytes literals, ordering via arrow
    compute), used by the binary-primary-key scan path. Returns a boolean
    numpy mask."""
    import pyarrow as pa
    import pyarrow.compute as pc

    n = table.num_rows
    if pred is None:
        return np.ones(n, dtype=bool)

    def ev(p: Predicate) -> np.ndarray:
        if isinstance(p, Compare):
            col = table.column(p.column).combine_chunks()
            lit = p.literal
            try:
                fn = {"eq": pc.equal, "ne": pc.not_equal, "lt": pc.less,
                      "le": pc.less_equal, "gt": pc.greater, "ge": pc.greater_equal}[p.op]
                # pin the scalar to the column type: untyped inference maps
                # a large u64 id (>= 2^63) to int64 and overflows
                out = fn(col, pa.scalar(lit, type=col.type)
                         if not isinstance(lit, (bytes, str)) else pa.scalar(lit))
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError,
                    pa.ArrowTypeError, OverflowError) as e:
                raise HoraeError(
                    f"predicate literal {lit!r} incompatible with column "
                    f"{p.column!r} ({col.type})"
                ) from e
            return pc.fill_null(out, False).to_numpy(zero_copy_only=False)
        if isinstance(p, InSet):
            col = table.column(p.column).combine_chunks()
            try:
                out = pc.is_in(col, value_set=pa.array(list(p.values), type=col.type))
            except (pa.ArrowInvalid, pa.ArrowTypeError, OverflowError) as e:
                raise HoraeError(
                    f"InSet values incompatible with column {p.column!r} ({col.type})"
                ) from e
            return pc.fill_null(out, False).to_numpy(zero_copy_only=False)
        if isinstance(p, And):
            out = ev(p.children[0])
            for c in p.children[1:]:
                out = out & ev(c)
            return out
        if isinstance(p, Or):
            out = ev(p.children[0])
            for c in p.children[1:]:
                out = out | ev(c)
            return out
        if isinstance(p, Not):
            return ~ev(p.child)
        raise HoraeError(f"unsupported predicate node on host path: {p!r}")

    return ev(pred)


def _isin_run_compressed(c: np.ndarray, probe: np.ndarray) -> np.ndarray:
    """np.isin that exploits sorted-scan locality: engine lanes arrive in
    (pk...) order, so tag/series columns are piecewise-constant. Detect the
    runs (one vector diff) and, when the column compresses well, probe only
    the run representatives and expand with repeat — the set probe is the
    scan's costliest host-filter leaf (~7 ns/row via np.isin), and on a
    10K-rows-per-series shape this turns it into ~2 ops/row. Columns that
    don't compress (n_runs > n/8) keep the plain probe."""
    n = len(c)
    if n < 4096:
        return np.isin(c, probe)
    neq = c[1:] != c[:-1]
    n_runs = int(np.count_nonzero(neq)) + 1
    if n_runs > n // 8:
        return np.isin(c, probe)
    starts = np.empty(n_runs, dtype=np.int64)
    starts[0] = 0
    starts[1:] = np.flatnonzero(neq) + 1
    reps = c[starts]
    hit = np.isin(reps, probe)
    lengths = np.empty(n_runs, dtype=np.int64)
    lengths[:-1] = starts[1:] - starts[:-1]
    lengths[-1] = n - starts[-1]
    return np.repeat(hit, lengths)


def eval_predicate_np(pred: Predicate | None, cols: dict[str, np.ndarray]) -> np.ndarray:
    """Vectorized predicate evaluation over numpy host lanes (numeric
    columns only; binary/string predicates go through eval_predicate_host).
    Raw predicates only — Slot/InSetProbe templates are device-side forms."""
    n = len(next(iter(cols.values())))
    if pred is None:
        return np.ones(n, dtype=bool)

    def ev(p: Predicate) -> np.ndarray:
        if isinstance(p, Compare):
            c = cols[p.column]
            if isinstance(p.literal, Slot):
                raise HoraeError("Slot template unsupported on the numpy path")
            lit = _checked_cast(p.literal, c.dtype, p.column)
            if p.op == "eq":
                return c == lit
            if p.op == "ne":
                return c != lit
            if p.op == "lt":
                return c < lit
            if p.op == "le":
                return c <= lit
            if p.op == "gt":
                return c > lit
            return c >= lit
        if isinstance(p, InSet):
            c = cols[p.column]
            vals_list = _representable_values(p.values, c.dtype)
            if not vals_list:
                return np.zeros(len(c), dtype=bool)
            probe = np.asarray(vals_list, dtype=c.dtype)
            return _isin_run_compressed(c, probe)
        if isinstance(p, And):
            out = ev(p.children[0])
            for ch in p.children[1:]:
                out = out & ev(ch)
            return out
        if isinstance(p, Or):
            out = ev(p.children[0])
            for ch in p.children[1:]:
                out = out | ev(ch)
            return out
        if isinstance(p, Not):
            return ~ev(p.child)
        raise HoraeError(f"unsupported predicate node on numpy path: {p!r}")

    return ev(pred)


# -- host-side min/max pruning ----------------------------------------------

def prune_range(pred: Predicate | None, stats: dict[str, tuple]) -> bool:
    """Can any row in a chunk with column [min, max] `stats` match?

    Conservative: returns True (keep) unless the predicate provably rejects
    the whole chunk. Used for SST- and row-group-level pruning, the analog of
    the reference's pruning predicate on ParquetExec (read.rs:459-463).
    """
    if pred is None:
        return True
    return _prune(pred, stats)


def _prune(pred: Predicate, stats: dict[str, tuple]) -> bool:
    if isinstance(pred, Compare):
        if pred.column not in stats:
            return True
        lo, hi = stats[pred.column]
        v = pred.literal
        try:
            if pred.op == "eq":
                return lo <= v <= hi
            if pred.op == "ne":
                return not (lo == hi == v)
            if pred.op == "lt":
                return lo < v
            if pred.op == "le":
                return lo <= v
            if pred.op == "gt":
                return hi > v
            return hi >= v
        except TypeError:
            return True  # mismatched stat/literal types (e.g. bytes stats): keep
    if isinstance(pred, InSet):
        if pred.column not in stats:
            return True
        lo, hi = stats[pred.column]
        try:
            return any(lo <= v <= hi for v in pred.values)
        except TypeError:
            return True
    if isinstance(pred, InSetProbe):
        return True  # membership values are dynamic; stay conservative
    if isinstance(pred, And):
        return all(_prune(c, stats) for c in pred.children)
    if isinstance(pred, Or):
        return any(_prune(c, stats) for c in pred.children)
    if isinstance(pred, Not):
        return True  # can't cheaply invert interval logic; stay conservative
    raise HoraeError(f"unknown predicate node: {pred!r}")
