"""Block-rank compaction kernels for the hot aggregation path (pure XLA).

The profile (bench.py) shows XLA's scatter-add dominating the downsample
pipeline: random-index updates serialize on TPU (~9ns/row measured). But the
engine's data is SORTED by primary key (SSTs sort on write; the scan kernel
re-sorts merged segments), which this kernel exploits:

  sorted_segment_sum_count(k, v, num_cells):
    phase 1 (per row-block of B rows, lax.map over chunks):
      - run boundaries + block-local dense rank (cumsum over <=B distinct
        cells in the block);
      - one-hot(rank) [B, R] matmul against (v, 1) feature columns on the
        MXU -> per-rank (sum, count) partials, plus each rank's global cell
        id recovered with a second one-hot matmul against k*boundary;
    phase 2: scatter-add the (num_blocks * R) rank partials into the
      dense [num_cells] grid — B/R times fewer scatter rows than scattering
      raw samples (8x for B=512, R=64).

  A block with more than R distinct cells can't compact (its rank overflows
  R); `distinct_cells_per_block_max` is a cheap dense pre-check and callers
  fall back to plain segment_sum for such batches. Time-series workloads
  average many samples per (series, bucket) cell, so the fast path is the
  common case.

  f32 one-hot matmuls keep cell-id recovery exact for num_cells < 2**24.

History: a hand-written Pallas/mosaic variant of phase 1 lived here behind
HORAEDB_PALLAS=1. The on-chip A/B (v5e, 64M rows, 2.88M cells) measured
the pure-XLA form at 375M rows/s vs the mosaic kernel's 43M — XLA's own
fusion of the one-hot matmul pipeline beats the manual schedule, so the
mosaic path was deleted (VERDICT r02 #8 / r03 weak #8: "make it win or
delete it"). benchmarks/results_tpu.jsonl r02 holds the measurement.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from horaedb_tpu.common.error import ensure
from horaedb_tpu.common.xprof import xjit

DEFAULT_BLOCK = 512
DEFAULT_RANKS = 64
_F32_EXACT = 1 << 24


def _distinct_max(k_sorted: jax.Array, block: int) -> jax.Array:
    """Traced form of the pre-check: max distinct cells in any row block as
    a device scalar (usable inside jit/shard_map)."""
    n = k_sorted.shape[0]
    nb = n // block
    if nb == 0:
        return jnp.zeros((), jnp.int32)
    k2 = k_sorted[: nb * block].reshape(nb, block)
    prev = jnp.concatenate([jnp.full((nb, 1), -1, k2.dtype), k2[:, :-1]], axis=1)
    return jnp.max(jnp.sum(k2 != prev, axis=1)).astype(jnp.int32)


def distinct_cells_per_block_max(k_sorted: jax.Array, block: int = DEFAULT_BLOCK) -> int:
    """Cheap dense pre-check: max distinct cells in any row block (counts a
    cell continuing from the previous block as new, matching the kernel).
    Concrete inputs only — inside jit use _distinct_max."""
    return int(_distinct_max(k_sorted, block))


# Row blocks per lax.map step in the pure-XLA path: bounds the materialized
# one-hot to chunk*block*ranks f32 (256*512*64*4 = 32 MB HBM peak). The
# one-hot is the path's HBM-traffic driver (~n*ranks*4 bytes total), which
# is why the defaults moved to block=512/ranks=64: same 8x compaction ratio,
# 4x less one-hot traffic than 2048/256 — measured 398M rows/s vs 66M on a
# v5e chip (64M rows, 2.88M cells).
XLA_CHUNK = 256


@xjit(kernel="block_sum_count", static_argnames=("num_cells", "block",
                                                 "ranks", "bf16_onehot",
                                                 "scan_prologue"))
def _block_sum_count_xla(k_sorted, v, num_cells, block, ranks, w=None,
                         bf16_onehot=False, scan_prologue=False):
    """Pure-XLA form of the block-rank compaction (same algorithm as the
    Pallas phase 1, expressed as chunked one-hot matmuls): the per-row
    scatter becomes an MXU contraction per row-block plus ONE scatter over
    nb*ranks partials — block/ranks-fold fewer scatter rows than scattering
    raw samples. Unlike the mosaic kernel this compiles everywhere,
    including remoted-TPU paths where custom-kernel compilation stalls.

    One einsum carries THREE feature columns — value, count weight, and the
    boundary-masked cell id — so the one-hot is read from HBM exactly once
    (the id-recovery einsum used to double the traffic).

    `w` (optional, f32) is each row's COUNT contribution: predicate-masked
    rows pass w=0 (with the value pre-masked to 0) while keeping their TRUE
    sorted cell id — masking via sentinel keys would interleave run breaks
    through the sorted stream and blow the per-block distinct-cell budget,
    forcing the adaptive scatter fallback exactly when a filter is active.

    ROOFLINE §1 experiment flags (both static, registry names in
    ops/agg_registry.py):
    - `bf16_onehot`: materialize the one-hot in bf16 and contract bf16
      (value, weight) features with f32 accumulation — halves the one-hot
      HBM traffic that dominates the kernel's model. Cell ids do NOT ride
      the einsum (bf16 would corrupt them above ~2^8); they recover
      EXACTLY via a boundary-masked integer max-reduce, the same trick the
      min/max kernel uses. Counts stay exact (0/1 is exact in bf16, f32
      accumulation); value sums carry the documented bf16 input-rounding
      budget (agg_registry.BF16_L1_BUDGET) that the calibrator verifies
      against a live f64 oracle before the lane may win.
    - `scan_prologue`: compute the block-local rank with a boundary-
      segmented `lax.associative_scan` instead of `cumsum` (log-depth
      vector-unit prologue instead of a linear chain)."""
    n = k_sorted.shape[0]
    nb = n // block
    ones = w is None
    k2 = k_sorted[: nb * block].reshape(nb, block).astype(jnp.int32)
    v2 = v[: nb * block].reshape(nb, block).astype(jnp.float32)
    w2 = None if ones else w[: nb * block].reshape(nb, block).astype(jnp.float32)
    pad = (-nb) % XLA_CHUNK
    if pad:
        k2 = jnp.concatenate(
            [k2, jnp.full((pad, block), num_cells, jnp.int32)]
        )
        v2 = jnp.concatenate([v2, jnp.zeros((pad, block), jnp.float32)])
        if not ones:
            w2 = jnp.concatenate([w2, jnp.zeros((pad, block), jnp.float32)])
    nsteps = k2.shape[0] // XLA_CHUNK
    k3 = k2.reshape(nsteps, XLA_CHUNK, block)
    v3 = v2.reshape(nsteps, XLA_CHUNK, block)
    w3 = None if ones else w2.reshape(nsteps, XLA_CHUNK, block)

    def step(xs):
        if ones:
            k, vv = xs  # [chunk, block]
            ww = jnp.ones_like(vv)
        else:
            k, vv, ww = xs
        prev = jnp.concatenate(
            [jnp.full((XLA_CHUNK, 1), -1, jnp.int32), k[:, :-1]], axis=1
        )
        boundary = k != prev
        b_i32 = boundary.astype(jnp.int32)
        if scan_prologue:
            rank = jax.lax.associative_scan(jnp.add, b_i32, axis=1) - 1
        else:
            rank = jnp.cumsum(b_i32, axis=1) - 1
        in_rank = rank < ranks
        oh_bool = (
            (rank[..., None]
             == jax.lax.broadcasted_iota(jnp.int32, (XLA_CHUNK, block, ranks), 2))
            & in_rank[..., None]
        )
        if bf16_onehot:
            # bf16 one-hot x bf16 (value, weight) features, f32 accumulate:
            # native MXU mode, half the materialized-one-hot traffic. Ids
            # recover via an exact integer max-reduce over the boundary row
            # (unused ranks yield -1 -> routed to the drop sentinel).
            oh = oh_bool.astype(jnp.bfloat16)
            feats = jnp.stack([vv, ww], axis=-1).astype(jnp.bfloat16)
            out = jnp.einsum(
                "cbr,cbf->crf", oh, feats,
                preferred_element_type=jnp.float32,
            )
            cells = jnp.max(
                jnp.where(oh_bool & boundary[..., None], k[..., None], -1),
                axis=1,
            )
            cells = jnp.where(cells < 0, num_cells, cells)
            return out[..., 0], out[..., 1], cells
        oh = oh_bool.astype(jnp.float32)
        # Precision.HIGHEST keeps f32 operands on the MXU: the default bf16
        # multiply would corrupt recovered cell ids above ~2^8 (each rank
        # sums exactly one nonzero term, so f32 recovery is exact < 2^24)
        # and erode value sums.
        feats = jnp.stack(
            [vv, ww, (k * boundary).astype(jnp.float32)], axis=-1
        )  # [chunk, block, 3]
        out = jnp.einsum(
            "cbr,cbf->crf", oh, feats, preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        # unused ranks carry (0, 0) partials into cell 0 — harmless adds
        return out[..., 0], out[..., 1], jnp.round(out[..., 2]).astype(jnp.int32)

    args = (k3, v3) if ones else (k3, v3, w3)
    sums, counts, cells = jax.lax.map(step, args)  # [nsteps, chunk, ranks]
    flat_cells = cells.reshape(-1)
    grid_sum = jax.ops.segment_sum(sums.reshape(-1), flat_cells, num_cells + 1)[:-1]
    grid_cnt = jax.ops.segment_sum(counts.reshape(-1), flat_cells, num_cells + 1)[:-1]
    if nb * block < n:
        kt = jnp.clip(k_sorted[nb * block:], 0, num_cells).astype(jnp.int32)
        vt = v[nb * block:].astype(jnp.float32)
        wt = (
            jnp.ones_like(vt) if ones
            else w[nb * block:].astype(jnp.float32)
        )
        grid_sum = grid_sum + jax.ops.segment_sum(vt, kt, num_cells + 1)[:-1]
        grid_cnt = grid_cnt + jax.ops.segment_sum(wt, kt, num_cells + 1)[:-1]
    return grid_sum, grid_cnt


@xjit(kernel="block_min_max", static_argnames=("num_cells", "block", "ranks"))
def _block_min_max_xla(k_sorted, v, num_cells, block, ranks, valid=None):
    """min/max companion of _block_sum_count_xla: per-block rank masking +
    a fused masked-reduce over the block axis (XLA fuses the where into the
    reduction — no matmul, no materialized one-hot), then ONE scatter-min/
    max over nb*ranks partials. Measured 360M rows/s vs 57M for the raw
    scatter on a v5e chip (100M rows, 1K cells).

    `valid` (optional bool) excludes rows that keep in-range sorted keys
    (the weights contract of the sum/count path); rows with sentinel keys
    >= num_cells are dropped via the final scatter either way."""
    n = k_sorted.shape[0]
    nb = n // block
    k2 = k_sorted[: nb * block].reshape(nb, block).astype(jnp.int32)
    v2 = v[: nb * block].reshape(nb, block).astype(jnp.float32)
    ones = valid is None
    ok2 = None if ones else valid[: nb * block].reshape(nb, block)
    pad = (-nb) % XLA_CHUNK
    if pad:
        k2 = jnp.concatenate([k2, jnp.full((pad, block), num_cells, jnp.int32)])
        v2 = jnp.concatenate([v2, jnp.zeros((pad, block), jnp.float32)])
        if not ones:
            ok2 = jnp.concatenate([ok2, jnp.zeros((pad, block), bool)])
    nsteps = k2.shape[0] // XLA_CHUNK
    k3 = k2.reshape(nsteps, XLA_CHUNK, block)
    v3 = v2.reshape(nsteps, XLA_CHUNK, block)
    ok3 = None if ones else ok2.reshape(nsteps, XLA_CHUNK, block)

    def step(xs):
        if ones:
            kk, vv = xs
            mask_extra = None
        else:
            kk, vv, mask_extra = xs
        prev = jnp.concatenate(
            [jnp.full((XLA_CHUNK, 1), -1, jnp.int32), kk[:, :-1]], axis=1
        )
        boundary = kk != prev
        rank = jnp.cumsum(boundary.astype(jnp.int32), axis=1) - 1
        in_rank = rank < ranks
        oh = (
            (rank[..., None]
             == jax.lax.broadcasted_iota(jnp.int32, (XLA_CHUNK, block, ranks), 2))
            & in_rank[..., None]
        )
        ohv = oh if mask_extra is None else oh & mask_extra[..., None]
        mn = jnp.min(jnp.where(ohv, vv[..., None], jnp.inf), axis=1)
        mx = jnp.max(jnp.where(ohv, vv[..., None], -jnp.inf), axis=1)
        # rank -> cell id via a max-reduce over the boundary row (exact int,
        # no f32 recovery needed); unused ranks yield -1
        cells = jnp.max(
            jnp.where(oh & boundary[..., None], kk[..., None], -1), axis=1
        )
        return mn, mx, cells

    args = (k3, v3) if ones else (k3, v3, ok3)
    mn, mx, cells = jax.lax.map(step, args)
    flat_cells = jnp.where(cells < 0, num_cells, cells).reshape(-1)
    flat_cells = jnp.minimum(flat_cells, num_cells)
    g_mn = jax.ops.segment_min(mn.reshape(-1), flat_cells, num_cells + 1)[:-1]
    g_mx = jax.ops.segment_max(mx.reshape(-1), flat_cells, num_cells + 1)[:-1]
    if nb * block < n:
        kt = k_sorted[nb * block:]
        vt = v[nb * block:].astype(jnp.float32)
        okt = None if ones else valid[nb * block:]
        idx = jnp.clip(kt, 0, num_cells).astype(jnp.int32)
        if okt is not None:
            idx = jnp.where(okt, idx, num_cells)
        g_mn = jnp.minimum(
            g_mn, jax.ops.segment_min(vt, idx, num_cells + 1)[:-1]
        )
        g_mx = jnp.maximum(
            g_mx, jax.ops.segment_max(vt, idx, num_cells + 1)[:-1]
        )
    return g_mn, g_mx


def _scatter_min_max(k, v, num_cells, valid=None):
    idx = jnp.clip(k, 0, num_cells).astype(jnp.int32)
    if valid is not None:
        idx = jnp.where(valid, idx, num_cells)
    mn = jax.ops.segment_min(v, idx, num_cells + 1)[:-1]
    mx = jax.ops.segment_max(v, idx, num_cells + 1)[:-1]
    return mn, mx


def sorted_segment_min_max(
    k_sorted,
    v,
    num_cells: int,
    block: int = DEFAULT_BLOCK,
    ranks: int = DEFAULT_RANKS,
    impl: str | None = None,
    valid=None,
):
    """(min, max) per cell for SORTED cell ids. Same adaptive structure as
    sorted_segment_sum_count: block-rank compaction (masked reduces, no
    matmul) with a scatter fallback when any block exceeds the rank budget.
    `impl` takes the registry vocabulary: 'scatter'/'scatter_fused'/'lanes'
    map to the plain scatter (no fused/lane min-max variant exists),
    'reduceat' is the host run-boundary lane (concrete inputs only), and
    every block_* name uses the masked-reduce compaction at its block/rank
    config (bf16/scan flags are sum-count-only and are ignored here). Rows
    excluded via `valid` must keep in-range
    sorted keys; rows may also carry sentinel keys >= num_cells (dropped by
    every impl's final scatter/clip) provided sentinel runs stay contiguous
    in the stream. +/-inf fills mark empty cells.

    Non-f32 floats always take the dtype-preserving scatter or host
    reduceat: the block path computes in f32, and a lax.cond joining
    f32/f64 branches would be a trace-time type error anyway."""
    ensure(num_cells < _F32_EXACT, f"num_cells {num_cells} exceeds f32-exact range")
    traced = (
        isinstance(k_sorted, jax.core.Tracer) or isinstance(v, jax.core.Tracer)
    )
    impl = impl or _sorted_impl()
    ensure(impl in _SORTED_IMPL_NAMES,
           f"unknown sorted impl {impl!r} ({'|'.join(_SORTED_IMPL_NAMES)})")
    if impl == "auto":
        from horaedb_tpu.ops import agg_registry

        impl = agg_registry.choose_sorted(
            k_sorted.shape[0], num_cells, concrete=not traced
        )
    if jnp.asarray(v).dtype != jnp.float32 and impl != "reduceat":
        impl = "scatter"
    if impl == "reduceat":
        ensure(not traced,
               "sorted impl 'reduceat' is a host lane; it cannot run on "
               "traced values inside jit")
        from horaedb_tpu.ops import agg_registry

        return agg_registry.host_reduceat_min_max(
            k_sorted, v, num_cells, valid=valid
        )
    if impl in ("scatter", "scatter_fused", "lanes"):
        return _scatter_min_max(k_sorted, v, num_cells, valid=valid)
    if impl != "block":
        block, ranks = _BLOCK_VARIANTS[impl][:2]

    def fast(k, vv, ok=None):
        return _block_min_max_xla(k, vv, num_cells, block, ranks, valid=ok)

    if isinstance(k_sorted, jax.core.Tracer):
        if valid is None:
            return jax.lax.cond(
                _distinct_max(k_sorted, block) > ranks,
                lambda k, vv: _scatter_min_max(k, vv, num_cells),
                lambda k, vv: fast(k, vv),
                k_sorted, v,
            )
        return jax.lax.cond(
            _distinct_max(k_sorted, block) > ranks,
            lambda k, vv, ok: _scatter_min_max(k, vv, num_cells, valid=ok),
            fast,
            k_sorted, v, valid,
        )
    if distinct_cells_per_block_max(k_sorted, block) > ranks:
        return _scatter_min_max(k_sorted, v, num_cells, valid=valid)
    return fast(k_sorted, v, valid)


def _scatter_sum_count(k_sorted, v, num_cells, w=None):
    k = jnp.clip(k_sorted, 0, num_cells).astype(jnp.int32)
    # dtype-preserving for floats (f64 stays f64 — the engine's precision
    # contract, data.py; f32 stays the TPU trade-off). Integer inputs widen
    # to 64-bit accumulation: exact (the reason ints route here instead of
    # the f32 block compaction) and wrap-proof for narrow int sums.
    if jnp.issubdtype(v.dtype, jnp.floating):
        vf = v
    elif jnp.issubdtype(v.dtype, jnp.unsignedinteger):
        vf = v.astype(jnp.uint64)
    else:
        vf = v.astype(jnp.int64)  # bool included
    cw = jnp.ones_like(vf) if w is None else w.astype(vf.dtype)
    s = jax.ops.segment_sum(vf, k, num_cells + 1)[:-1]
    c = jax.ops.segment_sum(cw, k, num_cells + 1)[:-1]
    return s, c


@xjit(kernel="scatter_fused", static_argnames=("num_cells",))
def _scatter_fused_sum_count(k_sorted, v, num_cells, w=None):
    """ONE stacked (value, weight) segment-sum with indices_are_sorted=True
    instead of two scalar scatters — the sorted contract lets XLA skip the
    scatter's conflict handling, and stacking halves the scatter passes
    (the TPU tiling penalty that rules stacking out in
    aggregate.masked_segment_stats does not apply to the CPU backend this
    lane wins on; on accelerators it simply loses the calibration A/B).
    f32 accumulation — the dispatcher routes non-f32 inputs to the
    dtype-preserving scatter before this is reachable."""
    k = jnp.clip(k_sorted, 0, num_cells).astype(jnp.int32)
    vf = v.astype(jnp.float32)
    cw = jnp.ones_like(vf) if w is None else w.astype(jnp.float32)
    feats = jnp.stack([vf, cw], axis=-1)  # [n, 2]
    out = jax.ops.segment_sum(
        feats, k, num_cells + 1, indices_are_sorted=True
    )[:-1]
    return out[:, 0], out[:, 1]


# registry block-compaction variants: impl name -> (block, ranks,
# bf16_onehot, scan_prologue). The vocabulary lives in
# ops/agg_registry.py; execution stays here.
_BLOCK_VARIANTS = {
    "block": (DEFAULT_BLOCK, DEFAULT_RANKS, False, False),
    "block_wide": (2048, 256, False, False),
    "block_r32": (DEFAULT_BLOCK, 32, False, False),
    "block_bf16": (DEFAULT_BLOCK, DEFAULT_RANKS, True, False),
    "block_scan": (DEFAULT_BLOCK, DEFAULT_RANKS, False, True),
}

_SORTED_IMPL_NAMES = (
    "auto", "scatter", "scatter_fused", "lanes", "reduceat",
    *_BLOCK_VARIANTS,
)


def _unsorted_impl() -> str:
    """Strategy override for UNSORTED input: HORAEDB_UNSORTED_IMPL in
    {auto, scatter, sort, bincount}. auto = the calibrated registry choice
    for concrete inputs; under jit, device-sort + block compaction on
    accelerators (when the grid is f32-exact), plain scatter on CPU."""
    import os

    return os.environ.get("HORAEDB_UNSORTED_IMPL", "auto")


def unsorted_strategy(n: int, num_cells: int, dtype, impl: str | None = None) -> str:
    """Resolve the unsorted-reduction strategy to 'sort' or 'scatter'.

    auto gates: density (below ~8 rows/cell the post-sort stream fails the
    distinct-per-block check — block=512/ranks=64 needs >= block/ranks rows
    per cell — and the compaction would fall back to scatter anyway, making
    the device sort pure waste), backend (CPU scatter is not the
    bottleneck), f32-exact grid size, and dtype (wider floats keep the
    dtype-preserving scatter; the block compaction accumulates f32). All
    static at trace time, so the choice compiles away."""
    impl = impl or _unsorted_impl()
    if impl != "auto":
        return impl
    return (
        "sort"
        if n >= 8 * num_cells
        and jax.default_backend() != "cpu"
        and num_cells < _F32_EXACT
        and dtype == jnp.float32
        else "scatter"
    )


def segment_sum_count(k, v, num_cells: int, impl: str | None = None, weights=None):
    """(sum, count) per cell for UNSORTED cell ids (invalid rows must carry
    id >= num_cells; their values must be pre-masked to 0). `weights`
    (optional) is each row's count contribution — pass the predicate mask
    when invalid rows keep in-range cell ids instead of sentinels.

    'sort' device-sorts the rows (lax.sort runs ~4 ns/row on v5e — far
    cheaper than a 9 ns/row scatter it replaces TWO of) and reduces with the
    sorted block compaction: measured 2.1x the raw double-scatter on a v5e
    chip (64M rows, 2.88M cells). 'bincount' is the host hash-grouping lane
    (concrete inputs only). 'auto' on concrete inputs asks the calibrated
    registry (ops/agg_registry.py); under jit it resolves by the static
    density/backend heuristic at trace time and jitted callers bake the
    choice into the executable."""
    traced = isinstance(k, jax.core.Tracer) or isinstance(v, jax.core.Tracer)
    resolved = impl or _unsorted_impl()
    if resolved == "auto" and not traced:
        from horaedb_tpu.ops import agg_registry

        resolved = agg_registry.choose_unsorted(
            k.shape[0], num_cells, concrete=True
        )
    impl = unsorted_strategy(
        k.shape[0], num_cells, jnp.asarray(v).dtype, resolved
    )
    if impl == "bincount":
        ensure(not traced,
               "unsorted impl 'bincount' is a host lane; it cannot run on "
               "traced values inside jit")
        from horaedb_tpu.ops import agg_registry

        return agg_registry.host_bincount_sum_count(
            k, v, num_cells, weights=weights
        )
    if impl == "scatter":
        return _scatter_sum_count(k, v, num_cells, w=weights)
    ensure(impl == "sort", f"unknown unsorted impl {impl!r}")
    ensure(num_cells < _F32_EXACT, f"num_cells {num_cells} exceeds f32-exact range")
    kc = jnp.clip(k, 0, num_cells).astype(jnp.int32)
    if weights is None:
        k2, v2 = jax.lax.sort((kc, v), num_keys=1)
        return sorted_segment_sum_count(k2, v2, num_cells, impl="block")
    k2, v2, w2 = jax.lax.sort((kc, v, weights), num_keys=1)
    return sorted_segment_sum_count(k2, v2, num_cells, impl="block", weights=w2)


def _sorted_impl() -> str:
    """Strategy override: HORAEDB_SORTED_IMPL naming any registry impl
    (ops/agg_registry.py; `HORAEDB_AGG_IMPL` takes precedence inside the
    registry's dispatcher). auto = the calibrated per-platform choice."""
    import os

    return os.environ.get("HORAEDB_SORTED_IMPL", "auto")


def sorted_segment_sum_count(
    k_sorted,
    v,
    num_cells: int,
    block: int = DEFAULT_BLOCK,
    ranks: int = DEFAULT_RANKS,
    impl: str | None = None,
    weights=None,
):
    """(sum, count) per cell for SORTED cell ids (invalid rows must carry
    id >= num_cells). Adaptive: falls back to plain segment_sum when any
    block holds more than `ranks` distinct cells (the rank compaction would
    drop rows). Trace-safe: under jit/shard_map the adaptive check becomes
    a lax.cond between the compacted and scatter paths.

    `weights` (optional) is each row's count contribution; pass the
    predicate mask (0/1) instead of sentinel keys so masked rows keep their
    sorted cell id and the stream stays compactable (values must then be
    pre-masked to 0).

    `impl` overrides the strategy explicitly (A/B harnesses) with any
    registry name (ops/agg_registry.py): scatter | scatter_fused | lanes |
    reduceat (host, concrete inputs only) | block | block_wide | block_r32
    | block_bf16 | block_scan. None reads HORAEDB_SORTED_IMPL at trace
    time; 'auto' asks the calibrated registry dispatcher — note that
    jitted callers bake the strategy into their compiled executable, so
    flipping the env var mid-process does not retrace existing caches."""
    ensure(num_cells < _F32_EXACT, f"num_cells {num_cells} exceeds f32-exact range")
    traced = (
        isinstance(k_sorted, jax.core.Tracer) or isinstance(v, jax.core.Tracer)
    )
    impl = impl or _sorted_impl()
    # fail loudly on removed/unknown strategy names (e.g. the deleted
    # 'pallas') rather than silently measuring a different path
    ensure(impl in _SORTED_IMPL_NAMES,
           f"unknown sorted impl {impl!r} ({'|'.join(_SORTED_IMPL_NAMES)})")
    if impl == "auto":
        from horaedb_tpu.ops import agg_registry

        impl = agg_registry.choose_sorted(
            k_sorted.shape[0], num_cells, concrete=not traced
        )
    if jnp.asarray(v).dtype != jnp.float32 and impl != "reduceat":
        # non-f32 inputs take a dtype-preserving route: the compactions
        # accumulate f32, which loses exactness for integer sums above
        # 2^24 (scatter and the host reduceat widen ints to 64-bit instead
        # — exact), and a cond joining f32/f64 branches cannot trace
        impl = "scatter"
    if impl == "reduceat":
        ensure(not traced,
               "sorted impl 'reduceat' is a host lane; it cannot run on "
               "traced values inside jit")
        from horaedb_tpu.ops import agg_registry

        return agg_registry.host_reduceat_sum_count(
            k_sorted, v, num_cells, weights=weights
        )
    if impl == "scatter":
        return _scatter_sum_count(k_sorted, v, num_cells, w=weights)
    if impl == "scatter_fused":
        return _scatter_fused_sum_count(k_sorted, v, num_cells, w=weights)
    if impl == "lanes":
        from horaedb_tpu.ops.aggregate import lane_segment_sum_count

        return lane_segment_sum_count(k_sorted, v, num_cells, w=weights)
    if impl != "block":
        block, ranks, bf16_onehot, scan_prologue = _BLOCK_VARIANTS[impl]
    else:
        bf16_onehot = scan_prologue = False

    def fast(k, vv, ww=None):
        return _block_sum_count_xla(
            k, vv, num_cells, block, ranks, w=ww,
            bf16_onehot=bf16_onehot, scan_prologue=scan_prologue,
        )

    if isinstance(k_sorted, jax.core.Tracer):
        # inside jit: runtime branch (int() on the pre-check would raise
        # ConcretizationTypeError; both branches compile, one executes)
        if weights is None:
            return jax.lax.cond(
                _distinct_max(k_sorted, block) > ranks,
                lambda k, vv: _scatter_sum_count(k, vv, num_cells),
                lambda k, vv: fast(k, vv),
                k_sorted, v,
            )
        return jax.lax.cond(
            _distinct_max(k_sorted, block) > ranks,
            lambda k, vv, ww: _scatter_sum_count(k, vv, num_cells, w=ww),
            fast,
            k_sorted, v, weights,
        )
    if distinct_cells_per_block_max(k_sorted, block) > ranks:
        return _scatter_sum_count(k_sorted, v, num_cells, w=weights)
    return fast(k_sorted, v, weights)
