"""Struct-of-arrays device blocks.

The host↔device interchange format: a `Block` is an ordered mapping of column
name -> 1-D device array, all the same padded length, plus `num_valid`. This
is the TPU analog of the reference's Arrow RecordBatch flowing through its
ExecutionPlan (SURVEY C2: "pk columns + value + seq lane in a struct-of-arrays
layout in HBM").

Conversion accepts pyarrow RecordBatches with numeric columns; strings/binary
stay on host (SURVEY §7 risk (b)) — the metric engine's data-plane schema is
all-numeric by construction (MetricId, TSID, FieldId, Timestamp, Value).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from horaedb_tpu.common.error import HoraeError, ensure

# Default padding granule: big enough to keep XLA recompiles rare across
# varying batch sizes, small enough not to waste HBM on tiny writes.
DEFAULT_PAD_MULTIPLE = 8192

_ARROW_TO_NP = {
    pa.int64(): np.int64,
    pa.int32(): np.int32,
    pa.uint64(): np.uint64,
    pa.uint32(): np.uint32,
    pa.float64(): np.float64,
    pa.float32(): np.float32,
    pa.timestamp("ms"): np.int64,
}


def _pad_len(n: int, multiple: int) -> int:
    if n == 0:
        return multiple
    return ((n + multiple - 1) // multiple) * multiple


def sort_sentinel(dtype) -> np.generic:
    """Padding key that sorts after every valid key."""
    dt = np.dtype(dtype)
    if np.issubdtype(dt, np.floating):
        return dt.type(np.inf)
    return np.iinfo(dt).max


# The packed-u64 merge sentinel: masked/padding rows sink to all-ones, which
# sorts after every valid (≤63-bit) packed key. ONE definition shared by the
# single-device packed kernel (storage/read.py) and the cross-chip merge
# (parallel/merge.py) — the masked-row contract between them is this value.
PACK_SENTINEL = np.uint64(0xFFFF_FFFF_FFFF_FFFF)


@dataclass
class Block:
    """A padded SoA batch on device."""

    columns: dict[str, jax.Array]
    num_valid: int

    @property
    def padded_len(self) -> int:
        return next(iter(self.columns.values())).shape[0] if self.columns else 0

    @property
    def names(self) -> list[str]:
        return list(self.columns.keys())

    def valid_mask(self) -> jax.Array:
        return jnp.arange(self.padded_len) < self.num_valid

    # -- conversions --------------------------------------------------------
    @classmethod
    def from_numpy(
        cls,
        arrays: dict[str, np.ndarray],
        pad_multiple: int = DEFAULT_PAD_MULTIPLE,
        pad_keys: tuple[str, ...] = (),
    ) -> "Block":
        """Pad host arrays to a static length and move them to device.

        Columns named in `pad_keys` get max-value sentinels in the padding so
        they sort to the tail; everything else pads with zeros.
        """
        lengths = {len(a) for a in arrays.values()}
        ensure(len(lengths) == 1, f"ragged columns: { {k: len(v) for k, v in arrays.items()} }")
        n = lengths.pop()
        padded = _pad_len(n, pad_multiple)
        out: dict[str, jax.Array] = {}
        for name, arr in arrays.items():
            if padded != n:
                fill = sort_sentinel(arr.dtype) if name in pad_keys else arr.dtype.type(0)
                arr = np.concatenate([arr, np.full(padded - n, fill, dtype=arr.dtype)])
            out[name] = jnp.asarray(arr)
        return cls(columns=out, num_valid=n)

    @classmethod
    def from_arrow(
        cls,
        batch: pa.RecordBatch,
        pad_multiple: int = DEFAULT_PAD_MULTIPLE,
        pad_keys: tuple[str, ...] = (),
    ) -> "Block":
        arrays: dict[str, np.ndarray] = {}
        for name, col in zip(batch.schema.names, batch.columns):
            arrays[name] = arrow_column_to_numpy(col)
        return cls.from_numpy(arrays, pad_multiple=pad_multiple, pad_keys=pad_keys)

    def to_numpy(self) -> dict[str, np.ndarray]:
        """Device -> host, truncated back to the valid row count."""
        return {k: np.asarray(v)[: self.num_valid] for k, v in self.columns.items()}

    def to_arrow(self, schema: pa.Schema | None = None) -> pa.RecordBatch:
        host = self.to_numpy()
        if schema is None:
            return pa.RecordBatch.from_pydict(dict(host))
        cols = []
        for f in schema:
            np_arr = host[f.name]
            cols.append(pa.array(np_arr, type=f.type) if f.type != pa.timestamp("ms")
                        else pa.array(np_arr.astype("datetime64[ms]")))
        return pa.RecordBatch.from_arrays(cols, schema=schema)


def arrow_column_to_numpy(col: pa.Array) -> np.ndarray:
    """Lossless numeric conversion; nulls in numeric storage columns become 0
    (only `__reserved__` is nullable in the storage schema and it is unused)."""
    if col.null_count:
        col = col.fill_null(0)
    t = col.type
    if t in _ARROW_TO_NP:
        return col.to_numpy(zero_copy_only=False).astype(_ARROW_TO_NP[t], copy=False)
    raise HoraeError(f"unsupported device column type: {t}")
