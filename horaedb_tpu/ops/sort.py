"""Multi-column lexicographic sort on device.

Replaces the reference's per-batch DataFusion SortExec (storage.rs:244-256)
and the sorted-merge ordering contract (pk asc, then __seq__ asc,
read.rs:412-427). XLA's sort is a single fused kernel over the whole block —
the O(n log n) the reference pays per batch on CPU runs at vector width here.

`jnp.lexsort` treats the LAST key as primary, so callers pass keys
most-significant-first and we reverse internally. All sorts are stable, which
preserves the seq tie-break invariant when seq is included as the least
significant key.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("num_keys",))
def _sort_perm(keys: tuple[jax.Array, ...], num_keys: int) -> jax.Array:
    del num_keys  # shape info only, encoded in the tuple arity
    return jnp.lexsort(tuple(reversed(keys)))


def sort_permutation(keys: list[jax.Array]) -> jax.Array:
    """Stable permutation ordering rows by `keys` (most-significant first)."""
    return _sort_perm(tuple(keys), len(keys))


def apply_permutation(columns: dict[str, jax.Array], perm: jax.Array) -> dict[str, jax.Array]:
    return {k: jnp.take(v, perm, axis=0) for k, v in columns.items()}


def sort_columns(
    columns: dict[str, jax.Array],
    key_names: list[str],
) -> dict[str, jax.Array]:
    """Sort every column by the named key columns (most-significant first).

    Padding rows must already carry max-sentinel keys (blocks.py) so they
    remain at the tail after the sort.
    """
    perm = sort_permutation([columns[k] for k in key_names])
    return apply_permutation(columns, perm)
