"""Multi-column lexicographic sort on device.

Replaces the reference's per-batch DataFusion SortExec (storage.rs:244-256)
and the sorted-merge ordering contract (pk asc, then __seq__ asc,
read.rs:412-427). XLA's sort is a single fused kernel over the whole block —
the O(n log n) the reference pays per batch on CPU runs at vector width here.

`jnp.lexsort` treats the LAST key as primary, so callers pass keys
most-significant-first and we reverse internally. All sorts are stable, which
preserves the seq tie-break invariant when seq is included as the least
significant key.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from horaedb_tpu.common.xprof import xjit


@xjit(kernel="sort_perm", static_argnames=("num_keys",))
def _sort_perm(keys: tuple[jax.Array, ...], num_keys: int) -> jax.Array:
    # ONE variadic lax.sort with an iota payload: lax.sort is directly
    # lexicographic over the first num_keys operands, so the permutation
    # falls out of a single fused sort (lexsort would run one sort pass per
    # key). is_stable preserves the seq tie-break contract.
    n = keys[0].shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    out = jax.lax.sort((*keys, iota), num_keys=num_keys, is_stable=True)
    return out[-1]


def sort_permutation(keys: list[jax.Array]) -> jax.Array:
    """Stable permutation ordering rows by `keys` (most-significant first)."""
    return _sort_perm(tuple(keys), len(keys))


def apply_permutation(columns: dict[str, jax.Array], perm: jax.Array) -> dict[str, jax.Array]:
    return {k: jnp.take(v, perm, axis=0) for k, v in columns.items()}


def sort_columns(
    columns: dict[str, jax.Array],
    key_names: list[str],
) -> dict[str, jax.Array]:
    """Sort every column by the named key columns (most-significant first).

    ONE variadic lax.sort carries every non-key column along as a payload —
    no permutation materialization, no per-column gathers (measured 5.3x
    the lexsort+gather form on a v5e at the 100-way-merge shape).

    Padding rows must already carry max-sentinel keys (blocks.py) so they
    remain at the tail after the sort.
    """
    other = [k for k in columns if k not in key_names]
    ops = [columns[k] for k in key_names] + [columns[k] for k in other]
    out = jax.lax.sort(tuple(ops), num_keys=len(key_names), is_stable=True)
    return dict(zip(list(key_names) + other, out))
