"""ctypes binding to the C++ remote-write parser (native/remote_write_parser.cc).

The shared library auto-builds on first use if the .so is missing and a C++
toolchain exists; `load()` returns None when unavailable so callers fall back
to the pure-Python decoder.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

from horaedb_tpu.common.error import HoraeError
from horaedb_tpu.ingest.types import ParsedWriteRequest

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_SO_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libremote_write.so"))

_lib = None
_lib_lock = threading.Lock()


class _RwResult(ctypes.Structure):
    _fields_ = [
        ("n_series", ctypes.c_int64),
        ("n_labels", ctypes.c_int64),
        ("n_samples", ctypes.c_int64),
        ("n_exemplars", ctypes.c_int64),
        ("n_metadata", ctypes.c_int64),
        ("series_label_start", ctypes.POINTER(ctypes.c_int64)),
        ("series_label_count", ctypes.POINTER(ctypes.c_int64)),
        ("series_sample_start", ctypes.POINTER(ctypes.c_int64)),
        ("series_sample_count", ctypes.POINTER(ctypes.c_int64)),
        ("label_name_off", ctypes.POINTER(ctypes.c_int64)),
        ("label_name_len", ctypes.POINTER(ctypes.c_int64)),
        ("label_value_off", ctypes.POINTER(ctypes.c_int64)),
        ("label_value_len", ctypes.POINTER(ctypes.c_int64)),
        ("sample_value", ctypes.POINTER(ctypes.c_double)),
        ("sample_ts", ctypes.POINTER(ctypes.c_int64)),
        ("sample_series", ctypes.POINTER(ctypes.c_int64)),
        ("exemplar_value", ctypes.POINTER(ctypes.c_double)),
        ("exemplar_ts", ctypes.POINTER(ctypes.c_int64)),
        ("exemplar_series", ctypes.POINTER(ctypes.c_int64)),
        ("n_ex_labels", ctypes.c_int64),
        ("exemplar_label_start", ctypes.POINTER(ctypes.c_int64)),
        ("exemplar_label_count", ctypes.POINTER(ctypes.c_int64)),
        ("ex_label_name_off", ctypes.POINTER(ctypes.c_int64)),
        ("ex_label_name_len", ctypes.POINTER(ctypes.c_int64)),
        ("ex_label_value_off", ctypes.POINTER(ctypes.c_int64)),
        ("ex_label_value_len", ctypes.POINTER(ctypes.c_int64)),
        ("meta_type", ctypes.POINTER(ctypes.c_int64)),
        ("meta_name_off", ctypes.POINTER(ctypes.c_int64)),
        ("meta_name_len", ctypes.POINTER(ctypes.c_int64)),
    ]


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", os.path.abspath(_NATIVE_DIR)],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_SO_PATH)
    except Exception as e:  # noqa: BLE001
        logger.warning("native parser build failed: %s", e)
        return False


def load():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO_PATH) and not _build():
            return None
        lib = ctypes.CDLL(_SO_PATH)
        lib.rw_parser_new.restype = ctypes.c_void_p
        lib.rw_parser_free.argtypes = [ctypes.c_void_p]
        lib.rw_parse.restype = ctypes.c_int
        lib.rw_parse.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.POINTER(_RwResult),
        ]
        _lib = lib
        return _lib


def _as_np(ptr, n: int, dtype) -> np.ndarray:
    """Copy an arena lane out into a standalone numpy array (the arena is
    reused by the next parse on the same handle)."""
    if n == 0:
        return np.empty(0, dtype=dtype)
    return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)


class NativeParser:
    """One parser handle == one arena; not thread-safe (pool it)."""

    def __init__(self):
        lib = load()
        if lib is None:
            raise HoraeError("native remote-write parser unavailable")
        self._lib = lib
        self._h = lib.rw_parser_new()

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.rw_parser_free(h)
            self._h = None

    def parse(self, payload: bytes) -> ParsedWriteRequest:
        res = _RwResult()
        rc = self._lib.rw_parse(self._h, payload, len(payload), ctypes.byref(res))
        if rc != 0:
            raise HoraeError("malformed remote-write payload")
        ns, nl = res.n_series, res.n_labels
        nsm, nex, nmd = res.n_samples, res.n_exemplars, res.n_metadata
        return ParsedWriteRequest(
            payload=payload,
            series_label_start=_as_np(res.series_label_start, ns, np.int64),
            series_label_count=_as_np(res.series_label_count, ns, np.int64),
            series_sample_start=_as_np(res.series_sample_start, ns, np.int64),
            series_sample_count=_as_np(res.series_sample_count, ns, np.int64),
            label_name_off=_as_np(res.label_name_off, nl, np.int64),
            label_name_len=_as_np(res.label_name_len, nl, np.int64),
            label_value_off=_as_np(res.label_value_off, nl, np.int64),
            label_value_len=_as_np(res.label_value_len, nl, np.int64),
            sample_value=_as_np(res.sample_value, nsm, np.float64),
            sample_ts=_as_np(res.sample_ts, nsm, np.int64),
            sample_series=_as_np(res.sample_series, nsm, np.int64),
            exemplar_value=_as_np(res.exemplar_value, nex, np.float64),
            exemplar_ts=_as_np(res.exemplar_ts, nex, np.int64),
            exemplar_series=_as_np(res.exemplar_series, nex, np.int64),
            exemplar_label_start=_as_np(res.exemplar_label_start, nex, np.int64),
            exemplar_label_count=_as_np(res.exemplar_label_count, nex, np.int64),
            ex_label_name_off=_as_np(res.ex_label_name_off, res.n_ex_labels, np.int64),
            ex_label_name_len=_as_np(res.ex_label_name_len, res.n_ex_labels, np.int64),
            ex_label_value_off=_as_np(res.ex_label_value_off, res.n_ex_labels, np.int64),
            ex_label_value_len=_as_np(res.ex_label_value_len, res.n_ex_labels, np.int64),
            meta_type=_as_np(res.meta_type, nmd, np.int64),
            meta_name_off=_as_np(res.meta_name_off, nmd, np.int64),
            meta_name_len=_as_np(res.meta_name_len, nmd, np.int64),
        )
