"""ctypes binding to the C++ remote-write parser (native/remote_write_parser.cc).

The shared library auto-builds on first use if the .so is missing and a C++
toolchain exists; `load()` returns None when unavailable so callers fall back
to the pure-Python decoder.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

from horaedb_tpu.common.error import HoraeError
from horaedb_tpu.ingest.types import ParsedWriteRequest

logger = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_SO_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libremote_write.so"))

_lib = None
_lib_lock = threading.Lock()


# Must match rw_abi_version() in remote_write_parser.cc; a stale committed
# or leftover .so is rebuilt instead of silently shadowing the source.
_ABI_VERSION = 5


class _RwResult(ctypes.Structure):
    _fields_ = [
        ("n_series", ctypes.c_int64),
        ("n_labels", ctypes.c_int64),
        ("n_samples", ctypes.c_int64),
        ("n_exemplars", ctypes.c_int64),
        ("n_metadata", ctypes.c_int64),
        ("series_label_start", ctypes.POINTER(ctypes.c_int64)),
        ("series_label_count", ctypes.POINTER(ctypes.c_int64)),
        ("series_sample_start", ctypes.POINTER(ctypes.c_int64)),
        ("series_sample_count", ctypes.POINTER(ctypes.c_int64)),
        ("label_name_off", ctypes.POINTER(ctypes.c_int64)),
        ("label_name_len", ctypes.POINTER(ctypes.c_int64)),
        ("label_value_off", ctypes.POINTER(ctypes.c_int64)),
        ("label_value_len", ctypes.POINTER(ctypes.c_int64)),
        ("sample_value", ctypes.POINTER(ctypes.c_double)),
        ("sample_ts", ctypes.POINTER(ctypes.c_int64)),
        ("sample_series", ctypes.POINTER(ctypes.c_int64)),
        ("exemplar_value", ctypes.POINTER(ctypes.c_double)),
        ("exemplar_ts", ctypes.POINTER(ctypes.c_int64)),
        ("exemplar_series", ctypes.POINTER(ctypes.c_int64)),
        ("n_ex_labels", ctypes.c_int64),
        ("exemplar_label_start", ctypes.POINTER(ctypes.c_int64)),
        ("exemplar_label_count", ctypes.POINTER(ctypes.c_int64)),
        ("ex_label_name_off", ctypes.POINTER(ctypes.c_int64)),
        ("ex_label_name_len", ctypes.POINTER(ctypes.c_int64)),
        ("ex_label_value_off", ctypes.POINTER(ctypes.c_int64)),
        ("ex_label_value_len", ctypes.POINTER(ctypes.c_int64)),
        ("meta_type", ctypes.POINTER(ctypes.c_int64)),
        ("meta_name_off", ctypes.POINTER(ctypes.c_int64)),
        ("meta_name_len", ctypes.POINTER(ctypes.c_int64)),
    ]


class _RwHashResult(ctypes.Structure):
    _fields_ = [
        ("series_metric_id", ctypes.POINTER(ctypes.c_uint64)),
        ("series_tsid", ctypes.POINTER(ctypes.c_uint64)),
        ("series_name_off", ctypes.POINTER(ctypes.c_int64)),
        ("series_name_len", ctypes.POINTER(ctypes.c_int64)),
        ("series_key_off", ctypes.POINTER(ctypes.c_int64)),
        ("series_key_len", ctypes.POINTER(ctypes.c_int64)),
        ("key_arena", ctypes.POINTER(ctypes.c_uint8)),
        ("key_arena_len", ctypes.c_int64),
        # ABI v5: inverted-index lanes per sorted non-name label pair
        ("tag_hash", ctypes.POINTER(ctypes.c_uint64)),
        ("tag_k_off", ctypes.POINTER(ctypes.c_int64)),
        ("tag_k_len", ctypes.POINTER(ctypes.c_int64)),
        ("tag_v_off", ctypes.POINTER(ctypes.c_int64)),
        ("tag_v_len", ctypes.POINTER(ctypes.c_int64)),
        ("series_tag_start", ctypes.POINTER(ctypes.c_int64)),
        ("n_tags", ctypes.c_int64),
    ]


class _RwFlushResult(ctypes.Structure):
    _fields_ = [
        ("n", ctypes.c_int64),
        ("mid", ctypes.POINTER(ctypes.c_uint64)),
        ("tsid", ctypes.POINTER(ctypes.c_uint64)),
        ("ts", ctypes.POINTER(ctypes.c_int64)),
        ("val", ctypes.POINTER(ctypes.c_double)),
    ]


def _build(force: bool = False) -> bool:
    try:
        cmd = ["make", "-C", os.path.abspath(_NATIVE_DIR)]
        if force:
            subprocess.run(
                ["make", "-C", os.path.abspath(_NATIVE_DIR), "clean"],
                check=True, capture_output=True, timeout=30,
            )
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except Exception as e:  # noqa: BLE001
        logger.warning("native parser build failed: %s", e)
        return False


def _try_load():
    lib = ctypes.CDLL(_SO_PATH)
    try:
        lib.rw_abi_version.restype = ctypes.c_int
        version = lib.rw_abi_version()
    except AttributeError:
        version = 0
    if version != _ABI_VERSION:
        logger.warning(
            "native parser .so has ABI v%s, want v%s — rebuilding", version, _ABI_VERSION
        )
        return None
    lib.rw_parser_new.restype = ctypes.c_void_p
    lib.rw_parser_free.argtypes = [ctypes.c_void_p]
    lib.rw_parse.restype = ctypes.c_int
    lib.rw_parse.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.POINTER(_RwResult),
    ]
    lib.rw_parse_hashed.restype = ctypes.c_int
    lib.rw_parse_hashed.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.POINTER(_RwResult),
        ctypes.POINTER(_RwHashResult),
    ]
    lib.rw_accum_new.restype = ctypes.c_void_p
    lib.rw_accum_free.argtypes = [ctypes.c_void_p]
    lib.rw_accum_clear.argtypes = [ctypes.c_void_p]
    lib.rw_accum_rows.restype = ctypes.c_int64
    lib.rw_accum_rows.argtypes = [ctypes.c_void_p]
    lib.rw_accum_add.restype = ctypes.c_int64
    lib.rw_accum_add.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.rw_accum_flush.restype = ctypes.c_int
    lib.rw_accum_flush.argtypes = [ctypes.c_void_p, ctypes.POINTER(_RwFlushResult)]
    lib.rw_copy_id_lanes.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p
    ]
    return lib


def load():
    """Load (building if needed) the native library; None if unavailable.

    The .so is never committed (supply-chain hygiene): it auto-builds from
    remote_write_parser.cc, and an existing binary whose `rw_abi_version`
    mismatches this binding is discarded and rebuilt from source.
    """
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO_PATH) and not _build():
            return None
        lib = None
        try:
            lib = _try_load()
        except OSError as e:
            logger.warning("native parser load failed: %s", e)
        if lib is None:
            if not _build(force=True):
                return None
            try:
                lib = _try_load()
            except OSError as e:
                logger.warning("native parser load failed after rebuild: %s", e)
                return None
        _lib = lib
        return _lib


def _frozen_empty(dtype) -> np.ndarray:
    # readonly, matching the frombuffer views the non-empty path returns —
    # an in-place op on a shared empty must raise, not mutate a singleton
    a = np.empty(0, dtype)
    a.setflags(write=False)
    return a


_EMPTY_I64 = _frozen_empty(np.int64)
_EMPTY_F64 = _frozen_empty(np.float64)


_EMPTY = {np.dtype(np.int64): _EMPTY_I64, np.dtype(np.float64): _EMPTY_F64,
          np.dtype(np.uint64): _frozen_empty(np.uint64)}


def _as_np(ptr, n: int, dtype) -> np.ndarray:
    """Copy an arena lane out into a standalone numpy array (the arena is
    reused by the next parse on the same handle). string_at is one C memcpy;
    frombuffer wraps it zero-copy (readonly, which downstream respects).
    Empty lanes share module-level immutables — parse_light returns ~12 of
    them per call on the hot path."""
    dt = np.dtype(dtype)
    if n == 0:
        return _EMPTY.get(dt) if dt in _EMPTY else np.empty(0, dtype=dt)
    return np.frombuffer(ctypes.string_at(ptr, n * dt.itemsize), dtype=dt)


class NativeParser:
    """One parser handle == one arena; not thread-safe (pool it)."""

    def __init__(self):
        lib = load()
        if lib is None:
            raise HoraeError("native remote-write parser unavailable")
        self._lib = lib
        self._h = lib.rw_parser_new()
        # Reused per-handle result structs: C overwrites them on every
        # parse, which matches the borrow discipline — a request from this
        # handle is only valid until the handle's next parse anyway.
        self._res = _RwResult()
        self._hres = _RwHashResult()
        # Optional numpy scratch arena (pooled_parser.DecodeArena): when
        # set, parse_light's id-lane copies reuse its buffers instead of
        # allocating per request. Same lifetime contract as the C arena:
        # lanes are valid only until this handle's next parse.
        self.arena = None

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.rw_parser_free(h)
            self._h = None

    def parse_light(self, payload: bytes) -> ParsedWriteRequest:
        """Parse WITHOUT copying the sample lanes out of the arena — the
        native-accum ingest path reads them directly via rw_accum_add, which
        must run on this parser before its next parse. Only the id lanes the
        hot resolution touches are copied (metric_id/tsid/name_len, plus
        exemplars when present); name/key bytes resolve LAZILY through the
        held arena pointers, so the returned request is only valid while the
        parser stays borrowed and unreused."""
        res = self._res
        hres = self._hres
        rc = self._lib.rw_parse_hashed(
            self._h, payload, len(payload), ctypes.byref(res), ctypes.byref(hres)
        )
        if rc != 0:
            raise HoraeError("malformed remote-write payload")
        ns, nex = res.n_series, res.n_exemplars
        empty64 = _EMPTY_I64
        nexl = res.n_ex_labels if nex else 0
        # one FFI crossing copies the three hot id lanes out of the C
        # arena — into the pooled DecodeArena's reusable scratch buffers
        # when one is attached (zero allocations per steady-state request)
        arena = self.arena
        if arena is not None:
            mid = arena.take("mid", ns, np.uint64)
            tsid = arena.take("tsid", ns, np.uint64)
            nlen = arena.take("nlen", ns, np.int64)
        else:
            mid = np.empty(ns, np.uint64)
            tsid = np.empty(ns, np.uint64)
            nlen = np.empty(ns, np.int64)
        if ns:
            self._lib.rw_copy_id_lanes(
                self._h,
                mid.ctypes.data, tsid.ctypes.data, nlen.ctypes.data,
            )
        return ParsedWriteRequest(
            payload=payload,
            series_label_start=empty64,
            series_label_count=empty64,
            series_sample_start=empty64,
            series_sample_count=empty64,
            label_name_off=empty64, label_name_len=empty64,
            label_value_off=empty64, label_value_len=empty64,
            sample_value=_EMPTY_F64,
            sample_ts=empty64,
            sample_series=empty64,
            exemplar_value=_as_np(res.exemplar_value, nex, np.float64),
            exemplar_ts=_as_np(res.exemplar_ts, nex, np.int64),
            exemplar_series=_as_np(res.exemplar_series, nex, np.int64),
            exemplar_label_start=_as_np(res.exemplar_label_start, nex, np.int64),
            exemplar_label_count=_as_np(res.exemplar_label_count, nex, np.int64),
            ex_label_name_off=_as_np(res.ex_label_name_off, nexl, np.int64),
            ex_label_name_len=_as_np(res.ex_label_name_len, nexl, np.int64),
            ex_label_value_off=_as_np(res.ex_label_value_off, nexl, np.int64),
            ex_label_value_len=_as_np(res.ex_label_value_len, nexl, np.int64),
            # metadata records are rare (clients send them on a slow clock,
            # usually in dedicated payloads): copy only when present
            meta_type=_as_np(res.meta_type, res.n_metadata, np.int64),
            meta_name_off=_as_np(res.meta_name_off, res.n_metadata, np.int64),
            meta_name_len=_as_np(res.meta_name_len, res.n_metadata, np.int64),
            series_metric_id=mid,
            series_tsid=tsid,
            series_name_len=nlen,
            n_samples_hint=int(res.n_samples),
            lazy_hres=hres,
            n_series_hint=int(ns),
        )

    def sample_lanes(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(value, ts, owning-series-index) copies of the CURRENT parse's
        sample lanes. Only the cardinality-limit partial-accept path uses
        this (engine/engine.py): the all-or-nothing C++ accumulator cannot
        take a subset, so a limited payload materializes and masks."""
        res = self._res
        n = int(res.n_samples)
        return (
            _as_np(res.sample_value, n, np.float64),
            _as_np(res.sample_ts, n, np.int64),
            _as_np(res.sample_series, n, np.int64),
        )

    def sample_ts_view(self) -> np.ndarray:
        """Standalone copy of the CURRENT parse's sample-ts lane (one
        memcpy; the arena stays untouched). Valid only directly after a
        parse/parse_light on this handle — the late-sample watermark
        accounting (engine/data.py) reads it before the accumulator add."""
        return _as_np(self._res.sample_ts, int(self._res.n_samples), np.int64)

    def parse(self, payload: bytes) -> ParsedWriteRequest:
        res = _RwResult()
        hres = _RwHashResult()
        rc = self._lib.rw_parse_hashed(
            self._h, payload, len(payload), ctypes.byref(res), ctypes.byref(hres)
        )
        if rc != 0:
            raise HoraeError("malformed remote-write payload")
        ns, nl = res.n_series, res.n_labels
        nsm, nex, nmd = res.n_samples, res.n_exemplars, res.n_metadata
        return ParsedWriteRequest(
            payload=payload,
            series_label_start=_as_np(res.series_label_start, ns, np.int64),
            series_label_count=_as_np(res.series_label_count, ns, np.int64),
            series_sample_start=_as_np(res.series_sample_start, ns, np.int64),
            series_sample_count=_as_np(res.series_sample_count, ns, np.int64),
            label_name_off=_as_np(res.label_name_off, nl, np.int64),
            label_name_len=_as_np(res.label_name_len, nl, np.int64),
            label_value_off=_as_np(res.label_value_off, nl, np.int64),
            label_value_len=_as_np(res.label_value_len, nl, np.int64),
            sample_value=_as_np(res.sample_value, nsm, np.float64),
            sample_ts=_as_np(res.sample_ts, nsm, np.int64),
            sample_series=_as_np(res.sample_series, nsm, np.int64),
            exemplar_value=_as_np(res.exemplar_value, nex, np.float64),
            exemplar_ts=_as_np(res.exemplar_ts, nex, np.int64),
            exemplar_series=_as_np(res.exemplar_series, nex, np.int64),
            exemplar_label_start=_as_np(res.exemplar_label_start, nex, np.int64),
            exemplar_label_count=_as_np(res.exemplar_label_count, nex, np.int64),
            ex_label_name_off=_as_np(res.ex_label_name_off, res.n_ex_labels, np.int64),
            ex_label_name_len=_as_np(res.ex_label_name_len, res.n_ex_labels, np.int64),
            ex_label_value_off=_as_np(res.ex_label_value_off, res.n_ex_labels, np.int64),
            ex_label_value_len=_as_np(res.ex_label_value_len, res.n_ex_labels, np.int64),
            meta_type=_as_np(res.meta_type, nmd, np.int64),
            meta_name_off=_as_np(res.meta_name_off, nmd, np.int64),
            meta_name_len=_as_np(res.meta_name_len, nmd, np.int64),
            series_metric_id=_as_np(hres.series_metric_id, ns, np.uint64),
            series_tsid=_as_np(hres.series_tsid, ns, np.uint64),
            series_name_off=_as_np(hres.series_name_off, ns, np.int64),
            series_name_len=_as_np(hres.series_name_len, ns, np.int64),
            series_key_off=_as_np(hres.series_key_off, ns, np.int64),
            series_key_len=_as_np(hres.series_key_len, ns, np.int64),
            key_arena=ctypes.string_at(hres.key_arena, hres.key_arena_len)
            if hres.key_arena_len
            else b"",
            tag_hash=_as_np(hres.tag_hash, hres.n_tags, np.uint64),
            tag_k_off=_as_np(hres.tag_k_off, hres.n_tags, np.int64),
            tag_k_len=_as_np(hres.tag_k_len, hres.n_tags, np.int64),
            tag_v_off=_as_np(hres.tag_v_off, hres.n_tags, np.int64),
            tag_v_len=_as_np(hres.tag_v_len, hres.n_tags, np.int64),
            series_tag_start=_as_np(hres.series_tag_start, ns + 1, np.int64)
            if ns
            else None,
        )


class NativeAccum:
    """C++ ingest accumulator handle (the metric engine's native write
    buffer): (metric_id, tsid) -> dense-id map + flat sample lanes, flushed
    as pk-sorted output lanes. Not thread-safe; owned by one SampleManager.
    """

    def __init__(self):
        lib = load()
        if lib is None:
            raise HoraeError("native remote-write parser unavailable")
        self._lib = lib
        self._h = lib.rw_accum_new()

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.rw_accum_free(h)
            self._h = None

    @property
    def rows(self) -> int:
        return int(self._lib.rw_accum_rows(self._h))

    def add(self, parser: NativeParser) -> int:
        """Append the parser's current parse (must directly follow a
        parse/parse_light on that handle). Returns total buffered rows."""
        n = int(self._lib.rw_accum_add(parser._h, self._h))
        if n < 0:
            raise HoraeError("accum_add: parser holds no hash lanes")
        return n

    def take_sorted(self):
        """(mid, tsid, ts, val) numpy lanes sorted by (mid, tsid, ts), then
        CLEAR the accumulator. The returned arrays are independent copies —
        callers own them (and re-buffer them on a failed write)."""
        res = _RwFlushResult()
        self._lib.rw_accum_flush(self._h, ctypes.byref(res))
        n = int(res.n)
        out = (
            _as_np(res.mid, n, np.uint64),
            _as_np(res.tsid, n, np.uint64),
            _as_np(res.ts, n, np.int64),
            _as_np(res.val, n, np.float64),
        )
        self._lib.rw_accum_clear(self._h)
        return out
