"""Per-table series-cardinality defense: HLL sketch + graceful limiter.

Cardinality explosions — a label carrying request ids, a runaway
deployment minting a fresh pod name per second — are how real TSDBs die:
the inverted index and series registry grow without bound until memory
or the write path gives out. The defense here is a **HyperLogLog sketch
on the ingest path** (exported as ``horaedb_series_cardinality{table}``)
plus a configurable limit that degrades *gracefully*: when the estimate
crosses the limit, samples of NEW series are rejected (counted +
sampled-logged, surfaced as a 503/Retry-After partial-accept through
PR 6's error taxonomy) while samples of EXISTING series keep landing —
never a hang, never silent loss of in-budget traffic.

Why a sketch instead of the exact in-memory index count: the limiter
must stay off the ~110 ns/sample ingest budget. The sketch add is one
vectorized hash + scatter-max over the per-payload series lanes (the
hash-vs-sort group-by analysis, arXiv:2411.13245, is the reference for
keeping grouping cost vectorized and branch-free), costs O(series) not
O(samples), is idempotent (re-adding a known series is free of state
growth), and needs 2^p bytes of state total — 16 KiB at p=14 for ~0.8%
relative error, plenty for a threshold check.
"""

from __future__ import annotations

import numpy as np

from horaedb_tpu.common.error import UnavailableError

# splitmix64 finalizer constants (public domain, Vigna)
_C1 = np.uint64(0xBF58476D1CE4E5B9)
_C2 = np.uint64(0x94D049BB133111EB)
_PHI = np.uint64(0x9E3779B97F4A7C15)


def mix_series_hash(metric_ids: np.ndarray, tsids: np.ndarray) -> np.ndarray:
    """One well-mixed u64 per (metric_id, tsid) pair. tsid alone is a
    seahash of the label key but is SHARED across metrics with identical
    tags, so metric_id must fold in before finalizing."""
    with np.errstate(over="ignore"):
        x = (np.asarray(metric_ids, dtype=np.uint64) * _PHI) ^ \
            np.asarray(tsids, dtype=np.uint64)
        x ^= x >> np.uint64(30)
        x *= _C1
        x ^= x >> np.uint64(27)
        x *= _C2
        x ^= x >> np.uint64(31)
    return x


class SeriesSketch:
    """Vectorized HyperLogLog over 64-bit series hashes.

    ``add_pairs`` returns True when any register grew (i.e. the estimate
    may have changed), so callers can recompute/export the gauge lazily.
    """

    def __init__(self, p: int = 14):
        assert 4 <= p <= 18
        self.p = p
        self.m = 1 << p
        self._reg = np.zeros(self.m, dtype=np.uint8)
        self._est: float | None = 0.0
        if self.m >= 128:
            self._alpha = 0.7213 / (1 + 1.079 / self.m)
        else:
            self._alpha = {64: 0.709, 32: 0.697}.get(self.m, 0.673)

    def add_pairs(self, metric_ids: np.ndarray, tsids: np.ndarray) -> bool:
        if len(metric_ids) == 0:
            return False
        return self.add_hashes(mix_series_hash(metric_ids, tsids))

    def add_hashes(self, h: np.ndarray) -> bool:
        p = np.uint64(self.p)
        idx = (h >> (np.uint64(64) - p)).astype(np.int64)
        # remaining 64-p bits, with a guard bit so the word is never zero
        # and the rank caps at (64 - p + 1)
        with np.errstate(over="ignore"):
            w = (h << p) | np.uint64(1 << (self.p - 1))
        # leading-zero count via the float64 exponent: frexp gives e with
        # 2^(e-1) <= w < 2^e, so bit_length == e and lz == 64 - e. The
        # u64->f64 rounding can only push w across a power of two UPWARD,
        # which at most underestimates lz by carrying into the next
        # exponent at the extreme top (clipped below).
        _, e = np.frexp(w.astype(np.float64))
        rank = np.clip(65 - e, 1, 64 - self.p + 1).astype(np.uint8)
        before = self._reg[idx]
        if bool(np.all(rank <= before)):
            return False
        np.maximum.at(self._reg, idx, rank)
        self._est = None  # dirty
        return True

    def estimate(self) -> float:
        if self._est is not None:
            return self._est
        reg = self._reg
        inv = np.ldexp(1.0, -reg.astype(np.int32))
        e = self._alpha * self.m * self.m / float(inv.sum())
        if e <= 2.5 * self.m:
            zeros = int(np.count_nonzero(reg == 0))
            if zeros:
                e = self.m * np.log(self.m / zeros)
        self._est = float(e)
        return self._est


class CardinalityLimited(UnavailableError):
    """Partial-accept overload signal: the table's series-cardinality
    limit is reached, samples of NEW series in this request were rejected
    (existing-series samples were accepted and are durable per the normal
    ack contract). The HTTP layer sheds this as 503 + Retry-After with
    the partial-accept accounting in the body (server/main.py) — senders
    back off instead of hammering, and in-budget traffic keeps flowing."""

    def __init__(
        self,
        table: str,
        limit: int,
        estimate: float,
        accepted_samples: int,
        rejected_samples: int,
        rejected_series: int,
    ):
        super().__init__(
            f"series cardinality limit reached on {table}: "
            f"~{estimate:.0f} series >= limit {limit}; rejected "
            f"{rejected_series} new series ({rejected_samples} samples), "
            f"accepted {accepted_samples} existing-series samples",
            retry_after_s=30.0,
        )
        self.table = table
        self.limit = limit
        self.estimate = estimate
        self.accepted_samples = accepted_samples
        self.rejected_samples = rejected_samples
        self.rejected_series = rejected_series
