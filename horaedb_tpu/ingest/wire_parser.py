"""Pure-Python hand-rolled remote-write wire decoder.

The Python analog of the C++ decoder (native/remote_write_parser.cc) and of
the reference's hand-rolled `pb_reader.rs`: no protobuf runtime, no protoc
codegen — just varints and field tags. Serves as (a) a protoc-free fallback
when neither the native library nor the generated pb classes are available,
and (b) the third corner of the parser comparison bench (the reference
benches four decoders, bench.rs:60-162).

Zero-copy like the native parser: label values land as (offset, length)
slices into the caller's buffer.

Strictness contract: this decoder matches the NATIVE parser's acceptance
rules (groups rejected, overlong 10th varint byte rejected, field 0
rejected) — intentionally stricter than the protobuf runtime on some
malformed/legacy constructs, exactly like the reference's hand-rolled
decoder (pb_reader.rs skips no groups either). Differential parity with
the runtime oracle is asserted over VALID payloads.
"""

from __future__ import annotations

import struct

import numpy as np

from horaedb_tpu.common.error import HoraeError
from horaedb_tpu.ingest.types import ParsedWriteRequest

def _varint(buf: bytes, i: int, end: int) -> tuple[int, int]:
    """(value, next_index); raises on truncation/overlong."""
    shift = 0
    v = 0
    while i < end:
        b = buf[i]
        i += 1
        if shift == 63:
            if b > 1:
                raise HoraeError("malformed remote-write payload")
            return v | (b << 63), i
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7
    raise HoraeError("malformed remote-write payload")


def _skip(buf: bytes, i: int, end: int, wt: int) -> int:
    if wt == 0:
        _, i = _varint(buf, i, end)
        return i
    if wt == 1:
        if i + 8 > end:
            raise HoraeError("malformed remote-write payload")
        return i + 8
    if wt == 2:
        ln, i = _varint(buf, i, end)
        if i + ln > end:
            raise HoraeError("malformed remote-write payload")
        return i + ln
    if wt == 5:
        if i + 4 > end:
            raise HoraeError("malformed remote-write payload")
        return i + 4
    raise HoraeError("malformed remote-write payload")  # groups unsupported


def _tag(buf: bytes, i: int, end: int) -> tuple[int, int, int]:
    """(field, wire_type, next_index); field number 0 is malformed per the
    proto spec (the protobuf runtime rejects it too — differential parity)."""
    tag, i = _varint(buf, i, end)
    field = tag >> 3
    if field == 0:
        raise HoraeError("malformed remote-write payload")
    return field, tag & 7, i


def _len_prefixed(buf: bytes, i: int, end: int) -> tuple[int, int, int]:
    """(start, stop, next_index) of a length-delimited field."""
    ln, i = _varint(buf, i, end)
    if i + ln > end:
        raise HoraeError("malformed remote-write payload")
    return i, i + ln, i + ln


class WireParser:
    """Stateless pure-Python decoder with the same columnar output as the
    native parser (minus the id-hash lanes)."""

    def parse(self, payload: bytes) -> ParsedWriteRequest:
        sls, slc, sss, ssc = [], [], [], []
        lno, lnl, lvo, lvl = [], [], [], []
        sval, sts, ssr = [], [], []
        exv, ext, exs = [], [], []
        exls, exlc = [], []
        exno, exnl, exvo, exvl = [], [], [], []
        mty, mno, mnl = [], [], []

        def parse_label(i, end, no, nl, vo, vl):
            noff = nlen = voff = vlen = 0
            while i < end:
                field, wt, i = _tag(payload, i, end)
                if field == 1 and wt == 2:
                    noff, stop, i = _len_prefixed(payload, i, end)
                    nlen = stop - noff
                elif field == 2 and wt == 2:
                    voff, stop, i = _len_prefixed(payload, i, end)
                    vlen = stop - voff
                else:
                    i = _skip(payload, i, end, wt)
            no.append(noff)
            nl.append(nlen)
            vo.append(voff)
            vl.append(vlen)

        def parse_sample(i, end, series):
            value = 0.0
            ts = 0
            while i < end:
                field, wt, i = _tag(payload, i, end)
                if field == 1 and wt == 1:
                    if i + 8 > end:
                        raise HoraeError("malformed remote-write payload")
                    value = struct.unpack_from("<d", payload, i)[0]
                    i += 8
                elif field == 2 and wt == 0:
                    raw, i = _varint(payload, i, end)
                    ts = raw - (1 << 64) if raw >= 1 << 63 else raw
                else:
                    i = _skip(payload, i, end, wt)
            sval.append(value)
            sts.append(ts)
            ssr.append(series)

        def parse_exemplar(i, end, series):
            value = 0.0
            ts = 0
            exls.append(len(exno))
            while i < end:
                field, wt, i = _tag(payload, i, end)
                if field == 1 and wt == 2:
                    s, e, i = _len_prefixed(payload, i, end)
                    parse_label(s, e, exno, exnl, exvo, exvl)
                elif field == 2 and wt == 1:
                    if i + 8 > end:
                        raise HoraeError("malformed remote-write payload")
                    value = struct.unpack_from("<d", payload, i)[0]
                    i += 8
                elif field == 3 and wt == 0:
                    raw, i = _varint(payload, i, end)
                    ts = raw - (1 << 64) if raw >= 1 << 63 else raw
                else:
                    i = _skip(payload, i, end, wt)
            exlc.append(len(exno) - exls[-1])
            exv.append(value)
            ext.append(ts)
            exs.append(series)

        def parse_timeseries(i, end):
            series = len(sls)
            sls.append(len(lno))
            sss.append(len(sval))
            while i < end:
                field, wt, i = _tag(payload, i, end)
                if field == 1 and wt == 2:
                    s, e, i = _len_prefixed(payload, i, end)
                    parse_label(s, e, lno, lnl, lvo, lvl)
                elif field == 2 and wt == 2:
                    s, e, i = _len_prefixed(payload, i, end)
                    parse_sample(s, e, series)
                elif field == 3 and wt == 2:
                    s, e, i = _len_prefixed(payload, i, end)
                    parse_exemplar(s, e, series)
                else:
                    i = _skip(payload, i, end, wt)
            slc.append(len(lno) - sls[-1])
            ssc.append(len(sval) - sss[-1])

        def parse_metadata(i, end):
            mtype = noff = nlen = 0
            while i < end:
                field, wt, i = _tag(payload, i, end)
                if field == 1 and wt == 0:
                    mtype, i = _varint(payload, i, end)
                elif field == 2 and wt == 2:
                    noff, stop, i = _len_prefixed(payload, i, end)
                    nlen = stop - noff
                else:
                    i = _skip(payload, i, end, wt)
            mty.append(mtype)
            mno.append(noff)
            mnl.append(nlen)

        i, end = 0, len(payload)
        while i < end:
            field, wt, i = _tag(payload, i, end)
            if field == 1 and wt == 2:
                s, e, i = _len_prefixed(payload, i, end)
                parse_timeseries(s, e)
            elif field == 3 and wt == 2:
                s, e, i = _len_prefixed(payload, i, end)
                parse_metadata(s, e)
            else:
                i = _skip(payload, i, end, wt)

        a64 = lambda xs: np.asarray(xs, dtype=np.int64)  # noqa: E731
        return ParsedWriteRequest(
            payload=payload,
            series_label_start=a64(sls), series_label_count=a64(slc),
            series_sample_start=a64(sss), series_sample_count=a64(ssc),
            label_name_off=a64(lno), label_name_len=a64(lnl),
            label_value_off=a64(lvo), label_value_len=a64(lvl),
            sample_value=np.asarray(sval, dtype=np.float64),
            sample_ts=a64(sts), sample_series=a64(ssr),
            exemplar_value=np.asarray(exv, dtype=np.float64),
            exemplar_ts=a64(ext), exemplar_series=a64(exs),
            exemplar_label_start=a64(exls), exemplar_label_count=a64(exlc),
            ex_label_name_off=a64(exno), ex_label_name_len=a64(exnl),
            ex_label_value_off=a64(exvo), ex_label_value_len=a64(exvl),
            meta_type=a64(mty), meta_name_off=a64(mno), meta_name_len=a64(mnl),
        )
