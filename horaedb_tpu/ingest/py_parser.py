"""Pure-Python fallback decoder (and differential-test oracle).

Uses the protoc-generated classes (pb/remote_write_pb2.py) — the known-good
decode the native parser is differentially tested against, mirroring the
reference's equivalence test vs prost (equivalence_test.rs:18-177).
"""

from __future__ import annotations

import numpy as np

from horaedb_tpu.common.error import HoraeError
from horaedb_tpu.ingest.types import ParsedWriteRequest
from horaedb_tpu.pb import remote_write_pb2


class PyParser:
    """Decodes via the protobuf runtime, then pivots to columnar form.
    Offsets are into a rebuilt side buffer (the runtime copies strings, so
    zero-copy into the original payload is not possible here)."""

    def parse(self, payload: bytes) -> ParsedWriteRequest:
        req = remote_write_pb2.WriteRequest()
        try:
            req.ParseFromString(payload)
        except Exception as e:  # noqa: BLE001
            raise HoraeError("malformed remote-write payload") from e

        side = bytearray()
        sls, slc, sss, ssc = [], [], [], []
        lno, lnl, lvo, lvl = [], [], [], []
        sv, st, ss = [], [], []
        ev, et, es = [], [], []
        els, elc = [], []
        eno, enl, evo, evl = [], [], [], []
        mt, mno, mnl = [], [], []

        def put(b: bytes) -> tuple[int, int]:
            off = len(side)
            side.extend(b)
            return off, len(b)

        for si, series in enumerate(req.timeseries):
            sls.append(len(lno))
            sss.append(len(sv))
            for lab in series.labels:
                o, l = put(lab.name)
                lno.append(o); lnl.append(l)
                o, l = put(lab.value)
                lvo.append(o); lvl.append(l)
            for smp in series.samples:
                sv.append(smp.value); st.append(smp.timestamp); ss.append(si)
            for ex in series.exemplars:
                ev.append(ex.value); et.append(ex.timestamp); es.append(si)
                els.append(len(eno))
                for lab in ex.labels:
                    o, l = put(lab.name); eno.append(o); enl.append(l)
                    o, l = put(lab.value); evo.append(o); evl.append(l)
                elc.append(len(eno) - els[-1])
            slc.append(len(lno) - sls[-1])
            ssc.append(len(sv) - sss[-1])
        for md in req.metadata:
            mt.append(int(md.type))
            o, l = put(md.metric_family_name)
            mno.append(o); mnl.append(l)

        i64 = lambda x: np.asarray(x, dtype=np.int64)  # noqa: E731
        return ParsedWriteRequest(
            payload=bytes(side),
            series_label_start=i64(sls), series_label_count=i64(slc),
            series_sample_start=i64(sss), series_sample_count=i64(ssc),
            label_name_off=i64(lno), label_name_len=i64(lnl),
            label_value_off=i64(lvo), label_value_len=i64(lvl),
            sample_value=np.asarray(sv, dtype=np.float64),
            sample_ts=i64(st), sample_series=i64(ss),
            exemplar_value=np.asarray(ev, dtype=np.float64),
            exemplar_ts=i64(et), exemplar_series=i64(es),
            exemplar_label_start=i64(els), exemplar_label_count=i64(elc),
            ex_label_name_off=i64(eno), ex_label_name_len=i64(enl),
            ex_label_value_off=i64(evo), ex_label_value_len=i64(evl),
            meta_type=i64(mt), meta_name_off=i64(mno), meta_name_len=i64(mnl),
        )
