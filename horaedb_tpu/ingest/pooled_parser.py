"""Pooled parser front-end (reference: pooled_parser.rs:38-73).

`decode` uses a fresh arena; `decode_async` borrows one of POOL_SIZE pooled
native arenas (auto-returned), so steady-state ingest allocates nothing per
request — the deadpool pattern of the reference.
"""

from __future__ import annotations

import asyncio
import logging

import numpy as np

from horaedb_tpu.common import colblock, memtrace, tracing
from horaedb_tpu.ingest.types import ParsedWriteRequest
from horaedb_tpu.server.metrics import GLOBAL_METRICS

logger = logging.getLogger(__name__)

POOL_SIZE = 64


class DecodeArena:
    """Per-parser scratch buffers reused across requests.

    Steady-state ingest parses the same payload SHAPE every scrape
    interval, but each parse_light still paid fresh numpy allocations for
    the id-lane copies (~90 ns/sample parse budget, ROOFLINE §7). A
    pooled parser owns one arena; `take` hands out views into buffers
    that grow geometrically and never shrink, so after warmup a request
    allocates nothing. Lanes come off the column-block allocator
    (common/colblock.py aligned_empty), so the 64-byte alignment
    contract holds from wire decode through the memtable arena to device
    staging — no downstream layer ever repacks a parse lane. Returned
    views follow the pool's borrow
    discipline: valid only until the owning parser's next parse —
    callers that hold lanes past the borrow (exemplar persistence) copy
    them out first.

    `allocations`/`takes` are test hooks: the allocation-count assertion
    (tests) pins the steady state at zero new buffers per request."""

    __slots__ = ("_bufs", "allocations", "takes")

    def __init__(self) -> None:
        self._bufs: dict[str, np.ndarray] = {}
        self.allocations = 0
        self.takes = 0

    def take(self, tag: str, n: int, dtype) -> np.ndarray:
        self.takes += 1
        dt = np.dtype(dtype)
        buf = self._bufs.get(tag)
        if buf is None or len(buf) < n or buf.dtype != dt:
            cap = max(int(n), 256)
            if buf is not None and buf.dtype == dt:
                cap = max(cap, 2 * len(buf))
            buf = colblock.aligned_empty(cap, dt)
            self._bufs[tag] = buf
            self.allocations += 1
            memtrace.track_bytes(buf.nbytes, "parse", "alloc")
        else:
            # steady state: a pooled buffer reissued, zero fresh bytes
            memtrace.track_bytes(int(n) * dt.itemsize, "parse", "reuse")
        return buf[:n]

PARSE_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_ingest_parse_seconds",
    help="Remote-write wire decode time (the ingest parse lane), including "
         "any worker-thread handoff for large payloads.",
)
POOL_WAIT_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_ingest_pool_wait_seconds",
    help="Time spent waiting for a parser arena; sustained non-zero tail "
         "means POOL_SIZE is the ingest bottleneck.",
)


def _new_backend():
    """Backend chain: C++ parser -> protobuf-runtime PyParser -> hand-rolled
    pure-Python WireParser (no native code, no protoc codegen; lacks the
    hash lanes, so the engine takes its slow path). Native backends get a
    DecodeArena so pooled parses reuse their scratch lane buffers."""
    from horaedb_tpu.ingest import native

    if native.load() is not None:
        p = native.NativeParser()
        p.arena = DecodeArena()
        return p
    try:
        from horaedb_tpu.ingest.py_parser import PyParser

        logger.warning("native remote-write parser unavailable; using protobuf runtime")
        return PyParser()
    except ImportError:
        from horaedb_tpu.ingest.wire_parser import WireParser

        logger.warning("protobuf runtime unavailable; using pure-Python wire decoder")
        return WireParser()


class ParserPool:
    """Bounded pool of parser arenas (deadpool analog, POOL_SIZE=64)."""

    def __init__(self, size: int = POOL_SIZE):
        self._size = size
        self._sem = asyncio.Semaphore(size)
        self._free: list = []
        self._in_use = 0
        self._waiting = 0

    async def decode(self, payload: bytes) -> ParsedWriteRequest:
        async with self.borrow() as parser:
            # native parse releases no GIL-bound state we await on; run in a
            # thread so large payloads don't stall the event loop
            with tracing.span("parse", bytes=len(payload)), \
                    PARSE_SECONDS.time():
                return await asyncio.to_thread(parser.parse, payload)

    def borrow(self):
        """Async context manager lending a parser backend for multi-call use
        (parse_light + accum-add must run on one arena before its next
        parse). The borrowed parser returns to the pool on exit unless the
        body was cancelled mid-parse."""
        return _Borrow(self)

    @property
    def status(self) -> dict:
        """Pool telemetry (reference: pool_stats bin)."""
        return {
            "size": self._size,
            "available": self._size - self._in_use,
            "waiting": self._waiting,
        }


class _Borrow:
    def __init__(self, pool: ParserPool):
        self._pool = pool
        self._parser = None

    async def __aenter__(self):
        pool = self._pool
        pool._waiting += 1
        try:
            with POOL_WAIT_SECONDS.time():
                await pool._sem.acquire()
        finally:
            pool._waiting -= 1
        pool._in_use += 1
        self._parser = pool._free.pop() if pool._free else _new_backend()
        return self._parser

    async def __aexit__(self, exc_type, exc, tb):
        pool = self._pool
        if self._parser is not None and exc_type is not asyncio.CancelledError:
            pool._free.append(self._parser)
        self._parser = None
        pool._in_use -= 1
        pool._sem.release()
        return False


_DEFAULT_POOL = None


class PooledParser:
    """API mirror of the reference PooledParser."""

    @staticmethod
    def decode(payload: bytes) -> ParsedWriteRequest:
        """One-shot decode with a fresh parser (pooled_parser.rs `decode`)."""
        return _new_backend().parse(payload)

    @staticmethod
    async def decode_async(payload: bytes) -> ParsedWriteRequest:
        """Pooled decode (pooled_parser.rs `decode_async`)."""
        global _DEFAULT_POOL
        if _DEFAULT_POOL is None:
            _DEFAULT_POOL = ParserPool()
        return await _DEFAULT_POOL.decode(payload)
