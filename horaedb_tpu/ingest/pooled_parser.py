"""Pooled parser front-end (reference: pooled_parser.rs:38-73).

`decode` uses a fresh arena; `decode_async` borrows one of POOL_SIZE pooled
native arenas (auto-returned), so steady-state ingest allocates nothing per
request — the deadpool pattern of the reference.
"""

from __future__ import annotations

import asyncio
import logging

from horaedb_tpu.ingest.types import ParsedWriteRequest

logger = logging.getLogger(__name__)

POOL_SIZE = 64


def _new_backend():
    from horaedb_tpu.ingest import native

    if native.load() is not None:
        return native.NativeParser()
    from horaedb_tpu.ingest.py_parser import PyParser

    logger.warning("native remote-write parser unavailable; using Python fallback")
    return PyParser()


class ParserPool:
    """Bounded pool of parser arenas (deadpool analog, POOL_SIZE=64)."""

    def __init__(self, size: int = POOL_SIZE):
        self._size = size
        self._sem = asyncio.Semaphore(size)
        self._free: list = []
        self._in_use = 0
        self._waiting = 0

    async def decode(self, payload: bytes) -> ParsedWriteRequest:
        self._waiting += 1
        try:
            await self._sem.acquire()
        finally:
            self._waiting -= 1
        self._in_use += 1
        parser = self._free.pop() if self._free else _new_backend()
        try:
            # native parse releases no GIL-bound state we await on; run in a
            # thread so large payloads don't stall the event loop
            result = await asyncio.to_thread(parser.parse, payload)
        except asyncio.CancelledError:
            # the worker thread may still be mutating this arena: never
            # return it to the pool (a fresh one is allocated on demand)
            parser = None
            raise
        finally:
            if parser is not None:
                self._free.append(parser)
            self._in_use -= 1
            self._sem.release()
        return result

    @property
    def status(self) -> dict:
        """Pool telemetry (reference: pool_stats bin)."""
        return {
            "size": self._size,
            "available": self._size - self._in_use,
            "waiting": self._waiting,
        }


_DEFAULT_POOL = None


class PooledParser:
    """API mirror of the reference PooledParser."""

    @staticmethod
    def decode(payload: bytes) -> ParsedWriteRequest:
        """One-shot decode with a fresh parser (pooled_parser.rs `decode`)."""
        return _new_backend().parse(payload)

    @staticmethod
    async def decode_async(payload: bytes) -> ParsedWriteRequest:
        """Pooled decode (pooled_parser.rs `decode_async`)."""
        global _DEFAULT_POOL
        if _DEFAULT_POOL is None:
            _DEFAULT_POOL = ParserPool()
        return await _DEFAULT_POOL.decode(payload)
