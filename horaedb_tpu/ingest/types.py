"""Columnar decoded form of a remote-write request.

Instead of the reference's pooled object tree (WriteRequest -> TimeSeries ->
Label/Sample, pooled_types.rs), the parse result is struct-of-arrays: flat
sample/label lanes plus per-series ranges — the layout the engine ships to
device HBM and feeds the metric-engine id hashing without another pivot.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class ParsedWriteRequest:
    """All arrays are views/copies detached from the parser arena; `payload`
    is the original buffer that label offsets point into (zero-copy slices).
    """

    payload: bytes
    # per-series ranges into the label/sample lanes
    series_label_start: np.ndarray  # int64 [n_series]
    series_label_count: np.ndarray
    series_sample_start: np.ndarray
    series_sample_count: np.ndarray
    # flattened labels as (offset, length) into payload
    label_name_off: np.ndarray  # int64 [n_labels]
    label_name_len: np.ndarray
    label_value_off: np.ndarray
    label_value_len: np.ndarray
    # flattened samples
    sample_value: np.ndarray    # float64 [n_samples]
    sample_ts: np.ndarray       # int64 ms
    sample_series: np.ndarray   # int64 owning-series index
    # flattened exemplars
    exemplar_value: np.ndarray
    exemplar_ts: np.ndarray
    exemplar_series: np.ndarray
    # exemplar labels as (offset, length) into payload, per-exemplar ranges
    exemplar_label_start: np.ndarray
    exemplar_label_count: np.ndarray
    ex_label_name_off: np.ndarray
    ex_label_name_len: np.ndarray
    ex_label_value_off: np.ndarray
    ex_label_value_len: np.ndarray
    # metadata entries
    meta_type: np.ndarray
    meta_name_off: np.ndarray
    meta_name_len: np.ndarray

    # Metric-engine id lanes (native parser only; None from the pure-Python
    # fallback): per-series seahash ids + the canonical sorted series key
    # materialized into key_arena (reference hash contract:
    # src/metric_engine/src/types.rs:18-41).
    series_metric_id: np.ndarray | None = None  # uint64 [n_series]
    series_tsid: np.ndarray | None = None       # uint64 [n_series]
    series_name_off: np.ndarray | None = None   # __name__ value slice
    series_name_len: np.ndarray | None = None   # -1 = missing __name__
    series_key_off: np.ndarray | None = None    # into key_arena
    series_key_len: np.ndarray | None = None
    key_arena: bytes = b""
    # Inverted-index lanes (native parser ABI v5): per sorted non-name
    # label pair — posting hash (tag_hash_of contract) + payload slices;
    # series s owns [series_tag_start[s], series_tag_start[s+1]). None from
    # the pure-Python fallback (or resolved lazily via lazy_hres).
    tag_hash: np.ndarray | None = None          # uint64 [n_tags]
    tag_k_off: np.ndarray | None = None         # int64 into payload
    tag_k_len: np.ndarray | None = None
    tag_v_off: np.ndarray | None = None
    tag_v_len: np.ndarray | None = None
    series_tag_start: np.ndarray | None = None  # int64 [n_series + 1]
    # set by parse_light (sample lanes stay in the parser arena for the
    # native accumulator); None -> count the materialized lane
    n_samples_hint: int | None = None
    n_series_hint: int | None = None
    # parse_light: held _RwHashResult whose pointers reach into the parser
    # arena — name/key accessors below resolve through it lazily. ONLY valid
    # while the producing parser stays borrowed and unreused.
    lazy_hres: object | None = None

    @property
    def n_series(self) -> int:
        if self.n_series_hint is not None:
            return self.n_series_hint
        return len(self.series_label_start)

    @property
    def n_samples(self) -> int:
        if self.n_samples_hint is not None:
            return self.n_samples_hint
        return len(self.sample_value)

    def label_name(self, i: int) -> bytes:
        o, l = int(self.label_name_off[i]), int(self.label_name_len[i])
        return self.payload[o : o + l]

    def label_value(self, i: int) -> bytes:
        o, l = int(self.label_value_off[i]), int(self.label_value_len[i])
        return self.payload[o : o + l]

    def series_labels(self, series: int) -> list[tuple[bytes, bytes]]:
        s = int(self.series_label_start[series])
        c = int(self.series_label_count[series])
        return [(self.label_name(i), self.label_value(i)) for i in range(s, s + c)]

    def exemplar_labels(self, ex: int) -> list[tuple[bytes, bytes]]:
        s = int(self.exemplar_label_start[ex])
        c = int(self.exemplar_label_count[ex])
        out = []
        for i in range(s, s + c):
            no, nl = int(self.ex_label_name_off[i]), int(self.ex_label_name_len[i])
            vo, vl = int(self.ex_label_value_off[i]), int(self.ex_label_value_len[i])
            out.append((self.payload[no:no + nl], self.payload[vo:vo + vl]))
        return out

    def meta_name(self, i: int) -> bytes:
        o, l = int(self.meta_name_off[i]), int(self.meta_name_len[i])
        return self.payload[o : o + l]

    def series_name(self, s: int) -> bytes:
        """__name__ label value of series `s` (hash-lane fast path only)."""
        n = int(self.series_name_len[s])
        if n < 0:
            return b""
        if self.series_name_off is not None:
            o = int(self.series_name_off[s])
        else:  # lazy: offsets live in the held arena pointers
            o = int(self.lazy_hres.series_name_off[s])
        return self.payload[o : o + n]

    def series_tag_rows(self, s: int) -> "list[tuple[int, bytes, bytes]] | None":
        """Inverted-index rows of series `s` as (posting_hash, key, value),
        in canonical sorted order — hashes precomputed by the native parser
        (the tag_hash_of contract), key/value sliced zero-copy from the
        payload. None when the producing parser exposed no tag lanes (pure
        Python fallback): callers then derive rows from the series key."""
        if self.series_tag_start is not None:
            src = self  # copied numpy lanes (full parse)
        else:
            src = self.lazy_hres  # held arena pointers (parse_light)
            if src is None or not src.tag_hash:
                return None
        lo = int(src.series_tag_start[s])
        hi = int(src.series_tag_start[s + 1])
        p = self.payload
        return [
            (
                int(src.tag_hash[i]),
                p[int(src.tag_k_off[i]):int(src.tag_k_off[i]) + int(src.tag_k_len[i])],
                p[int(src.tag_v_off[i]):int(src.tag_v_off[i]) + int(src.tag_v_len[i])],
            )
            for i in range(lo, hi)
        ]

    def series_key(self, s: int) -> bytes:
        """Canonical sorted series key of series `s` (hash-lane fast path)."""
        if self.series_key_off is not None:
            o, l = int(self.series_key_off[s]), int(self.series_key_len[s])
            return self.key_arena[o : o + l]
        import ctypes

        h = self.lazy_hres
        o, l = int(h.series_key_off[s]), int(h.series_key_len[s])
        if l == 0:
            return b""
        base = ctypes.cast(h.key_arena, ctypes.c_void_p).value
        return ctypes.string_at(base + o, l)
