"""Prometheus remote-write ingest (reference: src/remote_write).

`PooledParser.decode(buf)` parses a remote-write protobuf payload into
columnar arrays (`ParsedWriteRequest`) using the native C++ zero-copy parser
(native/remote_write_parser.cc) with a pure-Python fallback. `decode_async`
borrows a pooled parser arena (POOL_SIZE=64, matching pooled_types.rs:25-192)
so steady-state ingest does no per-request allocation.
"""

from horaedb_tpu.ingest.types import ParsedWriteRequest
from horaedb_tpu.ingest.pooled_parser import PooledParser, ParserPool, POOL_SIZE

__all__ = ["ParsedWriteRequest", "PooledParser", "ParserPool", "POOL_SIZE"]
