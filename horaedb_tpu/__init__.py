"""horaedb_tpu — a TPU-native time-series metric engine.

A ground-up rebuild of Apache HoraeDB's new metric engine (the `main`-branch
rewrite surveyed in SURVEY.md) designed TPU-first:

- Columnar, time-partitioned LSM storage over object storage: every write is a
  sorted parquet SST; a snapshot+delta manifest is the source of truth and the
  checkpoint/recovery log (reference: src/columnar_storage).
- The scan pipeline (predicate filter -> k-way sorted merge -> sequence-based
  dedup/value-merge -> aggregate) runs as jit-compiled JAX/XLA kernels on
  device, sharded over a `jax.sharding.Mesh` for multi-chip scale
  (reference: src/columnar_storage/src/read.rs, re-designed for XLA).
- Time-window compaction with TTL expiry re-encodes k overlapping SSTs into
  one via an on-device merge+dedup (reference: src/columnar_storage/src/compaction).
- Prometheus remote-write ingest via a pooled zero-copy C++ wire parser that
  emits columnar arrays ready for device transfer (reference: src/remote_write).
- The VictoriaMetrics-style metric/series/inverted-index tables specified by
  the reference RFC (docs/rfcs/20240827-metric-engine.md) but left todo!().

Package layout:
  common/    errors, ReadableDuration/ReadableSize, clock        (ref: src/common)
  pb/        protobuf types: sst manifest + Prometheus remote-write (ref: src/pb_types)
  objstore/  object-store abstraction (local FS / in-memory)     (ref: object_store crate)
  storage/   ColumnarStorage engine: manifest, SSTs, scan, compaction
  ops/       device kernels: sort/filter/merge/dedup/downsample/aggregate
  parallel/  device mesh, sharded segment-parallel scan (ICI collectives)
  ingest/    remote-write parser (C++ native + Python fallback)
  engine/    metric engine: metrics/series/inverted-index tables
  server/    HTTP server + config
"""

__version__ = "0.1.0"
