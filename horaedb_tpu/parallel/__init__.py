"""Device-mesh parallelism: the distributed execution layer.

The reference snapshot has no inter-node runtime (SURVEY §2.5) — its scale
story is shared object storage + per-segment plan parallelism on tokio
runtimes. The TPU-native analogs this package provides (SURVEY §5.8):

- a `jax.sharding.Mesh` over the slice (ICI) and across hosts (DCN via
  `jax.distributed`), replacing tokio thread-pool parallelism;
- segment/row data-parallel scans: rows shard over the mesh, each device
  filters+reduces its shard, partial aggregates combine with XLA collectives
  (psum/pmin/pmax riding ICI);
- series-dimension sharding for group-by outputs (the tensor-parallel analog)
  so huge cardinalities never materialize on one chip.
"""

from horaedb_tpu.parallel.mesh import make_mesh, mesh_devices
from horaedb_tpu.parallel.scan import sharded_downsample, sharded_grouped_stats
from horaedb_tpu.parallel.distributed import global_mesh, initialize

__all__ = [
    "make_mesh",
    "mesh_devices",
    "sharded_downsample",
    "sharded_grouped_stats",
    "initialize",
    "global_mesh",
]
