"""Mesh construction helpers.

Axis conventions:
- "rows":   data parallelism over row blocks (segments/SST shards) — the
            scan fan-out axis; collectives here are reductions (psum).
- "series": output-grid sharding over series (group) space — the
            tensor-parallel analog; group-by results stay sharded on it.

A 1-chip mesh is (1, 1) and all collectives degenerate to identity, so the
same pjit'ed code path serves laptop CPU, one TPU chip, and a full slice.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from horaedb_tpu.common.error import ensure


# Ambient mesh: the engine's storage paths (e.g. aggregate pushdown in
# storage/read.py) dispatch through the sharded kernels whenever an active
# mesh with >1 device is installed — the single-device paths stay the
# default so laptop CPU and one-chip runs never pay sharding overhead.
_ACTIVE: Mesh | None = None


def set_active_mesh(mesh: "Mesh | None") -> None:
    global _ACTIVE
    _ACTIVE = mesh


def active_mesh() -> "Mesh | None":
    """The installed mesh, or None when absent/degenerate (size 1)."""
    if _ACTIVE is None or _ACTIVE.size <= 1:
        return None
    return _ACTIVE


def mesh_devices(n: int | None = None) -> list:
    devs = jax.devices()
    if n is None:
        return devs
    ensure(n <= len(devs), f"requested {n} devices, have {len(devs)}")
    return devs[:n]


def make_mesh(
    n_devices: int | None = None,
    series_parallel: int = 1,
    axis_names: tuple[str, str] = ("rows", "series"),
) -> Mesh:
    """Build a 2D (rows x series) mesh over the first `n_devices` devices.

    `series_parallel` devices shard the group/series output dimension; the
    rest data-parallel the rows. On multi-host topologies callers should pick
    `series_parallel` to keep the series all-reduce inside one host's ICI
    domain (scaling-book recipe: reductions ride ICI, DCN only sees the
    row-axis partials).
    """
    devs = mesh_devices(n_devices)
    n = len(devs)
    ensure(n % series_parallel == 0,
           f"{n} devices not divisible by series_parallel={series_parallel}")
    arr = np.array(devs).reshape(n // series_parallel, series_parallel)
    return Mesh(arr, axis_names)
