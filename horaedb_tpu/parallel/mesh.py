"""The mesh execution layer: construction helpers + the one mesh-scan
entry every engine path shares.

Axis conventions:
- "rows":   data parallelism over row blocks (segments/SST shards) — the
            scan fan-out axis; collectives here are reductions (psum).
- "series": output-grid sharding over series (group) space — the
            tensor-parallel analog; group-by results stay sharded on it.

A 1-chip mesh is (1, 1) and all collectives degenerate to identity, so the
same pjit'ed code path serves laptop CPU, one TPU chip, and a full slice.

`mesh_downsample` is the first-class scale-up surface the distributed
scatter-gather rides: a node's region scans fan their sorted runs across
every local device (series-axis shard_map, replicated grid axes —
parallel/scan.py compiles the step), and it owns the host-side
discipline that keeps the sharded result bit-identical to the
single-device path — series padding to the axis size, per-lane row pads
(the sid lane pads OUT of every series slice so tail pad rows keep
sorted keys monotone and valid=0), and the f32-on-accelerator /
f64-on-CPU dtype rule."""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from horaedb_tpu.common import colblock, memtrace
from horaedb_tpu.common.error import ensure


# Ambient mesh: the engine's storage paths (e.g. aggregate pushdown in
# storage/read.py) dispatch through the sharded kernels whenever an active
# mesh with >1 device is installed — the single-device paths stay the
# default so laptop CPU and one-chip runs never pay sharding overhead.
_ACTIVE: Mesh | None = None


def set_active_mesh(mesh: "Mesh | None") -> None:
    global _ACTIVE
    _ACTIVE = mesh


def active_mesh() -> "Mesh | None":
    """The installed mesh, or None when absent/degenerate (size 1)."""
    if _ACTIVE is None or _ACTIVE.size <= 1:
        return None
    return _ACTIVE


def mesh_devices(n: int | None = None) -> list:
    devs = jax.devices()
    if n is None:
        return devs
    ensure(n <= len(devs), f"requested {n} devices, have {len(devs)}")
    return devs[:n]


def make_mesh(
    n_devices: int | None = None,
    series_parallel: int = 1,
    axis_names: tuple[str, str] = ("rows", "series"),
) -> Mesh:
    """Build a 2D (rows x series) mesh over the first `n_devices` devices.

    `series_parallel` devices shard the group/series output dimension; the
    rest data-parallel the rows. On multi-host topologies callers should pick
    `series_parallel` to keep the series all-reduce inside one host's ICI
    domain (scaling-book recipe: reductions ride ICI, DCN only sees the
    row-axis partials).
    """
    devs = mesh_devices(n_devices)
    n = len(devs)
    ensure(n % series_parallel == 0,
           f"{n} devices not divisible by series_parallel={series_parallel}")
    arr = np.array(devs).reshape(n // series_parallel, series_parallel)
    return Mesh(arr, axis_names)


def mesh_downsample(
    mesh: Mesh,
    ts_np,
    sid_np,
    val_np,
    t0,
    bucket_ms,
    num_series: int,
    num_buckets: int,
    with_minmax: bool = True,
    valid_np=None,
    sorted_input: bool = True,
) -> dict:
    """One run reduced over the mesh: rows shard over "rows"
    (psum/pmin/pmax combine the partial grids over ICI), the output grid
    shards over "series" (padded up to the axis size, trimmed on the way
    back). `valid_np` excludes rows (set-membership misses) via the
    kernel's weight column — their sid must stay monotone when
    `sorted_input`.

    Row padding is PER-LANE: the sid lane pads with `padded_series`
    (out of every device's series slice, so pad rows land on the
    sentinel key and stay contiguous at the sorted tail) and the
    validity lane pads False — a pad row can never perturb count/min/
    max partials, whatever the series count's divisibility
    (tests/test_parallel.py pins it with prime series counts).
    """
    from horaedb_tpu.parallel.scan import shard_rows, sharded_downsample

    series_par = mesh.shape["series"]
    padded_series = num_series + (-num_series % series_par)
    # f32 accumulation only on real accelerators (native lane width,
    # the documented precision trade-off); CPU/XLA-fallback meshes keep
    # the storage f64 so query results match the reference's f64
    # aggregation exactly (advisor round-1, blockagg precision).
    accel = mesh.devices.flat[0].platform not in ("cpu",)
    val_dtype = np.float32 if accel else np.float64
    # ONE frozen column block stages the shard lanes: dtype coercions go
    # through colblock.as_lane (view when no bytes move, one honest copy
    # when a conversion is unavoidable) and the H2D transfer is charged
    # once against the block — no intermediate staging alloc to
    # double-charge against
    block = colblock.ColBlock.wrap({
        "ts": colblock.as_lane(ts_np, np.int64, "host_prep"),
        "sid": colblock.as_lane(sid_np, np.int32, "host_prep"),
        "value": colblock.as_lane(val_np, val_dtype, "host_prep"),
        "ok": (
            np.ones(len(ts_np), dtype=bool) if valid_np is None
            else colblock.as_lane(valid_np, bool, "host_prep")
        ),
    }).freeze()
    memtrace.device_staged(block.nbytes, "h2d")
    (ts_d, sid_d, val_d, ok_d), _pad_valid = shard_rows(
        mesh,
        tuple(block.lane(k) for k in ("ts", "sid", "value", "ok")),
        pad_value=(0, padded_series, 0, False),
    )
    # pad rows carry ok=False (False pad on the bool lane), so ok_d
    # alone is the full validity mask
    out = sharded_downsample(
        mesh, ts_d, sid_d, val_d, ok_d,
        t0=t0, bucket_ms=bucket_ms,
        num_series=padded_series, num_buckets=num_buckets,
        with_minmax=with_minmax, sorted_input=sorted_input,
    )
    return {
        k: np.asarray(v)[:num_series]
        for k, v in out.items()
        if k in ("sum", "count", "min", "max")
    }
