"""Multi-host bootstrap: the distributed communication backend.

The reference snapshot has no inter-node runtime (SURVEY §2.5/§5.8) — its
"network" is the shared object store. This framework's distributed story is
jax's: `jax.distributed` + a global Mesh spanning hosts, with XLA inserting
the collectives (psum/pmin/pmax over ICI within a slice, DCN across hosts).
The object-store data plane is retained unchanged — every host reads SSTs
from shared storage, and the mesh axes decide which host scans what.

Usage on a multi-host slice (one process per host):

    from horaedb_tpu.parallel.distributed import initialize, global_mesh
    initialize()                     # env-driven (TPU pods auto-configure)
    mesh = global_mesh(series_parallel=4)

Collective layout guidance (the scaling-book recipe): keep the series-axis
all-reduces inside one host's ICI domain by making `series_parallel` divide
the per-host device count; the rows axis then spans hosts and its psum
partial-grid combines are the only DCN traffic — small (grid-sized), not
row-sized.
"""

from __future__ import annotations

import logging

import jax

from horaedb_tpu.common.error import ensure
from horaedb_tpu.parallel.mesh import make_mesh

logger = logging.getLogger(__name__)

_initialized = False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize jax.distributed. On TPU pods all arguments are discovered
    from the environment; pass them explicitly for manual clusters. Safe to
    call on single-process deployments (no-op)."""
    global _initialized
    if _initialized:
        return
    if num_processes is None and coordinator_address is None:
        import os

        coordinator_address = os.environ.get("COORDINATOR_ADDRESS") or os.environ.get(
            "JAX_COORDINATOR_ADDRESS"
        )
        if coordinator_address is None:
            logger.info("no coordinator configured; single-process deployment")
            _initialized = True
            return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    logger.info(
        "jax.distributed up: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def global_mesh(series_parallel: int = 1):
    """Mesh over ALL processes' devices (rows axis spans hosts/DCN; series
    axis should stay within a host's ICI domain)."""
    n_local = jax.local_device_count()
    ensure(
        series_parallel <= n_local and n_local % series_parallel == 0,
        f"series_parallel={series_parallel} must divide local device count {n_local} "
        "so series all-reduces ride ICI, not DCN",
    )
    return make_mesh(None, series_parallel=series_parallel)
