"""Sharded scan→filter→aggregate over the device mesh.

This is the distributed form of ops/aggregate.py: rows shard over the "rows"
mesh axis, the series/group dimension shards over "series", and partial
(sum, count, min, max) grids combine with psum/pmin/pmax over the rows axis —
the ICI collectives that replace the reference's single-node k-way merge of
per-SST streams (SURVEY §2.5: "sharded shuffle/merge collectives").

The output grids stay sharded over "series" (PartitionSpec("series") on the
leading dim), so a 10M-series group-by never materializes on a single chip.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from horaedb_tpu.common import colblock
from horaedb_tpu.common import deadline as deadline_ctx
from horaedb_tpu.common import memtrace
from horaedb_tpu.common.error import ensure
from horaedb_tpu.common.jaxcompat import shard_map
from horaedb_tpu.common.xprof import xjit
from horaedb_tpu.ops import filter as filter_ops
from horaedb_tpu.ops.filter import Predicate
from horaedb_tpu.server.metrics import GLOBAL_METRICS

H2D_SECONDS = GLOBAL_METRICS.histogram(
    "horaedb_h2d_transfer_seconds",
    help="Host->device placement time per sharded-scan input batch "
         "(dispatch only unless a scanstats collector fences transfers).",
)
H2D_BYTES = GLOBAL_METRICS.counter(
    "horaedb_h2d_transfer_bytes_total",
    help="Bytes placed onto the mesh by sharded scans.",
)
# same family storage/read.py registers (registration is idempotent): the
# mesh downsample is a distinct "sharded" route entry point
SCAN_PATH = GLOBAL_METRICS.counter(
    "horaedb_scan_path_total",
    help="Merge route the scan planner took (host SIMD, single-device "
         "kernel, or the cross-chip sharded merge).",
    labelnames=("path",),
)


def _local_grids(ts, sid, vals, valid, t0, bucket_ms, series_lo, local_series,
                 num_buckets, with_minmax, sorted_input=False, sorted_impl=None,
                 unsorted_impl=None):
    """Partial grids for this shard's rows, restricted to the series slice
    [series_lo, series_lo + local_series).

    sum and count share ONE variadic scatter (stacked features) — scatters
    are the expensive op on TPU (random-index updates don't vectorize), so
    the kernel issues as few as possible; min/max add two more and are only
    computed when requested.

    `sorted_input=True` declares rows ordered by (sid, ts) — the engine's
    natural scan-output order. The sum/count reduction then dispatches to
    the sorted-segment strategies (ops/blockagg.py; `sorted_impl=None`
    resolves through the calibrated registry dispatcher in
    ops/agg_registry.py at trace time, restricted to traceable impls —
    host lanes cannot ride shard_map); results are identical either way,
    sortedness only affects speed.
    """
    local_sid = sid - series_lo
    bucket = ((ts - t0) // bucket_ms).astype(jnp.int32)
    in_slice = (local_sid >= 0) & (local_sid < local_series)
    ok = valid & in_slice & (bucket >= 0) & (bucket < num_buckets)
    num_cells = local_series * num_buckets
    from horaedb_tpu.ops.aggregate import masked_cell_keys, masked_minmax

    # `safe` (in-range, mask rides the weight column) feeds sum/count;
    # `flat` (sentinel drop) feeds min/max — see masked_cell_keys.
    safe, flat = masked_cell_keys(local_sid, bucket, ok, local_series, num_buckets)
    # Rows OUTSIDE this shard's contiguous series slice go to the sentinel
    # key instead of a clipped in-range key: in (sid, ts) order they form a
    # contiguous prefix/suffix, so sentinel runs stay whole — clipping them
    # to local_sid 0/local_series-1 would fragment them into one run per
    # (foreign series x bucket) and trip the block compaction's
    # distinct-per-block check on sparse shards. Predicate/bucket misses
    # keep clipped keys (their mask rides the weight column).
    safe = jnp.where(in_slice, safe, num_cells)
    # typed zero fill: a weak 0.0 would promote integer vals to f32 and
    # bypass the dtype-preserving integer scatter route
    vals_masked = jnp.where(ok, vals, jnp.zeros((), vals.dtype))
    from horaedb_tpu.ops.blockagg import (
        _F32_EXACT,
        segment_sum_count,
        sorted_segment_min_max,
        sorted_segment_sum_count,
        unsorted_strategy,
    )

    mn = mx = None
    if sorted_input and num_cells < _F32_EXACT:
        s, c = sorted_segment_sum_count(
            safe, vals_masked, num_cells, impl=sorted_impl,
            weights=ok.astype(vals.dtype),
        )
        if with_minmax:
            mn, mx = sorted_segment_min_max(
                safe, vals_masked, num_cells, impl=sorted_impl, valid=ok
            )
    elif (
        num_cells < _F32_EXACT
        and unsorted_strategy(
            safe.shape[0], num_cells, vals_masked.dtype, unsorted_impl
        ) == "sort"
    ):
        # Unsorted rows, compaction-eligible: ONE device sort feeds both
        # reductions (sort ~4 ns/row replaces up to four 9 ns/row scatters).
        # Post-sort, sentinel keys are contiguous at the tail, so no weight
        # column is needed — invalid rows drop via the sentinel bucket.
        k2, v2 = lax.sort((flat, vals_masked), num_keys=1)
        s, c = sorted_segment_sum_count(k2, v2, num_cells, impl="block")
        if with_minmax:
            mn, mx = sorted_segment_min_max(k2, v2, num_cells, impl="block")
    else:
        s, c = segment_sum_count(
            safe, vals_masked, num_cells, impl="scatter",
            weights=ok.astype(vals.dtype),
        )
        if with_minmax:
            mn, mx = masked_minmax(vals, flat, ok, num_cells)
    shape = (local_series, num_buckets)
    if not with_minmax:
        return s.reshape(shape), c.reshape(shape), None, None
    return s.reshape(shape), c.reshape(shape), mn.reshape(shape), mx.reshape(shape)


@lru_cache(maxsize=128)
def build_sharded_downsample(
    mesh: Mesh,
    num_series: int,
    num_buckets: int,
    predicate: Predicate | None = None,
    with_minmax: bool = True,
    sorted_input: bool = False,
    sorted_impl: str | None = None,
    unsorted_impl: str | None = None,
):
    """Compile the sharded downsample step for a fixed grid shape.

    `sorted_impl` / `unsorted_impl` pin the reduction strategy into this
    executable (part of the memo key — required for in-process A/B, since
    the env default is read once at trace time).

    Returns fn(ts, sid, vals, valid, literals, t0, bucket_ms) -> dict of
    [num_series, num_buckets] grids sharded P("series", None). Inputs are
    1-D row arrays sharded P("rows") (row count must divide the rows axis).
    `with_minmax=False` halves the scatter count for mean/sum-only queries.

    Memoized: repeat queries with the same mesh/grid/predicate template reuse
    the jitted executable. Pass predicates through `split_literals` first (or
    literal-free) so a changed constant hits the cache.
    """
    series_par = mesh.shape["series"]
    ensure(num_series % series_par == 0,
           f"num_series={num_series} must divide over series axis={series_par}")
    local_series = num_series // series_par
    template, _ = filter_ops.split_literals(predicate)
    keys = ("sum", "count", "min", "max", "mean") if with_minmax else ("sum", "count", "mean")

    def step(ts, sid, vals, valid, literals, t0, bucket_ms):
        cols = {"__ts__": ts, "__sid__": sid, "__val__": vals}
        if template is not None:
            valid = valid & filter_ops.eval_predicate(template, cols, literals)
        s_idx = lax.axis_index("series")
        lo = (s_idx * local_series).astype(sid.dtype)
        s, c, mn, mx = _local_grids(
            ts, sid, vals, valid, t0, bucket_ms, lo, local_series, num_buckets,
            with_minmax, sorted_input=sorted_input, sorted_impl=sorted_impl,
            unsorted_impl=unsorted_impl,
        )
        # combine partials across the row shards (ICI all-reduce)
        s = lax.psum(s, "rows")
        c = lax.psum(c, "rows")
        out = {"sum": s, "count": c, "mean": s / c}
        if with_minmax:
            out["min"] = lax.pmin(mn, "rows")
            out["max"] = lax.pmax(mx, "rows")
        return out

    row_spec = P("rows")
    grid_spec = P("series", None)
    mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=(row_spec, row_spec, row_spec, row_spec, P(), P(), P()),
        out_specs={k: grid_spec for k in keys},
    )
    return xjit(mapped, kernel="sharded_downsample")


def sharded_downsample(
    mesh: Mesh,
    ts,
    sid,
    vals,
    valid,
    t0,
    bucket_ms,
    num_series: int,
    num_buckets: int,
    predicate: Predicate | None = None,
    with_minmax: bool = True,
    sorted_input: bool = False,
):
    """One-shot wrapper: splits predicate literals so repeat queries with new
    constants reuse the memoized executable."""
    # cooperative deadline before the device dispatch (host side, outside
    # the traced body): an expired query launches no kernel
    deadline_ctx.check("device_lane")
    SCAN_PATH.labels("sharded").inc()
    template, literals = filter_ops.split_literals(predicate)
    fn = build_sharded_downsample(
        mesh, num_series, num_buckets, template, with_minmax, sorted_input
    )
    lit_arrays = filter_ops.literal_arrays(
        template, literals,
        {"__ts__": ts.dtype, "__sid__": sid.dtype, "__val__": vals.dtype},
    )
    return fn(ts, sid, vals, valid, lit_arrays,
              jnp.asarray(t0, dtype=ts.dtype), jnp.asarray(bucket_ms, dtype=ts.dtype))


@lru_cache(maxsize=64)
def build_multisegment_downsample(
    mesh: Mesh,
    num_series: int,
    num_buckets: int,
):
    """3-axis scan step over a ("seg", "rows", "series") mesh — the
    TPU-native form of the reference's per-segment plan union
    (UnionExec over time segments, storage.rs:343-369):

    - "seg" shards independent time segments (no collective crosses it —
      segments are separate LSM windows; the pipeline-parallel analog);
    - "rows" data-parallels each segment's rows (psum/pmin/pmax combines);
    - "series" shards the output grids.

    Inputs are [n_segments, rows] arrays sharded P("seg", "rows") plus a
    per-segment t0 vector sharded P("seg"); output grids are
    [n_segments, num_series, num_buckets] sharded P("seg", "series", None).
    """
    series_par = mesh.shape["series"]
    ensure(num_series % series_par == 0,
           f"num_series={num_series} must divide over series axis={series_par}")
    local_series = num_series // series_par

    def step(ts, sid, vals, valid, t0_seg, bucket_ms):
        # shard-local shapes: [segs_local, rows_local]; the kernel handles
        # exactly one segment per seg-shard
        ensure(
            ts.shape[0] == 1,
            # jaxlint: disable=J002 trace-time assert formats a STATIC shape, not a tracer
            f"n_segments must equal the seg mesh axis "
            f"(got {ts.shape[0]} local segments per shard)",
        )
        s_idx = lax.axis_index("series")
        lo = (s_idx * local_series).astype(sid.dtype)
        s, c, mn, mx = _local_grids(
            ts[0], sid[0], vals[0], valid[0], t0_seg[0], bucket_ms,
            lo, local_series, num_buckets, True,
        )
        s = lax.psum(s, "rows")
        c = lax.psum(c, "rows")
        mn = lax.pmin(mn, "rows")
        mx = lax.pmax(mx, "rows")
        out = {"sum": s, "count": c, "min": mn, "max": mx, "mean": s / c}
        return {k: v[None] for k, v in out.items()}

    row_spec = P("seg", "rows")
    grid_spec = P("seg", "series", None)
    mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=(row_spec, row_spec, row_spec, row_spec, P("seg"), P()),
        out_specs={k: grid_spec for k in ("sum", "count", "min", "max", "mean")},
    )
    return xjit(mapped, kernel="multisegment_downsample")


def sharded_grouped_stats(
    mesh: Mesh,
    group_idx,
    vals,
    valid,
    num_groups: int,
    predicate: Predicate | None = None,
    with_minmax: bool = True,
):
    """Group-by aggregation (BASELINE config 3) = downsample with one bucket:
    group ids play the series role, bucket axis is singleton."""
    ts = jnp.zeros_like(group_idx)
    out = sharded_downsample(
        mesh, ts, group_idx, vals, valid,
        t0=0, bucket_ms=1, num_series=num_groups, num_buckets=1,
        predicate=predicate, with_minmax=with_minmax,
    )
    return {k: v[:, 0] for k, v in out.items()}


def shard_rows(mesh: Mesh, arrays: tuple, pad_value=0):
    """Place 1-D host arrays onto the mesh row-sharded (pads to a multiple of
    the rows axis; returns (device_arrays, valid_mask)). Placement is timed
    into `horaedb_h2d_transfer_seconds` — the transfer lane VERDICT r02
    found dominating "kernel-bound" configs; when a scanstats collector is
    attached the puts are fenced so the histogram carries true transfer
    time, not just dispatch.

    `pad_value` is one scalar for every lane, or a per-lane sequence
    (len == len(arrays)). Per-lane pads matter for sorted inputs: the
    sid lane must pad with an OUT-OF-RANGE sentinel (>= the padded
    series count) so tail pad rows keep the keys monotone — a scalar 0
    would plant series-0 keys after larger ones and violate the sorted-
    segment kernels' contract (ops/blockagg.py), where only the weight
    column and the valid mask kept results right by accident."""
    import time

    import numpy as np

    from horaedb_tpu.storage import scanstats

    # cooperative deadline before the H2D transfer: expired queries ship
    # no bytes to the device
    deadline_ctx.check("device_lane")
    rows_par = mesh.shape["rows"]
    n = len(arrays[0])
    pad = (-n) % rows_par
    sharding = NamedSharding(mesh, P("rows"))
    pads = (list(pad_value) if isinstance(pad_value, (tuple, list))
            else [pad_value] * len(arrays))
    ensure(len(pads) == len(arrays),
           f"per-lane pad_value needs {len(arrays)} entries, got {len(pads)}")
    # pad on host BEFORE the timer: the pad fill is host_prep work and
    # must not inflate the transfer lane (the exact misattribution the
    # histogram exists to prevent). Pad-free lanes stage AS-IS — the
    # jax.device_put below reads the caller's block lanes in place (no
    # intermediate staging copy); only a genuine pad pays one aligned
    # tracked copy per lane
    padded = []
    nbytes = 0
    for a, pv in zip(arrays, pads):
        if pad:
            g = colblock.aligned_empty(n + pad, a.dtype)
            g[:n] = a
            g[n:] = pv
            memtrace.track(g, "host_prep", "copy")
            a = g
        padded.append(a)
        nbytes += a.nbytes
    valid = np.ones(n + pad, dtype=bool)
    if pad:
        valid[n:] = False
    t0 = time.perf_counter()
    out = [jax.device_put(a, sharding) for a in padded]
    valid_dev = jax.device_put(valid, sharding)
    if scanstats.active():  # fence only for attribution (production path
        # stays async so H2D overlaps kernel dispatch)
        # jaxlint: disable=J001 h2d attribution fence; profiling runs only
        jax.block_until_ready(out + [valid_dev])
    H2D_SECONDS.observe(time.perf_counter() - t0)
    H2D_BYTES.inc(nbytes + valid.nbytes)
    return tuple(out), valid_dev
